"""Serving front end: `Request`/`Result` types, the blocking + streaming
`ServingEngine`, an offline batch mode, and the stdlib HTTP JSON endpoint
behind ``bpe-tpu serve``.

Layering (one thread owns the chip):

* transports (HTTP handler threads, `generate()` callers, the batch runner)
  only touch the `FifoScheduler` and per-request completion events;
* ONE worker thread runs the engine loop — admit queued requests into free
  slots (prefill), run a decode tick across every occupied slot, deliver
  sampled tokens to the per-request streams, retire finished slots — so the
  `SlotPoolEngine` itself never needs a lock;
* backpressure surfaces where it belongs: a full queue raises
  `QueueFullError` at submit time (HTTP 503), never blocking a transport.

Telemetry (PR-1 stream schema): per-request ``serve/queue_wait``,
``serve/prefill``, ``serve/decode`` span records, periodic
``{"kind": "engine"}`` records (active slots, queue depth, tokens/sec), and
the shared manifest/footer — all through one `telemetry.Telemetry`, so
``bpe-tpu report`` summarizes a serving run from the same JSONL it already
reads for training runs.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import queue
import threading
import time
import uuid
from pathlib import Path
from typing import Iterator

import numpy as np

from bpe_transformer_tpu.resilience.faults import FaultInjector
from bpe_transformer_tpu.serving.engine import SlotPoolEngine, TickEvent
from bpe_transformer_tpu.serving.kvpool.migrate import (
    supported_codecs as _supported_codecs,
)
from bpe_transformer_tpu.serving.metrics import ServingMetrics, render_prometheus
from bpe_transformer_tpu.serving.scheduler import (
    FifoScheduler,
    PrefillBudget,
    QueueFullError,
)
from bpe_transformer_tpu.telemetry.alerts import (
    AlertEngine,
    default_serving_rules,
)
from bpe_transformer_tpu.telemetry.flightrecorder import FlightRecorder
from bpe_transformer_tpu.telemetry.resources import (
    install_compile_counter,
    sample_resources,
)

__all__ = [
    "Request",
    "Result",
    "RequestHandle",
    "ServingEngine",
    "QueueFullError",
    "DuplicateRequestError",
    "make_http_server",
]

_STREAM_END = object()


class DuplicateRequestError(ValueError):
    """A request id already in flight on this replica.  Subclasses
    ValueError for direct ``submit()`` callers, but the HTTP layer maps
    it to a retryable 503, NOT a 400: the canonical producer is a client
    retrying a router 504 with the same echoed X-Request-Id (the id it
    was told to keep for correlation) — that retry must fail over to a
    replica that ISN'T still running the original generation, not be
    judged a client error fleet-wide."""


@dataclasses.dataclass(frozen=True)
class Request:
    """One generation request (token-id based; transports tokenize)."""

    prompt_ids: tuple[int, ...]
    max_new_tokens: int = 128
    temperature: float = 1.0
    top_k: int | None = None
    top_p: float | None = None
    seed: int = 0
    stop_id: int | None = None
    #: Seconds the request may wait IN THE QUEUE before it is failed fast
    #: with ``finish_reason="deadline"`` (None: wait indefinitely).
    deadline_s: float | None = None
    #: Optional session key (multi-turn conversations): the fleet router
    #: hashes it to a sticky replica so follow-up turns land where the
    #: session's radix prefix blocks live.  The replica itself only
    #: carries it (request metadata) — affinity is a routing concern.
    session: str | None = None
    #: Disaggregated prefill (ISSUE 15): run the chunk machine, then —
    #: instead of entering decode — export the finished prefix as a KV
    #: migration payload (``Result.kv_payload``, finish_reason
    #: ``"migrated"``).  The ``/kv/export`` endpoint sets this; needs a
    #: paged engine.
    migrate: bool = False
    #: ``migrate`` only: comma list of wire codecs the IMPORTER accepts
    #: (the ``X-KV-Accept`` header on ``/kv/export``) — the export picks
    #: the best locally available one (``migrate.negotiate_codec``).
    #: None = no negotiation happened -> raw, so a pre-negotiation peer
    #: is never handed a frame it cannot open.
    kv_accept: str | None = None
    request_id: str = dataclasses.field(
        default_factory=lambda: uuid.uuid4().hex
    )


@dataclasses.dataclass(frozen=True)
class Result:
    """A finished request: generated ids + why it stopped + phase timings."""

    request_id: str
    token_ids: tuple[int, ...]
    finish_reason: str  # stop | length | deadline | cancelled | error | migrated
    queue_wait_s: float = 0.0
    prefill_s: float = 0.0
    decode_s: float = 0.0
    #: ``finish_reason == "migrated"`` only: the serialized KV payload
    #: (serving/kvpool/migrate.py) another replica's ``/kv/import`` (or
    #: ``submit_import``) continues the generation from.
    kv_payload: bytes | None = None

    def timings(self) -> dict:
        return {
            "queue_wait_s": round(self.queue_wait_s, 6),
            "prefill_s": round(self.prefill_s, 6),
            "decode_s": round(self.decode_s, 6),
        }


class _Entry:
    """Worker-side state for one submitted request."""

    __slots__ = (
        "request", "tokens", "stream", "done", "result", "slot",
        "t_submit", "t_decode_start", "queue_wait_s", "prefill_s",
        "cancel_requested", "bucket", "t_prefill_start", "programs_before",
        "shared_tokens", "migrated_in",
    )

    def __init__(self, request: Request, t_submit: float):
        self.request = request
        self.tokens: list[int] = []
        self.stream: queue.Queue = queue.Queue()
        self.done = threading.Event()
        self.result: Result | None = None
        self.slot: int | None = None
        self.t_submit = t_submit
        self.t_decode_start = t_submit
        self.queue_wait_s = 0.0
        self.prefill_s = 0.0
        self.cancel_requested = False
        self.bucket: int | None = None  # prefill bucket, set at admission
        self.t_prefill_start = t_submit  # first chunk start (paged engine)
        self.programs_before = 0  # compile counter at admission (paged)
        self.shared_tokens = 0  # prefix-cache-reused prompt tokens (paged)
        self.migrated_in = False  # arrived as a KV graft (ISSUE 15)


class RequestHandle:
    """Caller-side view of an in-flight request."""

    def __init__(self, serving: "ServingEngine", entry: _Entry):
        self._serving = serving
        self._entry = entry

    @property
    def request_id(self) -> str:
        return self._entry.request.request_id

    def result(self, timeout: float | None = None) -> Result:
        """Block until the request finishes; raises TimeoutError."""
        if not self._entry.done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not done within {timeout}s"
            )
        return self._entry.result

    def tokens(self) -> Iterator[int]:
        """Stream token ids as the engine emits them (ends at completion)."""
        while True:
            item = self._entry.stream.get()
            if item is _STREAM_END:
                return
            yield item

    def cancel(self) -> None:
        self._serving.cancel(self.request_id)


class ServingEngine:
    """Continuous-batching serving: scheduler + slot pool + worker thread.

    Use as a context manager (or call :meth:`start`/:meth:`close`)::

        with ServingEngine(params, config, slots=8) as serving:
            result = serving.generate([1, 2, 3], max_new_tokens=16)
    """

    def __init__(
        self,
        params,
        config,
        *,
        tokenizer=None,
        slots: int = 8,
        max_queue: int = 64,
        max_wait_s: float = 0.0,
        prefill_buckets: tuple[int, ...] | None = None,
        min_bucket: int = 16,
        default_stop_id: int | None = None,
        default_max_new_tokens: int = 128,
        telemetry=None,
        engine_record_every_s: float = 1.0,
        idle_poll_s: float = 0.02,
        clock=time.monotonic,
        manifest: dict | None = None,
        paged: bool = False,
        block_size: int = 16,
        num_kv_blocks: int | None = None,
        prefill_chunk: int | None = None,
        prefill_token_budget: int | None = None,
        prefix_cache: bool = True,
        kv_dtype: str | None = None,
        weight_dtype: str | None = None,
        fused_sampling: bool = False,
        speculate_k: int = 0,
        draft_spec=None,
        alert_rules=None,
        role: str = "both",
        flightrecorder_capacity: int = 256,
    ):
        # Count XLA compiles (the engine's bucketed prefills included) into
        # the process-wide telemetry.resources counter before the first
        # program builds.
        install_compile_counter()
        if role not in ("prefill", "decode", "both"):
            raise ValueError(
                f'role={role!r} must be "prefill", "decode", or "both"'
            )
        if role != "both" and not paged:
            raise ValueError(
                f"role={role!r} needs paged=True (KV migration lives in "
                "the block pool)"
            )
        if speculate_k and not paged:
            raise ValueError(
                "speculate_k needs paged=True (the verify pass scores "
                "through the paged scatter; the KV rewind lives in the "
                "block pool)"
            )
        if speculate_k:
            from bpe_transformer_tpu.serving.spec.engine import SpecEngine

            if draft_spec is None:
                raise ValueError(
                    "speculate_k needs a draft_spec (DraftSpec or a "
                    "prebuilt DraftModel)"
                )
            self.engine = SpecEngine(
                params, config, draft=draft_spec, speculate_k=speculate_k,
                slots=slots, block_size=block_size,
                num_blocks=num_kv_blocks,
                prefill_buckets=prefill_buckets, min_bucket=min_bucket,
                prefill_chunk=prefill_chunk, prefix_cache=prefix_cache,
                kv_dtype=kv_dtype, weight_dtype=weight_dtype,
                fused_sampling=fused_sampling,
            )
        elif paged:
            from bpe_transformer_tpu.serving.kvpool.paged_engine import (
                PagedEngine,
            )

            self.engine = PagedEngine(
                params, config, slots=slots, block_size=block_size,
                num_blocks=num_kv_blocks,
                prefill_buckets=prefill_buckets, min_bucket=min_bucket,
                prefill_chunk=prefill_chunk, prefix_cache=prefix_cache,
                kv_dtype=kv_dtype, weight_dtype=weight_dtype,
                fused_sampling=fused_sampling,
            )
        else:
            self.engine = SlotPoolEngine(
                params, config, slots=slots,
                prefill_buckets=prefill_buckets, min_bucket=min_bucket,
                weight_dtype=weight_dtype, fused_sampling=fused_sampling,
            )
        self.paged = paged
        #: Disaggregated-fleet role (ISSUE 15): ``"prefill"`` replicas run
        #: the chunk machine then stream finished prefixes out over
        #: ``/kv/export`` instead of ticking (plain /generate refused);
        #: ``"decode"`` replicas additionally accept grafts on
        #: ``/kv/import`` and — fed only imports — never compile a chunk
        #: program; ``"both"`` (default) serves everything.
        self.role = role
        #: Speculative decoding active (the engine is a SpecEngine): the
        #: stats/statusz/metrics surfaces grow the acceptance gauges and
        #: the engine-record cadence emits kind="spec" records.
        self.spec = bool(speculate_k)
        #: Always-on decision ring (telemetry/flightrecorder.py): every
        #: admit/park/reject/deadline/finish, migration, rewind, drain and
        #: worker-error decision lands here as host-side bookkeeping (zero
        #: device syncs — pinned by the fetch-count test), flushed as a
        #: kind="blackbox" dump on alert/manual/worker-error triggers.
        self.flightrecorder = FlightRecorder(
            "serve", capacity=flightrecorder_capacity, clock=clock
        )
        if paged:
            # Paged KV rewinds (speculative rejection rollbacks, partial
            # chains) are pool decisions too: the engine tees them in.
            self.engine.recorder = self.flightrecorder
        #: Chunked-prefill fairness (paged only): prefill tokens allowed
        #: between consecutive decode ticks (None = run chunks to
        #: completion, the dense engine's schedule).
        self._prefill_budget = PrefillBudget(
            prefill_token_budget if paged else None,
            recorder=self.flightrecorder,
        )
        #: Admissions parked on KV-block exhaustion (paged): retried in
        #: FIFO order before any newer queue pop, as decode retirements
        #: free blocks.
        self._admit_backlog: list[_Entry] = []
        #: Slots mid-chunked-prefill -> their entries (paged).
        self._prefill_entries: dict[int, _Entry] = {}
        #: Inbound KV grafts awaiting a slot/blocks, FIFO:
        #: ``(entry, payload_dict, payload_bytes_len, recv_unix)`` —
        #: fed by submit_import / adopt_migration (transport threads),
        #: drained by the worker ahead of fresh admissions.
        self._import_queue: collections.deque = collections.deque()
        self._import_lock = threading.Lock()
        #: Drain-evacuation targets: in-process peer ServingEngines the
        #: worker exports every queued + in-flight session to when a
        #: ``drain(evacuate_to=...)`` runs (round-robin).
        self._evacuate_peers: list = []
        self._evacuate_rr = 0
        #: Over-the-wire drain-evacuation targets (ISSUE 20): peer base
        #: URLs — queued requests replay as seeded ``/generate`` calls,
        #: in-flight slots export + relay to a peer's ``/kv/import``; the
        #: relay thread completes the original caller's handle with the
        #: peer's tokens (token-identical: same KV, same RNG state).
        self._evacuate_urls: list[str] = []
        #: Controller-initiated hot rebalancing (``POST /admin/evacuate``):
        #: pending ``(target_url, max_sessions, done_event, out_dict)``
        #: requests the worker consumes at the top of each step.
        self._rebalance_queue: collections.deque = collections.deque()
        self._relays_ok = 0
        self._relays_failed = 0
        self._rebalanced_out = 0
        #: Bounded retry policy for one payload relay (per-attempt HTTP
        #: timeout, exponential backoff between attempts).
        self.relay_attempts = 4
        self.relay_timeout_s = 600.0
        self.relay_backoff_s = 0.2
        #: Wire codec for migration payload exports (v2 frames): a comma
        #: list negotiated against the importer (``negotiate_codec``).
        #: zlib is stdlib, so every same-version peer decodes it.
        self.export_codec = "zstd,zlib"
        #: Fleet chaos harness (ISSUE 20): per-replica BT_FAULTS plan —
        #: no-op (cheap comparisons) unless the env var is set.
        self.faults = FaultInjector.from_env()
        self._decode_ticks = 0
        #: Replay-once idempotency on imports: key -> _Entry, so a retried
        #: ``/kv/import`` (response lost, connection dropped mid-reply)
        #: attaches to the original graft instead of double-grafting.
        self._idem_keys: collections.OrderedDict = collections.OrderedDict()
        self._idem_lock = threading.Lock()
        self.scheduler = FifoScheduler(
            max_queue=max_queue, max_wait_s=max_wait_s, clock=clock
        )
        self.tokenizer = tokenizer
        self.default_stop_id = default_stop_id
        self.default_max_new_tokens = default_max_new_tokens
        self.manifest = manifest
        #: Live counter/histogram aggregate behind /metrics and stats() —
        #: fed from the same measurements the serve/* spans carry.
        self.metrics = ServingMetrics(clock=clock)
        self._telemetry = telemetry
        self._record_every_s = engine_record_every_s
        self._idle_poll_s = idle_poll_s
        self._clock = clock
        self._t0 = clock()
        self._last_record_t = self._t0
        self._last_record_tokens = 0
        self._entries: dict[str, _Entry] = {}
        self._entries_lock = threading.Lock()
        self._slot_entries: dict[int, _Entry] = {}
        #: Per-request trace ring (newest last): the finished requests'
        #: phase timelines behind /statusz "recent_requests" — the same
        #: numbers the serve/* spans carry, queryable from a live server
        #: without tailing the JSONL.
        self._recent: collections.deque = collections.deque(maxlen=32)
        #: Serving anomaly watchdog (telemetry/alerts.py): fed a gauge
        #: sample on the engine-record cadence INDEPENDENT of whether a
        #: telemetry sink exists — /statusz must show active alerts on a
        #: server run without --metrics-jsonl.  Transitions (fire/clear)
        #: are emitted as kind="alert" records when a sink is attached.
        self._alerts = AlertEngine(
            alert_rules
            if alert_rules is not None
            else default_serving_rules()
        )
        self._requests_finished = 0
        self._thread: threading.Thread | None = None
        self._running = False
        self._draining = False
        self._worker_error: BaseException | None = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "ServingEngine":
        if self._thread is not None:
            return self
        self._running = True
        self._t0 = self._clock()
        self._last_record_t = self._t0
        self._thread = threading.Thread(
            target=self._run, name="serving-engine", daemon=True
        )
        self._thread.start()
        return self

    def drain(
        self, timeout_s: float = 30.0, evacuate_to=None, evacuate_urls=None,
    ) -> bool:
        """Graceful shutdown, phase 1: stop ADMITTING (new submits raise
        ``RuntimeError`` -> HTTP 503) but keep the worker running until
        every queued and in-flight request finishes — the SIGTERM path of
        ``bpe-tpu serve`` (preemption must not cancel work the engine can
        still complete).  Returns True when fully drained, False on
        timeout (the caller's ``close()`` then cancels the stragglers).

        ``evacuate_to`` (ISSUE 15) turns drain into session *evacuation*:
        a list of in-process peer ``ServingEngine`` replicas the worker
        migrates every queued AND in-flight session to — mid-generation
        slots are exported as KV payloads and grafted onto a peer, which
        continues the generation bit-for-bit and completes the original
        caller's handle — so draining a loaded replica finishes in
        payload-transfer time instead of longest-generation time, with
        zero failed requests and zero token divergence.

        ``evacuate_urls`` (ISSUE 20) is the cross-process form: peer base
        URLs.  Queued (never-admitted) requests replay on a peer as
        seeded ``/generate`` calls; in-flight sessions export and relay
        to a peer's ``/kv/import`` with an idempotency key + bounded
        retries — the relay thread completes the original caller's
        handle with the peer's returned tokens, so the caller's open
        connection never notices the replica it was talking to left."""
        if evacuate_to:
            peers = [p for p in evacuate_to if p.accepting_imports()]
            self._evacuate_peers = peers
        if evacuate_urls:
            self._evacuate_urls = [u.rstrip("/") for u in evacuate_urls]
        self._draining = True
        self.flightrecorder.record(
            "drain",
            queue_depth=self.scheduler.depth,
            active_slots=self.engine.active_count,
            evacuating=bool(self._evacuate_peers or self._evacuate_urls),
        )
        if self._telemetry is not None:
            self._telemetry.event(
                "serve_drain",
                queue_depth=self.scheduler.depth,
                active_slots=self.engine.active_count,
                evacuating=bool(self._evacuate_peers or self._evacuate_urls),
            )
        deadline = self._clock() + timeout_s
        while True:
            # The entries registry is the superset of unfinished work:
            # queue depth and active_count both read 0 for a request the
            # worker has popped but not yet slotted (it sits in a
            # multi-second prefill compile exactly when a drain is likely
            # to ask) — _finish() is the only thing that unregisters.
            with self._entries_lock:
                pending = len(self._entries)
            if (
                not pending
                and not self.engine.active_count
                and not self.scheduler.depth
            ):
                return True
            if (
                self._worker_error is not None
                or not self._running
                or self._clock() >= deadline
            ):
                return False
            time.sleep(min(self._idle_poll_s, 0.05))

    def close(self) -> None:
        """Stop the worker; in-flight and queued requests finish as
        ``cancelled``."""
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        drain = self.scheduler.pop_ready(self.scheduler.max_queue)
        for qe in drain.admit + drain.expired + drain.cancelled:
            self._finish(qe.item, "cancelled")
        for slot in list(self._slot_entries):
            entry = self._slot_entries.pop(slot)
            self.engine.release(slot)
            self._finish(entry, "cancelled")
        for slot in list(self._prefill_entries):
            entry = self._prefill_entries.pop(slot)
            self.engine.release(slot)
            self._finish(entry, "cancelled")
        for entry in self._admit_backlog:
            self._finish(entry, "cancelled")
        self._admit_backlog = []
        with self._import_lock:
            imports = [item[0] for item in self._import_queue]
            self._import_queue.clear()
        for entry in imports:
            self._finish(entry, "cancelled")
        if self._telemetry is not None:
            self._telemetry.footer(
                clean=self._worker_error is None,
                requests=self._requests_finished,
                ticks=self.engine.ticks,
                tokens=self.engine.tokens_emitted,
            )

    def __enter__(self) -> "ServingEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------- transport side

    def submit(self, request: Request) -> RequestHandle:
        """Validate + enqueue; raises `QueueFullError` (backpressure) or
        ``ValueError`` (prompt the context window cannot serve)."""
        if self._worker_error is not None:
            raise RuntimeError(
                "serving engine worker died"
            ) from self._worker_error
        if not self._running:
            raise RuntimeError("serving engine is not running (use start())")
        if self._draining:
            raise RuntimeError(
                "serving engine is draining (shutting down); not accepting "
                "new requests"
            )
        if request.migrate and not self.paged:
            raise ValueError(
                "migrate-at-prefill needs a paged engine (the KV payload "
                "is a block chain)"
            )
        if self.role == "prefill" and not request.migrate:
            # A prefill-role replica never ticks: a plain generate would
            # park in a slot forever.  503 (RuntimeError at the HTTP
            # layer) so a misdirected client fails over, not a 400.
            raise RuntimeError(
                "prefill-role replica serves /kv/export only (finished "
                "prefixes stream out as KV payloads; decode lives on "
                "decode-role replicas)"
            )
        plen = len(request.prompt_ids)
        ctx = self.engine.config.context_length
        if plen < 1:
            raise ValueError("prompt must contain at least one token")
        if plen > ctx - 1:
            raise ValueError(
                f"prompt of {plen} tokens leaves no room to generate in a "
                f"context of {ctx}"
            )
        if request.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {request.max_new_tokens}"
            )
        if self.paged:
            # A request whose worst-case block chain exceeds the whole pool
            # can NEVER be admitted: fail fast at the transport instead of
            # deadlocking the admission backlog.
            need = self.engine.blocks_needed(plen, request.max_new_tokens)
            if need > self.engine.allocator.usable_blocks:
                raise ValueError(
                    f"request needs {need} KV blocks; the pool holds "
                    f"{self.engine.allocator.usable_blocks}"
                )
        entry = _Entry(request, self._clock())
        with self._entries_lock:
            if request.request_id in self._entries:
                # Client-supplied ids (X-Request-Id) key the entries
                # registry and the trace streams: a duplicate in flight
                # would orphan the first caller's completion event.
                raise DuplicateRequestError(
                    f"request id {request.request_id!r} is already in "
                    "flight on this replica"
                )
            self._entries[request.request_id] = entry
        try:
            self.scheduler.submit(
                entry,
                request_id=request.request_id,
                deadline_s=request.deadline_s,
            )
        except BaseException as exc:
            # Any enqueue failure (backpressure, a bad deadline value, ...)
            # must unregister the entry — a leaked entry holds a Queue and
            # an Event forever.
            with self._entries_lock:
                self._entries.pop(request.request_id, None)
            if isinstance(exc, QueueFullError):
                self.metrics.on_reject()
                self.flightrecorder.record(
                    "reject",
                    request_id=request.request_id,
                    queue_depth=self.scheduler.depth,
                )
            raise
        self.metrics.on_submit()
        return RequestHandle(self, entry)

    def generate(
        self,
        prompt_ids,
        *,
        max_new_tokens: int | None = None,
        temperature: float = 1.0,
        top_k: int | None = None,
        top_p: float | None = None,
        seed: int = 0,
        stop_id: int | None = None,
        deadline_s: float | None = None,
        session: str | None = None,
        request_id: str | None = None,
        migrate: bool = False,
        kv_accept: str | None = None,
        timeout: float | None = None,
    ) -> Result:
        """Blocking one-call generation.  ``request_id`` adopts a
        caller-supplied trace id (the router's ``X-Request-Id``) so one id
        stitches router hops, serve spans, and engine slot state.
        ``migrate=True`` is the /kv/export path: the result carries the
        finished prefix as a KV payload instead of a full generation."""
        kwargs = {} if request_id is None else {"request_id": request_id}
        handle = self.submit(
            Request(
                prompt_ids=tuple(int(t) for t in prompt_ids),
                max_new_tokens=(
                    self.default_max_new_tokens
                    if max_new_tokens is None
                    else max_new_tokens
                ),
                temperature=temperature,
                top_k=top_k,
                top_p=top_p,
                seed=seed,
                stop_id=self.default_stop_id if stop_id is None else stop_id,
                deadline_s=deadline_s,
                session=session,
                migrate=migrate,
                kv_accept=kv_accept,
                **kwargs,
            )
        )
        return handle.result(timeout)

    # ------------------------------------------------------- KV migration

    def accepting_imports(self) -> bool:
        """Whether this replica can graft KV payloads right now (paged,
        not prefill-role, worker alive, not draining)."""
        return (
            self.paged
            and self.role != "prefill"
            and self._running
            and not self._draining
            and self._worker_error is None
        )

    def submit_import(
        self,
        payload_bytes: bytes,
        *,
        idempotency_key: str | None = None,
    ) -> RequestHandle:
        """Accept a serialized KV migration payload (the ``/kv/import``
        body): validate it against this engine's geometry, register the
        request, and queue the graft for the worker.  The handle resolves
        with the COMPLETE generation — tokens emitted before the
        migration (carried in the payload) plus everything decoded here.

        ``idempotency_key`` (ISSUE 20, the ``X-Idempotency-Key`` header)
        makes the graft exactly-once under retries: a repeated key —
        whether the original graft is queued, decoding, or already
        finished — attaches to the original entry and resolves with ITS
        result instead of grafting a second copy.  The sender keeps one
        key per exported payload across every retry of that transfer.

        Raises ``ValueError`` (bad payload / geometry mismatch -> 400),
        ``QueueFullError`` (backpressure -> 503),
        :class:`DuplicateRequestError`, or ``RuntimeError`` (not
        accepting -> 503)."""
        from bpe_transformer_tpu.serving.kvpool.migrate import (
            payload_from_bytes,
        )

        if self._worker_error is not None:
            raise RuntimeError(
                "serving engine worker died"
            ) from self._worker_error
        if not self._running:
            raise RuntimeError("serving engine is not running (use start())")
        if self._draining:
            raise RuntimeError("serving engine is draining; not accepting")
        if not self.paged:
            raise RuntimeError("KV import needs a paged engine")
        if self.role == "prefill":
            raise RuntimeError(
                "prefill-role replica does not accept KV imports"
            )
        if idempotency_key:
            with self._idem_lock:
                known = self._idem_keys.get(idempotency_key)
            if known is not None:
                return RequestHandle(self, known)
        payload = payload_from_bytes(payload_bytes)
        meta = payload["meta"]
        # Full structural validation at the TRANSPORT: a corrupt payload
        # must 400 here, never reach the worker thread.
        self.engine.validate_import_payload(payload)
        request = Request(
            prompt_ids=tuple(int(t) for t in meta["prompt"]),
            max_new_tokens=max(int(meta["max_new_tokens"]), 1),
            temperature=float(meta["temperature"]),
            seed=int(meta["seed"]),
            stop_id=meta["stop_id"],
            deadline_s=meta.get("deadline_s"),
            session=meta.get("session"),
            request_id=meta.get("request_id") or uuid.uuid4().hex,
        )
        entry = _Entry(request, self._clock())
        self._entry_from_meta(entry, meta)
        if idempotency_key:
            # Claim-or-attach under one lock: a concurrent duplicate that
            # raced past the cheap pre-parse check attaches to whichever
            # entry claimed first — the graft below runs exactly once per
            # key.  The claim survives the entry finishing (bounded LRU),
            # so a retry whose original already completed gets the cached
            # result instead of a second graft.
            with self._idem_lock:
                known = self._idem_keys.get(idempotency_key)
                if known is not None:
                    return RequestHandle(self, known)
                self._idem_keys[idempotency_key] = entry
                while len(self._idem_keys) > 4096:
                    self._idem_keys.popitem(last=False)
        try:
            with self._entries_lock:
                if request.request_id in self._entries:
                    raise DuplicateRequestError(
                        f"request id {request.request_id!r} is already in "
                        "flight on this replica"
                    )
                self._entries[request.request_id] = entry
            try:
                # Capacity check + append under ONE lock hold: each queued
                # item carries a whole decoded KV payload, so a racy check
                # would let concurrent imports blow the memory bound the
                # backpressure exists to enforce.
                with self._import_lock:
                    if len(self._import_queue) >= self.scheduler.max_queue:
                        raise QueueFullError(
                            f"import queue full ({self.scheduler.max_queue})"
                        )
                    self._import_queue.append(
                        (entry, payload, len(payload_bytes), time.time())
                    )
            except BaseException:
                with self._entries_lock:
                    self._entries.pop(request.request_id, None)
                raise
        except BaseException:
            if idempotency_key:
                # A failed graft must not poison the key: the sender's
                # retry (same key) deserves a fresh attempt.
                with self._idem_lock:
                    if self._idem_keys.get(idempotency_key) is entry:
                        del self._idem_keys[idempotency_key]
            raise
        self.metrics.on_submit()
        self.scheduler.notify()
        return RequestHandle(self, entry)

    def adopt_migration(self, entry: _Entry, payload) -> None:
        """In-process drain evacuation, receiving side: adopt a peer's
        live ``_Entry`` (its stream/done handles stay with the original
        caller) and queue its KV payload for grafting.  Called from the
        EVACUATING replica's worker thread.  ``payload`` is either the
        serialized bytes or the already-parsed dict — queued grafts move
        between peers without a pointless reserialize/reparse round
        trip of multi-MB KV rows."""
        from bpe_transformer_tpu.serving.kvpool.migrate import (
            payload_from_bytes,
            payload_nbytes,
        )

        if not self.accepting_imports():
            raise RuntimeError("replica is not accepting imports")
        if isinstance(payload, (bytes, bytearray)):
            nbytes = len(payload)
            payload = payload_from_bytes(payload)
        else:
            nbytes = payload_nbytes(payload)
        self.engine.validate_import_payload(payload)
        with self._entries_lock:
            if entry.request.request_id in self._entries:
                raise DuplicateRequestError(
                    f"request id {entry.request.request_id!r} already in "
                    "flight on the evacuation target"
                )
            self._entries[entry.request.request_id] = entry
        with self._import_lock:
            self._import_queue.append(
                (entry, payload, nbytes, time.time())
            )
        self.scheduler.notify()

    def adopt_entry(self, entry: _Entry) -> None:
        """In-process drain evacuation for NOT-YET-ADMITTED requests: the
        peer's queued entry re-enters this replica's scheduler whole (same
        stream/done handles, same request id)."""
        if not self.accepting_imports():
            raise RuntimeError("replica is not accepting new requests")
        with self._entries_lock:
            if entry.request.request_id in self._entries:
                raise DuplicateRequestError(
                    f"request id {entry.request.request_id!r} already in "
                    "flight on the evacuation target"
                )
            self._entries[entry.request.request_id] = entry
        try:
            self.scheduler.submit(
                entry,
                request_id=entry.request.request_id,
                deadline_s=entry.request.deadline_s,
            )
        except BaseException:
            with self._entries_lock:
                self._entries.pop(entry.request.request_id, None)
            raise
        self.metrics.on_submit()

    @staticmethod
    def _entry_from_meta(entry: _Entry, meta: dict) -> None:
        """Restore the serving-layer request state a payload carries:
        tokens already emitted and the phase timings accrued before the
        migration (so Result timings stay end-to-end)."""
        entry.tokens = [int(t) for t in meta.get("emitted") or []]
        entry.queue_wait_s = float(meta.get("queue_wait_s") or 0.0)
        entry.prefill_s = float(meta.get("prefill_s") or 0.0)
        entry.bucket = meta.get("bucket")
        entry.shared_tokens = int(meta.get("shared_tokens") or 0)
        entry.migrated_in = True

    def stream(self, request: Request) -> Iterator[int]:
        """Submit and yield token ids as they are generated."""
        return self.submit(request).tokens()

    def cancel(self, request_id: str) -> bool:
        """Cancel a queued or in-flight request."""
        if self.scheduler.cancel(request_id):
            return True
        with self._entries_lock:
            entry = self._entries.get(request_id)
        if entry is not None and not entry.done.is_set():
            entry.cancel_requested = True
            return True
        return False

    def decode_roofline(self) -> dict:
        """The decode tick's analytic roofline at CURRENT occupancy
        (`telemetry.attribution.decode_tick_roofline`): per tick, the
        weight sweep is the engine's resident matmul-weight bytes
        (int8-halved under ``weight_dtype="int8"``), the KV stream is the
        live positions times the per-position footprint (int8-halved
        under ``kv_dtype="int8"``), and activations are a documented
        estimate — transient block tensors plus the vocab-sized tail
        (unfused: logits + masked-logits + gumbel round trips; fused:
        only the caller-side gumbel tensor the kernel reads)."""
        import jax

        from bpe_transformer_tpu.telemetry.attribution import (
            decode_tick_roofline,
        )
        from bpe_transformer_tpu.utils.flops import decode_tick_flops

        engine = self.engine
        config = engine.config
        active = engine.active_count
        positions = engine._positions
        live = int(((positions + 1) * engine._active).sum())
        act_itemsize = np.dtype(config.activation_dtype).itemsize
        # ~12 d_model-sized transients per token per block (q/k/v/att/
        # norms/ffn intermediates at d_ff ~ 2.7 d) — an estimate, labeled
        # as such.  The vocab-sized tail: unfused pays ~3 (slots, vocab)
        # f32 round trips (logits, filter_logits' masked copy, the
        # categorical gumbel; the sort passes are extra, uncounted);
        # fused still pays ONE — the caller-side gumbel tensor the kernel
        # reads (drawing it in-kernel would delete it; noted, not done) —
        # so fusion shrinks the term 3x, never to zero.
        act_bytes = active * config.num_layers * 12 * config.d_model * (
            act_itemsize
        )
        vocab_trip = 2 * active * config.vocab_size * 4
        act_bytes += vocab_trip if engine.fused_sampling else 3 * vocab_trip
        row = decode_tick_roofline(
            flops=decode_tick_flops(config, active, live),
            weight_bytes=engine.tick_weight_bytes,
            kv_bytes=engine.kv_bytes_per_token * (live + active),
            act_bytes=act_bytes,
            device_kind=jax.devices()[0].device_kind,
        )
        row.update(
            {
                "active_slots": active,
                "live_positions": live,
                "weight_dtype": engine.weight_dtype,
                "fused_sampling": engine.fused_sampling,
            }
        )
        return row

    def stats(self) -> dict:
        """Engine/queue gauges + the live request counters — the same
        aggregate ``GET /metrics`` renders, reachable offline.  A paged
        engine adds the kvpool gauges (block occupancy, prefix-cache
        hit/miss counters, chunked-prefill queue depth)."""
        with self._import_lock:
            import_backlog = len(self._import_queue)
        stats = {
            "engine_kind": (
                "spec" if self.spec else "paged" if self.paged else "dense"
            ),
            "role": self.role,
            "import_backlog": import_backlog,
            "slots": self.engine.n_slots,
            "active_slots": self.engine.active_count,
            "queue_depth": self.scheduler.depth,
            "ticks": self.engine.ticks,
            "tokens_emitted": self.engine.tokens_emitted,
            "requests_finished": self._requests_finished,
            "compiled_programs": self.engine.compiled_programs(),
            "prefill_buckets": list(self.engine.buckets),
            # Quantized-decode gauges (ISSUE 11): what the weights weigh,
            # at what width, and what one tick streams — plus the
            # analytic tick roofline the report/compare gate reads.
            "weight_dtype": self.engine.weight_dtype,
            "params_bytes": self.engine.params_bytes,
            "tick_weight_bytes": self.engine.tick_weight_bytes,
            "fused_sampling": self.engine.fused_sampling,
            "decode_roofline": self.decode_roofline(),
            "alerts_firing": len(self._alerts.active()),
            **self.metrics.snapshot(),
        }
        if self.paged:
            stats.update(self.engine.gauges())
            stats["block_size"] = self.engine.block_size
            stats["kv_dtype"] = self.engine.kv_dtype
            stats["admit_backlog"] = len(self._admit_backlog)
        return stats

    def statusz(self) -> dict:
        """The ``GET /statusz`` payload: run manifest, uptime, compile
        accounting (per-engine program count + process-wide compile
        events), per-slot state, queue depth, the recent-request trace
        ring (per-request phase timelines), and the last-error ring."""
        resources = sample_resources()
        with self._import_lock:
            import_backlog = len(self._import_queue)
        page = {
            "manifest": self.manifest,
            "uptime_s": round(self.metrics.uptime_s(), 3),
            "engine_kind": (
                "spec" if self.spec else "paged" if self.paged else "dense"
            ),
            # Disaggregated-fleet role (ISSUE 15): the router partitions
            # the fleet off this field — prefill-role replicas take
            # /kv/export only, decode-role replicas take imports.
            "role": self.role,
            "migrations_out": self.metrics.migrations_out,
            "migrations_in": self.metrics.migrations_in,
            "import_backlog": import_backlog,
            # Wire codecs this replica can DECODE (v2 payloads), best
            # first — what a migration sender negotiates against.
            "kv_accept": ",".join(_supported_codecs()),
            # Over-the-wire session moves (ISSUE 20): relayed out OK /
            # failed after retries, and controller-initiated rebalances.
            "relays_ok": self._relays_ok,
            "relays_failed": self._relays_failed,
            "rebalanced_out": self._rebalanced_out,
            # The fleet router reads these to route around a replica that
            # is shutting down (PR-5 drain) or whose worker died, and to
            # weight by free capacity.  Load is reported as OCCUPANCY, not
            # decode activity: a paged slot mid-chunked-prefill is busy,
            # and a block-starved parked admission is queued work — a
            # replica saturated with prefills must not look idle.
            "draining": self._draining,
            "speculate_k": self.engine.k if self.spec else None,
            "weight_dtype": self.engine.weight_dtype,
            "params_bytes": self.engine.params_bytes,
            "fused_sampling": self.engine.fused_sampling,
            "decode_roofline": self.decode_roofline(),
            "compiled_programs": self.engine.compiled_programs(),
            "compile_events": resources["compile_events"],
            "prefill_buckets": list(self.engine.buckets),
            "queue_depth": (
                self.scheduler.depth + len(self._admit_backlog)
                + import_backlog
            ),
            "slots": self.engine.n_slots,
            "active_slots": self.engine.n_slots - self.engine.free_slots,
            "requests_finished": self._requests_finished,
            "worker_alive": self._thread is not None
            and self._worker_error is None,
            "slot_states": self.engine.slot_states(),
            # Newest-last ring of finished request timelines (request_id +
            # queue_wait/prefill/decode + bucket): the per-request trace
            # view, live, without tailing the telemetry JSONL.
            "recent_requests": list(self._recent),
            # Anomaly-watchdog verdicts (telemetry/alerts.py): the
            # currently-firing rules with their evidence — what the fleet
            # aggregator folds and an operator's first question answered.
            "alerts": self._alerts.active(),
            # Last-N firing/cleared transitions with timestamps: an alert
            # that cleared five minutes ago is still the answer to "what
            # happened?" — active() alone forgets it.
            "alert_history": self._alerts.history(16),
            # Decision-ring counters (GET /debug/flightrecorder holds the
            # ring itself; the operator page just shows it is alive).
            "flightrecorder": self.flightrecorder.stats(),
            "resources": resources,
            "last_errors": self.metrics.last_errors(),
        }
        if self.paged:
            page["kvpool"] = {
                **self.engine.gauges(),
                "block_size": self.engine.block_size,
                "kv_dtype": self.engine.kv_dtype,
                "admit_backlog": len(self._admit_backlog),
            }
        return page

    def prometheus_metrics(self) -> str:
        """The ``GET /metrics`` body (Prometheus text exposition)."""
        return render_prometheus(
            self.metrics, self.stats(), sample_resources()
        )

    # ------------------------------------------------------------ batch mode

    def run_batch(self, prompts: list, **knobs) -> list[Result]:
        """Offline batch: submit every prompt (waiting out backpressure
        instead of failing) and return results in input order."""
        handles: list[RequestHandle] = []
        for prompt in prompts:
            while True:
                try:
                    handles.append(
                        self.submit(
                            Request(
                                prompt_ids=tuple(int(t) for t in prompt),
                                **{
                                    "max_new_tokens": self.default_max_new_tokens,
                                    "stop_id": self.default_stop_id,
                                    **knobs,
                                },
                            )
                        )
                    )
                    break
                except QueueFullError:
                    time.sleep(0.005)  # the worker is draining the queue
        return [h.result() for h in handles]

    def serve_batch_file(
        self, prompts_path, output_path, **knobs
    ) -> list[Result]:
        """Offline file mode: one prompt per input line -> one JSONL result
        line per prompt (input order), tokenizing/detokenizing with the
        attached tokenizer."""
        if self.tokenizer is None:
            raise ValueError("batch file mode needs a tokenizer")
        lines = [
            ln
            for ln in Path(prompts_path).read_text(
                encoding="utf-8"
            ).splitlines()
            if ln.strip()
        ]
        prompts = [self.tokenizer.encode(ln) for ln in lines]
        results = self.run_batch(prompts, **knobs)
        with open(output_path, "w", encoding="utf-8") as f:
            for text, result in zip(lines, results):
                ids = list(result.token_ids)
                if result.finish_reason == "stop":
                    ids = ids[:-1]  # don't render the stop token itself
                f.write(
                    json.dumps(
                        {
                            "prompt": text,
                            "completion": self.tokenizer.decode(ids),
                            "finish_reason": result.finish_reason,
                            "n_tokens": len(result.token_ids),
                            **result.timings(),
                        }
                    )
                    + "\n"
                )
        return results

    # ---------------------------------------------------------- worker loop

    def _run(self) -> None:
        try:
            while self._running:
                if not self._step():
                    self.scheduler.wait_for_work(self._idle_poll_s)
        except BaseException as exc:  # noqa: BLE001 — fail loudly, unblock callers
            self._worker_error = exc
            self._running = False
            self.metrics.record_error(repr(exc), source="worker")
            self.flightrecorder.record("worker_error", error=repr(exc))
            if self._telemetry is not None:
                self._telemetry.event("serve_worker_error", error=repr(exc))
            # A dead worker is a terminal incident: flush the decision ring
            # while the evidence is still warm (force past the cooldown).
            self.blackbox_dump("worker_error", force=True)
            for slot in list(self._slot_entries):
                entry = self._slot_entries.pop(slot)
                self.engine.release(slot)
                self._finish(entry, "error")
            for slot in list(self._prefill_entries):
                entry = self._prefill_entries.pop(slot)
                self._finish(entry, "error")
            for entry in self._admit_backlog:
                self._finish(entry, "error")
            self._admit_backlog = []
            with self._import_lock:
                dead_imports = [item[0] for item in self._import_queue]
                self._import_queue.clear()
            for entry in dead_imports:
                self._finish(entry, "error")
            # Every other registered request must unblock too — queued ones
            # AND ones popped for admission when the step raised: their
            # callers are parked on done.wait() and nothing else will run
            # the queue again.  (_finish is idempotent, so sweeping the
            # registry after the explicit drains is safe.)
            drain = self.scheduler.pop_ready(self.scheduler.max_queue)
            for qe in drain.admit + drain.expired + drain.cancelled:
                self._finish(qe.item, "error")
            with self._entries_lock:
                leftover = list(self._entries.values())
            for entry in leftover:
                self._finish(entry, "error")

    def _step(self) -> bool:
        """One engine-loop iteration: cancellations, admissions, chunked
        prefill under the per-tick token budget (paged), then a decode
        tick.  Returns whether any work happened."""
        worked = False

        # Drain evacuation (ISSUE 15): once draining with peers attached,
        # every queued and in-flight session leaves as a KV payload (or a
        # whole queue entry) before anything else runs this iteration.
        if self._draining and (self._evacuate_peers or self._evacuate_urls):
            worked |= self._evacuate_step()

        # Controller-initiated hot rebalancing (ISSUE 20): export victim
        # sessions and relay them to the requested peer without draining.
        if self._rebalance_queue:
            worked |= self._rebalance_step()

        # In-flight cancellations retire their slots before the next tick
        # — decoding slots, slots mid-chunked-prefill, and block-starved
        # parked admissions alike.
        for slot, entry in list(self._slot_entries.items()):
            if entry.cancel_requested:
                del self._slot_entries[slot]
                self.engine.release(slot)
                self._finish(entry, "cancelled")
                worked = True
        for slot, entry in list(self._prefill_entries.items()):
            if entry.cancel_requested:
                del self._prefill_entries[slot]
                self.engine.release(slot)
                self._finish(entry, "cancelled")
                worked = True
        if self._admit_backlog:
            now = self._clock()
            kept = []
            for entry in self._admit_backlog:
                deadline = entry.request.deadline_s
                if entry.cancel_requested:
                    self._finish(entry, "cancelled")
                    worked = True
                elif (
                    deadline is not None
                    and now >= entry.t_submit + deadline
                ):
                    # The deadline contract follows the request out of the
                    # scheduler: a block-starved parked admission expires
                    # exactly like a queued one would.
                    self._finish(entry, "deadline")
                    worked = True
                else:
                    kept.append(entry)
            self._admit_backlog = kept

        # Inbound KV grafts land BEFORE fresh admissions: migrated work is
        # the fleet's oldest (it already paid queue wait + prefill on its
        # source replica).
        worked |= self._advance_imports()

        # Admissions: block-starved parked entries retry FIRST, strictly
        # FIFO — while any is parked, newer submissions stay queued so a
        # big request cannot be starved by a stream of small ones.
        while self._admit_backlog and self.engine.free_slots:
            if not self._try_admit(self._admit_backlog[0]):
                break
            self._admit_backlog.pop(0)
            worked = True
        # Pending grafts gate fresh admissions exactly like a parked
        # backlog: admitting newer work would consume the slots/blocks
        # the migrated sessions wait for.
        with self._import_lock:
            imports_pending = bool(self._import_queue)
        n_free = (
            0 if (self._admit_backlog or imports_pending)
            else self.engine.free_slots
        )
        engine_idle = (
            self.engine.active_count == 0 and not self._prefill_entries
        )
        pop = self.scheduler.pop_ready(n_free, engine_idle=engine_idle)
        for qe in pop.cancelled:
            self._finish(qe.item, "cancelled")
            worked = True
        for qe in pop.expired:
            self._finish(qe.item, "deadline")
            worked = True
        for qe in pop.admit:
            # Strict FIFO past a block-starved admission: once one entry
            # parks, everything popped behind it parks too — admitting it
            # would consume the very blocks the parked request waits for.
            if self._admit_backlog or not self._try_admit(qe.item):
                self._admit_backlog.append(qe.item)
            worked = True

        worked |= self._advance_prefills()

        if self.engine.active_count:
            # Chaos hook: SIGKILL-mid-decode fires here, between slots
            # holding live KV and the tick that would advance them — the
            # worst instant a replica can die.
            self._decode_ticks += 1
            self.faults.at_decode_tick(self._decode_ticks)
            t0 = self._clock()
            events = self.engine.tick()
            tick_s = self._clock() - t0
            self._deliver(events, tick_s)
            # Tick summary, coalesced: consecutive ticks merge into one
            # ring entry (count + refreshed fields) so steady-state decode
            # chatter cannot evict the rare decision events around it.
            self.flightrecorder.record(
                "tick",
                coalesce=True,
                n_events=len(events),
                tick_s=round(tick_s, 6),
                active_slots=self.engine.active_count,
                queue_depth=self.scheduler.depth,
            )
            worked = True
        self._maybe_emit_engine_record()
        return worked

    def _try_admit(self, entry: _Entry) -> bool:
        """Admit one popped entry into the engine.  Dense engine: one-shot
        bucketed prefill, always succeeds (the scheduler never over-pops
        slots).  Paged engine: reserve the slot + worst-case block chain
        and queue the prompt's chunks; returns False when the pool is
        block-starved so the caller parks the entry and retries as decode
        retirements free blocks."""
        request = entry.request
        t0 = self._clock()
        if self.paged:
            from bpe_transformer_tpu.serving.kvpool.blocks import (
                NoFreeBlocksError,
            )

            entry.programs_before = self.engine.compiled_programs()
            try:
                slot = self.engine.begin(
                    request.prompt_ids,
                    max_new_tokens=request.max_new_tokens,
                    temperature=request.temperature,
                    top_k=request.top_k,
                    top_p=request.top_p,
                    seed=request.seed,
                    stop_id=request.stop_id,
                    request_id=request.request_id,
                )
            except NoFreeBlocksError:
                # Coalesced: the backlog head retries every step while the
                # pool stays dry — one ring entry per parked request, with
                # a retry count, not one per retry.
                self.flightrecorder.record(
                    "park",
                    coalesce=True,
                    request_id=request.request_id,
                    prompt_len=len(request.prompt_ids),
                    backlog=len(self._admit_backlog),
                )
                return False
            entry.queue_wait_s = t0 - entry.t_submit
            self._span(
                "queue_wait", entry.t_submit, entry.queue_wait_s, request
            )
            entry.slot = slot
            entry.bucket = self.engine.slot_bucket(slot)
            entry.shared_tokens = self.engine.slot_shared_len(slot)
            entry.t_prefill_start = t0
            entry.prefill_s = 0.0
            self._prefill_entries[slot] = entry
            self.flightrecorder.record(
                "admit",
                request_id=request.request_id,
                slot=slot,
                prompt_len=len(request.prompt_ids),
                queue_wait_s=round(entry.queue_wait_s, 6),
                shared_tokens=entry.shared_tokens or None,
            )
            return True

        entry.queue_wait_s = t0 - entry.t_submit
        entry.bucket = self.engine.bucket_for(len(request.prompt_ids))
        programs_before = self.engine.compiled_programs()
        event = self.engine.admit(
            request.prompt_ids,
            max_new_tokens=request.max_new_tokens,
            temperature=request.temperature,
            top_k=request.top_k,
            top_p=request.top_p,
            seed=request.seed,
            stop_id=request.stop_id,
            request_id=request.request_id,
        )
        now = self._clock()
        entry.prefill_s = now - t0
        entry.t_decode_start = now
        entry.slot = event.slot
        self.metrics.on_prefill(
            entry.bucket,
            len(request.prompt_ids),
            entry.prefill_s,
            # A bucket's first admission pays its XLA compile — keep that
            # wall out of the bucket's steady-state throughput gauge.
            compiled=self.engine.compiled_programs() > programs_before,
        )
        self._span("queue_wait", entry.t_submit, entry.queue_wait_s, request)
        self._span("prefill", t0, entry.prefill_s, request)
        # Time to first token: wait + prefill, observed request-level for
        # the ttfb SLO histogram (never as a span — see metrics.phases).
        self.metrics.observe_phase(
            "ttfb", entry.queue_wait_s + entry.prefill_s
        )
        self.flightrecorder.record(
            "admit",
            request_id=request.request_id,
            slot=event.slot,
            prompt_len=len(request.prompt_ids),
            bucket=entry.bucket,
            queue_wait_s=round(entry.queue_wait_s, 6),
        )
        entry.tokens.append(event.token)
        entry.stream.put(event.token)
        if event.finished:
            self._finish(entry, event.finished)
        else:
            self._slot_entries[event.slot] = entry
        return True

    def _advance_imports(self) -> bool:
        """Graft queued KV payloads into the engine, FIFO.  A graft that
        cannot land yet (no free slot, block-starved pool) stays queued
        and retries as retirements free capacity — the import twin of the
        parked-admission backlog."""
        from bpe_transformer_tpu.serving.kvpool.blocks import (
            NoFreeBlocksError,
        )

        worked = False
        while True:
            with self._import_lock:
                if not self._import_queue:
                    return worked
                entry, payload, nbytes, recv_unix = self._import_queue[0]
            if entry.cancel_requested:
                with self._import_lock:
                    self._import_queue.popleft()
                self._finish(entry, "cancelled")
                worked = True
                continue
            deadline = entry.request.deadline_s
            if (
                deadline is not None
                and self._clock() >= entry.t_submit + deadline
            ):
                # The deadline contract follows the request through a
                # migration: a graft parked past its budget expires like
                # a queued admission would (t_submit = graft receipt).
                with self._import_lock:
                    self._import_queue.popleft()
                self._finish(entry, "deadline")
                worked = True
                continue
            if not self.engine.free_slots:
                return worked
            t0 = self._clock()
            try:
                slot = self.engine.import_slot(payload)
            except NoFreeBlocksError:
                return worked  # pool dry: retry as decode frees blocks
            with self._import_lock:
                self._import_queue.popleft()
            import_s = self._clock() - t0
            meta = payload["meta"]
            entry.slot = slot
            now = self._clock()
            if meta.get("decoding"):
                # Backdated by the decode seconds already accrued on the
                # exporting replica: the final Result.decode_s (and its
                # closing span) stays end-to-end across the migration.
                entry.t_decode_start = now - float(
                    meta.get("decode_s") or 0.0
                )
                self._slot_entries[slot] = entry
            else:
                entry.t_prefill_start = now
                entry.programs_before = self.engine.compiled_programs()
                self._prefill_entries[slot] = entry
            self.metrics.on_migration("in", nbytes)
            exported_unix = meta.get("exported_unix")
            transfer_s = (
                max(recv_unix - exported_unix, 0.0)
                if isinstance(exported_unix, (int, float))
                else None
            )
            export_s = meta.get("export_s")
            total_s = import_s + (transfer_s or 0.0) + (export_s or 0.0)
            self._span(
                "migration_import", t0, import_s, entry.request
            )
            self.metrics.observe_phase("migration", total_s)
            self._emit_migration(
                direction="import",
                request_id=entry.request.request_id,
                bytes=nbytes,
                blocks=int(meta["n_blocks"]),
                export_s=export_s,
                transfer_s=transfer_s,
                import_s=round(import_s, 6),
                total_s=round(total_s, 6),
                decoding=bool(meta.get("decoding")),
            )
            worked = True

    def _export_entry(
        self, entry: _Entry, slot: int, codec: str = "raw"
    ) -> tuple[bytes, int]:
        """Export ``slot`` (holding ``entry``'s generation) as payload
        bytes, with the serving-layer continuation state — emitted tokens,
        token history (the speculative importer's draft re-prefill input),
        accrued phase timings — folded into the meta.  Releases the slot.
        Returns ``(payload_bytes, n_blocks)``."""
        from bpe_transformer_tpu.serving.kvpool.migrate import (
            payload_to_bytes,
        )

        t0 = self._clock()
        # Decode seconds accrued HERE ride the meta so the importer can
        # backdate its decode clock — Result.decode_s and the total SLO
        # histogram stay end-to-end across the migration.
        decode_accrued = (
            t0 - entry.t_decode_start
            if slot in self._slot_entries or self.engine._active[slot]
            else 0.0
        )
        payload = self.engine.export_slot(
            slot,
            {
                "emitted": [int(t) for t in entry.tokens],
                "history": [
                    int(t) for t in entry.request.prompt_ids
                ] + [int(t) for t in entry.tokens],
                "queue_wait_s": round(entry.queue_wait_s, 6),
                "prefill_s": round(entry.prefill_s, 6),
                "decode_s": round(max(decode_accrued, 0.0), 6),
                "bucket": entry.bucket,
                "shared_tokens": entry.shared_tokens,
                "deadline_s": entry.request.deadline_s,
                "session": entry.request.session,
                "exported_unix": time.time(),
            },
        )
        self.engine.release(slot)
        # The device-extract wall rides the meta so the IMPORT side's
        # migration record carries the full export/transfer/import split
        # (serialization + HTTP land in transfer_s via exported_unix).
        payload["meta"]["export_s"] = round(self._clock() - t0, 6)
        # Chaos hook: truncate/bit-flip the bytes in flight (fires once) —
        # the importer's CRC/length checks must 400 the graft.
        data = self.faults.on_export_payload(
            payload_to_bytes(payload, codec=codec)
        )
        return data, int(payload["meta"]["n_blocks"])

    def _complete_migration_export(self, entry: _Entry, slot: int) -> None:
        """Prefill-role handoff: the finished prefix (first token already
        sampled and delivered) leaves as a KV payload; the request
        finishes here as ``"migrated"`` with the payload on its result."""
        from bpe_transformer_tpu.serving.kvpool.migrate import (
            negotiate_codec,
        )

        t0 = self._clock()
        data, blocks = self._export_entry(
            entry, slot, codec=negotiate_codec(entry.request.kv_accept)
        )
        export_s = self._clock() - t0
        self.metrics.on_migration("out", len(data))
        self._span("migration_export", t0, export_s, entry.request)
        self._emit_migration(
            direction="export",
            request_id=entry.request.request_id,
            bytes=len(data),
            blocks=blocks,
            export_s=round(export_s, 6),
        )
        self._finish(entry, "migrated", kv_payload=data)

    def _evacuate_step(self) -> bool:
        """Move every queued + in-flight session to an evacuation peer
        (round-robin): queued entries re-enter the peer's scheduler whole;
        in-flight slots (decoding AND mid-prefill) export as KV payloads
        the peer grafts and continues bit-for-bit.  The original callers'
        handles complete from the peer — zero failed requests.

        Peers are either in-process ``ServingEngine`` objects (entries
        move whole, payload dicts skip the bytes codec) or — when only
        ``_evacuate_urls`` is set — remote replicas: queued requests
        replay as seeded ``/generate`` calls and exported sessions relay
        to ``/kv/import`` from background threads (the worker must not
        block on a peer's decode), each under one idempotency key across
        its bounded retries."""
        from bpe_transformer_tpu.serving.kvpool.migrate import (
            negotiate_codec,
            payload_to_bytes,
        )

        peers = [p for p in self._evacuate_peers if p.accepting_imports()]
        urls = list(self._evacuate_urls)
        if not peers and not urls:
            self._evacuate_peers = []
            return False
        wire = not peers
        wire_codec = negotiate_codec(self.export_codec)

        def next_peer():
            self._evacuate_rr += 1
            return peers[self._evacuate_rr % len(peers)]

        worked = False
        # Not-yet-admitted work first (cheap: no KV moves) — the queue,
        # then block-starved parked admissions and queued grafts.
        pop = self.scheduler.pop_ready(self.scheduler.max_queue)
        for qe in pop.cancelled:
            self._finish(qe.item, "cancelled")
        for qe in pop.expired:
            self._finish(qe.item, "deadline")
        moved_entries = list(self._admit_backlog)
        self._admit_backlog = []
        with self._import_lock:
            moved_imports = list(self._import_queue)
            self._import_queue.clear()
        for qe in pop.admit:
            moved_entries.append(qe.item)
        for entry in moved_entries:
            if wire:
                # Nothing emitted yet: a seeded /generate replay on the
                # peer is token-identical.  The entry stays registered
                # until the relay thread finishes it (drain waits on the
                # registry).
                self._relay_entry_thread(entry, None, urls, "evacuate")
                worked = True
                continue
            with self._entries_lock:
                self._entries.pop(entry.request.request_id, None)
            try:
                next_peer().adopt_entry(entry)
            except (RuntimeError, ValueError) as exc:
                self.metrics.record_error(repr(exc), source="evacuate")
                self._finish(entry, "error")
            worked = True
        for entry, payload, nbytes, _recv in moved_imports:
            if wire:
                data = payload_to_bytes(payload, codec=wire_codec)
                self._relay_entry_thread(entry, data, urls, "evacuate")
                worked = True
                continue
            with self._entries_lock:
                self._entries.pop(entry.request.request_id, None)
            try:
                # Already parsed: hand the dict over directly (the bytes
                # codec is for the HTTP transport, not in-process moves).
                next_peer().adopt_migration(entry, payload)
            except (RuntimeError, ValueError) as exc:
                self.metrics.record_error(repr(exc), source="evacuate")
                self._finish(entry, "error")
            worked = True

        # In-flight sessions: export + graft.  The entry object itself
        # moves — its stream/done handles keep serving the original
        # caller from the peer's worker (in-process) or complete with the
        # peer's returned tokens (over the wire).
        in_flight = list(self._prefill_entries.items()) + list(
            self._slot_entries.items()
        )
        for slot, entry in in_flight:
            self._prefill_entries.pop(slot, None)
            self._slot_entries.pop(slot, None)
            t0 = self._clock()
            data, blocks = self._export_entry(
                entry, slot, codec=wire_codec if wire else "raw"
            )
            export_s = self._clock() - t0
            entry.slot = None
            self.metrics.on_migration("out", len(data))
            self._span("migration_export", t0, export_s, entry.request)
            self._emit_migration(
                direction="evacuate",
                request_id=entry.request.request_id,
                bytes=len(data),
                blocks=blocks,
                export_s=round(export_s, 6),
            )
            if wire:
                self._relay_entry_thread(entry, data, urls, "evacuate")
                worked = True
                continue
            with self._entries_lock:
                self._entries.pop(entry.request.request_id, None)
            try:
                next_peer().adopt_migration(entry, data)
            except (RuntimeError, ValueError) as exc:
                self.metrics.record_error(repr(exc), source="evacuate")
                self._finish(entry, "error")
            worked = True
        if worked and self._telemetry is not None:
            self._telemetry.event(
                "serve_evacuate",
                sessions=len(in_flight),
                queued=len(moved_entries) + len(moved_imports),
                peers=len(peers) or len(urls),
                wire=wire,
            )
        return worked

    # ------------------------------------- over-the-wire relay (ISSUE 20)

    def _relay_entry_thread(self, entry, data, urls, direction) -> None:
        threading.Thread(
            target=self._relay_entry,
            args=(entry, data, urls, direction),
            name="kv-relay",
            daemon=True,
        ).start()

    def _relay_entry(self, entry, data, urls, direction) -> None:
        """Move one session to a peer over HTTP and complete the original
        caller's handle with the peer's result.  ``data=None`` replays a
        never-admitted request as a seeded ``/generate`` (token-identical:
        nothing was emitted yet); otherwise ``data`` is an exported KV
        payload POSTed to ``/kv/import`` under ONE idempotency key held
        across every retry — the receiver grafts exactly once even when a
        response is lost mid-reply.  Connect/read failures rotate to the
        next peer URL with exponential backoff; a 400 is permanent (the
        payload itself is bad — retrying the same bytes cannot help)."""
        import urllib.error
        import urllib.request

        idem_key = uuid.uuid4().hex
        rid = entry.request.request_id
        t0 = self._clock()
        result = None
        last_exc: Exception | None = None
        for attempt in range(self.relay_attempts):
            url = urls[attempt % len(urls)]
            try:
                if data is None:
                    req = entry.request
                    body = json.dumps(
                        {
                            "prompt_ids": list(req.prompt_ids),
                            "max_new_tokens": req.max_new_tokens,
                            "temperature": req.temperature,
                            "top_k": req.top_k,
                            "top_p": req.top_p,
                            "seed": req.seed,
                            "stop_id": req.stop_id,
                            "deadline_s": req.deadline_s,
                            "session": req.session,
                        }
                    ).encode("utf-8")
                    http_req = urllib.request.Request(
                        url + "/generate",
                        data=body,
                        headers={
                            "Content-Type": "application/json",
                            "X-Request-Id": rid,
                        },
                    )
                else:
                    http_req = urllib.request.Request(
                        url + "/kv/import",
                        data=data,
                        headers={
                            "Content-Type": "application/octet-stream",
                            "X-Request-Id": rid,
                            "X-Idempotency-Key": idem_key,
                        },
                    )
                with urllib.request.urlopen(
                    http_req, timeout=self.relay_timeout_s
                ) as resp:
                    result = json.loads(resp.read())
                break
            except urllib.error.HTTPError as exc:
                last_exc = exc
                if exc.code == 400:
                    break
            except (OSError, ValueError) as exc:
                last_exc = exc
            if attempt + 1 < self.relay_attempts:
                time.sleep(self.relay_backoff_s * (2 ** attempt))
        transfer_s = self._clock() - t0
        if result is None:
            self._relays_failed += 1
            self.metrics.record_error(
                f"relay failed: {last_exc!r}",
                source="relay",
                request_id=rid,
            )
            self.flightrecorder.record(
                "relay_failed",
                request_id=rid,
                direction=direction,
                error=repr(last_exc),
            )
            self._finish(entry, "error")
            return
        # Peer token_ids = tokens emitted before the move + everything it
        # decoded; stream only the suffix so the caller sees no repeats.
        all_tokens = [int(t) for t in result.get("token_ids", [])]
        for tok in all_tokens[len(entry.tokens):]:
            entry.tokens.append(tok)
            entry.stream.put(tok)
        self._relays_ok += 1
        self._emit_migration(
            direction=f"{direction}_relay",
            request_id=rid,
            bytes=len(data) if data is not None else 0,
            transfer_s=round(transfer_s, 6),
            total_s=round(transfer_s, 6),
        )
        self._finish(entry, result.get("finish_reason") or "stop")

    def request_rebalance(
        self,
        target_url: str,
        max_sessions: int = 1,
        timeout_s: float = 30.0,
    ) -> dict:
        """Transport side of ``POST /admin/evacuate`` (controller hot
        rebalancing): ask the worker to export up to ``max_sessions``
        decoding sessions and relay them to ``target_url``'s
        ``/kv/import``.  Blocks until the exports happen (the relays
        complete asynchronously; each original caller's handle resolves
        with the peer's tokens).  Returns ``{"moved", "request_ids",
        "target"}``."""
        if not self.paged:
            raise RuntimeError("rebalancing needs a paged engine")
        if self._worker_error is not None:
            raise RuntimeError(
                "serving engine worker died"
            ) from self._worker_error
        if not self._running:
            raise RuntimeError("serving engine is not running")
        done = threading.Event()
        out: dict = {}
        self._rebalance_queue.append(
            (target_url.rstrip("/"), max(1, int(max_sessions)), done, out)
        )
        self.scheduler.notify()
        if not done.wait(timeout_s):
            raise TimeoutError("rebalance request not picked up by worker")
        return out

    def _rebalance_step(self) -> bool:
        """Worker side: export the requested victim sessions and hand them
        to relay threads.  Victims are the decoding slots with the most
        budget remaining — the sessions that gain the most from moving to
        a less loaded replica (and whose KV is cheapest per remaining
        token to have shipped)."""
        from bpe_transformer_tpu.serving.kvpool.migrate import (
            negotiate_codec,
        )

        worked = False
        codec = negotiate_codec(self.export_codec)
        while self._rebalance_queue:
            target, n, done, out = self._rebalance_queue.popleft()
            victims = sorted(
                self._slot_entries.items(),
                key=lambda kv: (
                    kv[1].request.max_new_tokens - len(kv[1].tokens)
                ),
                reverse=True,
            )[:n]
            moved = []
            for slot, entry in victims:
                self._slot_entries.pop(slot, None)
                t0 = self._clock()
                data, blocks = self._export_entry(entry, slot, codec=codec)
                export_s = self._clock() - t0
                entry.slot = None
                self.metrics.on_migration("out", len(data))
                self._span("migration_export", t0, export_s, entry.request)
                self._emit_migration(
                    direction="rebalance",
                    request_id=entry.request.request_id,
                    bytes=len(data),
                    blocks=blocks,
                    export_s=round(export_s, 6),
                )
                self._relay_entry_thread(entry, data, [target], "rebalance")
                moved.append(entry.request.request_id)
                self._rebalanced_out += 1
                worked = True
            out.update(moved=len(moved), request_ids=moved, target=target)
            self.flightrecorder.record(
                "rebalance", target=target, moved=len(moved)
            )
            done.set()
        return worked

    def _emit_migration(self, **fields) -> None:
        """One ``kind="migration"`` record (bytes, blocks, phase split) —
        the telemetry spine's view of each KV move."""
        # Tee into the decision ring BEFORE the sink guard: the flight
        # recorder must see every KV move even on a server run without
        # --metrics-jsonl.
        self.flightrecorder.record(
            "migration", **{k: v for k, v in fields.items() if v is not None}
        )
        if self._telemetry is None:
            return
        self._telemetry.emit(
            {
                "kind": "migration",
                "t": round(self._clock() - self._t0, 6),
                "time_unix": round(time.time(), 6),
                **{k: v for k, v in fields.items() if v is not None},
            }
        )

    def _advance_prefills(self) -> bool:
        """Run pending prefill chunks (paged engine) under the per-tick
        token budget, oldest admission first.  A completed prefill
        delivers its first token and moves the slot to the decode set —
        the paged twin of the dense admission's tail."""
        if not self.paged or not self._prefill_entries:
            return False
        worked = False
        budget = self._prefill_budget
        budget.start_tick()
        for slot in list(self.engine.pending_prefills()):
            entry = self._prefill_entries.get(slot)
            if entry is None:
                continue
            while True:
                chunk_tokens = self.engine.next_chunk_tokens(slot)
                if not budget.admits(chunk_tokens):
                    return worked  # budget spent: decode tick runs next
                t0 = self._clock()
                event = self.engine.prefill_step(slot)
                entry.prefill_s += self._clock() - t0
                budget.spend(chunk_tokens)
                worked = True
                if event is not None:
                    del self._prefill_entries[slot]
                    self._complete_prefill(entry, event)
                    break
        return worked

    def _complete_prefill(self, entry: _Entry, event: TickEvent) -> None:
        request = entry.request
        self.metrics.on_prefill(
            entry.bucket,
            # COMPUTED prompt tokens: the prefix-cache-shared prefix paid
            # no compute, so it stays out of the throughput accounting.
            len(request.prompt_ids) - entry.shared_tokens,
            entry.prefill_s,
            compiled=self.engine.compiled_programs() > entry.programs_before,
        )
        self._span(
            "prefill", entry.t_prefill_start, entry.prefill_s, request
        )
        self.metrics.observe_phase(
            "ttfb", entry.queue_wait_s + entry.prefill_s
        )
        entry.t_decode_start = self._clock()
        entry.tokens.append(event.token)
        entry.stream.put(event.token)
        if event.finished:
            self._finish(entry, event.finished)
        elif entry.request.migrate:
            # Disaggregated prefill handoff (ISSUE 15): the finished
            # prefix (first token included) leaves as a KV payload
            # instead of entering this replica's decode set.
            self._complete_migration_export(entry, event.slot)
        else:
            self._slot_entries[event.slot] = entry

    def _deliver(self, events: list[TickEvent], tick_s: float) -> None:
        self.metrics.on_decode_tick(len(events), tick_s)
        for event in events:
            entry = self._slot_entries.get(event.slot)
            if entry is None:
                continue  # released between admit and tick (cancellation)
            entry.tokens.append(event.token)
            entry.stream.put(event.token)
            if event.finished:
                del self._slot_entries[event.slot]
                self._finish(entry, event.finished)

    def _finish(
        self, entry: _Entry, reason: str, kv_payload: bytes | None = None
    ) -> None:
        if entry.done.is_set():
            return
        now = self._clock()
        decode_s = (
            now - entry.t_decode_start
            if entry.slot is not None and reason != "migrated"
            else 0.0
        )
        if entry.slot is not None and reason != "migrated":
            self._span("decode", entry.t_decode_start, decode_s, entry.request)
        elif reason in ("deadline", "cancelled") and not entry.migrated_in:
            # Never admitted: the whole life was queue wait.
            entry.queue_wait_s = now - entry.t_submit
            self._span("queue_wait", entry.t_submit, entry.queue_wait_s,
                       entry.request)
        entry.result = Result(
            request_id=entry.request.request_id,
            token_ids=tuple(entry.tokens),
            finish_reason=reason,
            queue_wait_s=entry.queue_wait_s,
            prefill_s=entry.prefill_s,
            decode_s=decode_s,
            kv_payload=kv_payload,
        )
        self._requests_finished += 1
        self.metrics.on_finish(reason)
        # Deadline expiries are first-class incident evidence (the park ->
        # deadline chain IS a block-exhaustion story); ordinary completions
        # ride along as "finish" so the ring shows request turnover.
        self.flightrecorder.record(
            "deadline" if reason == "deadline" else "finish",
            request_id=entry.request.request_id,
            reason=reason if reason != "deadline" else None,
            n_tokens=len(entry.tokens) or None,
            slot=entry.slot,
        )
        # Whole-request latency for the total SLO histogram (request-level
        # only — a total SPAN would double-count in the report's
        # per-request phase assembly).
        self.metrics.observe_phase(
            "total", entry.queue_wait_s + entry.prefill_s + decode_s
        )
        # Per-request trace: the finished timeline joins the /statusz ring.
        # Same numbers as the serve/* spans and Result.timings() — one
        # measurement, three surfaces.
        self._recent.append(
            {
                "request_id": entry.request.request_id,
                "finish_reason": reason,
                "n_tokens": len(entry.tokens),
                "prompt_len": len(entry.request.prompt_ids),
                "bucket": entry.bucket,
                "slot": entry.slot,
                "t_submit": round(entry.t_submit - self._t0, 6),
                "queue_wait_s": round(entry.queue_wait_s, 6),
                "prefill_s": round(entry.prefill_s, 6),
                "decode_s": round(decode_s, 6),
            }
        )
        with self._entries_lock:
            self._entries.pop(entry.request.request_id, None)
        entry.stream.put(_STREAM_END)
        entry.done.set()

    # ------------------------------------------------------------ telemetry

    def _span(self, name: str, start: float, dur: float, request: Request):
        """Emit one request-phase span record.  Spans are emitted directly
        (not via Telemetry's nesting stack — concurrent requests interleave,
        so LIFO nesting does not apply).  The same duration feeds the live
        /metrics histogram, so the scrape and the stream always agree."""
        self.metrics.observe_phase(name, dur)
        if self._telemetry is None:
            return
        self._telemetry.emit(
            {
                "kind": "span",
                "name": name,
                "path": f"serve/{name}",
                "t": round(start - self._t0, 6),
                "dur_s": round(dur, 6),
                "request_id": request.request_id,
                # Absolute span START time: every stream has its own t
                # epoch, so cross-stream request assembly (router lanes
                # joining these lanes in telemetry/trace.request_timeline)
                # orders hops by wall clock.  Spans are emitted at phase
                # end, so start = now - dur.
                "time_unix": round(time.time() - dur, 6),
            }
        )

    def _feed_alerts(self, t: float, resources: dict | None) -> None:
        """One watchdog sample on the engine-record cadence; transitions
        go to the telemetry stream when one is attached (the active set
        is always queryable via /statusz regardless)."""
        sample: dict = {
            "queue_depth": self.scheduler.depth + len(self._admit_backlog),
            "active_slots": self.engine.active_count,
        }
        if resources is not None:
            sample["compile_events"] = resources.get("compile_events")
        if self.paged:
            gauges = self.engine.gauges()
            sample["kv_blocks_free"] = gauges.get("kv_blocks_free")
            sample["kv_blocks_total"] = gauges.get("kv_blocks_total")
            if self.spec:
                sample["spec_accept_rate"] = gauges.get("spec_accept_rate")
                sample["spec_proposed"] = gauges.get("spec_proposed_tokens")
        for transition in self._alerts.feed(sample, round(t, 6)):
            self.flightrecorder.record(
                "alert",
                rule=transition.get("rule"),
                state=transition.get("state"),
                severity=transition.get("severity"),
            )
            if self._telemetry is not None:
                self._telemetry.emit(transition)
            if transition.get("state") == "firing":
                # An alert edge is THE black-box trigger: flush the ring
                # (with the alert itself as its newest entry) while the
                # decisions that led here are still in it.  The recorder's
                # cooldown de-dupes a storm of edges into one dump.
                self.blackbox_dump(f"alert:{transition.get('rule')}")

    def blackbox_dump(self, trigger: str, force: bool = False) -> dict | None:
        """Flush the decision ring as a ``kind="blackbox"`` record with the
        host-side operational context an incident needs (queue/slot/kvpool
        state, active alerts + history tail) attached; emitted into the
        telemetry stream when a sink is attached, always retained on the
        recorder for ``GET /debug/flightrecorder``.  Returns the dump, or
        None while the post-dump cooldown holds (``force=True`` bypasses —
        the POST /debug/dump and terminal worker-error paths).

        Everything gathered here is host-side bookkeeping (slot_states and
        kvpool gauges are plain dict reads) — no device syncs, matching the
        recording path's fetch-count contract."""
        context: dict = {
            "queue_depth": self.scheduler.depth + len(self._admit_backlog),
            "active_slots": self.engine.active_count,
            "draining": self._draining,
            "requests_finished": self._requests_finished,
            "slot_states": self.engine.slot_states(),
            "alerts": self._alerts.active(),
            "alert_history": self._alerts.history(16),
        }
        if self.paged:
            context["kvpool"] = {
                **self.engine.gauges(),
                "admit_backlog": len(self._admit_backlog),
            }
        dump = self.flightrecorder.blackbox(
            trigger, context=context, force=force
        )
        if dump is not None and self._telemetry is not None:
            self._telemetry.emit(dump)
        return dump

    def _maybe_emit_engine_record(self) -> None:
        now = self._clock()
        elapsed = now - self._last_record_t
        if elapsed < self._record_every_s:
            return
        # Sampled UNCONDITIONALLY (sync-free, jax-optional — see
        # telemetry/resources.py): the compile-storm rule must see the
        # compile counter even on a server run without --metrics-jsonl.
        resources = sample_resources(t=round(now - self._t0, 6))
        # The watchdog samples BEFORE the idle short-circuit: an idle
        # engine is exactly when a queue-growth alert must clear.
        self._feed_alerts(now - self._t0, resources)
        if self._telemetry is None:
            self._last_record_t = now
            return
        tokens = self.engine.tokens_emitted
        # A fully idle engine stays silent (no tokens since the last record
        # and nothing in flight) — an idle server must not grow its JSONL.
        if (
            tokens == self._last_record_tokens
            and not self.engine.active_count
            and not self.scheduler.depth
        ):
            self._last_record_t = now
            return
        self._telemetry.emit(
            {
                "kind": "engine",
                "t": round(now - self._t0, 6),
                "active_slots": self.engine.active_count,
                "queue_depth": self.scheduler.depth,
                "tokens_per_sec": round(
                    (tokens - self._last_record_tokens) / max(elapsed, 1e-9), 3
                ),
                "tokens_total": tokens,
                "ticks": self.engine.ticks,
                "requests_finished": self._requests_finished,
                "compiled_programs": self.engine.compiled_programs(),
            }
        )
        # Resource accounting rides the same cadence: HBM/RSS/compile
        # trends of a serving process are as load-bearing as tokens/sec.
        # (The sample was taken once above — the watchdog's compile-storm
        # rule and this record must read the same numbers.)
        self._telemetry.emit(resources)
        # Decode-tick roofline on the same cadence (every engine kind):
        # the weight/KV/activation byte split of one tick at current
        # occupancy vs the chip ridge point — the record the report's
        # roofline section and the serve_weight_bytes compare-gate row
        # read (ISSUE 11).
        roof = self.decode_roofline()
        self._telemetry.emit(
            {
                "kind": "roofline",
                "t": round(now - self._t0, 6),
                "weight_bytes": roof["weight_bytes"],
                "kv_bytes": roof["kv_bytes"],
                "act_bytes": roof["act_bytes"],
                "flops": roof["flops"],
                "arithmetic_intensity": roof["arithmetic_intensity"],
                "ridge_flops_per_byte": roof["ridge_flops_per_byte"],
                "bound": roof["bound"],
                "projected_tick_s": roof["projected_tick_s"],
                "weight_frac": roof["weight_frac"],
                "active_slots": roof["active_slots"],
                "weight_dtype": roof["weight_dtype"],
                "fused_sampling": roof["fused_sampling"],
            }
        )
        if self.paged:
            # Paged-pool accounting on the same cadence: block occupancy,
            # prefix-cache effectiveness, chunked-prefill backlog — the
            # numbers `report`'s kvpool section and the router's health
            # weighting read.
            gauges = self.engine.gauges()
            self._telemetry.emit(
                {
                    "kind": "kvpool",
                    "t": round(now - self._t0, 6),
                    "blocks_total": gauges["kv_blocks_total"],
                    "blocks_free": gauges["kv_blocks_free"],
                    "blocks_shared": gauges["kv_blocks_shared"],
                    "prefix_hits": gauges["prefix_cache_hits"],
                    "prefix_misses": gauges["prefix_cache_misses"],
                    "prefix_hit_rate": gauges["prefix_hit_rate"],
                    "prefill_pending_tokens": gauges[
                        "prefill_pending_tokens"
                    ],
                    # KV-memory economics (ISSUE 9): resident pool bytes
                    # (int8 quarters f32 at fixed block count) and the
                    # per-token KV write footprint — the report/compare
                    # gate's KV-memory regression rows.
                    "kv_pool_bytes": gauges["kv_pool_bytes"],
                    "kv_bytes_per_token": gauges["kv_bytes_per_token"],
                }
            )
            if self.spec:
                # Speculative-decoding acceptance on the same cadence: the
                # accept rate and emitted-tokens-per-verify-pass the
                # report/monitor/compare surfaces read (ISSUE 10).
                self._telemetry.emit(
                    {
                        "kind": "spec",
                        "t": round(now - self._t0, 6),
                        "k": gauges["spec_k"],
                        "proposed": gauges["spec_proposed_tokens"],
                        "accepted": gauges["spec_accepted_tokens"],
                        "emitted": self.engine.spec_emitted,
                        "target_steps": gauges["spec_target_steps"],
                        "accept_rate": gauges["spec_accept_rate"],
                        "tokens_per_target_step": gauges[
                            "spec_tokens_per_target_step"
                        ],
                        "rewound": gauges["spec_rewound_tokens"],
                        "draft_frac": gauges["spec_draft_frac"],
                    }
                )
        self._last_record_t = now
        self._last_record_tokens = tokens


# ------------------------------------------------------------------ HTTP

def make_http_server(
    serving: ServingEngine, host: str = "127.0.0.1", port: int = 8000
):
    """A `ThreadingHTTPServer` exposing the serving engine as JSON-over-HTTP
    (stdlib only — no web framework dependency):

    * ``POST /generate`` — body ``{"prompt": str | "prompt_ids": [int],
      "max_new_tokens"?, "temperature"?, "top_k"?, "top_p"?, "seed"?,
      "stop_id"?, "deadline_s"?}`` -> ``{"completion"?, "token_ids",
      "finish_reason", "timings", "request_id"}``; 400 on bad input, 503
      when the admission queue is full (backpressure).  An inbound
      ``X-Request-Id`` header is adopted as the request's trace id and
      echoed back on EVERY response (errors included) — the fleet
      tracing contract (ISSUE 12).
    * ``GET /healthz`` — engine/queue stats (JSON).
    * ``GET /metrics`` — Prometheus text exposition: request/token
      counters, queue depth, slot occupancy, per-phase latency
      histograms, compile + HBM/RSS accounting (`serving/metrics.py`).
    * ``GET /statusz`` — JSON operator page: run manifest, uptime,
      compile counters, per-slot state, recent per-request phase
      timelines, last-error ring buffer.
    * ``POST /kv/export`` (ISSUE 15) — a /generate-shaped body, served by
      the chunk machine only: the finished prefix (first token sampled)
      returns as a binary KV migration payload
      (``application/octet-stream``) instead of being decoded here — the
      disaggregated router moves it to a decode replica's ``/kv/import``.
      When the first token already finishes the request (stop id, budget
      1), the normal JSON result returns instead.
    * ``POST /kv/import`` (ISSUE 15) — body is a ``/kv/export`` payload;
      the replica grafts it and decodes to completion, answering with the
      standard /generate JSON (token ids = tokens emitted before the
      migration + everything decoded here; greedy and seeded sampling are
      token-identical to an unmigrated run).  400 on a geometry/dtype
      mismatch, 503 on backpressure.

    * ``POST /admin/evacuate`` (ISSUE 20) — controller-initiated hot
      rebalancing: body ``{"target": url, "max_sessions"?}`` exports
      victim sessions and relays them to the target's ``/kv/import``
      (idempotency-keyed, bounded retries); the original callers' open
      requests complete with the target's tokens.
    * ``GET /debug/flightrecorder`` — the live decision ring + retained
      black-box dumps (``bpe-tpu incident`` sweeps this across the fleet).
    * ``POST /debug/dump`` — force a black-box flush now; answers with
      the ``kind="blackbox"`` dump.

    ``port=0`` binds an ephemeral port (tests); the caller owns
    ``serve_forever()`` / ``shutdown()``.
    """
    import socket
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        # Bounded request read + quiet logs: serving telemetry is the
        # observable surface, not stderr.
        def log_message(self, *args):  # noqa: D102
            pass

        def _fault_gate(self) -> bool:
            """Chaos hook (BT_FAULTS): a blackholed path drops the
            connection with no response — what a partitioned peer looks
            like from the caller's side.  Delays sleep inline inside
            ``on_http_request``."""
            if serving.faults.on_http_request(self.path) == "blackhole":
                try:
                    self.connection.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                self.close_connection = True
                return True
            return False

        def _reply(
            self, code: int, payload: dict, request_id: str | None = None
        ) -> None:
            self._reply_text(
                code, json.dumps(payload), "application/json",
                request_id=request_id,
            )

        def _reply_text(
            self,
            code: int,
            text: str,
            content_type: str,
            request_id: str | None = None,
        ) -> None:
            body = text.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            if request_id is not None:
                # Echoed on EVERY /generate response — 503 backpressure
                # and 400s included — so a client can hand the id to an
                # operator and the operator can find the request in the
                # trace streams (or prove it never reached the engine).
                self.send_header("X-Request-Id", request_id)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 (stdlib API)
            if self._fault_gate():
                return
            path = self.path.split("?", 1)[0]
            if path == "/healthz":
                return self._reply(200, {"ok": True, **serving.stats()})
            if path == "/metrics":
                return self._reply_text(
                    200,
                    serving.prometheus_metrics(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            if path == "/statusz":
                return self._reply(200, serving.statusz())
            if path == "/debug/flightrecorder":
                # The live decision ring + retained black-box dumps — what
                # `bpe-tpu incident` sweeps across the fleet.
                return self._reply(200, serving.flightrecorder.debug_page())
            return self._reply(404, {"error": "unknown path"})

        def _reply_payload(self, data: bytes, request_id: str) -> None:
            """A binary KV migration payload (/kv/export success)."""
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("X-Request-Id", request_id)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_POST(self):  # noqa: N802 (stdlib API)
            if self._fault_gate():
                return
            if self.path == "/kv/import":
                return self._kv_import()
            if self.path == "/admin/evacuate":
                return self._admin_evacuate()
            if self.path == "/debug/dump":
                # Operator-initiated black-box flush: always dumps (force
                # past the cooldown) and answers with the dump itself.
                dump = serving.blackbox_dump("manual", force=True)
                return self._reply(200, dump)
            if self.path not in ("/generate", "/kv/export"):
                return self._reply(404, {"error": "unknown path"})
            migrate = self.path == "/kv/export"
            # Trace-id adoption: an inbound X-Request-Id (minted by the
            # fleet router, or sent by a client directly) becomes THE
            # request_id tagging this request's serve/* spans and engine
            # slot state — one id stitches router -> replica -> engine.
            # Absent, one is minted here so the echo below always holds.
            trace_id = (self.headers.get("X-Request-Id") or "").strip()
            trace_id = trace_id[:128] or uuid.uuid4().hex
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
                prompt_ids = body.get("prompt_ids")
                if prompt_ids is None:
                    prompt = body.get("prompt")
                    if prompt is None:
                        raise ValueError("need 'prompt' or 'prompt_ids'")
                    if serving.tokenizer is None:
                        raise ValueError(
                            "'prompt' needs a tokenizer; send 'prompt_ids'"
                        )
                    prompt_ids = serving.tokenizer.encode(prompt)
                result = serving.generate(
                    prompt_ids,
                    max_new_tokens=body.get("max_new_tokens"),
                    temperature=float(body.get("temperature", 1.0)),
                    top_k=body.get("top_k"),
                    top_p=body.get("top_p"),
                    seed=int(body.get("seed", 0)),
                    stop_id=body.get("stop_id"),
                    deadline_s=body.get("deadline_s"),
                    session=body.get("session"),
                    request_id=trace_id,
                    migrate=migrate,
                    # Codec negotiation (ISSUE 20): the importer-to-be
                    # says what v2 frames it can open; the export picks
                    # the best one both sides share (absent: raw).
                    kv_accept=(
                        self.headers.get("X-KV-Accept") if migrate else None
                    ),
                )
            except (QueueFullError, DuplicateRequestError) as exc:
                # Both are "this replica can't take THIS request right
                # now": 503 so the router fails over instead of judging
                # the caller (a duplicate id means OUR copy is still
                # running — a peer can serve the retry).
                return self._reply(
                    503, {"error": str(exc), "request_id": trace_id},
                    request_id=trace_id,
                )
            except (ValueError, TypeError, json.JSONDecodeError) as exc:
                return self._reply(
                    400, {"error": str(exc), "request_id": trace_id},
                    request_id=trace_id,
                )
            except RuntimeError as exc:
                # Engine not running / worker died / draining: a JSON 503
                # beats the stdlib handler's closed socket.
                return self._reply(
                    503, {"error": str(exc), "request_id": trace_id},
                    request_id=trace_id,
                )
            if result.finish_reason == "migrated":
                return self._reply_payload(
                    result.kv_payload, result.request_id
                )
            payload = {
                "request_id": result.request_id,
                "token_ids": list(result.token_ids),
                "finish_reason": result.finish_reason,
                "timings": result.timings(),
            }
            if serving.tokenizer is not None:
                ids = list(result.token_ids)
                if result.finish_reason == "stop":
                    ids = ids[:-1]  # the stop token itself isn't prose
                payload["completion"] = serving.tokenizer.decode(ids)
            self._reply(200, payload, request_id=result.request_id)

        def _kv_import(self):
            """POST /kv/import: graft a KV payload, decode to completion,
            answer with the standard generate JSON."""
            trace_id = (self.headers.get("X-Request-Id") or "").strip()
            trace_id = trace_id[:128] or None
            try:
                length = int(self.headers.get("Content-Length", 0))
                data = self.rfile.read(length)
                idem = (
                    self.headers.get("X-Idempotency-Key") or ""
                ).strip()[:128] or None
                handle = serving.submit_import(data, idempotency_key=idem)
                result = handle.result()
            except (QueueFullError, DuplicateRequestError) as exc:
                return self._reply(
                    503, {"error": str(exc)}, request_id=trace_id
                )
            except (ValueError, TypeError, KeyError, IndexError) as exc:
                # KeyError/IndexError: a JSON-valid but structurally
                # corrupt payload header (missing meta keys, bogus array
                # manifest) — the caller's bad payload, never a replica
                # fault (a dropped connection here would make the router
                # mark healthy decode replicas down and replay the same
                # corrupt bytes across the pool).
                return self._reply(
                    400, {"error": f"bad payload: {exc!r}"},
                    request_id=trace_id,
                )
            except RuntimeError as exc:
                return self._reply(
                    503, {"error": str(exc)}, request_id=trace_id
                )
            payload = {
                "request_id": result.request_id,
                "token_ids": list(result.token_ids),
                "finish_reason": result.finish_reason,
                "timings": result.timings(),
            }
            if serving.tokenizer is not None:
                ids = list(result.token_ids)
                if result.finish_reason == "stop":
                    ids = ids[:-1]
                payload["completion"] = serving.tokenizer.decode(ids)
            self._reply(200, payload, request_id=result.request_id)

        def _admin_evacuate(self):
            """POST /admin/evacuate: controller-initiated hot rebalancing
            — body ``{"target": base_url, "max_sessions"?, "timeout_s"?}``
            exports victim sessions and relays them to the target's
            ``/kv/import``.  Answers with the moved request ids once the
            exports happen (relays complete asynchronously)."""
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
                target = body.get("target")
                if not target or not isinstance(target, str):
                    raise ValueError("need 'target' (peer base URL)")
                out = serving.request_rebalance(
                    target,
                    max_sessions=int(body.get("max_sessions", 1)),
                    timeout_s=float(body.get("timeout_s", 30.0)),
                )
            except (ValueError, TypeError, json.JSONDecodeError) as exc:
                return self._reply(400, {"error": str(exc)})
            except (RuntimeError, TimeoutError) as exc:
                return self._reply(503, {"error": str(exc)})
            return self._reply(200, out)

    return ThreadingHTTPServer((host, port), Handler)
