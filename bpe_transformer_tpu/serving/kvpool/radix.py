"""Radix (token-trie) prefix cache: prompt prefixes -> frozen KV block
chains (host-side, jax-free).

One trie node per FULL prompt block (``block_size`` tokens): the edge key
is the block's token tuple, the node's value its pool block id.  A new
request walks the trie block-by-block over its prompt; every matched node
is a prefill it never has to run — the engine refs the block into the
slot's block table and starts computing at the first unmatched position.
After a prefill completes, the prompt's full blocks are inserted so the
NEXT request with the same prefix hits.

Only fully-written prompt blocks are indexed (a partial tail block is
still written by its owner's decode steps, so it can never be shared),
which is what makes matched blocks frozen and sharing copy-on-write by
construction — see `blocks.py`.

The cache holds one allocator reference per indexed block, so indexed
blocks survive their original request.  When the pool runs dry the engine
calls :meth:`evict`: least-recently-used LEAF nodes whose block nobody
else references are dropped first (an interior node's block is still the
prefix of a live chain — evicting leaves first keeps every remaining
chain walkable).
"""

from __future__ import annotations

from bpe_transformer_tpu.serving.kvpool.blocks import BlockAllocator


class _Node:
    __slots__ = ("block_id", "children", "parent", "key", "stamp")

    def __init__(self, block_id: int, parent, key):
        self.block_id = block_id
        self.parent = parent
        self.key = key  # the token tuple of this block (edge from parent)
        self.children: dict[tuple, _Node] = {}
        self.stamp = 0  # LRU clock value of the last match/insert touch


class RadixPrefixCache:
    """Token-trie over full prompt blocks (see module docstring)."""

    def __init__(self, allocator: BlockAllocator):
        self._allocator = allocator
        self._root = _Node(block_id=-1, parent=None, key=None)
        self._clock = 0
        self._nodes = 0
        self.hits_tokens = 0
        self.misses_tokens = 0

    def __len__(self) -> int:
        return self._nodes

    # -------------------------------------------------------------- lookup

    def match(self, prompt: list[int]) -> list[int]:
        """Longest indexed prefix of ``prompt`` in full blocks: returns the
        matched block ids (allocator-ref'd for the caller — the caller owns
        releasing them).

        The match is capped at ``len(prompt) - 1`` tokens: at least one
        prompt position must be computed so the admission has logits to
        sample its first token from (a fully-cached prompt still needs its
        last position's forward).

        Deliberately does NOT touch the hit/miss counters: a block-starved
        admission is matched again on every retry, and charging lookups
        rather than admissions would inflate the hit rate with phantom
        tokens — the engine calls :meth:`charge` once per admission that
        actually proceeds.
        """
        bs = self._allocator.block_size
        matched: list[int] = []
        node = self._root
        self._clock += 1
        pos = 0
        # pos + bs <= len(prompt) - 1: the matched region always leaves at
        # least the last prompt token uncached (see docstring).
        while pos + bs <= len(prompt) - 1:
            key = tuple(prompt[pos: pos + bs])
            child = node.children.get(key)
            if child is None:
                break
            child.stamp = self._clock
            matched.append(child.block_id)
            node = child
            pos += bs
        if matched:
            self._allocator.ref(matched)
        return matched

    def charge(self, prompt_len: int, hit_tokens: int) -> None:
        """Account one ADMITTED prompt against the hit/miss counters:
        ``hit_tokens`` of its ``prompt_len`` were served from the cache."""
        self.hits_tokens += hit_tokens
        self.misses_tokens += prompt_len - hit_tokens

    # -------------------------------------------------------------- insert

    def insert(self, prompt: list[int], block_ids: list[int]) -> int:
        """Index ``prompt``'s full blocks under their pool block ids;
        returns how many NEW nodes were created (each new node takes one
        allocator reference).  Existing nodes keep their original block id
        — two racing identical prefills simply miss the dedup for the
        second one."""
        bs = self._allocator.block_size
        full = min(len(prompt) // bs, len(block_ids))
        node = self._root
        created = 0
        self._clock += 1
        for i in range(full):
            key = tuple(prompt[i * bs: (i + 1) * bs])
            child = node.children.get(key)
            if child is None:
                child = _Node(block_ids[i], parent=node, key=key)
                node.children[key] = child
                self._allocator.ref([block_ids[i]])
                self._nodes += 1
                created += 1
            child.stamp = self._clock
            node = child
        return created

    # ------------------------------------------------------------ eviction

    def evict(self, n_blocks: int) -> int:
        """Free up to ``n_blocks`` pool blocks by dropping LRU leaf nodes
        whose block has no reference besides the cache's own.  Returns how
        many blocks were actually freed.

        One DFS per WAVE, not per block: each pass collects every
        currently-evictable leaf, evicts them oldest-stamp-first, and only
        rescans when more blocks are still needed (evicting a leaf can
        turn its parent into the next wave's candidate) — so a
        multi-block shortfall on a large trie costs O(depth) scans, not
        O(shortfall) scans."""
        freed = 0
        while freed < n_blocks:
            victims = []
            stack = [self._root]
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                if (
                    node is not self._root
                    and not node.children
                    and self._allocator.refcount(node.block_id) == 1
                ):
                    victims.append(node)
            if not victims:
                break
            victims.sort(key=lambda node: node.stamp)
            for victim in victims:
                if freed >= n_blocks:
                    break
                del victim.parent.children[victim.key]
                self._nodes -= 1
                freed += self._allocator.deref([victim.block_id])
        return freed

    def gauges(self) -> dict:
        total = self.hits_tokens + self.misses_tokens
        return {
            "prefix_cache_hits": self.hits_tokens,
            "prefix_cache_misses": self.misses_tokens,
            "prefix_hit_rate": (
                round(self.hits_tokens / total, 6) if total else None
            ),
            "prefix_cache_nodes": self._nodes,
        }
