"""KV-slot migration payloads: the self-describing wire format that moves
one in-flight generation between replicas (ISSUE 15, disaggregated
prefill/decode serving).

A payload is everything another replica needs to continue a generation
bit-for-bit: the slot's *geometry* (block size, pool dtype, model KV
shape — validated against the importing engine before any block is
allocated), its *KV rows* (the referenced pool blocks, gathered per
block; int8 pools ship their per-block-per-head scale rows alongside),
and its *state machine* (prompt, prefill frontier for mid-prefill
migrations, or the full decode state — pending token, position, RNG key,
sampling knobs — for finished prefixes).  ``PagedEngine.export_slot``
builds one, ``PagedEngine.import_slot`` grafts one; this module owns the
host-side dict <-> bytes codec so the HTTP transport (``/kv/export`` ->
``/kv/import``), the router, and the in-process drain-evacuation path all
speak the same format.

The byte format is deliberately boring — magic + JSON header + raw
little-endian array bytes — so it is decodable with numpy alone (no
pickle, no jax): the router can size/forward payloads opaquely, and a
corrupted or truncated body fails loudly at the header/length checks
rather than grafting garbage KV.
"""

from __future__ import annotations

import json

import numpy as np

try:  # bfloat16 payload rows need the ml_dtypes numpy extension (jax
    # ships it); pure-numpy hosts still decode f32/int8 payloads fine.
    import ml_dtypes  # noqa: F401
except ImportError:
    pass

__all__ = [
    "PAYLOAD_MAGIC",
    "payload_to_bytes",
    "payload_from_bytes",
    "payload_nbytes",
    "synthetic_decode_payload",
]

#: Format magic + version.  Bump the digits on any incompatible layout
#: change — import refuses unknown versions instead of misreading rows.
PAYLOAD_MAGIC = b"BPEKV001"


def payload_to_bytes(payload: dict) -> bytes:
    """Serialize an ``export_slot`` payload: magic, an 8-byte little-endian
    header length, the JSON header (meta + array manifest), then each
    array's raw bytes in manifest order."""
    meta = payload["meta"]
    manifest: list[dict] = []
    chunks: list[bytes] = []
    for i, layer in enumerate(payload["layers"]):
        for name in sorted(layer):
            arr = np.ascontiguousarray(layer[name])
            manifest.append(
                {
                    "key": f"L{i}/{name}",
                    "dtype": str(arr.dtype),
                    "shape": list(arr.shape),
                }
            )
            chunks.append(arr.tobytes())
    header = json.dumps(
        {"meta": meta, "arrays": manifest}, separators=(",", ":")
    ).encode("utf-8")
    return b"".join(
        [PAYLOAD_MAGIC, len(header).to_bytes(8, "little"), header] + chunks
    )


def payload_from_bytes(data: bytes) -> dict:
    """Decode :func:`payload_to_bytes` output back into the payload dict.
    Raises ``ValueError`` on a bad magic, version, or truncated body."""
    if not data.startswith(PAYLOAD_MAGIC[:5]):
        raise ValueError("not a KV migration payload (bad magic)")
    if not data.startswith(PAYLOAD_MAGIC):
        raise ValueError(
            f"unsupported KV payload version {data[:8]!r} "
            f"(expected {PAYLOAD_MAGIC!r})"
        )
    off = len(PAYLOAD_MAGIC)
    if len(data) < off + 8:
        raise ValueError("truncated KV payload (no header length)")
    hlen = int.from_bytes(data[off: off + 8], "little")
    off += 8
    if len(data) < off + hlen:
        raise ValueError("truncated KV payload (header)")
    try:
        header = json.loads(data[off: off + hlen])
    except json.JSONDecodeError as exc:
        raise ValueError(f"corrupt KV payload header: {exc}") from None
    off += hlen
    meta = header["meta"]
    layers: list[dict] = [{} for _ in range(int(meta["num_layers"]))]
    for spec in header["arrays"]:
        dtype = np.dtype(spec["dtype"])
        shape = tuple(int(d) for d in spec["shape"])
        nbytes = dtype.itemsize * int(np.prod(shape)) if shape else dtype.itemsize
        if len(data) < off + nbytes:
            raise ValueError(
                f"truncated KV payload (array {spec['key']})"
            )
        arr = np.frombuffer(data, dtype=dtype, count=int(np.prod(shape)),
                            offset=off).reshape(shape)
        off += nbytes
        layer_idx, name = spec["key"].split("/", 1)
        idx = int(layer_idx[1:])
        if not 0 <= idx < len(layers):
            raise ValueError(
                f"corrupt KV payload: array {spec['key']!r} names layer "
                f"{idx} of {len(layers)}"
            )
        layers[idx][name] = arr
    return {"meta": meta, "layers": layers}


def payload_nbytes(payload: dict) -> int:
    """Raw KV bytes a payload carries (rows + scales, header excluded) —
    the transfer-size gauge the migration telemetry reports."""
    return sum(
        int(np.asarray(arr).nbytes)
        for layer in payload["layers"]
        for arr in layer.values()
    )


def synthetic_decode_payload(
    config,
    *,
    block_size: int,
    kv_dtype: str,
    prompt_len: int = 8,
    max_new_tokens: int = 3,
    seed: int = 0,
) -> dict:
    """A zero-KV decode-state payload shaped for ``import_slot`` — what
    ``bpe-tpu warmup --role decode`` grafts so a decode-role node compiles
    its tick + import copy programs WITHOUT ever touching the chunk
    ladder (the rows are zeros; warmup only cares about program shapes).

    ``config`` is duck-typed (any object with ``num_layers`` /
    ``num_heads`` / ``num_kv_heads`` / ``d_head`` / ``context_length`` /
    ``activation_dtype``); ``kv_dtype`` is the pool label — ``"int8"`` or
    the activation dtype name, exactly as ``PagedEngine.kv_dtype``
    reports it.
    """
    kv_heads = config.num_kv_heads or config.num_heads
    span = min(prompt_len + max_new_tokens, config.context_length)
    n_blocks = -(-span // block_size)
    store = "int8" if kv_dtype == "int8" else kv_dtype
    layers = []
    for _ in range(config.num_layers):
        layer = {
            "k": np.zeros(
                (n_blocks, kv_heads, block_size, config.d_head),
                np.dtype(store),
            ),
            "v": np.zeros(
                (n_blocks, kv_heads, block_size, config.d_head),
                np.dtype(store),
            ),
        }
        if kv_dtype == "int8":
            layer["k_scale"] = np.zeros((n_blocks, kv_heads), np.float32)
            layer["v_scale"] = np.zeros((n_blocks, kv_heads), np.float32)
        layers.append(layer)
    prompt = [1] * prompt_len
    meta = {
        "format": 1,
        "block_size": block_size,
        "kv_dtype": kv_dtype,
        "num_layers": config.num_layers,
        "kv_heads": kv_heads,
        "d_head": config.d_head,
        "context_length": config.context_length,
        "n_blocks": n_blocks,
        "prompt": prompt,
        "prompt_len": prompt_len,
        "next_pos": prompt_len,
        "decoding": True,
        "generated": 1,
        "max_new_tokens": max_new_tokens,
        "stop_id": None,
        "seed": seed,
        "temperature": 0.0,
        "top_k": 0,
        "top_p": 2.0,
        "token": 1,
        "position": prompt_len,
        # PRNGKey(seed) for small seeds is [seed >> 32, seed & 0xffffffff].
        "key": [seed >> 32, seed & 0xFFFFFFFF],
        "request_id": None,
        "emitted": [1],
        "history": prompt + [1],
    }
    return {"meta": meta, "layers": layers}
