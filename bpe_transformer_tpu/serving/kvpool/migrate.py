"""KV-slot migration payloads: the self-describing wire format that moves
one in-flight generation between replicas (ISSUE 15, disaggregated
prefill/decode serving).

A payload is everything another replica needs to continue a generation
bit-for-bit: the slot's *geometry* (block size, pool dtype, model KV
shape — validated against the importing engine before any block is
allocated), its *KV rows* (the referenced pool blocks, gathered per
block; int8 pools ship their per-block-per-head scale rows alongside),
and its *state machine* (prompt, prefill frontier for mid-prefill
migrations, or the full decode state — pending token, position, RNG key,
sampling knobs — for finished prefixes).  ``PagedEngine.export_slot``
builds one, ``PagedEngine.import_slot`` grafts one; this module owns the
host-side dict <-> bytes codec so the HTTP transport (``/kv/export`` ->
``/kv/import``), the router, and the in-process drain-evacuation path all
speak the same format.

The byte format is deliberately boring — magic + JSON header + raw
little-endian array bytes — so it is decodable with numpy alone (no
pickle, no jax): the router can size/forward payloads opaquely, and a
corrupted or truncated body fails loudly at the header/length checks
rather than grafting garbage KV.

Version 2 (``BPEKV002``, ISSUE 20) hardens the format for WAN-grade
links: the header carries a CRC32 over the (uncompressed) array section
and a codec flag — ``zstd`` when the extension is importable, ``zlib``
as the always-available stdlib fallback, ``raw`` otherwise — negotiated
per transfer via an accept list (the ``X-KV-Accept`` HTTP header on
``/kv/export``).  A bit-flipped or truncated body fails the CRC or
length check with ``ValueError`` — the transport maps that to a 400, so
a corrupt graft can never reach the worker.  Version-1 payloads still
decode (no CRC: best-effort legacy), so mixed-version fleets migrate
during a rolling deploy.
"""

from __future__ import annotations

import json
import zlib

import numpy as np

try:  # bfloat16 payload rows need the ml_dtypes numpy extension (jax
    # ships it); pure-numpy hosts still decode f32/int8 payloads fine.
    import ml_dtypes  # noqa: F401
except ImportError:
    pass

try:  # optional: the container may not ship python-zstandard; zlib is
    # the guaranteed stdlib fallback so negotiation always has a codec.
    import zstandard as _zstd  # type: ignore
except ImportError:
    _zstd = None

__all__ = [
    "PAYLOAD_MAGIC",
    "PAYLOAD_MAGIC_V1",
    "HAVE_ZSTD",
    "negotiate_codec",
    "supported_codecs",
    "payload_to_bytes",
    "payload_from_bytes",
    "payload_nbytes",
    "synthetic_decode_payload",
]

#: Format magic + version.  Bump the digits on any incompatible layout
#: change — import refuses unknown versions instead of misreading rows.
PAYLOAD_MAGIC = b"BPEKV002"
#: The PR 14 format: no CRC, no compression.  Still decoded (legacy).
PAYLOAD_MAGIC_V1 = b"BPEKV001"

HAVE_ZSTD = _zstd is not None

#: Codecs this host can encode/decode, best first.
_CODECS = (("zstd",) if HAVE_ZSTD else ()) + ("zlib", "raw")


def supported_codecs() -> tuple[str, ...]:
    """Codecs this host can decode, best first — what a replica
    advertises (statusz ``kv_accept``) and sends as ``X-KV-Accept``."""
    return _CODECS


def negotiate_codec(accept: str | None) -> str:
    """Pick the best locally available codec from a comma-separated accept
    list (e.g. the ``X-KV-Accept`` request header on ``/kv/export``).
    ``None``/empty means the peer predates negotiation — send ``raw`` so a
    v1-era importer is never handed a frame it cannot open."""
    if not accept:
        return "raw"
    offered = {tok.strip().lower() for tok in accept.split(",") if tok.strip()}
    for codec in _CODECS:
        if codec in offered:
            return codec
    return "raw"


def _compress(codec: str, data: bytes) -> bytes:
    if codec == "raw":
        return data
    if codec == "zlib":
        return zlib.compress(data, 1)
    if codec == "zstd":
        if _zstd is None:
            raise ValueError("zstd codec requested but zstandard not installed")
        return _zstd.ZstdCompressor(level=3).compress(data)
    raise ValueError(f"unknown KV payload codec {codec!r}")


def _decompress(codec: str, data: bytes, raw_nbytes: int) -> bytes:
    try:
        if codec == "raw":
            return data
        if codec == "zlib":
            return zlib.decompress(data)
        if codec == "zstd":
            if _zstd is None:
                raise ValueError(
                    "KV payload uses zstd but zstandard is not installed here"
                )
            return _zstd.ZstdDecompressor().decompress(
                data, max_output_size=raw_nbytes
            )
    except (zlib.error, MemoryError) as exc:
        raise ValueError(f"corrupt KV payload body ({codec}): {exc}") from None
    except Exception as exc:  # zstd errors are extension-specific types
        if codec == "zstd":
            raise ValueError(
                f"corrupt KV payload body (zstd): {exc}"
            ) from None
        raise
    raise ValueError(f"unknown KV payload codec {codec!r}")


def payload_to_bytes(payload: dict, *, codec: str = "raw") -> bytes:
    """Serialize an ``export_slot`` payload: magic, an 8-byte little-endian
    header length, the JSON header (meta + array manifest + codec +
    CRC32), then the array section — each array's raw bytes in manifest
    order, compressed as one frame when ``codec`` is not ``"raw"``."""
    meta = payload["meta"]
    manifest: list[dict] = []
    chunks: list[bytes] = []
    for i, layer in enumerate(payload["layers"]):
        for name in sorted(layer):
            arr = np.ascontiguousarray(layer[name])
            manifest.append(
                {
                    "key": f"L{i}/{name}",
                    "dtype": str(arr.dtype),
                    "shape": list(arr.shape),
                }
            )
            chunks.append(arr.tobytes())
    raw = b"".join(chunks)
    body = _compress(codec, raw)
    header = json.dumps(
        {
            "meta": meta,
            "arrays": manifest,
            "codec": codec,
            "crc32": zlib.crc32(raw) & 0xFFFFFFFF,
            "raw_nbytes": len(raw),
            "body_nbytes": len(body),
        },
        separators=(",", ":"),
    ).encode("utf-8")
    return b"".join(
        [PAYLOAD_MAGIC, len(header).to_bytes(8, "little"), header, body]
    )


def payload_from_bytes(data: bytes) -> dict:
    """Decode :func:`payload_to_bytes` output back into the payload dict.
    Accepts v2 (CRC-checked, optionally compressed) and legacy v1 frames.
    Raises ``ValueError`` on a bad magic, version, truncated body, CRC
    mismatch, or undecodable compression frame — loudly, so the transport
    can 400 instead of grafting garbage KV."""
    if not data.startswith(PAYLOAD_MAGIC[:5]):
        raise ValueError("not a KV migration payload (bad magic)")
    version_2 = data.startswith(PAYLOAD_MAGIC)
    if not version_2 and not data.startswith(PAYLOAD_MAGIC_V1):
        raise ValueError(
            f"unsupported KV payload version {data[:8]!r} "
            f"(expected {PAYLOAD_MAGIC!r} or {PAYLOAD_MAGIC_V1!r})"
        )
    off = len(PAYLOAD_MAGIC)
    if len(data) < off + 8:
        raise ValueError("truncated KV payload (no header length)")
    hlen = int.from_bytes(data[off: off + 8], "little")
    off += 8
    if len(data) < off + hlen:
        raise ValueError("truncated KV payload (header)")
    try:
        header = json.loads(data[off: off + hlen])
    except json.JSONDecodeError as exc:
        raise ValueError(f"corrupt KV payload header: {exc}") from None
    off += hlen
    meta = header["meta"]
    if version_2:
        codec = header.get("codec", "raw")
        body_nbytes = int(header.get("body_nbytes", len(data) - off))
        if len(data) < off + body_nbytes:
            raise ValueError(
                f"truncated KV payload (body: have {len(data) - off} of "
                f"{body_nbytes} bytes)"
            )
        raw = _decompress(
            codec, data[off: off + body_nbytes],
            int(header.get("raw_nbytes", 1 << 31)),
        )
        want_crc = int(header["crc32"]) & 0xFFFFFFFF
        got_crc = zlib.crc32(raw) & 0xFFFFFFFF
        if got_crc != want_crc:
            raise ValueError(
                f"KV payload CRC mismatch (header {want_crc:#010x}, "
                f"body {got_crc:#010x}) — refusing to graft corrupt KV"
            )
        section, sec_off = raw, 0
    else:
        section, sec_off = data, off
    layers: list[dict] = [{} for _ in range(int(meta["num_layers"]))]
    for spec in header["arrays"]:
        dtype = np.dtype(spec["dtype"])
        shape = tuple(int(d) for d in spec["shape"])
        nbytes = dtype.itemsize * int(np.prod(shape)) if shape else dtype.itemsize
        if len(section) < sec_off + nbytes:
            raise ValueError(
                f"truncated KV payload (array {spec['key']})"
            )
        arr = np.frombuffer(
            section, dtype=dtype, count=int(np.prod(shape)), offset=sec_off,
        ).reshape(shape)
        sec_off += nbytes
        layer_idx, name = spec["key"].split("/", 1)
        idx = int(layer_idx[1:])
        if not 0 <= idx < len(layers):
            raise ValueError(
                f"corrupt KV payload: array {spec['key']!r} names layer "
                f"{idx} of {len(layers)}"
            )
        layers[idx][name] = arr
    return {"meta": meta, "layers": layers}


def payload_nbytes(payload: dict) -> int:
    """Raw KV bytes a payload carries (rows + scales, header excluded) —
    the transfer-size gauge the migration telemetry reports."""
    return sum(
        int(np.asarray(arr).nbytes)
        for layer in payload["layers"]
        for arr in layer.values()
    )


def synthetic_decode_payload(
    config,
    *,
    block_size: int,
    kv_dtype: str,
    prompt_len: int = 8,
    max_new_tokens: int = 3,
    seed: int = 0,
) -> dict:
    """A zero-KV decode-state payload shaped for ``import_slot`` — what
    ``bpe-tpu warmup --role decode`` grafts so a decode-role node compiles
    its tick + import copy programs WITHOUT ever touching the chunk
    ladder (the rows are zeros; warmup only cares about program shapes).

    ``config`` is duck-typed (any object with ``num_layers`` /
    ``num_heads`` / ``num_kv_heads`` / ``d_head`` / ``context_length`` /
    ``activation_dtype``); ``kv_dtype`` is the pool label — ``"int8"`` or
    the activation dtype name, exactly as ``PagedEngine.kv_dtype``
    reports it.
    """
    kv_heads = config.num_kv_heads or config.num_heads
    span = min(prompt_len + max_new_tokens, config.context_length)
    n_blocks = -(-span // block_size)
    store = "int8" if kv_dtype == "int8" else kv_dtype
    layers = []
    for _ in range(config.num_layers):
        layer = {
            "k": np.zeros(
                (n_blocks, kv_heads, block_size, config.d_head),
                np.dtype(store),
            ),
            "v": np.zeros(
                (n_blocks, kv_heads, block_size, config.d_head),
                np.dtype(store),
            ),
        }
        if kv_dtype == "int8":
            layer["k_scale"] = np.zeros((n_blocks, kv_heads), np.float32)
            layer["v_scale"] = np.zeros((n_blocks, kv_heads), np.float32)
        layers.append(layer)
    prompt = [1] * prompt_len
    meta = {
        "format": 1,
        "block_size": block_size,
        "kv_dtype": kv_dtype,
        "num_layers": config.num_layers,
        "kv_heads": kv_heads,
        "d_head": config.d_head,
        "context_length": config.context_length,
        "n_blocks": n_blocks,
        "prompt": prompt,
        "prompt_len": prompt_len,
        "next_pos": prompt_len,
        "decoding": True,
        "generated": 1,
        "max_new_tokens": max_new_tokens,
        "stop_id": None,
        "seed": seed,
        "temperature": 0.0,
        "top_k": 0,
        "top_p": 2.0,
        "token": 1,
        "position": prompt_len,
        # PRNGKey(seed) for small seeds is [seed >> 32, seed & 0xffffffff].
        "key": [seed >> 32, seed & 0xFFFFFFFF],
        "request_id": None,
        "emitted": [1],
        "history": prompt + [1],
    }
    return {"meta": meta, "layers": layers}
