"""Refcounted fixed-size KV block allocator (host-side, jax-free).

The device pool is a flat array of ``num_blocks`` KV blocks per layer;
this allocator owns WHICH blocks are free, who holds references, and the
gauges the serving surface exports (``kv_blocks_{total,free,shared}``).
Pure host bookkeeping over small integer lists — it never touches the
device, so the router and tests can reason about pool pressure on hosts
with no accelerator runtime.

Reference protocol (copy-on-write sharing):

- every *user* of a block holds one reference: a slot whose block table
  points at it, and the radix prefix cache for every block it has
  indexed;
- a block with ``refcount >= 2`` is **shared** — by construction it is
  frozen (only fully-written prompt blocks enter the prefix cache, and
  decode writes land strictly beyond the prompt), so sharing needs no
  device-side copy;
- a block whose last reference drops returns to the free list.

Block id 0 is RESERVED as the trash block: masked device writes
(inactive slots, padded prefill tail) are steered to it instead of being
predicated out, so one compiled program serves every occupancy pattern.
The allocator never hands it out.
"""

from __future__ import annotations


class NoFreeBlocksError(RuntimeError):
    """The pool cannot satisfy an allocation even after cache eviction.

    Raised to the serving layer, which parks the admission until decode
    retirements free blocks (backpressure, not failure)."""


class BlockAllocator:
    """Free-list + refcount bookkeeping over ``num_blocks`` KV blocks.

    ``num_blocks`` INCLUDES the reserved trash block 0, so a pool sized
    ``slots * blocks_per_slot + 1`` is exactly dense-slot-pool capacity.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is reserved), got "
                f"{num_blocks}"
            )
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._refs = [0] * num_blocks
        self._refs[0] = 1  # the trash block is permanently held
        # LIFO free list: recently-freed blocks are re-used first (their
        # pool rows are hottest in any cache hierarchy).
        self._free = list(range(num_blocks - 1, 0, -1))

    # ------------------------------------------------------------- queries

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def usable_blocks(self) -> int:
        """Blocks a single request could ever hold (total minus trash)."""
        return self.num_blocks - 1

    def refcount(self, block_id: int) -> int:
        return self._refs[block_id]

    @property
    def shared_count(self) -> int:
        """Blocks referenced more than once (prefix-cache sharing at work;
        the cache's own index reference is excluded by the >2 threshold
        for blocks it holds — callers report the simpler >=2 count)."""
        return sum(1 for r in self._refs[1:] if r >= 2)

    # ----------------------------------------------------------- lifecycle

    def alloc(self, n: int) -> list[int]:
        """Take ``n`` free blocks (refcount 1 each); raises
        :class:`NoFreeBlocksError` without allocating when short."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            raise NoFreeBlocksError(
                f"need {n} KV blocks, only {len(self._free)} free"
            )
        taken = [self._free.pop() for _ in range(n)]
        for block_id in taken:
            self._refs[block_id] = 1
        return taken

    def ref(self, block_ids: list[int]) -> None:
        """Add one reference to each block (prefix-cache hit / index)."""
        for block_id in block_ids:
            if self._refs[block_id] < 1:
                raise ValueError(f"block {block_id} is not allocated")
            self._refs[block_id] += 1

    def deref(self, block_ids: list[int]) -> int:
        """Drop one reference per block; returns how many blocks freed."""
        freed = 0
        for block_id in block_ids:
            if block_id == 0:
                raise ValueError("the trash block is never deref'd")
            refs = self._refs[block_id]
            if refs < 1:
                raise ValueError(f"block {block_id} is not allocated")
            self._refs[block_id] = refs - 1
            if refs == 1:
                self._free.append(block_id)
                freed += 1
        return freed

    def gauges(self) -> dict:
        """The /metrics view: total (usable), free, shared."""
        return {
            "kv_blocks_total": self.usable_blocks,
            "kv_blocks_free": self.free_count,
            "kv_blocks_shared": self.shared_count,
        }
