"""Paged continuous-batching engine: the slot-pool contract on block-pool
KV memory, with radix prefix sharing and chunked prefill.

Drop-in peer of `serving.engine.SlotPoolEngine` (same admit/tick/release
lifecycle, same ``TickEvent`` vocabulary, same one-jitted-tick and
bounded-compile-count guarantees), with three new behaviors:

* **paged KV** — the cache is a flat pool of ``block_size``-token blocks
  (`models/decode.init_kv_pool`); each slot owns a *chain of block ids*
  in a block table that the decode tick and chunk prefill read through
  (gather) and write through (scatter).  Pool capacity is a knob
  (``num_blocks``) decoupled from ``slots * context_length``;
* **radix prefix sharing** — prompts consult the `RadixPrefixCache`
  before computing: matched full blocks are reference-counted into the
  slot's table and prefill starts at the first unmatched position, so a
  shared system prompt is computed once per fleet replica, not once per
  request.  Token-identical to the dense engine by construction: K/V at
  a position is a pure function of the token prefix, and shared blocks
  are frozen (copy-on-write, never rewritten);
* **int8 KV blocks** (``kv_dtype="int8"``) — the pool stores quantized
  K/V with per-block-per-head f32 scales in parallel scale pools;
  writers quantize at scatter time (decode: rescale-on-grow, prefill:
  per-block scatter-max — `models/decode.py`), readers dequantize on
  gather (XLA path) or in registers (the paged-native kernel).  Per-token
  HBM traffic drops ~2x vs bf16 / 4x vs f32, and the freed bytes raise
  the block count at fixed memory;
* **chunked prefill** — prefill is a resumable state machine
  (:meth:`begin` / :meth:`prefill_step`): each step runs ONE
  ``prefill_chunk``-token chunk, so the serving worker can interleave
  decode ticks between a long prompt's chunks and decode p99 stays
  bounded under heavy prefill traffic (the worker owns the per-tick
  token budget — `serving.scheduler.PrefillBudget`).

Compile count: one program per chunk bucket + one tick, asserted by
:meth:`compiled_programs` exactly like the dense engine.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from bpe_transformer_tpu.models.config import ModelConfig
from bpe_transformer_tpu.models.decode import (
    init_kv_pool,
    paged_chunk_prefill,
    paged_decode_step,
)
from bpe_transformer_tpu.serving.engine import (
    TOP_K_DISABLED,
    TOP_P_DISABLED,
    SlotPoolEngine,
    TickEvent,
    default_prefill_buckets,
    gumbel_rows,
    prepare_serving_weights,
    sample_tokens,
)
from bpe_transformer_tpu.serving.kvpool.blocks import (
    BlockAllocator,
    NoFreeBlocksError,
)
from bpe_transformer_tpu.serving.kvpool.radix import RadixPrefixCache

__all__ = ["PagedEngine", "PagedSlotInfo", "NoFreeBlocksError"]


def _chunk_program(
    params, lm_head, pool, table_row, chunk, start, chunk_len, key, temp,
    top_k, top_p, *, config: ModelConfig, block_size: int,
):
    """One chunk-bucket-shaped prefill step + first-token sampling.  The
    sampled token/key are meaningful only for a prompt's FINAL chunk (the
    host passes the request key there and ignores the outputs earlier),
    so key handling stays byte-identical to the dense prefill program."""
    logits, pool = paged_chunk_prefill(
        params, chunk, start, chunk_len, table_row, pool, config,
        lm_head=lm_head, block_size=block_size,
    )
    key, sub = jax.random.split(key)
    tok = sample_tokens(
        logits, sub[None], temp[None], top_k[None], top_p[None]
    )[0]
    return tok, key, pool


def _paged_tick_program(
    params, lm_head, pool, tables, tokens, positions, active, keys, temps,
    top_ks, top_ps, *, config: ModelConfig, block_size: int,
    fused: bool = False,
):
    """One engine tick over the paged pool — sampling identical to the
    dense `_tick_program`, decode reads/writes through the block table.
    ``fused=True`` runs the head projection + filter + sample tail as ONE
    Pallas kernel (see the dense twin's docstring)."""
    split = jax.vmap(jax.random.split)(keys)
    keys_next, subs = split[:, 0], split[:, 1]
    if fused:
        from bpe_transformer_tpu.kernels.pallas.sample import (
            fused_head_sample,
        )

        hidden, pool = paged_decode_step(
            params, tokens, positions, pool, tables, config,
            lm_head=lm_head, active=active, return_hidden=True,
            block_size=block_size,
        )
        gumbel = gumbel_rows(subs, config.vocab_size)
        nxt = fused_head_sample(
            hidden, lm_head, temps, top_ks, top_ps, gumbel
        )
    else:
        logits, pool = paged_decode_step(
            params, tokens, positions, pool, tables, config,
            lm_head=lm_head, active=active, block_size=block_size,
        )
        nxt = sample_tokens(logits, subs, temps, top_ks, top_ps)
    nxt = jnp.where(active, nxt, tokens)
    keys_next = jnp.where(active[:, None], keys_next, keys)
    positions = jnp.where(active, positions + 1, positions)
    return nxt, positions, keys_next, pool


def _copy_block_program(pool, src, dst):
    """Copy one block's rows (K/V and, for int8 pools, their scale rows)
    from pool block ``src`` to ``dst`` — the device half of a
    copy-on-write rewind (`PagedEngine.rewind`).  ``src``/``dst`` are
    traced scalars, so every copy shares one compiled program."""
    return [
        {name: arr.at[dst].set(arr[src]) for name, arr in layer.items()}
        for layer in pool
    ]


def _extract_block_program(pool, src):
    """Read one block's rows out of the pool (K/V + int8 scale rows) —
    the device half of :meth:`PagedEngine.export_slot`.  ``src`` is a
    traced scalar, so every block of every export shares ONE compiled
    program regardless of chain length."""
    return [{name: arr[src] for name, arr in layer.items()} for layer in pool]


def _inject_block_program(pool, rows, dst):
    """Write one migrated block's rows into the pool at block ``dst`` —
    the device half of :meth:`PagedEngine.import_slot` (the
    `_copy_block_program` idiom with host-supplied rows).  ``dst`` is a
    traced scalar and ``rows`` mirrors the pool's per-layer dict
    structure, so every grafted block shares ONE compiled program."""
    return [
        {name: arr.at[dst].set(row[name]) for name, arr in layer.items()}
        for layer, row in zip(pool, rows)
    ]


@dataclasses.dataclass
class PagedSlotInfo:
    """Host-side bookkeeping for one occupied slot (prefill + decode)."""

    prompt: np.ndarray  # int32 prompt ids (owned copy)
    prompt_len: int
    bucket: int  # the first computed chunk's program bucket (metrics)
    max_new_tokens: int  # effective: clamped to the context window
    stop_id: int | None
    seed: int
    temp_enc: np.float32
    top_k_enc: np.int32
    top_p_enc: np.float32
    block_ids: list  # every block this slot holds a reference on
    shared_len: int  # tokens reused from the prefix cache (block-aligned)
    next_pos: int  # prefill cursor: first position not yet computed
    generated: int = 0
    #: The serving request (= fleet trace id) occupying this slot — slot
    #: metadata for /statusz and cross-replica tracing, like the dense
    #: engine's SlotInfo.request_id.
    request_id: str | None = None


class PagedEngine:
    """Paged-KV continuous-batching engine (see module docstring).

    Single-threaded like the dense engine: one caller drives
    :meth:`begin`/:meth:`prefill_step`/:meth:`tick`/:meth:`release` (or
    the :meth:`admit` convenience that runs a whole prefill at once).
    """

    #: Optional flight recorder (telemetry/flightrecorder.py), attached by
    #: the serving engine: KV rewinds (speculative rejections, host-side
    #: truncations) are pool decisions the incident ring should show.
    recorder = None

    def __init__(
        self,
        params,
        config: ModelConfig,
        *,
        slots: int = 8,
        block_size: int = 16,
        num_blocks: int | None = None,
        prefill_buckets: tuple[int, ...] | None = None,
        min_bucket: int = 16,
        prefill_chunk: int | None = None,
        prefix_cache: bool = True,
        kv_dtype: str | None = None,
        weight_dtype: str | None = None,
        fused_sampling: bool = False,
    ):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if kv_dtype not in (None, "int8"):
            raise ValueError(
                f'kv_dtype={kv_dtype!r} must be None (activation width) '
                'or "int8"'
            )
        ctx = config.context_length
        if block_size < 1 or ctx % block_size:
            raise ValueError(
                f"block_size={block_size} must divide "
                f"context_length={ctx}"
            )
        self.config = config
        self.n_slots = slots
        self.block_size = block_size
        self.blocks_per_slot = ctx // block_size
        if prefill_chunk is None:
            prefill_chunk = ctx
        if prefill_chunk < 1 or (
            prefill_chunk < ctx and prefill_chunk % block_size
        ):
            raise ValueError(
                f"prefill_chunk={prefill_chunk} must be a positive "
                f"multiple of block_size={block_size} (chunks after the "
                "first must start block-aligned)"
            )
        self.prefill_chunk = min(prefill_chunk, ctx)

        if prefill_buckets is None:
            prefill_buckets = default_prefill_buckets(ctx, min_bucket)
        ladder = tuple(sorted(set(prefill_buckets)))
        if not ladder or ladder[-1] > ctx:
            raise ValueError(
                f"prefill buckets {ladder} must be non-empty and <= "
                f"context_length={ctx}"
            )
        if ladder[-1] < ctx:
            ladder = ladder + (ctx,)
        # Chunk program shapes: the bucket ladder capped at the chunk size
        # (a chunk is never longer than prefill_chunk, so larger buckets
        # would never compile anyway — the compile bound only shrinks).
        chunk_ladder = tuple(b for b in ladder if b < self.prefill_chunk)
        self.buckets = chunk_ladder + (self.prefill_chunk,)

        # Pool capacity: default exactly the dense slot pool's (every slot
        # can hold a full context) + the reserved trash block; prefix
        # sharing makes the same capacity serve MORE concurrent work.
        if num_blocks is None:
            num_blocks = slots * self.blocks_per_slot + 1
        self.allocator = BlockAllocator(num_blocks, block_size)
        self.prefix_cache = (
            RadixPrefixCache(self.allocator) if prefix_cache else None
        )

        act_dtype = jnp.dtype(config.activation_dtype)
        # Compute-dtype cast + optional per-channel int8 quantization:
        # every program (chunk prefill, tick, spec verify) then streams
        # 1-byte weights and dequantizes in registers.
        (
            self._params, self._lm_head, self.weight_dtype,
            self.params_bytes, self.tick_weight_bytes,
        ) = prepare_serving_weights(params, config, weight_dtype)
        self.fused_sampling = bool(fused_sampling)
        self._pool = init_kv_pool(
            config, num_blocks, block_size, act_dtype, kv_dtype=kv_dtype
        )
        #: "int8" for quantized pools, else the activation dtype name —
        #: the /statusz + stats() label.
        self.kv_dtype = kv_dtype or str(act_dtype)
        kv_heads = config.num_kv_heads or config.num_heads
        itemsize = 1 if kv_dtype == "int8" else act_dtype.itemsize
        #: Resident bytes of the whole KV pool (scale pools included):
        #: int8 quarters the f32 pool (halves bf16) at fixed block count —
        #: or, held fixed, buys 2-4x the blocks.
        self.kv_pool_bytes = sum(
            int(arr.size) * arr.dtype.itemsize
            for layer in self._pool
            for arr in layer.values()
        )
        #: KV footprint per token POSITION at pool width across all layers
        #: (k + v) — the unit of the attention READ stream, which scales
        #: with context and dominates the decode tick's HBM traffic; this
        #: is the knob int8 halves (vs bf16).  NOT a write-traffic
        #: counter: int8's decode scatter is a whole-block rescale RMW
        #: (~block_size rows, bounded at one block per slot per layer),
        #: amortized small against the context-sized read.
        self.kv_bytes_per_token = (
            2 * config.num_layers * kv_heads * config.d_head * itemsize
        )

        self._tables = np.zeros((slots, self.blocks_per_slot), np.int32)
        self._tokens = np.zeros(slots, np.int32)
        self._positions = np.zeros(slots, np.int32)
        self._active = np.zeros(slots, bool)
        self._keys = np.zeros((slots, 2), np.uint32)
        self._temps = np.zeros(slots, np.float32)
        self._top_ks = np.full(slots, TOP_K_DISABLED, np.int32)
        self._top_ps = np.full(slots, TOP_P_DISABLED, np.float32)
        self._slots: list[PagedSlotInfo | None] = [None] * slots
        self._prefilling: list[int] = []  # slots mid-prefill, begin order

        # Per-engine jit closures: compiled_programs() is an exact
        # per-engine compile counter, as in the dense engine.
        self._chunk_jit = jax.jit(
            functools.partial(
                _chunk_program, config=config, block_size=block_size
            )
        )
        self._tick_jit = jax.jit(
            functools.partial(
                _paged_tick_program, config=config, block_size=block_size,
                fused=self.fused_sampling,
            )
        )
        # Copy-on-write block copy (rewind into a shared block): compiled
        # only the first time a CoW rewind actually runs.  Per-engine
        # partial for the same reason as the migration jits below — a
        # bare ``jax.jit(fn)`` shares one cache across engines (keyed by
        # function identity), which would make compiled_programs() read
        # ANOTHER engine's CoW compile as this engine's.
        self._copy_jit = jax.jit(functools.partial(_copy_block_program))
        # KV migration halves (ISSUE 15): per-block extract (export) and
        # inject (import) — each compiled only when a migration runs, and
        # ONCE regardless of chain length (traced block ids).  Wrapped in
        # per-engine partials so compiled_programs() stays an exact
        # per-engine counter (bare ``jax.jit(fn)`` wrappers share one
        # cache across engines, keyed by function identity).
        self._extract_jit = jax.jit(
            functools.partial(_extract_block_program)
        )
        self._inject_jit = jax.jit(functools.partial(_inject_block_program))

        self.ticks = 0
        self.tokens_emitted = 0

    # ------------------------------------------------------------- queries

    @property
    def active_count(self) -> int:
        return int(self._active.sum())

    @property
    def free_slots(self) -> int:
        return sum(1 for info in self._slots if info is None)

    def compiled_programs(self) -> int:
        """XLA programs compiled by this engine so far — bounded by
        ``len(self.buckets) + 1`` (one chunk program per bucket + the
        tick), plus one more once a copy-on-write :meth:`rewind` has
        run, and one each for the migration extract/inject programs once
        an :meth:`export_slot`/:meth:`import_slot` has run (a pure
        decode-role replica therefore stays within tick + inject — the
        chunk ladder never compiles there)."""
        return (
            self._chunk_jit._cache_size()
            + self._tick_jit._cache_size()
            + self._copy_jit._cache_size()
            + self._extract_jit._cache_size()
            + self._inject_jit._cache_size()
        )

    def bucket_for(self, length: int) -> int:
        """The smallest chunk bucket holding ``length`` tokens (lengths
        beyond the chunk size run as multiple chunks of the largest)."""
        for b in self.buckets:
            if length <= b:
                return b
        return self.buckets[-1]

    def slot_bucket(self, slot: int) -> int | None:
        """The slot's first computed chunk bucket (metrics labeling)."""
        info = self._slots[slot]
        return None if info is None else info.bucket

    def slot_shared_len(self, slot: int) -> int:
        """Prompt tokens the slot reused from the prefix cache."""
        info = self._slots[slot]
        return 0 if info is None else info.shared_len

    def pending_prefills(self) -> tuple[int, ...]:
        """Slots with prefill chunks still to run, in begin order."""
        return tuple(self._prefilling)

    def prefill_remaining(self, slot: int) -> int:
        info = self._slots[slot]
        if info is None:
            return 0
        return info.prompt_len - info.next_pos

    def next_chunk_tokens(self, slot: int) -> int:
        """The token cost of the next :meth:`prefill_step` on ``slot``
        (what the serving worker charges against its per-tick budget)."""
        return min(self.prefill_chunk, self.prefill_remaining(slot))

    def pending_prefill_tokens(self) -> int:
        return sum(self.prefill_remaining(s) for s in self._prefilling)

    def gauges(self) -> dict:
        """The kvpool operational gauges (/metrics + kind="kvpool")."""
        out = self.allocator.gauges()
        if self.prefix_cache is not None:
            out.update(self.prefix_cache.gauges())
        else:
            out.update(
                {
                    "prefix_cache_hits": 0,
                    "prefix_cache_misses": 0,
                    "prefix_hit_rate": None,
                    "prefix_cache_nodes": 0,
                }
            )
        out["prefill_pending_tokens"] = self.pending_prefill_tokens()
        out["prefill_pending_slots"] = len(self._prefilling)
        out["kv_pool_bytes"] = self.kv_pool_bytes
        out["kv_bytes_per_token"] = self.kv_bytes_per_token
        return out

    def slot_states(self) -> list[dict]:
        """Per-slot occupancy snapshot (the ``/statusz`` view), extended
        with paged-memory facts: blocks held, shared-prefix tokens, and
        prefill progress for slots still chunking."""
        states: list[dict] = []
        for slot in range(self.n_slots):
            info = self._slots[slot]
            if info is None:
                states.append({"slot": slot, "active": False})
                continue
            states.append(
                {
                    "slot": slot,
                    "active": bool(self._active[slot]),
                    "position": int(self._positions[slot]),
                    "prompt_len": info.prompt_len,
                    "bucket": info.bucket,
                    "generated": info.generated,
                    "max_new_tokens": info.max_new_tokens,
                    "blocks": len(info.block_ids),
                    "shared_prefix_tokens": info.shared_len,
                    "prefill_pos": info.next_pos,
                    "request_id": info.request_id,
                }
            )
        return states

    # ------------------------------------------------------------ lifecycle

    def _validate(self, prompt: np.ndarray, max_new_tokens: int) -> None:
        plen = prompt.shape[0]
        ctx = self.config.context_length
        if plen < 1:
            raise ValueError("prompt must contain at least one token")
        if plen > ctx - 1:
            raise ValueError(
                f"prompt of {plen} tokens leaves no room to generate in a "
                f"context of {ctx}"
            )
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )

    def blocks_needed(self, prompt_len: int, max_new_tokens: int) -> int:
        """Worst-case block reservation for one request (before any
        prefix-cache credit): every position the request may ever write."""
        ctx = self.config.context_length
        eff = min(max_new_tokens, ctx - prompt_len)
        span = min(prompt_len + eff, ctx)
        return -(-span // self.block_size)  # ceil

    def _alloc_blocks(self, n: int) -> list:
        """Allocate ``n`` fresh blocks, evicting prefix-cache LRU leaves to
        cover a shortfall first (the same discipline :meth:`begin` applies
        to admissions); raises :class:`NoFreeBlocksError` when the pool
        cannot cover it even then."""
        shortfall = n - self.allocator.free_count
        if shortfall > 0 and self.prefix_cache is not None:
            self.prefix_cache.evict(shortfall)
        return self.allocator.alloc(n)

    def extend_blocks(self, slot: int, upto_len: int) -> None:
        """Grow ``slot``'s block chain to cover ``upto_len`` token
        positions (speculative-decoding scratch: the verify pass writes a
        few positions beyond the admission's worst-case reservation, and
        :meth:`rewind` returns whatever the acceptance didn't keep).
        Raises :class:`NoFreeBlocksError` when the pool is dry — the
        caller shrinks its speculation window instead of parking."""
        info = self._slots[slot]
        if info is None:
            raise ValueError(f"slot {slot} is not occupied")
        need = -(-min(upto_len, self.config.context_length) // self.block_size)
        extra = need - len(info.block_ids)
        if extra <= 0:
            return
        fresh = self._alloc_blocks(extra)
        start = len(info.block_ids)
        info.block_ids.extend(fresh)
        self._tables[slot, start: start + len(fresh)] = fresh

    def rewind(
        self, slot: int, new_len: int, *, keep_blocks: int | None = None
    ) -> dict:
        """Roll ``slot``'s written-KV frontier back to ``new_len`` tokens:
        positions ``0 .. new_len-1`` stay valid, everything beyond is
        abandoned (speculative-decoding rejection, or any host-side
        re-scoring that truncates a sequence).

        * **frontier rollback within a block** is pure bookkeeping — the
          abandoned rows stay in the pool but every reader masks keys by
          the slot's position, so they are invisible until overwritten;
        * **block release across boundaries** — chain blocks wholly beyond
          the frontier are deref'd (returned to the pool when this was the
          last reference).  ``keep_blocks`` floors the chain length:
          mid-flight callers pass their admission-time reservation so a
          rewind can never give away blocks the request still needs to
          finish (only speculative scratch beyond it is released);
        * **copy-on-write** — if the block the NEXT write lands in is
          shared (radix-indexed, or referenced by another slot), it is
          replaced by a fresh device copy and the shared copy is never
          mutated.  The copy may evict prefix-cache leaves and raises
          :class:`NoFreeBlocksError` when the pool cannot supply the
          replacement block;
        * **int8 pools** — block scales are monotone within an occupancy:
          a rewound row's magnitude stays folded into its block's scale
          until the block is fully vacated (the next write at offset 0
          resets it).  Valid rows keep their values (they were rescaled by
          ``old/new`` whenever the scale grew); writes after the rewind
          quantize against the possibly-inflated scale, so their precision
          is bounded by it — the cost of per-block scales, documented
          rather than repaired.

        Returns ``{"released": n_blocks, "cow": bool}``.  The caller owns
        position/sampling state — this is a KV-memory primitive.
        """
        info = self._slots[slot]
        if info is None:
            raise ValueError(f"slot {slot} is not occupied")
        if slot in self._prefilling:
            raise ValueError(f"slot {slot} is mid-prefill; cannot rewind")
        if new_len < 0 or new_len > self.config.context_length:
            raise ValueError(
                f"new_len={new_len} outside [0, "
                f"{self.config.context_length}]"
            )
        bs = self.block_size
        needed = -(-new_len // bs)
        floor = max(needed, keep_blocks or 0)
        released = 0
        if floor < len(info.block_ids):
            dropped = info.block_ids[floor:]
            info.block_ids = info.block_ids[:floor]
            self.allocator.deref(dropped)
            released = len(dropped)
            self._tables[slot, floor:] = 0
        # The block the next write lands in must be exclusively owned:
        # rewinding into a radix-shared region would otherwise scribble
        # over blocks other chains still read.
        cow = False
        idx = new_len // bs
        if idx < len(info.block_ids):
            shared = info.block_ids[idx]
            if self.allocator.refcount(shared) > 1:
                fresh = self._alloc_blocks(1)[0]
                self._pool = self._copy_jit(
                    self._pool, np.int32(shared), np.int32(fresh)
                )
                self.allocator.deref([shared])
                info.block_ids[idx] = fresh
                self._tables[slot, idx] = fresh
                cow = True
        info.shared_len = min(info.shared_len, new_len)
        if self.recorder is not None:
            # Coalesced per slot: spec verify passes rewind every tick —
            # one ring entry per slot's run of rewinds, host-side only.
            self.recorder.record(
                "rewind",
                coalesce=True,
                request_id=info.request_id,
                slot=slot,
                new_len=new_len,
                released=released or None,
                cow=cow or None,
            )
        return {"released": released, "cow": cow}

    # ------------------------------------------------------------ migration

    def export_slot(self, slot: int, extra_meta: dict | None = None) -> dict:
        """Serialize ``slot`` into a self-describing migration payload
        (ISSUE 15): the slot's pool rows (per block, through one compiled
        extract program; int8 pools ship their per-block-per-head scale
        rows alongside) plus everything needed to continue the generation
        bit-for-bit on another replica — the prompt, the prefill frontier
        (mid-prefill exports allowed), and, for finished prefixes, the
        full decode state including the RNG key, so greedy AND seeded
        sampling round-trip token-identically.

        Strictly read-only: refcounts, the radix index, and every pool row
        are untouched — a radix-shared source block is never mutated (or
        released) by exporting a slot that references it.  The caller owns
        releasing the slot once the payload has landed.  ``extra_meta``
        (serving-layer fields: emitted tokens, timings, the token history
        a speculative importer re-prefills its draft from) is merged into
        the payload meta.
        """
        info = self._slots[slot]
        if info is None:
            raise ValueError(f"slot {slot} is not occupied")
        decoding = bool(self._active[slot])
        if not decoding and slot not in self._prefilling:
            raise ValueError(f"slot {slot} has no exportable state")
        # Ship only WRITTEN blocks: the chain holds the admission's
        # worst-case reservation, but rows beyond the written frontier
        # (decode: positions < position; mid-prefill: < next_pos) are
        # recycled garbage the importer re-reserves locally — shipping
        # them would inflate the transfer (the disaggregated path's
        # dominant cost) with bytes nobody reads.
        frontier = int(self._positions[slot]) if decoding else info.next_pos
        n_written = -(-frontier // self.block_size)
        ids = info.block_ids[:n_written]
        per_block = [
            jax.tree_util.tree_map(
                np.asarray, self._extract_jit(self._pool, np.int32(bid))
            )
            for bid in ids
        ]
        layers = [
            {
                name: np.stack([blk[li][name] for blk in per_block])
                for name in per_block[0][li]
            }
            for li in range(len(self._pool))
        ] if per_block else [
            {name: np.zeros((0,) + tuple(arr.shape[1:]), arr.dtype)
             for name, arr in layer.items()}
            for layer in self._pool
        ]
        kv_heads = self.config.num_kv_heads or self.config.num_heads
        meta = {
            "format": 1,
            "block_size": self.block_size,
            "kv_dtype": self.kv_dtype,
            "num_layers": self.config.num_layers,
            "kv_heads": kv_heads,
            "d_head": self.config.d_head,
            "context_length": self.config.context_length,
            "n_blocks": len(ids),
            "prompt": [int(t) for t in info.prompt],
            "prompt_len": info.prompt_len,
            "next_pos": info.next_pos,
            "decoding": decoding,
            "generated": info.generated,
            "max_new_tokens": info.max_new_tokens,
            "stop_id": info.stop_id,
            "seed": info.seed,
            "temperature": float(info.temp_enc),
            "top_k": int(info.top_k_enc),
            "top_p": float(info.top_p_enc),
            "token": int(self._tokens[slot]),
            "position": int(self._positions[slot]),
            "key": [int(k) for k in self._keys[slot]],
            "request_id": info.request_id,
        }
        if extra_meta:
            meta.update(extra_meta)
        return {"meta": meta, "layers": layers}

    def validate_import_meta(self, meta: dict) -> None:
        """Reject a payload this engine cannot graft — geometry or pool
        dtype mismatch is a configuration error, caught before any block
        is allocated (HTTP 400, not a half-grafted slot)."""
        if meta.get("format") != 1:
            raise ValueError(
                f"unsupported payload format {meta.get('format')!r}"
            )
        kv_heads = self.config.num_kv_heads or self.config.num_heads
        expect = {
            "block_size": self.block_size,
            "kv_dtype": self.kv_dtype,
            "num_layers": self.config.num_layers,
            "kv_heads": kv_heads,
            "d_head": self.config.d_head,
            "context_length": self.config.context_length,
        }
        for key, want in expect.items():
            got = meta.get(key)
            if got != want:
                raise ValueError(
                    f"payload {key}={got!r} does not match this engine's "
                    f"{want!r}"
                )
        if meta["n_blocks"] > self.blocks_per_slot:
            raise ValueError(
                f"payload carries {meta['n_blocks']} blocks; a slot here "
                f"holds at most {self.blocks_per_slot}"
            )
        need = max(
            meta["n_blocks"],
            self.blocks_needed(meta["prompt_len"], meta["max_new_tokens"]),
        )
        if need > self.allocator.usable_blocks:
            # Could NEVER land (parking would deadlock the import queue).
            raise ValueError(
                f"grafting needs {need} KV blocks; the pool holds "
                f"{self.allocator.usable_blocks}"
            )
        if not meta["decoding"] and meta["next_pos"] % self.block_size:
            raise ValueError(
                f"mid-prefill frontier {meta['next_pos']} is not "
                f"block-aligned (block_size={self.block_size})"
            )

    def validate_import_payload(self, payload: dict) -> None:
        """:meth:`validate_import_meta` plus a STRUCTURAL check of the
        shipped arrays against the meta — a payload whose header parses
        but whose rows are inconsistent (wrong shape/dtype, missing
        scale arrays, short block dimension) must fail at the transport
        (HTTP 400) rather than inside the worker thread, where the
        resulting inject error would kill the replica and leak the
        freshly allocated chain."""
        meta = payload["meta"]
        self.validate_import_meta(meta)
        layers = payload["layers"]
        if len(layers) != self.config.num_layers:
            raise ValueError(
                f"payload ships {len(layers)} layers; this engine has "
                f"{self.config.num_layers}"
            )
        names = set(self._pool[0])
        n = int(meta["n_blocks"])
        for li, (layer, pool_layer) in enumerate(zip(layers, self._pool)):
            if set(layer) != names:
                raise ValueError(
                    f"payload layer {li} arrays {sorted(layer)} do not "
                    f"match the pool's {sorted(names)}"
                )
            for name, arr in layer.items():
                want_shape = (n,) + tuple(pool_layer[name].shape[1:])
                want_dtype = pool_layer[name].dtype
                arr = np.asarray(arr)
                if tuple(arr.shape) != want_shape or arr.dtype != want_dtype:
                    raise ValueError(
                        f"payload layer {li} array {name!r} is "
                        f"{arr.dtype}{tuple(arr.shape)}; this pool wants "
                        f"{want_dtype}{want_shape}"
                    )

    def import_slot(self, payload: dict) -> int:
        """Graft a migration payload into this pool: fresh blocks
        allocated (prefix-cache LRU leaves evicted to cover a shortfall,
        :class:`NoFreeBlocksError` raised when the pool still cannot —
        the caller parks and retries), rows scattered via one compiled
        per-block inject program, and the generation state restored so
        the next :meth:`tick` (or :meth:`prefill_step`, for mid-prefill
        payloads) continues bit-for-bit.  A finished prefix's full prompt
        blocks are indexed into the radix cache, so migrated sessions
        seed prefix sharing on their new home.  Returns the slot."""
        meta = payload["meta"]
        self.validate_import_payload(payload)
        free = [s for s in range(self.n_slots) if self._slots[s] is None]
        if not free:
            raise RuntimeError("no free slot")
        slot = free[0]
        # The payload ships only WRITTEN blocks; the rest of the
        # admission's worst-case reservation is re-reserved locally
        # (fresh blocks, no inject — their rows get written by this
        # replica's own chunks/ticks).
        n = int(meta["n_blocks"])
        chain = max(
            n,
            self.blocks_needed(
                int(meta["prompt_len"]), int(meta["max_new_tokens"])
            ),
        )
        fresh = self._alloc_blocks(chain)
        self._tables[slot, :chain] = fresh
        self._tables[slot, chain:] = 0
        for i, dst in enumerate(fresh[:n]):
            rows = [
                {name: arr[i] for name, arr in layer.items()}
                for layer in payload["layers"]
            ]
            self._pool = self._inject_jit(self._pool, rows, np.int32(dst))

        prompt = np.asarray(meta["prompt"], np.int32)
        plen = int(meta["prompt_len"])
        info = PagedSlotInfo(
            prompt=prompt,
            prompt_len=plen,
            bucket=self.bucket_for(min(plen, self.prefill_chunk)),
            max_new_tokens=int(meta["max_new_tokens"]),
            stop_id=meta["stop_id"],
            seed=int(meta["seed"]),
            temp_enc=np.float32(meta["temperature"]),
            top_k_enc=np.int32(meta["top_k"]),
            top_p_enc=np.float32(meta["top_p"]),
            block_ids=fresh,
            shared_len=0,
            next_pos=int(meta["next_pos"]),
            generated=int(meta["generated"]),
            request_id=meta.get("request_id"),
        )
        self._slots[slot] = info
        if meta["decoding"]:
            self._tokens[slot] = int(meta["token"])
            self._positions[slot] = int(meta["position"])
            self._keys[slot] = np.asarray(meta["key"], np.uint32)
            self._temps[slot] = info.temp_enc
            self._top_ks[slot] = info.top_k_enc
            self._top_ps[slot] = info.top_p_enc
            self._active[slot] = True
            if self.prefix_cache is not None:
                full = plen // self.block_size
                if full:
                    self.prefix_cache.insert(
                        [int(t) for t in prompt[: full * self.block_size]],
                        fresh[:full],
                    )
        else:
            self._prefilling.append(slot)
        return slot

    def begin(
        self,
        prompt_ids,
        *,
        max_new_tokens: int,
        temperature: float = 1.0,
        top_k: int | None = None,
        top_p: float | None = None,
        seed: int = 0,
        stop_id: int | None = None,
        request_id: str | None = None,
    ) -> int:
        """Reserve a slot + its worst-case block chain (prefix-cache blocks
        reused by reference) and queue the prompt for chunked prefill.
        Raises ``RuntimeError`` when no slot is free,
        :class:`NoFreeBlocksError` when the pool (after cache eviction)
        cannot cover the reservation — the caller parks the admission and
        retries as decode retirements free blocks."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        self._validate(prompt, max_new_tokens)
        plen = int(prompt.shape[0])
        free = [s for s in range(self.n_slots) if self._slots[s] is None]
        if not free:
            raise RuntimeError("no free slot")
        slot = free[0]

        need = self.blocks_needed(plen, max_new_tokens)
        if need > self.allocator.usable_blocks:
            raise ValueError(
                f"request needs {need} KV blocks; the pool holds "
                f"{self.allocator.usable_blocks}"
            )
        matched: list[int] = []
        if self.prefix_cache is not None:
            matched = self.prefix_cache.match([int(t) for t in prompt])
        try:
            fresh = self._alloc_blocks(need - len(matched))
        except NoFreeBlocksError:
            if matched:
                self.allocator.deref(matched)
            raise
        block_ids = matched + fresh
        self._tables[slot, : len(block_ids)] = block_ids
        self._tables[slot, len(block_ids):] = 0

        shared_len = len(matched) * self.block_size
        if self.prefix_cache is not None:
            # Charged only now that the admission proceeds: a parked
            # (block-starved) request re-matches on every retry and must
            # not inflate the hit/miss counters.
            self.prefix_cache.charge(plen, shared_len)
        ctx = self.config.context_length
        info = PagedSlotInfo(
            prompt=prompt,
            prompt_len=plen,
            bucket=self.bucket_for(min(plen - shared_len, self.prefill_chunk)),
            max_new_tokens=min(max_new_tokens, ctx - plen),
            stop_id=stop_id,
            seed=seed,
            temp_enc=np.float32(temperature),
            top_k_enc=np.int32(TOP_K_DISABLED if top_k is None else top_k),
            top_p_enc=np.float32(TOP_P_DISABLED if top_p is None else top_p),
            block_ids=block_ids,
            shared_len=shared_len,
            next_pos=shared_len,
            request_id=request_id,
        )
        self._slots[slot] = info
        self._prefilling.append(slot)
        return slot

    def prefill_step(self, slot: int) -> TickEvent | None:
        """Run ONE prefill chunk for ``slot``.  Returns ``None`` while
        chunks remain; on the final chunk, samples the request's first
        token, activates the slot for decode ticks, indexes the prompt's
        full blocks into the prefix cache, and returns the admission
        :class:`TickEvent` (exactly the dense engine's ``admit`` result)."""
        info = self._slots[slot]
        if info is None or slot not in self._prefilling:
            raise ValueError(f"slot {slot} has no pending prefill")
        plen = info.prompt_len
        chunk_len = min(self.prefill_chunk, plen - info.next_pos)
        bucket = self.bucket_for(chunk_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :chunk_len] = info.prompt[
            info.next_pos: info.next_pos + chunk_len
        ]
        final = info.next_pos + chunk_len == plen
        # Key discipline = dense prefill: the request key is split ONCE, on
        # the final chunk; earlier chunks get a throwaway key and their
        # sampled token/key outputs are discarded.
        key_in = jax.random.PRNGKey(info.seed)
        tok, key, self._pool = self._chunk_jit(
            self._params, self._lm_head, self._pool,
            self._tables[slot], padded, np.int32(info.next_pos),
            np.int32(chunk_len), key_in, info.temp_enc, info.top_k_enc,
            info.top_p_enc,
        )
        info.next_pos += chunk_len
        if not final:
            return None

        self._prefilling.remove(slot)
        token = int(tok)
        self._tokens[slot] = token
        self._positions[slot] = plen
        self._keys[slot] = np.asarray(key)
        self._temps[slot] = info.temp_enc
        self._top_ks[slot] = info.top_k_enc
        self._top_ps[slot] = info.top_p_enc
        self._active[slot] = True
        info.generated = 1
        self.tokens_emitted += 1
        if self.prefix_cache is not None:
            full = plen // self.block_size
            if full:
                self.prefix_cache.insert(
                    [int(t) for t in info.prompt[: full * self.block_size]],
                    info.block_ids[:full],
                )
        finished = SlotPoolEngine._finish_reason(info, token)
        if finished:
            self.release(slot)
        return TickEvent(slot=slot, token=token, finished=finished)

    def admit(
        self,
        prompt_ids,
        *,
        max_new_tokens: int,
        temperature: float = 1.0,
        top_k: int | None = None,
        top_p: float | None = None,
        seed: int = 0,
        stop_id: int | None = None,
        request_id: str | None = None,
    ) -> TickEvent:
        """Dense-engine-compatible admission: begin + run every prefill
        chunk back to back (no decode interleaving).  The serving worker
        drives chunks itself for budget-interleaved scheduling; tests and
        offline batch use this."""
        slot = self.begin(
            prompt_ids,
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            seed=seed,
            stop_id=stop_id,
            request_id=request_id,
        )
        while True:
            event = self.prefill_step(slot)
            if event is not None:
                return event

    def tick(self) -> list[TickEvent]:
        """One batched decode step across every occupied slot — semantics
        identical to the dense engine's tick."""
        if not self._active.any():
            return []
        tokens, positions, keys, self._pool = self._tick_jit(
            self._params, self._lm_head, self._pool, self._tables,
            self._tokens, self._positions, self._active, self._keys,
            self._temps, self._top_ks, self._top_ps,
        )
        tokens = np.asarray(tokens)
        self._tokens = tokens.copy()
        self._positions = np.asarray(positions).copy()
        self._keys = np.asarray(keys).copy()
        self.ticks += 1

        events: list[TickEvent] = []
        for slot in np.flatnonzero(self._active):
            slot = int(slot)
            info = self._slots[slot]
            token = int(tokens[slot])
            info.generated += 1
            self.tokens_emitted += 1
            finished = SlotPoolEngine._finish_reason(info, token)
            if finished:
                self.release(slot)
            events.append(
                TickEvent(slot=slot, token=token, finished=finished)
            )
        return events

    def release(self, slot: int) -> None:
        """Free a slot: drop its block references (blocks still indexed by
        the prefix cache survive for future hits), clear its table row."""
        info = self._slots[slot]
        self._active[slot] = False
        self._slots[slot] = None
        if slot in self._prefilling:
            self._prefilling.remove(slot)
        if info is not None and info.block_ids:
            self.allocator.deref(info.block_ids)
        self._tables[slot, :] = 0
