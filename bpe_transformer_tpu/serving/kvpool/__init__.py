"""Paged KV-cache memory for the serving engine.

The PR-2 slot pool stores one dense ``context_length`` KV row per slot:
every admission pays full prefill compute and full-row HBM even when
thousands of requests share the same system prompt.  This package replaces
that with **paged memory** (the vLLM formulation, TPU-shaped):

- `blocks`   — a jax-free refcounted allocator over fixed-size KV blocks
  (``block_size`` tokens each); KV for one request is a *chain of block
  ids*, not a contiguous row, so memory is provisioned by tokens actually
  written rather than worst-case context;
- `radix`    — a jax-free token-trie mapping prompt prefixes (at block
  granularity) to frozen block chains, so a shared system prompt is
  prefilled ONCE and subsequently reference-counted copy-on-write —
  shared blocks are never written again, new requests only allocate and
  compute their unshared suffix;
- `paged_engine` — the `PagedEngine`: the slot-pool engine's contract
  (admit / tick / release, one jitted tick, bounded compile count) on top
  of the block pool, with **chunked prefill** — long prompts prefill in
  fixed-size chunks the serving worker interleaves with decode ticks so
  heavy prefill traffic cannot starve decode latency;
- `migrate`  — the jax-free KV migration payload codec (ISSUE 15):
  `PagedEngine.export_slot` serializes a slot (block rows + scale rows +
  generation state) into a self-describing payload, `import_slot` grafts
  it into another replica's pool bit-for-bit — the transport under
  disaggregated prefill/decode serving and drain evacuation.

`blocks`, `radix`, and `migrate` import no jax (the router and tests
reason about them on chip-free hosts); `paged_engine` owns the device
programs.
"""

from bpe_transformer_tpu._lazy import lazy_attrs

__getattr__ = lazy_attrs(
    __name__,
    {
        "BlockAllocator": "blocks",
        "NoFreeBlocksError": "blocks",
        "RadixPrefixCache": "radix",
        "PagedEngine": "paged_engine",
        "payload_to_bytes": "migrate",
        "payload_from_bytes": "migrate",
        "payload_nbytes": "migrate",
        "synthetic_decode_payload": "migrate",
    },
)

__all__ = [
    "BlockAllocator",
    "NoFreeBlocksError",
    "PagedEngine",
    "RadixPrefixCache",
    "payload_from_bytes",
    "payload_nbytes",
    "payload_to_bytes",
    "synthetic_decode_payload",
]
