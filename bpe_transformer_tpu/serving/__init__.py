"""Continuous-batching inference serving.

The ROADMAP's "serve heavy traffic" leg: a fixed-capacity slot pool of
batched KV caches (`engine`), a paged block-pool alternative with radix
prefix sharing and chunked prefill (`kvpool`), a FIFO admission queue with
backpressure, deadlines, max-wait batching, and a chunked-prefill token
budget (`scheduler`), the request/transport layer — blocking + streaming
generation, offline batch files, a stdlib HTTP endpoint — behind
``bpe-tpu serve`` (`server`), and a jax-free fleet `router` that balances
requests across N replicas off their /statusz health surface
(``bpe-tpu route``).

Everything runs under ``JAX_PLATFORMS=cpu`` with tiny configs, so the full
engine is tier-1-testable; on TPU the same programs serve at chip speed.

PEP-562 lazy exports: the jax-free members (`FifoScheduler`,
`PrefillBudget`, `Router`, the kvpool host-side bookkeeping) must be
importable on hosts with no accelerator runtime — the engine/server
modules (which import jax) only load when their symbols are touched.
"""

from bpe_transformer_tpu._lazy import lazy_attrs

__getattr__ = lazy_attrs(
    __name__,
    {
        "SlotPoolEngine": "engine",
        "TickEvent": "engine",
        "default_prefill_buckets": "engine",
        "LatencyHistogram": "metrics",
        "ServingMetrics": "metrics",
        "render_prometheus": "metrics",
        "FifoScheduler": "scheduler",
        "PrefillBudget": "scheduler",
        "QueueFullError": "scheduler",
        "Request": "server",
        "RequestHandle": "server",
        "Result": "server",
        "ServingEngine": "server",
        "make_http_server": "server",
        "PagedEngine": "kvpool.paged_engine",
        "NoFreeBlocksError": "kvpool.blocks",
        "DraftSpec": "spec.draft",
        "DraftModel": "spec.draft",
        "SpecEngine": "spec.engine",
        "Router": "router",
        "make_router_http_server": "router",
    },
)

__all__ = [
    "DraftModel",
    "DraftSpec",
    "FifoScheduler",
    "LatencyHistogram",
    "NoFreeBlocksError",
    "PagedEngine",
    "SpecEngine",
    "PrefillBudget",
    "QueueFullError",
    "Request",
    "RequestHandle",
    "Result",
    "Router",
    "ServingEngine",
    "ServingMetrics",
    "SlotPoolEngine",
    "TickEvent",
    "default_prefill_buckets",
    "make_http_server",
    "make_router_http_server",
    "render_prometheus",
]
