"""Continuous-batching inference serving.

The ROADMAP's "serve heavy traffic" leg: a fixed-capacity slot pool of
batched KV caches (`engine`), a FIFO admission queue with backpressure,
deadlines, and max-wait batching (`scheduler`), and the request/transport
layer — blocking + streaming generation, offline batch files, a stdlib
HTTP endpoint — behind ``bpe-tpu serve`` (`server`).

Everything runs under ``JAX_PLATFORMS=cpu`` with tiny configs, so the full
engine is tier-1-testable; on TPU the same programs serve at chip speed.
"""

from bpe_transformer_tpu.serving.engine import (
    SlotPoolEngine,
    TickEvent,
    default_prefill_buckets,
)
from bpe_transformer_tpu.serving.metrics import (
    LatencyHistogram,
    ServingMetrics,
    render_prometheus,
)
from bpe_transformer_tpu.serving.scheduler import FifoScheduler, QueueFullError
from bpe_transformer_tpu.serving.server import (
    Request,
    RequestHandle,
    Result,
    ServingEngine,
    make_http_server,
)

__all__ = [
    "FifoScheduler",
    "LatencyHistogram",
    "QueueFullError",
    "Request",
    "RequestHandle",
    "Result",
    "ServingEngine",
    "ServingMetrics",
    "SlotPoolEngine",
    "TickEvent",
    "default_prefill_buckets",
    "make_http_server",
    "render_prometheus",
]
