"""Self-healing fleet control plane: sense -> decide -> act (ISSUE 20).

PR 12 built the fleet's senses (`bpe-tpu fleet`: aggregator, SLO burn
rates, edge-triggered alerts) and PR 14 its muscles (`/kv/export` ->
`/kv/import` migration, drain evacuation, two-tier routing).  This module
closes the loop: a **jax-free** controller (`bpe-tpu control`) polls the
aggregator's ``/statusz`` (and the router's) and ACTS:

* **hot rebalancing** — when one replica's queue/KV-headroom burn
  diverges from the fleet (session-affinity skew is a known source), it
  picks victim sessions on the hot replica and moves them to the coldest
  peer over the wire (``POST /admin/evacuate`` -> the replica's
  ``/kv/export`` -> peer ``/kv/import`` relay path);
* **tier retuning** — it watches the router's live prompt-mix window and
  adjusts the two-tier ``prefill_threshold`` split to the traffic
  actually arriving (``POST /admin/threshold``);
* **elastic capacity** — on SUSTAINED ``queue_growth`` /
  ``block_exhaustion`` alerts it spawns a replica from a pre-declared
  slot list through the supervisor machinery (crash-respawn with
  backoff, PR 5 idiom); a long-quiet fleet retires the newest spawned
  replica with SIGTERM (the replica's graceful drain evacuates its
  sessions when started with ``--evacuate-to``).

A controller that acts wrongly is worse than no controller, so every
action is wrapped in real robustness machinery:

* **per-action timeout + exponential backoff + bounded retries** — an
  actuator endpoint that hangs costs ``action_timeout_s``, not the loop;
* **action-budget crash-loop breaker** (:class:`ActionBudget`, the PR 5
  ``RollbackBudget`` idiom) — ``max_consecutive_failures`` failed
  actions without one success trips the breaker and the controller
  HALTS (observe-only until restarted), because a flapping controller
  amplifies the incident it is supposed to absorb;
* **hysteresis/cooldown per (rule, target)** — an edge-triggered alert
  or a noisy gauge cannot thrash the same replica twice inside
  ``cooldown_s``;
* **graceful degradation to observe-only** — stale fleet evidence (the
  aggregator's record is older than ``evidence_max_age_s``), an
  unreachable aggregator, or a partially-failed peer sweep each emit a
  ``kind="control"`` record saying why and hold the affected rules
  rather than acting on a wrong picture of the fleet.

Elastic capacity composes with the router's FIXED replica list via the
suspect quarantine: declare every potential slot to ``bpe-tpu route`` /
``bpe-tpu fleet`` up front — un-spawned slots sit quarantined at
near-zero poll cost, and a spawned replica rejoins on its first
successful probe.

Deliberately stdlib-only and importable without jax — it runs on the
same front-end box as the router and aggregator.
"""

from __future__ import annotations

import collections
import json
import shlex
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

from bpe_transformer_tpu.telemetry.flightrecorder import FlightRecorder

__all__ = [
    "ActionBudget",
    "ReplicaSpawner",
    "FleetController",
    "make_control_http_server",
    "main",
]


class ActionBudget:
    """Crash-loop breaker for control actions (the ``RollbackBudget``
    idiom): failures are only forgiven by real progress — here, a
    SUCCESSFUL action.  ``max_consecutive_failures`` failures in a row
    trip the breaker; a tripped controller stops acting (observe-only)
    until a human restarts it, because auto-untripping would just
    re-arm the flapping it exists to stop."""

    def __init__(self, max_consecutive_failures: int = 5):
        if max_consecutive_failures < 1:
            raise ValueError(
                "max_consecutive_failures must be >= 1, got "
                f"{max_consecutive_failures}"
            )
        self.max_consecutive_failures = max_consecutive_failures
        self.total_failures = 0
        self.consecutive = 0
        self.tripped = False

    def note(self, ok: bool) -> None:
        if ok:
            self.consecutive = 0
            return
        self.total_failures += 1
        self.consecutive += 1
        if self.consecutive >= self.max_consecutive_failures:
            self.tripped = True

    @property
    def state(self) -> str:
        return "tripped" if self.tripped else "closed"


class ReplicaSpawner:
    """Spawn/retire serve replicas from a pre-declared slot list, each
    child supervised the PR 5 way: a crash respawns it with exponential
    backoff until ``max_restarts`` consecutive failures, a retire
    SIGTERM stops it gracefully (the serve CLI drains — and evacuates,
    with ``--evacuate-to`` — before exiting).

    ``slots`` is ``[(url, argv), ...]``: the replica's base URL (as the
    router/fleet know it) and the command that serves it.  Slots start
    idle; ``spawn()`` starts the next idle one, ``retire()`` stops the
    most recently spawned.  Jax-free: children own any accelerator.
    """

    def __init__(
        self,
        slots: list[tuple[str, list[str]]],
        *,
        max_restarts: int = 3,
        backoff_s: float = 0.5,
        backoff_max_s: float = 30.0,
        log=print,
        sleep=time.sleep,
    ):
        self._slots = [
            {"url": url.rstrip("/"), "argv": list(argv), "proc": None,
             "thread": None, "retiring": False, "restarts": 0}
            for url, argv in slots
        ]
        self.max_restarts = max_restarts
        self.backoff_s = backoff_s
        self.backoff_max_s = backoff_max_s
        self._log = log
        self._sleep = sleep
        self._lock = threading.Lock()

    def active(self) -> list[str]:
        with self._lock:
            return [
                s["url"] for s in self._slots
                if s["proc"] is not None and not s["retiring"]
            ]

    def idle(self) -> int:
        with self._lock:
            return sum(1 for s in self._slots if s["proc"] is None)

    def spawn(self) -> str | None:
        """Start the next idle slot under supervision; returns its URL,
        or None when every slot is already live."""
        with self._lock:
            slot = next(
                (s for s in self._slots if s["proc"] is None), None
            )
            if slot is None:
                return None
            slot["retiring"] = False
            slot["restarts"] = 0
            slot["proc"] = subprocess.Popen(slot["argv"])
            slot["thread"] = threading.Thread(
                target=self._supervise, args=(slot,),
                name=f"spawn-{slot['url']}", daemon=True,
            )
            slot["thread"].start()
            self._log(f"controller: spawned replica {slot['url']}")
            return slot["url"]

    def _supervise(self, slot: dict) -> None:
        # The supervisor loop (resilience/supervisor.py, serving flavor):
        # a clean exit or a retire ends supervision; a crash respawns
        # with exponential backoff until the restart budget is spent.
        from bpe_transformer_tpu.resilience.supervisor import _describe_exit

        while True:
            proc = slot["proc"]
            rc = proc.wait()
            with self._lock:
                if slot["retiring"] or rc == 0:
                    slot["proc"] = None
                    slot["retiring"] = False
                    return
                slot["restarts"] += 1
                restarts = slot["restarts"]
                if restarts > self.max_restarts:
                    self._log(
                        f"controller: giving up on {slot['url']} — "
                        f"{_describe_exit(rc)}, {restarts} consecutive "
                        f"failures (max_restarts={self.max_restarts})"
                    )
                    slot["proc"] = None
                    return
            delay = min(
                self.backoff_s * (2 ** (restarts - 1)), self.backoff_max_s
            )
            self._log(
                f"controller: replica {slot['url']} {_describe_exit(rc)}; "
                f"respawning in {delay:.1f}s "
                f"({restarts}/{self.max_restarts})"
            )
            self._sleep(delay)
            with self._lock:
                if slot["retiring"]:
                    slot["proc"] = None
                    slot["retiring"] = False
                    return
                slot["proc"] = subprocess.Popen(slot["argv"])

    def retire(self, url: str | None = None) -> str | None:
        """SIGTERM the given (default: most recently spawned) live
        replica — its serve process drains gracefully; returns the URL
        retired, or None when nothing is live."""
        with self._lock:
            live = [
                s for s in self._slots
                if s["proc"] is not None and not s["retiring"]
            ]
            if url is not None:
                live = [s for s in live if s["url"] == url.rstrip("/")]
            if not live:
                return None
            slot = live[-1]
            slot["retiring"] = True
            slot["proc"].terminate()
            self._log(f"controller: retiring replica {slot['url']}")
            return slot["url"]

    def stop_all(self, timeout_s: float = 30.0) -> None:
        with self._lock:
            live = [s for s in self._slots if s["proc"] is not None]
            for slot in live:
                slot["retiring"] = True
                try:
                    slot["proc"].terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + timeout_s
        for slot in live:
            proc = slot["proc"]
            if proc is None:
                continue
            try:
                proc.wait(timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                proc.kill()

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [
                {
                    "url": s["url"],
                    "live": s["proc"] is not None and not s["retiring"],
                    "retiring": s["retiring"],
                    "restarts": s["restarts"],
                }
                for s in self._slots
            ]


class FleetController:
    """The closed loop.  One decision thread polls evidence and acts;
    HTTP handler threads read snapshots — same thread model as the
    router and aggregator.  ``run_once()`` is one sense->decide->act
    tick returning the ``kind="control"`` records it emitted (tests
    drive it directly; ``decide()`` is pure over gathered evidence)."""

    #: Decision rules, in priority order.
    RULES = ("rebalance", "retune", "scale_up", "scale_down")

    def __init__(
        self,
        fleet_url: str,
        *,
        router_url: str | None = None,
        spawner: ReplicaSpawner | None = None,
        poll_interval_s: float = 2.0,
        poll_timeout_s: float = 5.0,
        evidence_max_age_s: float = 10.0,
        cooldown_s: float = 30.0,
        action_timeout_s: float = 30.0,
        action_retries: int = 3,
        action_backoff_s: float = 0.5,
        max_consecutive_failures: int = 5,
        rebalance_min_gap: int = 3,
        rebalance_headroom_frac: float = 0.15,
        rebalance_batch: int = 1,
        retune_min_samples: int = 16,
        retune_margin: float = 0.25,
        scale_sustain_s: float = 10.0,
        scale_down_idle_s: float = 120.0,
        observe_only: bool = False,
        telemetry=None,
        clock=time.monotonic,
        wall_clock=time.time,
        sleep=time.sleep,
    ):
        self.fleet_url = self._canonical(fleet_url)
        self.router_url = (
            self._canonical(router_url) if router_url else None
        )
        self.spawner = spawner
        self.poll_interval_s = poll_interval_s
        self.poll_timeout_s = poll_timeout_s
        self.evidence_max_age_s = evidence_max_age_s
        self.cooldown_s = cooldown_s
        self.action_timeout_s = action_timeout_s
        self.action_retries = max(int(action_retries), 1)
        self.action_backoff_s = action_backoff_s
        self.rebalance_min_gap = rebalance_min_gap
        self.rebalance_headroom_frac = rebalance_headroom_frac
        self.rebalance_batch = rebalance_batch
        self.retune_min_samples = retune_min_samples
        self.retune_margin = retune_margin
        self.scale_sustain_s = scale_sustain_s
        self.scale_down_idle_s = scale_down_idle_s
        self.observe_only = observe_only
        self.budget = ActionBudget(max_consecutive_failures)
        self._telemetry = telemetry
        self._clock = clock
        self._wall = wall_clock
        self._sleep = sleep
        self._t0 = clock()
        self._lock = threading.Lock()
        #: (action, target) -> clock deadline before the pair may refire.
        self._cooldowns: dict[tuple[str, str], float] = {}
        #: Edge-triggering for hold records: the reason currently held
        #: on, so an hour of staleness is one record, not 1800.
        self._hold_reason: str | None = None
        #: Last clock time the fleet had work (scale-down idle timer).
        self._last_busy_t = clock()
        self.ticks = 0
        self.actions_ok = 0
        self.actions_failed = 0
        self.holds = 0
        self.cooldown_skips = 0
        self._recent: collections.deque = collections.deque(maxlen=256)
        self.flightrecorder = FlightRecorder("control", clock=clock)
        self._thread: threading.Thread | None = None
        self._running = False

    @staticmethod
    def _canonical(url: str) -> str:
        url = url if "://" in url else f"http://{url}"
        return url.rstrip("/")

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "FleetController":
        if self._thread is not None:
            return self
        self._running = True
        self._thread = threading.Thread(
            target=self._loop, name="control-loop", daemon=True
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self.spawner is not None:
            self.spawner.stop_all()

    def __enter__(self) -> "FleetController":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def _loop(self) -> None:
        while self._running:
            try:
                self.run_once()
            except Exception as exc:  # noqa: BLE001 — the loop must survive
                self._emit(self._record(
                    "hold", outcome="failed",
                    reason=f"tick crashed: {exc}",
                ))
                self.budget.note(False)
            time.sleep(self.poll_interval_s)

    # --------------------------------------------------------------- sense

    def _get_json(self, url: str) -> dict:
        with urllib.request.urlopen(
            url, timeout=self.poll_timeout_s
        ) as resp:
            return json.loads(resp.read())

    def _post_json(self, url: str, body: dict, timeout_s: float) -> dict:
        req = urllib.request.Request(
            url,
            data=json.dumps(body).encode("utf-8"),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return json.loads(resp.read())

    def gather(self) -> dict:
        """One evidence sweep: the aggregator's fleet surface plus (when
        configured) the router's.  Never raises — missing pieces are
        recorded so :meth:`decide` can hold the rules that need them."""
        ev: dict = {"fleet": None, "router": None, "errors": {}}
        try:
            ev["fleet"] = self._get_json(f"{self.fleet_url}/statusz")
        except (OSError, ValueError) as exc:
            ev["errors"]["fleet"] = str(exc)
        if self.router_url:
            try:
                ev["router"] = self._get_json(f"{self.router_url}/statusz")
            except (OSError, ValueError) as exc:
                ev["errors"]["router"] = str(exc)
        return ev

    def _staleness(self, ev: dict) -> str | None:
        """Why the fleet evidence cannot be acted on, or None when it
        can.  Decisions ride on the aggregator's LAST sweep; a wedged or
        dead aggregator must freeze the controller's hands, not its
        picture of a fleet that has since moved on."""
        if ev.get("fleet") is None:
            return f"fleet_unreachable: {ev['errors'].get('fleet')}"
        record = ev["fleet"].get("fleet")
        if not record:
            return "stale_evidence: aggregator has no fleet record yet"
        age = self._wall() - float(record.get("time_unix") or 0)
        if age > self.evidence_max_age_s:
            return (
                f"stale_evidence: fleet record is {age:.1f}s old "
                f"(max {self.evidence_max_age_s:.1f}s)"
            )
        return None

    @staticmethod
    def _partial_sweep(ev: dict) -> bool:
        """True when the aggregator's last sweep failed against SOME
        replica (an unreachable-but-declared host): the load picture is
        incomplete, so load-comparing rules (rebalance) must hold —
        while alert-driven scaling still acts (a dead replica is exactly
        when capacity is needed)."""
        per_replica = (ev.get("fleet") or {}).get("replicas") or []
        return any(
            not snap.get("online") and snap.get("error")
            for snap in per_replica
        )

    # -------------------------------------------------------------- decide

    def decide(self, ev: dict) -> list[dict]:
        """Pure decision pass over gathered evidence: the actions the
        rules WANT, best-first, before cooldown/budget/observe-only
        gating.  Each decision: ``{"action", "target", "reason",
        "params"}``."""
        out: list[dict] = []
        fleet_page = ev.get("fleet") or {}
        record = fleet_page.get("fleet") or {}
        per_replica = fleet_page.get("replicas") or []
        partial = self._partial_sweep(ev)

        # --- rebalance: hot/cold divergence across decode-capable,
        # paged, online replicas.
        candidates = [
            snap for snap in per_replica
            if snap.get("online") and not snap.get("draining")
            and snap.get("role") in ("decode", "both")
            and snap.get("slots")
        ]

        def load(snap):
            return (snap.get("queue_depth") or 0) + (
                snap.get("active_slots") or 0
            )

        def headroom(snap):
            total = snap.get("kv_blocks_total")
            if not total:
                return 1.0
            return (snap.get("kv_blocks_free") or 0) / total

        if len(candidates) >= 2:
            hot = max(candidates, key=load)
            cold = min(
                candidates, key=lambda s: (load(s), -headroom(s))
            )
            gap = load(hot) - load(cold)
            starved = (
                headroom(hot) < self.rebalance_headroom_frac
                and headroom(cold) >= 2 * self.rebalance_headroom_frac
            )
            if (
                hot is not cold
                and (hot.get("active_slots") or 0) >= 1
                and (cold.get("active_slots") or 0) < (cold.get("slots") or 0)
                and (gap >= self.rebalance_min_gap or starved)
            ):
                reason = (
                    f"kv headroom {headroom(hot):.2f} < "
                    f"{self.rebalance_headroom_frac:.2f} on {hot['url']}"
                    if starved else
                    f"load {load(hot)} on {hot['url']} vs {load(cold)} "
                    f"on {cold['url']} (gap >= {self.rebalance_min_gap})"
                )
                decision = {
                    "action": "rebalance",
                    "target": hot["url"],
                    "reason": reason,
                    "params": {
                        "to": cold["url"],
                        "max_sessions": self.rebalance_batch,
                    },
                }
                if partial:
                    # Incomplete load picture: the "cold" peer may just
                    # be the one the sweep could not see.
                    decision["hold"] = "partial_sweep"
                out.append(decision)

        # --- retune: router prompt-mix window vs the live threshold.
        router_page = ev.get("router")
        if router_page is not None:
            mix = router_page.get("prompt_mix") or {}
            has_prefill_tier = any(
                r.get("role") == "prefill" and r.get("available")
                for r in router_page.get("replicas") or []
            )
            if (
                has_prefill_tier
                and (mix.get("count") or 0) >= self.retune_min_samples
            ):
                # Top-quartile prompts take the two-tier path: long
                # enough that a prefill stall would hurt decode p99,
                # common enough to keep the prefill tier busy.
                desired = max(int(mix["p75"]), 2)
                current = router_page.get("prefill_threshold")
                moved_enough = current is None or abs(
                    desired - current
                ) > max(self.retune_margin * current, 1)
                if moved_enough and desired != current:
                    out.append({
                        "action": "retune",
                        "target": "router",
                        "reason": (
                            f"prompt mix p75={mix['p75']} "
                            f"(n={mix['count']}) vs threshold {current}"
                        ),
                        "params": {
                            "prefill_threshold": desired, "old": current
                        },
                    })

        # --- elastic capacity: sustained pressure alerts spawn, a
        # long-idle fleet retires (controller-spawned replicas only).
        if self.spawner is not None:
            t_now = float(record.get("t") or 0)
            sustained = [
                a for a in fleet_page.get("alerts") or []
                if a.get("rule") in ("queue_growth", "block_exhaustion")
                and t_now - float(a.get("since_t") or t_now)
                >= self.scale_sustain_s
            ]
            if sustained and self.spawner.idle() > 0:
                rules = ",".join(sorted(a["rule"] for a in sustained))
                out.append({
                    "action": "scale_up",
                    "target": "fleet",
                    "reason": f"sustained alerts: {rules} "
                    f">= {self.scale_sustain_s:.0f}s",
                    "params": {"alerts": rules},
                })
            busy = (
                (record.get("queue_depth") or 0) > 0
                or (record.get("active_slots") or 0) > 0
                or bool(fleet_page.get("alerts"))
            )
            now = self._clock()
            if busy:
                self._last_busy_t = now
            elif (
                self.spawner.active()
                and now - self._last_busy_t >= self.scale_down_idle_s
            ):
                out.append({
                    "action": "scale_down",
                    "target": self.spawner.active()[-1],
                    "reason": (
                        f"fleet idle {now - self._last_busy_t:.0f}s "
                        f">= {self.scale_down_idle_s:.0f}s"
                    ),
                    "params": {},
                })
        return out

    # ----------------------------------------------------------------- act

    def _execute(self, decision: dict) -> dict:
        """One decision -> the actuator call, with per-attempt timeout
        and exponential backoff over bounded retries.  Returns
        ``{"ok", "attempts", "detail"}``."""
        action = decision["action"]
        last = ""
        for attempt in range(self.action_retries):
            if attempt:
                self._sleep(self.action_backoff_s * (2 ** (attempt - 1)))
            try:
                if action == "rebalance":
                    out = self._post_json(
                        f"{decision['target']}/admin/evacuate",
                        {
                            "target": decision["params"]["to"],
                            "max_sessions": decision["params"][
                                "max_sessions"
                            ],
                            "timeout_s": self.action_timeout_s,
                        },
                        self.action_timeout_s + 5.0,
                    )
                    return {
                        "ok": True, "attempts": attempt + 1,
                        "detail": out,
                    }
                if action == "retune":
                    out = self._post_json(
                        f"{self.router_url}/admin/threshold",
                        {
                            "prefill_threshold": decision["params"][
                                "prefill_threshold"
                            ]
                        },
                        self.action_timeout_s,
                    )
                    return {
                        "ok": True, "attempts": attempt + 1,
                        "detail": out,
                    }
                if action == "scale_up":
                    url = self.spawner.spawn()
                    return {
                        "ok": url is not None, "attempts": attempt + 1,
                        "detail": {"url": url}
                        if url else "no idle replica slot",
                    }
                if action == "scale_down":
                    url = self.spawner.retire(decision["target"])
                    return {
                        "ok": url is not None, "attempts": attempt + 1,
                        "detail": {"url": url}
                        if url else "no live spawned replica",
                    }
                return {
                    "ok": False, "attempts": attempt + 1,
                    "detail": f"unknown action {action!r}",
                }
            except urllib.error.HTTPError as exc:
                # A 4xx is a semantic refusal (bad target, not paged):
                # retrying the same body cannot succeed.
                last = f"HTTP {exc.code}: {exc.read()[:200]!r}"
                if 400 <= exc.code < 500:
                    break
            except (OSError, ValueError) as exc:
                last = str(exc)
        return {"ok": False, "attempts": self.action_retries, "detail": last}

    # ---------------------------------------------------------------- tick

    def _record(self, action: str, **fields) -> dict:
        return {
            "kind": "control",
            "t": round(self._clock() - self._t0, 6),
            "time_unix": round(self._wall(), 3),
            "action": action,
            "breaker": self.budget.state,
            "consecutive_failures": self.budget.consecutive,
            **fields,
        }

    def _emit(self, record: dict) -> dict:
        self._recent.append(record)
        self.flightrecorder.record(
            f"control_{record['action']}",
            outcome=record.get("outcome"),
            target=record.get("target"),
            reason=record.get("reason"),
        )
        if self._telemetry is not None:
            self._telemetry.emit(record)
        return record

    def run_once(self) -> list[dict]:
        """One sense->decide->act tick; returns the control records it
        emitted (possibly none — a quiet healthy fleet is silent)."""
        with self._lock:
            self.ticks += 1
        emitted: list[dict] = []

        def hold(reason: str) -> list[dict]:
            # Edge-triggered: one record per hold episode, not per tick.
            with self._lock:
                self.holds += 1
                first = self._hold_reason != reason.split(":")[0]
                self._hold_reason = reason.split(":")[0]
            if first:
                emitted.append(self._emit(self._record(
                    "hold", outcome="held", reason=reason,
                )))
            return emitted

        if self.budget.tripped:
            return hold(
                "breaker_tripped: "
                f"{self.budget.consecutive} consecutive action failures"
            )
        ev = self.gather()
        stale = self._staleness(ev)
        if stale is not None:
            return hold(stale)
        with self._lock:
            self._hold_reason = None

        now = self._clock()
        for decision in self.decide(ev):
            key = (decision["action"], decision["target"])
            with self._lock:
                cooling = self._cooldowns.get(key, 0.0) > now
                if cooling:
                    self.cooldown_skips += 1
            if cooling:
                continue
            if decision.get("hold"):
                # The rule wanted to act but its evidence is partial:
                # observe-only, and still cool down (the next complete
                # sweep re-decides from scratch).
                with self._lock:
                    self._cooldowns[key] = now + self.cooldown_s
                emitted.append(self._emit(self._record(
                    decision["action"], outcome="observe_only",
                    target=decision["target"], reason=decision["reason"],
                    held_because=decision["hold"],
                    params=decision["params"],
                )))
                continue
            if self.observe_only:
                with self._lock:
                    self._cooldowns[key] = now + self.cooldown_s
                emitted.append(self._emit(self._record(
                    decision["action"], outcome="observe_only",
                    target=decision["target"], reason=decision["reason"],
                    params=decision["params"],
                )))
                continue
            t_act = self._clock()
            result = self._execute(decision)
            self.budget.note(result["ok"])
            with self._lock:
                self._cooldowns[key] = self._clock() + self.cooldown_s
                if result["ok"]:
                    self.actions_ok += 1
                else:
                    self.actions_failed += 1
            emitted.append(self._emit(self._record(
                decision["action"],
                outcome="ok" if result["ok"] else "failed",
                target=decision["target"], reason=decision["reason"],
                params=decision["params"],
                attempts=result["attempts"],
                dur_s=round(self._clock() - t_act, 6),
                detail=result["detail"],
            )))
            if self.budget.tripped:
                emitted.append(self._emit(self._record(
                    "hold", outcome="held",
                    reason="breaker_tripped: "
                    f"{self.budget.consecutive} consecutive action "
                    "failures — controller halting",
                )))
                with self._lock:
                    self._hold_reason = "breaker_tripped"
                break
        return emitted

    # ------------------------------------------------------------- surface

    def statusz(self) -> dict:
        with self._lock:
            recent = list(self._recent)[-32:]
            cooldowns = {
                f"{action}@{target}": round(deadline - self._clock(), 1)
                for (action, target), deadline in self._cooldowns.items()
                if deadline > self._clock()
            }
            stats = {
                "ticks": self.ticks,
                "actions_ok": self.actions_ok,
                "actions_failed": self.actions_failed,
                "holds": self.holds,
                "cooldown_skips": self.cooldown_skips,
                "hold_reason": self._hold_reason,
            }
        return {
            "uptime_s": round(self._clock() - self._t0, 3),
            "fleet_url": self.fleet_url,
            "router_url": self.router_url,
            "observe_only": self.observe_only,
            "breaker": self.budget.state,
            "consecutive_failures": self.budget.consecutive,
            "total_failures": self.budget.total_failures,
            **stats,
            "cooldowns": cooldowns,
            "spawner": (
                self.spawner.snapshot() if self.spawner else None
            ),
            "recent": recent,
            "flightrecorder": self.flightrecorder.stats(),
        }


def make_control_http_server(
    controller: FleetController, host: str = "127.0.0.1", port: int = 8300
):
    """``GET /statusz`` (loop state: breaker, cooldowns, recent actions),
    ``GET /healthz`` (ok = breaker closed), ``GET /debug/flightrecorder``
    (the decision ring, sweepable by ``bpe-tpu incident``)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # noqa: D102
            pass

        def _reply(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 (stdlib API)
            path = self.path.split("?", 1)[0]
            if path == "/healthz":
                page = controller.statusz()
                return self._reply(
                    200, {"ok": page["breaker"] == "closed", **page}
                )
            if path == "/statusz":
                return self._reply(200, controller.statusz())
            if path == "/debug/flightrecorder":
                return self._reply(
                    200, controller.flightrecorder.debug_page()
                )
            return self._reply(404, {"error": "unknown path"})

    return ThreadingHTTPServer((host, port), Handler)


def parse_spawn_slot(spec: str) -> tuple[str, list[str]]:
    """``--spawn 'URL=CMD ...'`` -> ``(url, argv)``; the command is
    shell-split (no shell runs it)."""
    url, sep, cmd = spec.partition("=")
    if not sep or not url.strip() or not cmd.strip():
        raise ValueError(
            f"--spawn wants 'URL=CMD ...', got {spec!r}"
        )
    return url.strip(), shlex.split(cmd)


def main(argv: list[str] | None = None) -> int:
    """``bpe-tpu control`` entry point (jax-free)."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="bpe-tpu control",
        description="Self-healing fleet control loop over the bpe-tpu "
        "fleet aggregator (jax-free): hot rebalancing, tier retuning, "
        "elastic capacity.",
    )
    parser.add_argument("--fleet", required=True, metavar="HOST:PORT",
                        help="fleet aggregator base URL (bpe-tpu fleet)")
    parser.add_argument("--router", default=None, metavar="HOST:PORT",
                        help="router base URL (enables tier retuning)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8300,
                        help="controller HTTP port (0: ephemeral)")
    parser.add_argument("--interval", type=float, default=2.0,
                        help="seconds between control ticks")
    parser.add_argument("--evidence-max-age", type=float, default=10.0,
                        help="hold (observe-only) when the aggregator's "
                        "fleet record is older than this")
    parser.add_argument("--cooldown", type=float, default=30.0,
                        help="per-(action, target) hysteresis window")
    parser.add_argument("--action-timeout", type=float, default=30.0,
                        help="per-attempt actuator timeout")
    parser.add_argument("--action-retries", type=int, default=3,
                        help="bounded retries per action (exponential "
                        "backoff between attempts)")
    parser.add_argument("--max-failures", type=int, default=5,
                        help="consecutive action failures before the "
                        "crash-loop breaker trips (controller halts)")
    parser.add_argument("--rebalance-gap", type=int, default=3,
                        help="queue+slots load gap between hottest and "
                        "coldest replica that triggers a rebalance")
    parser.add_argument("--scale-sustain", type=float, default=10.0,
                        help="seconds a queue_growth/block_exhaustion "
                        "alert must persist before scaling up")
    parser.add_argument("--scale-down-idle", type=float, default=120.0,
                        help="seconds of fleet idleness before retiring "
                        "a controller-spawned replica")
    parser.add_argument("--spawn", action="append", default=[],
                        metavar="URL=CMD",
                        help="declarable replica slot for elastic "
                        "capacity: base URL + the command that serves "
                        "it (repeatable; also declare URL to the "
                        "router/fleet)")
    parser.add_argument("--observe-only", action="store_true",
                        help="decide and record, never act")
    parser.add_argument("--once", action="store_true",
                        help="one control tick, print its records, exit")
    parser.add_argument("--metrics-jsonl", default=None,
                        help="write kind=control records (manifest + "
                        "footer) to this JSONL")
    args = parser.parse_args(argv if argv is not None else sys.argv[1:])

    try:
        slots = [parse_spawn_slot(spec) for spec in args.spawn]
    except ValueError as exc:
        print(f"control: {exc}", file=sys.stderr)
        return 2

    from bpe_transformer_tpu.telemetry.manifest import host_manifest
    from bpe_transformer_tpu.telemetry.sinks import MetricsLogger
    from bpe_transformer_tpu.telemetry.spans import Telemetry

    logger = MetricsLogger(jsonl_path=args.metrics_jsonl)
    telemetry = Telemetry(sink=logger.log) if args.metrics_jsonl else None
    if telemetry is not None:
        telemetry.emit(host_manifest("control"))

    spawner = ReplicaSpawner(slots) if slots else None
    controller = FleetController(
        args.fleet,
        router_url=args.router,
        spawner=spawner,
        poll_interval_s=args.interval,
        evidence_max_age_s=args.evidence_max_age,
        cooldown_s=args.cooldown,
        action_timeout_s=args.action_timeout,
        action_retries=args.action_retries,
        max_consecutive_failures=args.max_failures,
        rebalance_min_gap=args.rebalance_gap,
        scale_sustain_s=args.scale_sustain,
        scale_down_idle_s=args.scale_down_idle,
        observe_only=args.observe_only,
        telemetry=telemetry,
    )
    try:
        if args.once:
            for record in controller.run_once():
                print(json.dumps(record))
            return 0
        server = make_control_http_server(
            controller, host=args.host, port=args.port
        )
        host, port = server.server_address[:2]
        with controller:
            print(
                f"controlling on http://{host}:{port} (fleet "
                f"{args.fleet}"
                + (f", router {args.router}" if args.router else "")
                + (f", {len(slots)} spawn slot(s)" if slots else "")
                + ("; OBSERVE-ONLY" if args.observe_only else "")
                + "; GET /statusz /healthz; Ctrl-C stops)",
                flush=True,
            )
            try:
                server.serve_forever()
            except KeyboardInterrupt:
                pass
            finally:
                server.shutdown()
                server.server_close()
        return 0
    finally:
        if telemetry is not None:
            telemetry.footer(
                clean=controller.budget.state == "closed",
                actions_ok=controller.actions_ok,
                actions_failed=controller.actions_failed,
            )
        logger.close()


if __name__ == "__main__":
    sys.exit(main())
