"""Health-aware fleet router: one jax-free HTTP front over N engine
replicas (``bpe-tpu route``).

One ``bpe-tpu serve`` process owns one accelerator; serving real traffic
means a FLEET of replicas, and the fleet needs exactly two things a single
replica cannot provide: capacity-weighted spreading and survival of any
one replica draining (PR-5 rolling restarts) or dying (exit-75 respawn
window).  This router provides both from the replicas' existing
operational surface — no new protocol:

* a poller thread GETs each replica's ``/statusz`` every
  ``poll_interval_s``: ``queue_depth``, ``active_slots``/``slots``, the
  paged pool's ``kv_blocks_free``, ``draining``, ``worker_alive``, and
  the ``last_errors`` ring feed a per-replica health record; a failed
  poll marks the replica down immediately (fast failover), a healthy
  poll brings it back (rejoin after restart needs no operator action);
* ``POST /generate`` picks the healthy, non-draining replica with the
  most free capacity — weighted by free slots, free KV blocks, and queue
  depth — and proxies the request.  A refused/broken connection or a
  draining/backpressure 503 marks the replica and **re-queues the request
  on the next-best replica** (generation is deterministic per seed, so a
  replayed request returns the same tokens), so a rolling restart loses
  zero requests;
* **two-tier disaggregated scheduling** (ISSUE 15): with
  ``--prefill-threshold N`` and a fleet containing ``--role prefill``
  replicas, prompts of >= N tokens prefill on the best prefill-role
  replica (``POST /kv/export`` returns the finished prefix as a binary
  KV payload) and decode on the least-loaded decode-role replica
  (``POST /kv/import`` grafts it and runs pure ticks) — decode p99
  decouples from prompt-length variance because no decode tick ever
  waits behind a prompt-sized prefill.  Short prompts bypass straight
  to decode-capable replicas; a dead prefill tier degrades to normal
  single-tier balancing, never to an error;
* an optional ``"session"`` body key makes routing STICKY: the key hashes
  to one replica of the fixed fleet list, and while that replica is
  available it is tried first (weighted order is only the fallback on
  drain/death), so a multi-turn conversation keeps landing where its
  radix prefix blocks already live and re-prefills nothing.  The
  affinity hit rate is surfaced in ``/statusz`` + ``/metrics``;
* ``GET /statusz`` (the fleet table: per-replica health + routing
  counters) and ``GET /metrics`` (Prometheus: routed/retried/failed
  counters per replica, per-replica health gauges) make the router
  itself monitorable by the same tools (`bpe-tpu monitor --url`);
* **distributed request tracing** (ISSUE 12): every request gets a
  ``trace_id`` — an inbound ``X-Request-Id`` header honored, one minted
  otherwise — forwarded to the replica (whose serve layer adopts it as
  the ``request_id`` on its spans and slot state) and echoed back on
  EVERY response, 503/504 failures included.  With ``--metrics-jsonl``
  the router narrates its side of each request into its own telemetry
  stream: a ``router/pick`` span (replica selection), one ``router/hop``
  span per ATTEMPTED replica (connect time, time-to-first-byte, outcome
  — a failover request shows every hop it burned), and a
  ``router/request`` envelope span, all tagged ``request_id=trace_id``
  and stamped with absolute ``time_unix`` so
  ``telemetry.trace.request_timeline`` can stitch the router stream and
  the replica streams into one end-to-end timeline.

Deliberately stdlib-only and importable without jax — it runs on a
front-end box with no accelerator runtime, like ``bpe-tpu monitor``.
"""

from __future__ import annotations

import collections
import http.client
import json
import threading
import time
import urllib.request
import uuid
import zlib
from urllib.parse import urlsplit

from bpe_transformer_tpu.telemetry.flightrecorder import FlightRecorder

__all__ = ["ReplicaState", "Router", "make_router_http_server", "main"]


class ReplicaState:
    """The router's live view of one replica (mutated by the poller)."""

    __slots__ = (
        "url", "healthy", "draining", "queue_depth", "active_slots",
        "slots", "kv_blocks_free", "kv_blocks_total", "last_error",
        "last_poll_t", "consecutive_failures", "routed", "retried_away",
        "role", "suspect", "next_probe_t", "probe_backoff_s",
    )

    def __init__(self, url: str):
        self.url = url.rstrip("/")
        self.healthy = False  # unknown until the first poll
        self.draining = False
        self.queue_depth = 0
        self.active_slots = 0
        self.slots = 0
        self.kv_blocks_free = None
        self.kv_blocks_total = None
        #: Disaggregated-fleet role from /statusz (ISSUE 15): "prefill" |
        #: "decode" | "both" — pre-role replicas report nothing and
        #: default to "both".
        self.role = "both"
        self.last_error: str | None = None
        self.last_poll_t: float | None = None
        self.consecutive_failures = 0
        self.routed = 0
        self.retried_away = 0
        #: Suspect replicas (ISSUE 20): after ``suspect_after`` consecutive
        #: connect failures the replica is quarantined — excluded from
        #: routing AND from the regular poll sweep, probed only when the
        #: exponential backoff deadline (``next_probe_t``) passes.  A live
        #: request never pays a connect timeout against a host the fleet
        #: already knows is gone; a successful probe clears the flag.
        self.suspect = False
        self.next_probe_t: float | None = None
        self.probe_backoff_s = 0.0

    @property
    def available(self) -> bool:
        return self.healthy and not self.draining and not self.suspect

    def weight(self) -> float:
        """Free-capacity score (higher = more headroom): free slots are
        the primary axis, free KV blocks (paged replicas) scale it — a
        replica with slots but a starved block pool would only park
        admissions — and queued requests count against."""
        free_slots = max(self.slots - self.active_slots, 0)
        score = float(free_slots) - float(self.queue_depth)
        if self.kv_blocks_total:
            score += free_slots * (
                (self.kv_blocks_free or 0) / self.kv_blocks_total
            )
        return score

    def snapshot(self) -> dict:
        return {
            "url": self.url,
            "role": self.role,
            "healthy": self.healthy,
            "draining": self.draining,
            "available": self.available,
            "weight": round(self.weight(), 3),
            "queue_depth": self.queue_depth,
            "active_slots": self.active_slots,
            "slots": self.slots,
            "kv_blocks_free": self.kv_blocks_free,
            "kv_blocks_total": self.kv_blocks_total,
            "routed": self.routed,
            "retried_away": self.retried_away,
            "consecutive_failures": self.consecutive_failures,
            "suspect": self.suspect,
            "probe_backoff_s": round(self.probe_backoff_s, 3),
            "last_error": self.last_error,
        }


class Router:
    """Weighted balancer + failover over a fixed replica list (see module
    docstring).  Thread-safe: HTTP handler threads call :meth:`handle`
    while the poller refreshes health."""

    def __init__(
        self,
        replica_urls: list[str],
        *,
        poll_interval_s: float = 1.0,
        poll_timeout_s: float = 5.0,
        request_timeout_s: float = 600.0,
        connect_timeout_s: float = 5.0,
        prefill_threshold: int | None = None,
        suspect_after: int = 3,
        probe_backoff_s: float = 1.0,
        probe_backoff_max_s: float = 30.0,
        prompt_mix_window: int = 256,
        clock=time.monotonic,
        telemetry=None,
    ):
        if not replica_urls:
            raise ValueError("router needs at least one replica URL")
        self.replicas = [
            ReplicaState(self._canonical(url)) for url in replica_urls
        ]
        self.poll_interval_s = poll_interval_s
        self.poll_timeout_s = poll_timeout_s
        #: ``request_timeout_s`` bounds only the RESPONSE (a generation may
        #: legitimately run minutes); ``connect_timeout_s`` bounds the TCP
        #: connect, so a network-blackholed replica costs seconds before
        #: failover, not the whole request budget.
        self.request_timeout_s = request_timeout_s
        self.connect_timeout_s = connect_timeout_s
        #: Two-tier scheduling (ISSUE 15): prompts of at least this many
        #: tokens prefill on a prefill-role replica (``/kv/export``) and
        #: decode on the least-loaded decode-role replica
        #: (``/kv/import``), so decode ticks never pay a prompt-sized
        #: stall.  Shorter prompts bypass straight to decode-capable
        #: replicas.  None disables (single-tier routing) — as does a
        #: fleet with no available prefill-role replica (the threshold
        #: degrades to normal balancing, never to an error).
        self.prefill_threshold = prefill_threshold
        #: Suspect quarantine (ISSUE 20): consecutive connect failures
        #: before a replica is suspected, and the probe backoff that
        #: replaces the regular poll while it is (doubles per failed
        #: probe, capped).
        self.suspect_after = max(int(suspect_after), 1)
        self.probe_backoff_s = probe_backoff_s
        self.probe_backoff_max_s = probe_backoff_max_s
        self.suspected_total = 0
        self.probes_total = 0
        self.recoveries_total = 0
        #: Live prompt-mix window (ISSUE 20): recent prompt token counts,
        #: so the fleet controller can retune --prefill-threshold to the
        #: traffic actually arriving instead of a provisioning-time guess.
        self._prompt_mix: collections.deque = collections.deque(
            maxlen=max(int(prompt_mix_window), 1)
        )
        self.threshold_updates = 0
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        self._rr = 0  # round-robin tiebreak cursor
        self.requests_routed = 0
        self.requests_retried = 0
        self.requests_failed = 0
        #: 4xx pass-throughs: the CALLER's error, served correctly by the
        #: fleet — counted separately so client mistakes never burn the
        #: availability SLO's error budget (requests_failed stays what its
        #: help text says: requests no replica could serve).
        self.requests_client_errors = 0
        #: Session-affinity accounting: requests that carried a session
        #: key, and how many were SERVED by their sticky replica (a miss
        #: means the sticky home was draining/dead and the weighted
        #: fallback answered — its prefix blocks start cold there).
        self.session_requests = 0
        self.affinity_hits = 0
        #: Two-tier accounting: requests served via the prefill->decode
        #: migration path (export + import both landed).
        self.requests_migrated = 0
        #: Optional Telemetry: the router's OWN trace stream — pick/hop/
        #: request spans per proxied request (`bpe-tpu route
        #: --metrics-jsonl`).  Emission is direct (no nesting stack):
        #: handler threads interleave, like serving/server._span.
        self._telemetry = telemetry
        #: Always-on decision ring (telemetry/flightrecorder.py): every
        #: pick/hop/request outcome the span path already computes is teed
        #: in, sink or no sink — `bpe-tpu incident` sweeps it over
        #: GET /debug/flightrecorder next to the replicas' rings.
        self.flightrecorder = FlightRecorder("route", clock=clock)
        self._thread: threading.Thread | None = None
        self._running = False

    def _span(self, name: str, dur: float, trace_id: str, **attrs) -> None:
        """Emit one router-phase span tagged with the request's trace id.
        Spans carry absolute ``time_unix`` start stamps so cross-stream
        assembly (router + replica JSONLs) can order hops on one axis."""
        # Tee into the decision ring BEFORE the sink guard: hop outcomes
        # must be sweepable from a router run without --metrics-jsonl.
        self.flightrecorder.record(
            name,
            request_id=trace_id,
            dur_s=round(max(float(dur), 0.0), 6),
            **{k: v for k, v in attrs.items() if v is not None},
        )
        if self._telemetry is None:
            return
        dur = max(float(dur), 0.0)
        self._telemetry.emit(
            {
                "kind": "span",
                "name": name,
                "path": f"router/{name}",
                "t": round(max(self._telemetry.now() - dur, 0.0), 6),
                "dur_s": round(dur, 6),
                "request_id": trace_id,
                "time_unix": round(time.time() - dur, 6),
                **{k: v for k, v in attrs.items() if v is not None},
            }
        )

    @staticmethod
    def _canonical(url: str) -> str:
        return url if "://" in url else f"http://{url}"

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "Router":
        if self._thread is not None:
            return self
        self.poll_once()  # routing before the first poll would be blind
        self._running = True
        self._thread = threading.Thread(
            target=self._poll_loop, name="router-poller", daemon=True
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def __enter__(self) -> "Router":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def _poll_loop(self) -> None:
        while self._running:
            time.sleep(self.poll_interval_s)
            if self._running:
                self.poll_once()

    # -------------------------------------------------------------- health

    def poll_once(self) -> None:
        """Refresh every replica's health from its ``/statusz``.  Replicas
        are polled CONCURRENTLY: one blackholed host must cost one poll
        timeout, not delay the whole fleet's health refresh by N of them.

        SUSPECT replicas (>= ``suspect_after`` consecutive connect
        failures) are skipped until their exponential-backoff probe
        deadline passes — a dead host costs one connect timeout per
        probe window, not one per poll interval."""
        now = self._clock()
        due = []
        with self._lock:
            for replica in self.replicas:
                if replica.suspect:
                    if (
                        replica.next_probe_t is not None
                        and now < replica.next_probe_t
                    ):
                        continue
                    self.probes_total += 1
                due.append(replica)
        threads = [
            threading.Thread(
                target=self._poll_replica, args=(replica,), daemon=True
            )
            for replica in due
        ]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join(timeout=self.poll_timeout_s + 1.0)

    def _poll_replica(self, replica: ReplicaState) -> None:
        try:
            with urllib.request.urlopen(
                f"{replica.url}/statusz", timeout=self.poll_timeout_s
            ) as resp:
                page = json.loads(resp.read())
        except (OSError, ValueError) as exc:
            self._mark_down(replica, f"poll failed: {exc}")
            return
        kvpool = page.get("kvpool") or {}
        with self._lock:
            replica.healthy = bool(page.get("worker_alive", True))
            replica.draining = bool(page.get("draining", False))
            replica.role = str(page.get("role") or "both")
            replica.queue_depth = int(page.get("queue_depth") or 0)
            replica.slots = int(page.get("slots") or 0)
            replica.active_slots = int(page.get("active_slots") or 0)
            replica.kv_blocks_free = kvpool.get("kv_blocks_free")
            replica.kv_blocks_total = kvpool.get("kv_blocks_total")
            replica.consecutive_failures = 0
            if replica.suspect:
                # Recovery: a successful probe clears the quarantine and
                # the replica rejoins routing on the next pick.
                replica.suspect = False
                replica.next_probe_t = None
                replica.probe_backoff_s = 0.0
                self.recoveries_total += 1
                self.flightrecorder.record(
                    "suspect_cleared", replica=replica.url
                )
            replica.last_poll_t = self._clock()
            errors = page.get("last_errors") or []
            replica.last_error = (
                errors[-1].get("error")
                if errors and isinstance(errors[-1], dict)
                else None
            )

    def _mark_down(self, replica: ReplicaState, error: str) -> None:
        with self._lock:
            replica.healthy = False
            replica.consecutive_failures += 1
            replica.last_error = error
            replica.last_poll_t = self._clock()
            if replica.consecutive_failures < self.suspect_after:
                return
            # Quarantine (ISSUE 20): enough consecutive connect failures
            # that live requests must stop paying the connect timeout.
            # Each failed probe doubles the next probe's deadline, capped.
            if not replica.suspect:
                replica.suspect = True
                replica.probe_backoff_s = self.probe_backoff_s
                self.suspected_total += 1
                self.flightrecorder.record(
                    "suspect_marked", replica=replica.url,
                    failures=replica.consecutive_failures,
                )
            else:
                replica.probe_backoff_s = min(
                    replica.probe_backoff_s * 2.0, self.probe_backoff_max_s
                )
            replica.next_probe_t = self._clock() + replica.probe_backoff_s

    # -------------------------------------------------------------- routing

    def pick_order(
        self,
        session: str | None = None,
        *,
        sticky: ReplicaState | None = None,
        pool: str = "generate",
    ) -> list[ReplicaState]:
        """Available replicas, best weight first; round-robin rotation
        breaks exact ties so equal replicas share load evenly.

        ``pool`` partitions the fleet by role (ISSUE 15): ``"generate"``
        (default) is every decode-capable replica — prefill-role replicas
        never take a whole generation; ``"prefill"`` the DEDICATED
        chunk-machine tier (role ``prefill`` only: a ``both`` replica may
        be dense or already loaded with decode work, and a failed export
        there would bounce as a client error — the single-tier fallback
        already covers it); ``"decode"`` the graft-accepting tier
        (decode + both).

        A ``session`` key prepends its STICKY replica (stable hash over the
        fixed fleet list, so stickiness survives health flaps of OTHER
        replicas) when it is available — multi-turn traffic lands where its
        radix prefix blocks live; the weighted order remains the failover
        tail, so a draining/dead sticky home degrades to normal balancing
        rather than an error.  A caller that already resolved the sticky
        home passes it as ``sticky`` (skips the re-hash)."""
        roles = {
            "generate": ("decode", "both"),
            "decode": ("decode", "both"),
            "prefill": ("prefill",),
        }[pool]
        with self._lock:
            avail = [
                r for r in self.replicas
                if r.available and r.role in roles
            ]
            self._rr += 1
            rotation = self._rr
        rotated = avail[rotation % len(avail):] + avail[: rotation % len(avail)] if avail else []
        order = sorted(rotated, key=lambda r: -r.weight())
        if sticky is None and session is not None:
            sticky = self.sticky_replica(session)
        if sticky is not None and sticky in order:
            order.remove(sticky)
            order.insert(0, sticky)
        return order

    def _has_prefill_tier(self) -> bool:
        with self._lock:
            return any(
                r.available and r.role == "prefill" for r in self.replicas
            )

    def sticky_replica(self, session: str) -> ReplicaState:
        """The session's affinity home: a stable hash into the FIXED
        replica list (never the currently-available subset — availability
        churn elsewhere must not reshuffle every session)."""
        digest = zlib.crc32(str(session).encode("utf-8"))
        return self.replicas[digest % len(self.replicas)]

    def _post(
        self,
        replica: ReplicaState,
        path: str,
        body: bytes,
        trace_id: str | None = None,
        content_type: str = "application/json",
    ):
        """POST ``path`` with a short CONNECT timeout and the full
        request timeout only on the response.  Returns ``(phase, value,
        timing)``: ``("response", (status, ctype, data_bytes))`` on an
        HTTP answer, ``("connect", exc)`` when the replica was
        unreachable (safe to fail over), ``("slow", exc)`` when an
        ESTABLISHED request timed out (the generation is still running —
        replaying would duplicate it), ``("read", exc)`` when the
        connection died mid-request (replica killed — replay is safe,
        the work died with it).  ``timing`` carries ``connect_s`` and
        ``ttfb_s`` (send -> response headers; for these blocking
        endpoints the first byte arrives when the replica finishes, so
        hop ttfb ~= the replica's whole request) for the hop span.  The
        trace id is forwarded as ``X-Request-Id`` so the replica adopts
        it."""
        parts = urlsplit(replica.url)
        timing: dict = {"connect_s": None, "ttfb_s": None}
        conn = http.client.HTTPConnection(
            parts.hostname, parts.port, timeout=self.connect_timeout_s
        )
        try:
            t0 = self._clock()
            try:
                conn.connect()
            except OSError as exc:
                return "connect", exc, timing
            timing["connect_s"] = round(self._clock() - t0, 6)
            conn.sock.settimeout(self.request_timeout_s)
            headers = {"Content-Type": content_type}
            if trace_id is not None:
                headers["X-Request-Id"] = trace_id
            try:
                t_send = self._clock()
                conn.request("POST", path, body=body, headers=headers)
                resp = conn.getresponse()
                timing["ttfb_s"] = round(self._clock() - t_send, 6)
                data = resp.read()
            except TimeoutError as exc:  # socket.timeout on the read side
                return "slow", exc, timing
            except (OSError, http.client.HTTPException) as exc:
                return "read", exc, timing
            ctype = (resp.getheader("Content-Type") or "").split(";")[0]
            return "response", (resp.status, ctype, data), timing
        finally:
            conn.close()

    def _post_generate(
        self, replica: ReplicaState, body: bytes, trace_id: str | None = None
    ):
        """:meth:`_post` to /generate with the response parsed as JSON —
        the single-tier proxy hop."""
        phase, value, timing = self._post(replica, "/generate", body, trace_id)
        if phase != "response":
            return phase, value, timing
        status, _ctype, data = value
        try:
            payload = json.loads(data)
            if not isinstance(payload, dict):
                raise ValueError
        except ValueError:
            payload = {"error": data.decode("utf-8", "replace")[:200]}
        return "response", (status, payload), timing

    def handle_generate(
        self, body: bytes, trace_id: str | None = None
    ) -> tuple[int, dict]:
        """Proxy one generate request with failover: try replicas in
        weight order (the request's sticky session replica first, when it
        has one and it is available); connection failures, mid-request
        deaths, and 503s (draining replica, full queue) re-queue the
        request on the next-best replica.

        ``trace_id`` is the request's fleet-wide identity (an inbound
        ``X-Request-Id``; minted here when absent): forwarded to every
        attempted replica, stamped on the router's own spans, and
        guaranteed present in the returned payload's ``request_id`` so
        even an all-replicas-down 503 is traceable."""
        if trace_id is None:
            trace_id = uuid.uuid4().hex
        t_request = self._clock()
        route: dict = {"hops": 0, "replica": None}
        code, payload = self._route_generate(body, trace_id, route)
        payload.setdefault("request_id", trace_id)
        self._span(
            "request", self._clock() - t_request, trace_id,
            status=code, hops=route["hops"], replica=route["replica"],
        )
        return code, payload

    @staticmethod
    def _prompt_tokens(parsed: dict) -> int:
        """Approximate prompt length for the two-tier threshold:
        ``prompt_ids`` counts exactly; a text ``prompt`` is estimated at
        ~4 chars/token (the router has no tokenizer — the threshold is a
        scheduling heuristic, not a contract)."""
        ids = parsed.get("prompt_ids")
        if isinstance(ids, list):
            return len(ids)
        prompt = parsed.get("prompt")
        if isinstance(prompt, str):
            return -(-len(prompt) // 4)
        return 0

    def _route_generate(
        self, body: bytes, trace_id: str, route: dict
    ) -> tuple[int, dict]:
        session = None
        # The body is parsed once for everything the router reads out of
        # it: the sticky session key, the two-tier threshold's prompt
        # length, and the live prompt-mix window the fleet controller
        # retunes the threshold from (ISSUE 20 — the mix must be observed
        # even while the threshold is unarmed, or the controller has no
        # evidence to arm it with).
        parsed = None
        if body:
            try:
                parsed = json.loads(body)
                if isinstance(parsed, dict):
                    session = parsed.get("session")
                else:
                    parsed = None
            except ValueError:
                pass  # the replica will 400 it; routing just goes unsticky
        if parsed is not None:
            n_prompt = self._prompt_tokens(parsed)
            if n_prompt > 0:
                with self._lock:
                    self._prompt_mix.append(n_prompt)
        # Two-tier dispatch (ISSUE 15): a long prompt with a live prefill
        # tier prefills there and decodes on the least-loaded decode
        # node; everything else (short prompts, no prefill tier, no
        # threshold) takes the single-tier path below.
        if (
            self.prefill_threshold is not None
            and parsed is not None
            and self._prompt_tokens(parsed) >= self.prefill_threshold
            and self._has_prefill_tier()
        ):
            return self._route_disagg(body, trace_id, route, session)
        return self._route_single(body, trace_id, route, session)

    def _route_single(
        self, body: bytes, trace_id: str, route: dict, session
    ) -> tuple[int, dict]:
        """Single-tier proxying with failover (the pre-disaggregation
        path): weighted order over decode-capable replicas, the sticky
        session home first."""
        sticky = (
            self.sticky_replica(session) if session is not None else None
        )
        if session is not None:
            with self._lock:
                self.session_requests += 1
        t_pick = self._clock()
        order = self.pick_order(session, sticky=sticky)
        self._span(
            "pick", self._clock() - t_pick, trace_id,
            n_available=len(order), sticky=bool(sticky is not None),
        )
        if not order:
            with self._lock:
                self.requests_failed += 1
            return 503, {"error": "no available replica"}
        last_error = "no available replica"
        for i, replica in enumerate(order):
            if i > 0:
                with self._lock:
                    self.requests_retried += 1
                    order[i - 1].retried_away += 1
            # Accumulate, don't assign: a request that burned prefill-tier
            # hops before falling back here keeps them on its span.
            route["hops"] += 1
            t_hop = self._clock()
            phase, value, timing = self._post_generate(
                replica, body, trace_id
            )
            hop_dur = self._clock() - t_hop

            def hop_span(outcome, status=None):
                # One span per ATTEMPTED replica — a failover request's
                # trace shows every hop it burned, not just the winner.
                self._span(
                    "hop", hop_dur, trace_id, replica=replica.url,
                    hop=i, outcome=outcome, status=status,
                    connect_s=timing["connect_s"], ttfb_s=timing["ttfb_s"],
                )

            if phase == "response":
                status, payload = value
                if status == 200:
                    hop_span("ok", status=200)
                    route["replica"] = replica.url
                    with self._lock:
                        replica.routed += 1
                        self.requests_routed += 1
                        if sticky is not None and replica is sticky:
                            self.affinity_hits += 1
                    payload["replica"] = replica.url
                    return 200, payload
                detail = str(payload.get("error", ""))
                hop_span("backpressure" if status == 503 else "client_error",
                         status=status)
                if status == 503:
                    # Draining or backpressured: route around it.  A
                    # drain 503 means the replica is going away — flag it
                    # so new picks skip it before the next poll lands.
                    if "drain" in detail:
                        with self._lock:
                            replica.draining = True
                    last_error = f"{replica.url}: 503 {detail}"
                    continue
                # 4xx is the CALLER's error: no other replica will judge
                # it differently, so fail it through without retrying —
                # and without charging the fleet's failure counter (a
                # malformed-request storm must not page an availability
                # SLO the fleet is actually meeting).
                with self._lock:
                    self.requests_client_errors += 1
                return status, {"error": detail or f"HTTP {status}"}
            if phase == "slow":
                # The replica ACCEPTED the request and is still working:
                # it is not dead, and replaying elsewhere would run the
                # same generation twice fleet-wide.  Fail THIS request
                # through as a gateway timeout; routing state untouched.
                hop_span("slow")
                with self._lock:
                    self.requests_failed += 1
                return 504, {
                    "error": f"{replica.url} did not answer within "
                    f"{self.request_timeout_s}s (generation still "
                    "running; not replayed)"
                }
            # "connect" (unreachable) or "read" (died mid-request): the
            # replica is gone and so is any in-flight work — mark it down
            # and replay the request elsewhere.
            hop_span(f"{phase}_failed")
            self._mark_down(replica, f"{phase} failed: {value}")
            last_error = f"{replica.url}: {value}"
        with self._lock:
            self.requests_failed += 1
        return 503, {"error": f"all replicas unavailable (last: {last_error})"}

    def _route_disagg(
        self, body: bytes, trace_id: str, route: dict, session
    ) -> tuple[int, dict]:
        """The two-tier path: ``/kv/export`` on the best prefill replica
        (failover across the prefill pool), then ``/kv/import`` of the
        returned payload on the least-loaded decode replica (failover
        across the decode pool — an import replay is safe: the dead
        replica's graft died with it).  A JSON 200 from /kv/export means
        the first token already finished the request — returned as-is.
        When every prefill attempt fails, the request falls back to the
        single-tier path rather than failing (decode-capable replicas can
        always serve it whole)."""
        payload = None
        for i, replica in enumerate(self.pick_order(pool="prefill")):
            route["hops"] += 1
            t_hop = self._clock()
            phase, value, timing = self._post(
                replica, "/kv/export", body, trace_id
            )
            hop_dur = self._clock() - t_hop

            def hop_span(outcome, status=None, replica=replica,
                         timing=timing, hop_dur=hop_dur, i=i):
                self._span(
                    "hop", hop_dur, trace_id, replica=replica.url,
                    hop=i, outcome=outcome, status=status, tier="prefill",
                    connect_s=timing["connect_s"], ttfb_s=timing["ttfb_s"],
                )

            if phase == "response":
                status, ctype, data = value
                if status == 200 and ctype == "application/octet-stream":
                    hop_span("exported", status=200)
                    payload = data
                    break
                if status == 200:
                    # Finished at the first token: a complete JSON result.
                    hop_span("ok", status=200)
                    try:
                        out = json.loads(data)
                    except ValueError:
                        out = {"error": "bad replica response"}
                    route["replica"] = replica.url
                    with self._lock:
                        replica.routed += 1
                        self.requests_routed += 1
                    out["replica"] = replica.url
                    return 200, out
                hop_span(
                    "backpressure" if status == 503 else "client_error",
                    status=status,
                )
                if status == 503:
                    if b"drain" in data:
                        with self._lock:
                            replica.draining = True
                    continue
                with self._lock:
                    self.requests_client_errors += 1
                detail = data.decode("utf-8", "replace")[:200]
                return status, {"error": detail or f"HTTP {status}"}
            if phase == "slow":
                hop_span("slow")
                with self._lock:
                    self.requests_failed += 1
                return 504, {
                    "error": f"{replica.url} did not answer within "
                    f"{self.request_timeout_s}s (prefill still running; "
                    "not replayed)"
                }
            hop_span(f"{phase}_failed")
            self._mark_down(replica, f"{phase} failed: {value}")
        if payload is None:
            # No prefill tier could take it: serve whole on the decode
            # pool (strictly better than failing the request).
            return self._route_single(body, trace_id, route, session)

        # Decode tier: graft the payload, weighted least-loaded first
        # (sticky session home tried first — the migrated prefix seeds
        # its radix cache there).
        if session is not None:
            with self._lock:
                self.session_requests += 1
        last_error = "no available decode replica"
        order = self.pick_order(session, pool="decode")
        for i, replica in enumerate(order):
            route["hops"] += 1
            t_hop = self._clock()
            phase, value, timing = self._post(
                replica, "/kv/import", payload, trace_id,
                content_type="application/octet-stream",
            )
            hop_dur = self._clock() - t_hop

            def hop_span(outcome, status=None, replica=replica,
                         timing=timing, hop_dur=hop_dur, i=i):
                self._span(
                    "hop", hop_dur, trace_id, replica=replica.url,
                    hop=i, outcome=outcome, status=status, tier="decode",
                    connect_s=timing["connect_s"], ttfb_s=timing["ttfb_s"],
                )

            if phase == "response":
                status, _ctype, data = value
                try:
                    out = json.loads(data)
                    if not isinstance(out, dict):
                        raise ValueError
                except ValueError:
                    out = {"error": data.decode("utf-8", "replace")[:200]}
                if status == 200:
                    hop_span("ok", status=200)
                    route["replica"] = replica.url
                    with self._lock:
                        replica.routed += 1
                        self.requests_routed += 1
                        self.requests_migrated += 1
                        if session is not None and replica is self.sticky_replica(session):
                            self.affinity_hits += 1
                    out["replica"] = replica.url
                    return 200, out
                detail = str(out.get("error", ""))
                hop_span(
                    "backpressure" if status == 503 else "client_error",
                    status=status,
                )
                if status == 503:
                    if "drain" in detail:
                        with self._lock:
                            replica.draining = True
                    last_error = f"{replica.url}: 503 {detail}"
                    continue
                with self._lock:
                    self.requests_client_errors += 1
                return status, {"error": detail or f"HTTP {status}"}
            if phase == "slow":
                hop_span("slow")
                with self._lock:
                    self.requests_failed += 1
                return 504, {
                    "error": f"{replica.url} did not answer within "
                    f"{self.request_timeout_s}s (decode still running; "
                    "not replayed)"
                }
            # connect/read failure: the graft died with the replica —
            # replaying the payload elsewhere is safe and deterministic.
            hop_span(f"{phase}_failed")
            self._mark_down(replica, f"{phase} failed: {value}")
            last_error = f"{replica.url}: {value}"
        with self._lock:
            self.requests_failed += 1
        return 503, {
            "error": f"no decode replica could graft (last: {last_error})"
        }

    # ------------------------------------------------------------- surface

    def set_prefill_threshold(self, threshold: int | None) -> int | None:
        """Retune the two-tier split at runtime (``POST /admin/threshold``
        — the fleet controller's tier-retuning actuator).  ``None``
        disables two-tier routing; returns the new value."""
        if threshold is not None:
            threshold = int(threshold)
            if threshold < 1:
                raise ValueError("prefill_threshold must be >= 1 (or null)")
        with self._lock:
            old = self.prefill_threshold
            self.prefill_threshold = threshold
            self.threshold_updates += 1
        self.flightrecorder.record(
            "threshold_set", old=old, new=threshold
        )
        return threshold

    def prompt_mix_summary(self) -> dict:
        """Percentile summary of the recent prompt-length window — the
        evidence the controller's tier-retuning rule reads."""
        with self._lock:
            window = sorted(self._prompt_mix)
            threshold = self.prefill_threshold
        if not window:
            return {"count": 0}
        n = len(window)

        def pct(p: float) -> int:
            return window[min(int(p * (n - 1) + 0.5), n - 1)]

        return {
            "count": n,
            "mean": round(sum(window) / n, 1),
            "p25": pct(0.25),
            "p50": pct(0.50),
            "p75": pct(0.75),
            "p90": pct(0.90),
            "max": window[-1],
            "long_frac": (
                round(sum(1 for x in window if x >= threshold) / n, 4)
                if threshold is not None else None
            ),
        }

    def statusz(self) -> dict:
        with self._lock:
            replicas = [r.snapshot() for r in self.replicas]
            routed, retried, failed = (
                self.requests_routed,
                self.requests_retried,
                self.requests_failed,
            )
            client_errors = self.requests_client_errors
            sessions, hits = self.session_requests, self.affinity_hits
            migrated = self.requests_migrated
            suspected, probes, recoveries = (
                self.suspected_total, self.probes_total,
                self.recoveries_total,
            )
            threshold_updates = self.threshold_updates
        return {
            "uptime_s": round(self._clock() - self._t0, 3),
            "replicas": replicas,
            "available": sum(1 for r in replicas if r["available"]),
            "prefill_threshold": self.prefill_threshold,
            "prompt_mix": self.prompt_mix_summary(),
            "threshold_updates": threshold_updates,
            # Suspect quarantine (ISSUE 20): lifetime mark/probe/recover
            # counters plus the live count of quarantined replicas.
            "suspect": sum(1 for r in replicas if r["suspect"]),
            "suspected_total": suspected,
            "probes_total": probes,
            "recoveries_total": recoveries,
            "requests_routed": routed,
            "requests_retried": retried,
            "requests_failed": failed,
            "requests_client_errors": client_errors,
            # Two-tier scheduling (ISSUE 15): requests served through the
            # prefill->migrate->decode path.
            "requests_migrated": migrated,
            # Session affinity (sticky routing): how much multi-turn
            # traffic actually landed on its prefix-block home.
            "session_requests": sessions,
            "affinity_hits": hits,
            "affinity_hit_rate": (
                round(hits / sessions, 6) if sessions else None
            ),
            "flightrecorder": self.flightrecorder.stats(),
        }

    def blackbox_dump(self, trigger: str, force: bool = False) -> dict | None:
        """Flush the router's decision ring as a ``kind="blackbox"`` record
        with the fleet table attached; emitted to the telemetry stream when
        a sink is attached, always retained for the /debug endpoints."""
        with self._lock:
            context = {
                "replicas": [r.snapshot() for r in self.replicas],
                "requests_routed": self.requests_routed,
                "requests_retried": self.requests_retried,
                "requests_failed": self.requests_failed,
            }
        dump = self.flightrecorder.blackbox(
            trigger, context=context, force=force
        )
        if dump is not None and self._telemetry is not None:
            self._telemetry.emit(dump)
        return dump

    def prometheus_metrics(self, prefix: str = "bpe_tpu_router") -> str:
        with self._lock:
            replicas = [r.snapshot() for r in self.replicas]
            routed, retried, failed = (
                self.requests_routed,
                self.requests_retried,
                self.requests_failed,
            )
            client_errors = self.requests_client_errors
            sessions, hits = self.session_requests, self.affinity_hits
            migrated = self.requests_migrated
        # serving/metrics.py is jax-free at import: the router can share
        # the exposition formatter without touching an accelerator runtime.
        from bpe_transformer_tpu.serving.metrics import emit_prometheus

        lines: list = []

        def emit(name, kind, help_text, samples):
            emit_prometheus(lines, prefix, name, kind, help_text, samples)

        emit("requests_routed_total", "counter",
             "Requests successfully proxied to a replica.", [({}, routed)])
        emit("requests_retried_total", "counter",
             "Requests replayed on another replica after a failure/503.",
             [({}, retried)])
        emit("requests_failed_total", "counter",
             "Requests no replica could serve (4xx pass-throughs "
             "excluded — see requests_client_errors_total).",
             [({}, failed)])
        emit("requests_client_errors_total", "counter",
             "4xx responses passed through (caller's error; not an "
             "availability failure).",
             [({}, client_errors)])
        emit("session_requests_total", "counter",
             "Requests that carried a session key (sticky routing).",
             [({}, sessions)])
        emit("affinity_hits_total", "counter",
             "Session requests served by their sticky replica.",
             [({}, hits)])
        emit("requests_migrated_total", "counter",
             "Requests served via the two-tier prefill->decode KV "
             "migration path.", [({}, migrated)])
        emit("replica_healthy", "gauge", "Replica reachable and worker alive.",
             [({"replica": r["url"]}, int(r["healthy"])) for r in replicas])
        emit("replica_role", "gauge",
             "Disaggregated-fleet role per replica (1 for the labeled "
             "role).",
             [({"replica": r["url"], "role": r["role"]}, 1)
              for r in replicas])
        emit("replica_draining", "gauge", "Replica draining (rolling restart).",
             [({"replica": r["url"]}, int(r["draining"])) for r in replicas])
        emit("replica_suspect", "gauge",
             "Replica quarantined after consecutive connect failures "
             "(probed on exponential backoff).",
             [({"replica": r["url"]}, int(r["suspect"])) for r in replicas])
        emit("replicas_suspected_total", "counter",
             "Replicas marked suspect over the router's lifetime.",
             [({}, self.suspected_total)])
        emit("suspect_probes_total", "counter",
             "Backoff probes sent to suspect replicas.",
             [({}, self.probes_total)])
        emit("suspect_recoveries_total", "counter",
             "Suspect replicas cleared by a successful probe.",
             [({}, self.recoveries_total)])
        emit("replica_weight", "gauge", "Free-capacity routing weight.",
             [({"replica": r["url"]}, r["weight"]) for r in replicas])
        emit("replica_routed_total", "counter", "Requests routed per replica.",
             [({"replica": r["url"]}, r["routed"]) for r in replicas])
        return "\n".join(lines) + "\n"


def make_router_http_server(
    router: Router, host: str = "127.0.0.1", port: int = 8100
):
    """A `ThreadingHTTPServer` front for the router: ``POST /generate``
    (proxied with failover), ``GET /statusz`` (fleet table), ``GET
    /metrics`` (Prometheus), ``GET /healthz``, plus the forensics pair —
    ``GET /debug/flightrecorder`` (the live decision ring) and ``POST
    /debug/dump`` (force a black-box flush).  ``port=0`` binds an
    ephemeral port; the caller owns ``serve_forever()``/``shutdown()``."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # noqa: D102
            pass

        def _reply(
            self, code: int, payload: dict, request_id: str | None = None
        ) -> None:
            body = json.dumps(payload).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            if request_id is not None:
                # Echoed on EVERY proxied response — the all-replicas-down
                # 503 and the not-replayed 504 read-timeout included — so
                # a client-side failure report carries the id that finds
                # the request in the router/replica trace streams.
                self.send_header("X-Request-Id", request_id)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 (stdlib API)
            path = self.path.split("?", 1)[0]
            if path == "/healthz":
                page = router.statusz()
                return self._reply(
                    200, {"ok": page["available"] > 0, **page}
                )
            if path == "/statusz":
                return self._reply(200, router.statusz())
            if path == "/metrics":
                body = router.prometheus_metrics().encode("utf-8")
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return None
            if path == "/debug/flightrecorder":
                return self._reply(200, router.flightrecorder.debug_page())
            return self._reply(404, {"error": "unknown path"})

        def do_POST(self):  # noqa: N802 (stdlib API)
            if self.path == "/debug/dump":
                dump = router.blackbox_dump("manual", force=True)
                return self._reply(200, dump)
            if self.path == "/admin/threshold":
                # Runtime tier retuning (ISSUE 20): the fleet controller
                # adjusts the two-tier split to the live prompt mix.
                length = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                    if not isinstance(body, dict):
                        raise ValueError("body must be a JSON object")
                    new = router.set_prefill_threshold(
                        body.get("prefill_threshold")
                    )
                except (ValueError, TypeError) as exc:
                    return self._reply(400, {"error": str(exc)})
                return self._reply(200, {"prefill_threshold": new})
            if self.path != "/generate":
                return self._reply(404, {"error": "unknown path"})
            trace_id = (self.headers.get("X-Request-Id") or "").strip()
            trace_id = trace_id[:128] or uuid.uuid4().hex
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length) or b"{}"
            code, payload = router.handle_generate(body, trace_id=trace_id)
            return self._reply(code, payload, request_id=trace_id)

    return ThreadingHTTPServer((host, port), Handler)


def main(argv: list[str] | None = None) -> int:
    """``bpe-tpu route`` entry point (jax-free)."""
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        prog="bpe-tpu route",
        description="Health-aware HTTP router over bpe-tpu serve replicas "
        "(jax-free).",
    )
    parser.add_argument("--replica", action="append", required=True,
                        metavar="HOST:PORT",
                        help="replica base URL (repeatable)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8100,
                        help="router HTTP port (0: ephemeral)")
    parser.add_argument("--poll-interval", type=float, default=1.0,
                        help="seconds between replica health polls")
    parser.add_argument("--request-timeout", type=float, default=600.0,
                        help="seconds to wait for a replica's RESPONSE "
                        "(generations may run long; a timeout is NOT "
                        "replayed — the work is still running)")
    parser.add_argument("--connect-timeout", type=float, default=5.0,
                        help="seconds to wait for a replica's TCP connect "
                        "(failover to the next replica after)")
    parser.add_argument("--prefill-threshold", type=int, default=None,
                        metavar="TOKENS",
                        help="two-tier disaggregated scheduling: prompts "
                        "of >= TOKENS prefill on a --role prefill replica "
                        "(/kv/export) and decode on the least-loaded "
                        "decode replica (/kv/import); shorter prompts "
                        "bypass straight to decode nodes (default: "
                        "single-tier routing); retunable at runtime via "
                        "POST /admin/threshold")
    parser.add_argument("--suspect-after", type=int, default=3,
                        metavar="N",
                        help="consecutive connect failures before a "
                        "replica is quarantined as suspect and probed on "
                        "exponential backoff instead of every poll")
    parser.add_argument("--metrics-jsonl", default=None,
                        help="write the router's trace stream (pick/hop/"
                        "request spans per proxied request, manifest + "
                        "footer) to this JSONL; one trace_id joins it to "
                        "the replicas' streams")
    args = parser.parse_args(argv if argv is not None else sys.argv[1:])

    from bpe_transformer_tpu.telemetry.manifest import host_manifest
    from bpe_transformer_tpu.telemetry.sinks import MetricsLogger
    from bpe_transformer_tpu.telemetry.spans import Telemetry

    logger = MetricsLogger(jsonl_path=args.metrics_jsonl)
    telemetry = Telemetry(sink=logger.log) if args.metrics_jsonl else None
    if telemetry is not None:
        # host_manifest, not run_manifest: the router must never touch a
        # jax backend as a side effect of writing its stream header.
        telemetry.emit(host_manifest("route"))

    router = Router(
        args.replica,
        poll_interval_s=args.poll_interval,
        request_timeout_s=args.request_timeout,
        connect_timeout_s=args.connect_timeout,
        prefill_threshold=args.prefill_threshold,
        suspect_after=args.suspect_after,
        telemetry=telemetry,
    )
    server = make_router_http_server(router, host=args.host, port=args.port)
    host, port = server.server_address[:2]
    try:
        with router:
            available = sum(1 for r in router.replicas if r.available)
            print(
                f"routing on http://{host}:{port} over {len(router.replicas)} "
                f"replicas ({available} available; POST /generate, GET /healthz "
                "/metrics /statusz; Ctrl-C stops)",
                flush=True,
            )
            try:
                server.serve_forever()
            except KeyboardInterrupt:
                pass
            finally:
                server.shutdown()
                server.server_close()
    finally:
        if telemetry is not None:
            telemetry.footer(
                clean=True, requests=router.requests_routed,
                failed=router.requests_failed,
            )
        logger.close()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
