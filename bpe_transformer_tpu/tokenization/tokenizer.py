"""Byte-pair-encoding tokenizer: encode/decode + bounded-memory streaming.

Behavioral parity targets (all host CPU):

* id-level equality with ``tiktoken.get_encoding("gpt2")`` when loaded from
  the GPT-2 vocab/merges artifacts (pinned by the reference's tokenizer test
  suite, `/root/reference/tests/test_tokenizer.py:88-413`);
* special tokens are never split and map straight to their vocab id, with
  longer specials winning over overlapping shorter ones;
* ``encode_iterable`` streams a file handle with bounded memory (the
  reference enforces <1 MB address-space growth on a 5 MB corpus,
  `test_tokenizer.py:416-429`).

Design: instead of the reference's per-pass rescan of the merge list
(`bpe_tokenizer.py:209-290`), merges are compiled once into a rank table over
*id pairs*; each pre-token then repeatedly applies its lowest-rank adjacent
pair (earliest position on ties), which is the same greedy order at much
lower cost.  The per-pretoken memo cache is bounded so streaming encodes
cannot grow without limit (the reference's cache is unbounded).
"""

from __future__ import annotations

import pickle
from abc import ABC, abstractmethod
from collections.abc import Iterable, Iterator
from multiprocessing import Pool
from pathlib import Path

from bpe_transformer_tpu.settings import ENCODING
from bpe_transformer_tpu.tokenization.pretokenization import (
    iter_pretoken_strings,
    split_on_special_tokens,
)

_REPLACEMENT = "�".encode(ENCODING)

# Per-process tokenizer for Pool workers: the parent pickles the tokenizer
# ONCE per worker (initializer) instead of once per task, so each worker
# compiles its merge tables / native engine a single time and reuses them.
_WORKER_TOKENIZER: "BPETokenizer | None" = None


def _stream_worker_init(tokenizer: "BPETokenizer") -> None:
    global _WORKER_TOKENIZER
    _WORKER_TOKENIZER = tokenizer


def _stream_worker_encode(segment: str) -> list[int]:
    return _WORKER_TOKENIZER.encode(segment)


class Tokenizer(ABC):
    """Minimal tokenizer interface (mirrors the reference ABC,
    `/root/reference/bpe_transformer/tokenization/tokenizer.py:6-31`)."""

    @property
    @abstractmethod
    def vocab(self) -> dict[int, bytes]: ...

    @property
    @abstractmethod
    def merges(self) -> list[tuple[bytes, bytes]]: ...

    @abstractmethod
    def encode(self, text: str) -> list[int]: ...

    @abstractmethod
    def encode_iterable(self, iterable: Iterable[str]) -> Iterator[int]: ...

    @abstractmethod
    def decode(self, ids: list[int]) -> str: ...


class BPETokenizer(Tokenizer):
    """Encode/decode text with a trained byte-level BPE vocabulary."""

    #: Memo-cache capacity (distinct pre-tokens).  Cleared when full so a
    #: pathological stream cannot grow the process footprint unboundedly.
    CACHE_CAPACITY = 50_000

    def __init__(
        self,
        vocab: dict[int, bytes],
        merges: list[tuple[bytes, bytes]],
        special_tokens: list[str] | None = None,
    ):
        self._vocab = vocab
        self._merges = merges
        self._special_tokens = list(dict.fromkeys(special_tokens or []))
        # Special tokens absent from the vocab get fresh ids at the end.
        present = set(vocab.values())
        for token in self._special_tokens:
            token_bytes = token.encode(ENCODING)
            if token_bytes not in present:
                vocab[len(vocab)] = token_bytes
                present.add(token_bytes)
        self._id_of: dict[bytes, int] = {v: k for k, v in vocab.items()}
        self._special_ids = {
            t: self._id_of[t.encode(ENCODING)] for t in self._special_tokens
        }

        # Compile merges to an id-pair rank table: (left_id, right_id) ->
        # (rank, merged_id).  Merges whose operands or result are absent from
        # the vocab can never apply and are dropped.
        self._pair_rank: dict[tuple[int, int], tuple[int, int]] = {}
        for rank, (left, right) in enumerate(merges):
            li = self._id_of.get(left)
            ri = self._id_of.get(right)
            mi = self._id_of.get(left + right)
            if li is None or ri is None or mi is None:
                continue
            self._pair_rank.setdefault((li, ri), (rank, mi))

        # Byte-value -> id table for seeding pre-tokens.
        self._byte_id = [self._id_of.get(bytes([b])) for b in range(256)]
        self._cache: dict[bytes, tuple[int, ...]] = {}

        # Native (C++) fused pretokenize+encode hot path; falls back to the
        # Python encoder when no toolchain is available.  Built lazily so
        # pickling to Pool workers stays cheap (see __getstate__).
        self._native = None
        self._native_tried = False

    # ------------------------------------------------------------- native

    def _native_encoder(self):
        """The C++ engine for this vocab/merge table, or None."""
        if not self._native_tried:
            self._native_tried = True
            try:
                from bpe_transformer_tpu.native import NativeBPEEncoder, is_available

                if is_available():
                    self._native = NativeBPEEncoder(self._byte_id, self._pair_rank)
            except Exception:
                self._native = None
        return self._native

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_native"] = None
        state["_native_tried"] = False
        state["_cache"] = {}
        return state

    # ---------------------------------------------------------------- props

    @property
    def vocab(self) -> dict[int, bytes]:
        return self._vocab

    @property
    def merges(self) -> list[tuple[bytes, bytes]]:
        return self._merges

    @property
    def special_tokens(self) -> list[str]:
        return list(self._special_tokens)

    # ------------------------------------------------------------- loading

    @classmethod
    def from_files(
        cls,
        vocab_filepath: str | Path,
        merges_filepath: str | Path,
        special_tokens: list[str] | None = None,
    ) -> "BPETokenizer":
        """Build a tokenizer from pickled trainer artifacts.

        Special tokens missing from the stored vocab are appended at the end,
        as the reference loader does (`bpe_tokenizer.py:292-320`).
        """
        return cls(
            vocab=cls.load_vocab(vocab_filepath, special_tokens),
            merges=cls.load_merges(merges_filepath),
            special_tokens=special_tokens,
        )

    @staticmethod
    def load_vocab(
        file_path: str | Path, special_tokens: list[str] | None = None
    ) -> dict[int, bytes]:
        with open(file_path, "rb") as f:
            vocab: dict[int, bytes] = pickle.load(f)
        if special_tokens:
            present = set(vocab.values())
            for token in special_tokens:
                token_bytes = token.encode(ENCODING)
                if token_bytes not in present:
                    vocab[len(vocab)] = token_bytes
        return vocab

    @staticmethod
    def load_merges(file_path: str | Path) -> list[tuple[bytes, bytes]]:
        with open(file_path, "rb") as f:
            merges: list[tuple[bytes, bytes]] = pickle.load(f)
        return merges

    # ------------------------------------------------------------- encode

    def encode(self, text: str) -> list[int]:
        """Encode ``text`` into token ids (specials map directly)."""
        out: list[int] = []
        native = self._native_encoder()
        parts = split_on_special_tokens(text, self._special_tokens, training=False)
        for part in parts:
            if not part:
                continue
            special_id = self._special_ids.get(part)
            if special_id is not None:
                out.append(special_id)
                continue
            if native is not None:
                out.extend(native.encode_part(part))
                continue
            for pretoken in iter_pretoken_strings(part):
                out.extend(self._encode_pretoken(pretoken.encode(ENCODING)))
        return out

    def _encode_pretoken(self, pretoken: bytes) -> tuple[int, ...]:
        cached = self._cache.get(pretoken)
        if cached is not None:
            return cached

        byte_id = self._byte_id
        # Bytes absent from the vocab are skipped (same policy as the native
        # engine, so both paths emit identical streams on any vocab).
        ids = [i for b in pretoken if (i := byte_id[b]) is not None]
        rank_of = self._pair_rank
        while len(ids) > 1:
            # Lowest-rank adjacent pair wins; earliest position breaks ties.
            best_rank = None
            best_pos = -1
            merged_id = -1
            for i in range(len(ids) - 1):
                hit = rank_of.get((ids[i], ids[i + 1]))
                if hit is not None and (best_rank is None or hit[0] < best_rank):
                    best_rank, merged_id = hit
                    best_pos = i
            if best_pos < 0:
                break
            ids[best_pos : best_pos + 2] = (merged_id,)

        result = tuple(ids)
        if len(self._cache) >= self.CACHE_CAPACITY:
            self._cache.clear()
        self._cache[pretoken] = result
        return result

    def encode_array(self, text: str):
        """Encode ``text`` to an int32 numpy array.

        Bulk-pipeline fast path (corpus -> memmap tokenization): with the
        native engine the ids never materialize as Python objects.
        """
        import numpy as np

        native = self._native_encoder()
        if native is None:
            return np.asarray(self.encode(text), dtype=np.int32)
        chunks = []
        for part in split_on_special_tokens(text, self._special_tokens, training=False):
            if not part:
                continue
            special_id = self._special_ids.get(part)
            if special_id is not None:
                chunks.append(np.asarray([special_id], dtype=np.int32))
            else:
                chunks.append(native.encode_part_array(part))
        if not chunks:
            return np.empty(0, dtype=np.int32)
        return np.concatenate(chunks)

    # ------------------------------------------------------------- decode

    def decode(self, ids: list[int]) -> str:
        """Decode ids to text; unknown ids become U+FFFD."""
        vocab = self._vocab
        data = b"".join(vocab.get(i, _REPLACEMENT) for i in ids)
        return data.decode(ENCODING, errors="replace")

    # ----------------------------------------------------------- streaming

    def encode_iterable(
        self, iterable: Iterable[str], n_workers: int | None = None
    ) -> Iterator[int]:
        """Lazily encode a string iterable (e.g. a file handle).

        Buffers only up to the last newline, so memory stays bounded
        regardless of input size.  ``n_workers > 1`` fans complete lines out
        over a process pool.
        """
        if n_workers is None or n_workers <= 1:
            yield from self._encode_stream_serial(iterable)
        else:
            yield from self._encode_stream_parallel(iterable, n_workers)

    @staticmethod
    def _iter_segments(iterable: Iterable[str]) -> Iterator[str]:
        """Newline-bounded segments of a string stream.

        The single segmentation policy shared by every streaming encode path
        (serial, parallel, array) so they all emit identical token streams:
        buffer each incoming chunk and flush up to the last newline.
        """
        pending = ""
        for chunk in iterable:
            pending += chunk
            cut = pending.rfind("\n")
            if cut != -1:
                yield pending[: cut + 1]
                pending = pending[cut + 1 :]
        if pending:
            yield pending

    def _encode_stream_serial(self, iterable: Iterable[str]) -> Iterator[int]:
        for segment in self._iter_segments(iterable):
            yield from self.encode(segment)

    def encode_iterable_arrays(self, iterable: Iterable[str]) -> Iterator["object"]:
        """Lazily encode a string stream, yielding one int32 array per
        newline-bounded segment.

        Same segmentation (and therefore the same token stream) as
        :meth:`encode_iterable`; with the native engine the ids never
        materialize as Python objects.  Bulk-pipeline fast path.
        """
        for segment in self._iter_segments(iterable):
            yield self.encode_array(segment)

    def _encode_stream_parallel(
        self, iterable: Iterable[str], n_workers: int
    ) -> Iterator[int]:
        batch: list[str] = []
        batch_size = n_workers * 10
        # Build (and disk-cache) the native engine once before forking so
        # workers load the cached .so instead of racing N concurrent builds.
        self._native_encoder()
        with Pool(
            processes=n_workers,
            initializer=_stream_worker_init,
            initargs=(self,),
        ) as pool:
            for segment in self._iter_segments(iterable):
                batch.append(segment)
                if len(batch) >= batch_size:
                    for encoded in pool.map(_stream_worker_encode, batch, chunksize=5):
                        yield from encoded
                    batch = []
            if batch:
                for encoded in pool.map(_stream_worker_encode, batch, chunksize=5):
                    yield from encoded
