"""Host-side GPT-2-style pre-tokenization.

Behavioral parity target: the reference's preprocessing layer
(`/root/reference/bpe_transformer/tokenization/preprocessing/pretokenization.py`):
chunk a file at special-token boundaries so chunks can be counted
independently, split each chunk on special tokens (dropping them for BPE
training, keeping them as standalone parts for encoding), and apply the GPT-2
regex to produce pre-tokens.

Design differences from the reference (same observable behavior):

* Pre-tokens are represented as ``tuple[int, ...]`` of byte values — the
  natural units the BPE trainer merges.  (The reference reaches the same
  representation implicitly via ``tuple(bytes)``.)
* A single code path handles serial and parallel counting; parallel mode
  fans chunks out over ``multiprocessing.Pool`` (the TPU host VM's many CPU
  cores are the right place for this — device code never touches text).
* Chunk decoding always uses ``errors="ignore"`` (the reference's serial
  path forgot it; we match the *tested* parallel behavior).
"""

from __future__ import annotations

import os
from collections import Counter
from functools import reduce
from multiprocessing import Pool, cpu_count
from pathlib import Path
from typing import BinaryIO, Iterable

import regex as re

from bpe_transformer_tpu.settings import ENCODING, GPT2_SPLIT_PATTERN

# Compiled once per process; `regex` caches are per-pattern-string anyway but
# an explicit compile keeps the hot loop free of dict lookups.
_GPT2_RE = re.compile(GPT2_SPLIT_PATTERN)

Pretoken = tuple[int, ...]


def find_chunk_boundaries(
    file: BinaryIO,
    desired_num_chunks: int,
    special_tokens: list[str] | None = None,
) -> list[int]:
    """Byte offsets that cut ``file`` into ~equal chunks at safe boundaries.

    A boundary is only placed at the start of a special token (default:
    newline) so no pre-token ever straddles two chunks.  May return fewer
    boundaries than requested when guesses collide.  Mirrors the reference's
    scan-ahead strategy (`pretokenization.py:114-168`).
    """
    if special_tokens:
        needles = [t.encode(ENCODING) for t in special_tokens]
    else:
        needles = [b"\n"]

    file.seek(0, os.SEEK_END)
    file_size = file.tell()
    file.seek(0)

    chunk_size = file_size // max(desired_num_chunks, 1)
    guesses = [i * chunk_size for i in range(desired_num_chunks + 1)]
    guesses[-1] = file_size

    read_ahead = 4096
    for bi in range(1, len(guesses) - 1):
        pos = guesses[bi]
        file.seek(pos)
        while True:
            window = file.read(read_ahead)
            if window == b"":
                guesses[bi] = file_size
                break
            hits = [window.find(n) for n in needles]
            hits = [h for h in hits if h != -1]
            if hits:
                guesses[bi] = pos + min(hits)
                break
            pos += read_ahead

    return sorted(set(guesses))


def split_on_special_tokens(
    text: str,
    special_tokens: list[str] | None = None,
    *,
    training: bool = True,
) -> list[str]:
    """Split ``text`` at special tokens so BPE never merges across them.

    ``training=True`` drops the special tokens from the output parts;
    ``training=False`` keeps each special token as its own part (so the
    encoder can map it straight to its vocab id).  Longer special tokens win
    over their prefixes (e.g. ``<|eot|><|eot|>`` before ``<|eot|>``).
    """
    if not special_tokens:
        return [text]
    ordered = sorted(special_tokens, key=len, reverse=True)
    alternation = "|".join(re.escape(t) for t in ordered)
    pattern = alternation if training else f"({alternation})"
    return re.split(pattern, text)


def iter_pretoken_strings(text: str) -> Iterable[str]:
    """Yield GPT-2 pre-token strings of ``text`` in order."""
    for m in _GPT2_RE.finditer(text):
        yield m.group()


def pretokenize_text(text: str) -> list[bytes]:
    """GPT-2 pre-tokens of ``text`` as UTF-8 byte strings, in order."""
    return [s.encode(ENCODING) for s in iter_pretoken_strings(text)]


def count_pretokens_in_text(
    text: str,
    special_tokens: list[str] | None = None,
    *,
    training: bool = True,
    into: Counter[Pretoken] | None = None,
) -> Counter[Pretoken]:
    """Count pre-tokens (as byte-value tuples) in a text string."""
    counter: Counter[Pretoken] = into if into is not None else Counter()
    specials = set(special_tokens) if special_tokens else set()
    for part in split_on_special_tokens(text, special_tokens, training=training):
        if not part:
            continue
        if part in specials:
            counter[tuple(part.encode(ENCODING))] += 1
            continue
        for m in _GPT2_RE.finditer(part):
            counter[tuple(m.group().encode(ENCODING))] += 1
    return counter


def count_pretokens_in_chunk(
    file_path: str | Path,
    start: int,
    end: int,
    training: bool = True,
    special_tokens: list[str] | None = None,
) -> Counter[Pretoken]:
    """Pre-token counts of ``file_path[start:end]`` (a worker unit)."""
    with open(file_path, "rb") as f:
        f.seek(start)
        text = f.read(end - start).decode(ENCODING, errors="ignore")
    return count_pretokens_in_text(text, special_tokens, training=training)


def count_pretokens_in_chunk_native(
    file_path: str | Path,
    start: int,
    end: int,
    training: bool = True,
    special_tokens: list[str] | None = None,
) -> Counter[Pretoken]:
    """C++-scanner variant of :func:`count_pretokens_in_chunk`.

    Special-token splitting stays in Python (same ``split_on_special_tokens``
    semantics); the GPT-2 regex scan + counting of each specials-free part
    runs in the native engine.  Same Counter[tuple[int, ...]] output.
    """
    from bpe_transformer_tpu.native import NativePretokenCounter

    with open(file_path, "rb") as f:
        f.seek(start)
        text = f.read(end - start).decode(ENCODING, errors="ignore")
    native = NativePretokenCounter()
    out: Counter[Pretoken] = Counter()
    specials = set(special_tokens) if special_tokens else set()
    for part in split_on_special_tokens(text, special_tokens, training=training):
        if not part:
            continue
        if part in specials:
            out[tuple(part.encode(ENCODING))] += 1
            continue
        native.add(part)
    for data, count in native.items():
        out[tuple(data)] += count
    return out


def count_pretokens(
    file_path: str | Path,
    special_tokens: list[str] | None = None,
    *,
    training: bool = True,
    n_workers: int | None = None,
    parallel: bool = True,
    engine: str = "auto",
) -> Counter[Pretoken]:
    """Pre-token counts for a whole file, optionally fanned out over processes.

    This is the entry point the BPE trainer uses.  ``n_workers`` defaults to 4
    and is clamped to the host CPU count, matching the reference's dispatch
    behavior (`pretokenization.py:73-111`).

    ``engine``: "auto" runs each chunk through the C++ scanner when the
    native engine is available (identical counts, several-fold faster);
    "python"/"native" force a path ("native" raises if unavailable).
    """
    if n_workers is None or n_workers <= 0:
        n_workers = 4
    n_workers = min(n_workers, cpu_count())
    if engine not in ("auto", "python", "native"):
        raise ValueError(f"unknown engine: {engine!r}")
    if engine == "auto":
        # BT_NATIVE=0 must disable auto-selection even when the library is
        # already loaded in this process (is_available() caches the load).
        if os.environ.get("BT_NATIVE", "1") == "0":
            engine = "python"
        else:
            from bpe_transformer_tpu.native import is_available

            engine = "native" if is_available() else "python"
    elif engine == "native":
        from bpe_transformer_tpu.native import is_available, unavailable_reason

        if not is_available():
            raise RuntimeError(f"native engine unavailable: {unavailable_reason()}")
    chunk_fn = (
        count_pretokens_in_chunk_native
        if engine == "native"
        else count_pretokens_in_chunk
    )

    with open(file_path, "rb") as f:
        boundaries = find_chunk_boundaries(f, n_workers if parallel else 4, special_tokens)

    spans = list(zip(boundaries[:-1], boundaries[1:]))
    if not parallel or n_workers == 1 or len(spans) <= 1:
        total: Counter[Pretoken] = Counter()
        for start, end in spans:
            total += chunk_fn(file_path, start, end, training, special_tokens)
        return total

    args = [(file_path, start, end, training, special_tokens) for start, end in spans]
    with Pool(processes=n_workers) as pool:
        per_chunk = pool.starmap(chunk_fn, args)
    return reduce(lambda a, b: a + b, per_chunk, Counter())


# Reference-compatible aliases (`pretokenization.py:41,73,255`).
def pretokenize(
    file_path: str | Path,
    training: bool = True,
    parallel_processing: bool = True,
    n_workers: int | None = 4,
    special_tokens: list[str] | None = None,
) -> Counter[Pretoken]:
    return count_pretokens(
        file_path,
        special_tokens,
        training=training,
        n_workers=n_workers,
        parallel=parallel_processing,
    )


def parallel_pretokenization(
    file_path: str | Path,
    n_workers: int | None = None,
    training: bool = True,
    special_tokens: list[str] | None = None,
) -> Counter[Pretoken]:
    return count_pretokens(
        file_path, special_tokens, training=training, n_workers=n_workers, parallel=True
    )


def serial_pretokenization(
    file_path: str | Path,
    training: bool = True,
    special_tokens: list[str] | None = None,
) -> Counter[Pretoken]:
    return count_pretokens(file_path, special_tokens, training=training, parallel=False)
