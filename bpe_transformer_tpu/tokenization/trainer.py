"""Greedy byte-pair-encoding trainer (host CPU).

Produces the same vocabulary and the same *ordered* merge list as the
reference trainer (`/root/reference/bpe_transformer/tokenization/
bpe_trainer.py`), which is pinned exactly by the reference's
``train-bpe-reference-merges.txt`` fixture:

* base vocab = 256 single bytes, then special tokens;
* at each step merge the adjacent pair with the highest total count, ties
  broken toward the lexicographically *greater* ``(bytes, bytes)`` pair;
* within a pre-token, occurrences merge leftmost-first and never overlap;
* a merge is only recorded if it actually applied somewhere.

The internal design is different from the reference: distinct pre-tokens are
stored once in an indexed word table with multiplicities, pair bookkeeping is
exact (full recount of a word's adjacent pairs on every rewrite, rather than
the reference's delta tracking), and the max-heap uses lazy invalidation via
a count check at pop time.
"""

from __future__ import annotations

import heapq
import pickle
from collections import Counter
from pathlib import Path

from bpe_transformer_tpu.settings import DEFAULT_OUTPUT_DIR, ENCODING
from bpe_transformer_tpu.tokenization.pretokenization import Pretoken, count_pretokens

#: Streaming read size and the pending-buffer cap that triggers the exact
#: incremental add_prefix flush (module-level so tests can shrink them).
STREAM_CHUNK_CHARS = 1 << 22
PENDING_FLUSH_CHARS = 1 << 26

Pair = tuple[int, int]


class _HeapEntry:
    """Max-heap entry: most frequent pair first; on ties the pair whose
    ``(bytes, bytes)`` representation is lexicographically greater wins.

    ``pair_bytes`` is captured at push time; vocab entries are immutable once
    assigned, so the captured value never goes stale.
    """

    __slots__ = ("count", "pair", "pair_bytes")

    def __init__(self, count: int, pair: Pair, pair_bytes: tuple[bytes, bytes]):
        self.count = count
        self.pair = pair
        self.pair_bytes = pair_bytes

    def __lt__(self, other: "_HeapEntry") -> bool:
        if self.count != other.count:
            return self.count > other.count
        return self.pair_bytes > other.pair_bytes


def _merge_occurrences(word: list[int], a: int, b: int, z: int) -> list[int] | None:
    """Replace leftmost, non-overlapping ``(a, b)`` runs in ``word`` with ``z``.

    Returns the rewritten word, or None when the pair does not occur.
    """
    n = len(word)
    out: list[int] = []
    i = 0
    hit = False
    while i < n - 1:
        if word[i] == a and word[i + 1] == b:
            out.append(z)
            i += 2
            hit = True
        else:
            out.append(word[i])
            i += 1
    if not hit:
        return None
    if i == n - 1:
        out.append(word[-1])
    return out


class BPETrainer:
    """Train a byte-level BPE vocabulary on a text corpus.

    Same public surface as the reference trainer: ``vocab_size`` /
    ``special_tokens`` constructor, :meth:`train`, :attr:`vocab`,
    :attr:`merges`, :meth:`save_trainer`.
    """

    def __init__(self, vocab_size: int, special_tokens: list[str] | None = None):
        if vocab_size < 256:
            raise ValueError("Invalid vocab size: must be at least 256")
        self._target_vocab_size = vocab_size
        # Preserve caller order, dropping duplicates.
        self._special_tokens = list(dict.fromkeys(special_tokens or []))
        self._vocab: dict[int, bytes] = {i: bytes([i]) for i in range(256)}
        for offset, token in enumerate(self._special_tokens):
            self._vocab[256 + offset] = token.encode(ENCODING)
        self._merges: list[tuple[bytes, bytes]] = []

    # ------------------------------------------------------------------ API

    @property
    def vocab(self) -> dict[int, bytes]:
        return self._vocab

    @property
    def merges(self) -> list[tuple[bytes, bytes]]:
        return self._merges

    @property
    def special_tokens(self) -> list[str]:
        return self._special_tokens

    @property
    def vocab_size(self) -> int:
        return self._target_vocab_size

    def train(self, input_path: str | Path, n_workers: int | None = None) -> None:
        """Pre-tokenize ``input_path`` and learn merges to the target size.

        With a C++ toolchain the whole pipeline (scan, count, merge loop)
        runs natively, streaming the file in bounded-memory chunks; the
        Python counting + merge path is the fallback.
        """
        if self._train_native_file(input_path):
            return
        pretoken_counts = count_pretokens(
            input_path,
            self._special_tokens,
            training=True,
            n_workers=n_workers,
        )
        self.train_from_pretokens(pretoken_counts)

    def _train_native_file(self, input_path: str | Path) -> bool:
        """Stream-count + train via the C++ engine; False when unavailable."""
        import os

        if os.environ.get("BT_NATIVE", "1") == "0":
            return False
        try:
            from bpe_transformer_tpu.native import engine as native_engine

            if not native_engine.is_available():
                return False
            counter = native_engine.NativePretokenCounter()
        except Exception:
            return False

        from bpe_transformer_tpu.tokenization.pretokenization import (
            split_on_special_tokens,
        )

        specials = self._special_tokens

        def feed(text: str) -> None:
            for part in split_on_special_tokens(text, specials, training=True):
                if part:
                    counter.add(part)

        # newline="" disables universal-newline translation so CRLF corpora
        # count identically to the binary-read Python path.
        with open(input_path, encoding=ENCODING, errors="ignore", newline="") as f:
            if specials:
                # Cut the stream only at complete special-token occurrences:
                # pre-tokens never span a special, so these cuts are exactly
                # lossless (mirrors find_chunk_boundaries' invariant).
                max_keep = max(len(s) for s in specials) - 1
                pending = ""
                while True:
                    chunk = f.read(STREAM_CHUNK_CHARS)
                    if not chunk:
                        break
                    pending += chunk
                    cut = max(pending.rfind(s) for s in specials)
                    if cut > 0:
                        feed(pending[:cut])
                        pending = pending[cut:]
                    elif len(pending) > PENDING_FLUSH_CHARS:
                        if cut == 0:
                            # The only special occurrence sits at index 0:
                            # strip it (training=True discards specials) so
                            # its bytes never reach add_prefix as ordinary
                            # text.  Longest-first mirrors
                            # split_on_special_tokens' overlap handling.
                            for s in sorted(specials, key=len, reverse=True):
                                if pending.startswith(s):
                                    pending = pending[len(s):]
                                    break
                        # No special in sight: keep memory bounded by exact
                        # token streaming, retaining enough characters to
                        # cover a special straddling the boundary.
                        head = pending[: len(pending) - max_keep]
                        data = head.encode(ENCODING)
                        consumed = counter.add_prefix(data)
                        pending = (
                            data[consumed:].decode(ENCODING)
                            + pending[len(pending) - max_keep :]
                        )
                if pending:
                    feed(pending)
            else:
                # No specials: exact incremental scan — the C++ side counts
                # every pre-token that provably cannot change with more
                # input, and returns the undecided tail to carry over.
                tail = b""
                while True:
                    chunk = f.read(STREAM_CHUNK_CHARS)
                    if not chunk:
                        break
                    data = tail + chunk.encode(ENCODING)
                    consumed = counter.add_prefix(data)
                    tail = data[consumed:]
                if tail:
                    counter.add(tail)

        vocab = self._vocab
        base = len(vocab)
        pairs = counter.train_bpe(
            [vocab[i] for i in range(base)], self._target_vocab_size
        )
        next_id = base
        for a, b in pairs:
            self._merges.append((vocab[a], vocab[b]))
            vocab[next_id] = vocab[a] + vocab[b]
            next_id += 1
        return True

    def train_from_pretokens(self, pretoken_counts: Counter[Pretoken]) -> None:
        """Learn merges from pre-token multiplicities (already counted).

        Uses the C++ merge loop (`native/src/bt_native.cpp:bt_train_bpe`,
        same selection semantics) when a toolchain is available; the Python
        loop below is the reference implementation and fallback.
        """
        if self._train_native(pretoken_counts):
            return
        words: list[list[int]] = []
        counts: list[int] = []
        for pretoken, count in pretoken_counts.items():
            if len(pretoken) < 2:
                continue
            words.append(list(pretoken))
            counts.append(count)

        pair_counts: Counter[Pair] = Counter()
        pair_words: dict[Pair, set[int]] = {}
        for idx, word in enumerate(words):
            c = counts[idx]
            for pair in zip(word, word[1:]):
                pair_counts[pair] += c
                pair_words.setdefault(pair, set()).add(idx)

        vocab = self._vocab
        heap = [
            _HeapEntry(c, pair, (vocab[pair[0]], vocab[pair[1]]))
            for pair, c in pair_counts.items()
        ]
        heapq.heapify(heap)

        next_id = len(vocab)
        while len(vocab) < self._target_vocab_size and heap:
            entry = heapq.heappop(heap)
            pair = entry.pair
            if pair_counts.get(pair, 0) != entry.count:
                continue  # superseded by a later count update

            a, b = pair
            members = pair_words.get(pair)
            if not members:
                continue
            touched: set[Pair] = set()
            merged_any = False
            for idx in list(members):
                old_word = words[idx]
                new_word = _merge_occurrences(old_word, a, b, next_id)
                if new_word is None:
                    continue
                merged_any = True
                c = counts[idx]
                for p in zip(old_word, old_word[1:]):
                    pair_counts[p] -= c
                    s = pair_words.get(p)
                    if s is not None:
                        s.discard(idx)
                    touched.add(p)
                for p in zip(new_word, new_word[1:]):
                    pair_counts[p] += c
                    pair_words.setdefault(p, set()).add(idx)
                    touched.add(p)
                words[idx] = new_word

            if not merged_any:
                continue

            self._merges.append((vocab[a], vocab[b]))
            vocab[next_id] = vocab[a] + vocab[b]
            next_id += 1
            for p in touched:
                c = pair_counts.get(p, 0)
                if c > 0:
                    heapq.heappush(heap, _HeapEntry(c, p, (vocab[p[0]], vocab[p[1]])))

    def _train_native(self, pretoken_counts: Counter[Pretoken]) -> bool:
        """Learn merges via the C++ loop; False when unavailable."""
        import os

        if os.environ.get("BT_NATIVE", "1") == "0":
            return False
        try:
            from bpe_transformer_tpu.native import engine as native_engine

            if not native_engine.is_available():
                return False
            vocab = self._vocab
            base = len(vocab)
            words: list[Pretoken] = []
            counts: list[int] = []
            for pretoken, count in pretoken_counts.items():
                if len(pretoken) < 2:
                    continue
                words.append(pretoken)
                counts.append(count)
            pairs = native_engine.train_bpe_merges(
                words,
                counts,
                [vocab[i] for i in range(base)],
                self._target_vocab_size,
            )
        except Exception:
            return False
        next_id = base
        for a, b in pairs:
            self._merges.append((vocab[a], vocab[b]))
            vocab[next_id] = vocab[a] + vocab[b]
            next_id += 1
        return True

    def save_trainer(self, output_dir: Path | None = None) -> None:
        """Pickle ``vocab.pkl`` and ``merges.pkl`` under ``output_dir``.

        Artifact format matches the reference (`bpe_trainer.py:447-472`), so
        tokenizers can load either implementation's output.
        """
        if output_dir is None:
            output_dir = DEFAULT_OUTPUT_DIR / "tokenizer" / "bpe_trainer"
        output_dir = Path(output_dir)
        output_dir.mkdir(parents=True, exist_ok=True)
        with open(output_dir / "vocab.pkl", "wb") as f:
            pickle.dump(self._vocab, f)
        with open(output_dir / "merges.pkl", "wb") as f:
            pickle.dump(self._merges, f)


def train_bpe(
    input_path: str | Path,
    vocab_size: int,
    special_tokens: list[str] | None = None,
    n_workers: int | None = None,
) -> tuple[dict[int, bytes], list[tuple[bytes, bytes]]]:
    """Convenience wrapper: train and return ``(vocab, merges)``.

    Mirrors the reference's package-level ``train_bpe`` (`main.py:8-17`).
    """
    trainer = BPETrainer(vocab_size=vocab_size, special_tokens=special_tokens)
    trainer.train(input_path, n_workers=n_workers)
    return trainer.vocab, trainer.merges
