"""Host-side tokenization stack: pre-tokenization, BPE training, encoding."""

from bpe_transformer_tpu.tokenization.pretokenization import (
    count_pretokens,
    find_chunk_boundaries,
    parallel_pretokenization,
    pretokenize,
    pretokenize_text,
    serial_pretokenization,
    split_on_special_tokens,
)
from bpe_transformer_tpu.tokenization.tokenizer import BPETokenizer, Tokenizer
from bpe_transformer_tpu.tokenization.trainer import BPETrainer, train_bpe

__all__ = [
    "BPETokenizer",
    "BPETrainer",
    "Tokenizer",
    "count_pretokens",
    "find_chunk_boundaries",
    "parallel_pretokenization",
    "pretokenize",
    "pretokenize_text",
    "serial_pretokenization",
    "split_on_special_tokens",
    "train_bpe",
]
