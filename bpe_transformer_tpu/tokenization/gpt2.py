"""GPT-2 tokenizer-artifact interop (host CPU).

The GPT-2 release serializes its byte-level vocabulary through a reversible
byte->printable-unicode remapping (public algorithm from the GPT-2 codebase).
This module rebuilds that table and loads ``vocab.json`` / ``merges.txt``
pairs in that format into the plain ``dict[int, bytes]`` / list-of-byte-pairs
representation the rest of this framework uses.

Parity target: the reference consumes the same artifact format in its test
harness (`/root/reference/tests/common.py:10-54`).
"""

from __future__ import annotations

import json
from functools import lru_cache
from pathlib import Path


@lru_cache(maxsize=1)
def bytes_to_unicode() -> dict[int, str]:
    """Map every byte 0..255 to a printable unicode character, reversibly.

    Printable latin-1 bytes keep their own character; the remaining 68 bytes
    are shifted up by 256 so every byte has a visible representation.
    """
    keep = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("¡"), ord("¬") + 1))
        + list(range(ord("®"), ord("ÿ") + 1))
    )
    byte_values = keep[:]
    char_codes = keep[:]
    bump = 0
    for b in range(256):
        if b not in keep:
            byte_values.append(b)
            char_codes.append(256 + bump)
            bump += 1
    return {b: chr(c) for b, c in zip(byte_values, char_codes)}


@lru_cache(maxsize=1)
def unicode_to_bytes() -> dict[str, int]:
    return {c: b for b, c in bytes_to_unicode().items()}


def decode_gpt2_token(token: str) -> bytes:
    """Decode one remapped-unicode token string back to raw bytes."""
    table = unicode_to_bytes()
    return bytes(table[ch] for ch in token)


def load_gpt2_vocab(vocab_path: str | Path) -> dict[int, bytes]:
    """Load a GPT-2-format ``vocab.json`` into ``{id: raw_bytes}``."""
    with open(vocab_path, encoding="utf-8") as f:
        token_to_id: dict[str, int] = json.load(f)
    return {idx: decode_gpt2_token(tok) for tok, idx in token_to_id.items()}


def load_gpt2_merges(merges_path: str | Path) -> list[tuple[bytes, bytes]]:
    """Load a GPT-2-format ``merges.txt`` into ordered raw-byte pairs."""
    merges: list[tuple[bytes, bytes]] = []
    with open(merges_path, encoding="utf-8") as f:
        for line in f:
            line = line.rstrip()
            parts = line.split(" ")
            if len(parts) != 2 or not line:
                continue  # header / blank lines
            merges.append((decode_gpt2_token(parts[0]), decode_gpt2_token(parts[1])))
    return merges
