"""Global constants for the host-side tokenization stack.

TPU-native rebuild of the reference's constants module
(`/root/reference/bpe_transformer/settings.py:1-10`).  The GPT-2
pre-tokenization regex is kept verbatim for token-level parity with the
reference and with tiktoken's "gpt2" encoding; the output-dir quirk of the
reference (a path nested *under a file*) is fixed to a real directory.
"""

from __future__ import annotations

from pathlib import Path

#: Canonical text encoding used across the tokenization stack.
ENCODING: str = "utf-8"

#: GPT-2 pre-tokenization pattern (Radford et al., 2019).  Public regex, also
#: used by tiktoken's "gpt2" encoding.  Requires the `regex` module (\p{...}).
GPT2_SPLIT_PATTERN: str = r"""'(?:[sdmt]|ll|ve|re)| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"""

#: Default directory for trainer artifacts (vocab/merges pickles).
DEFAULT_OUTPUT_DIR: Path = Path(__file__).resolve().parent.parent / "output"

# Backwards-compatible aliases matching the reference's public names
# (`settings.py:4` ENCODING_STD, `settings.py:8` PAT).
ENCODING_STD = ENCODING
PAT = GPT2_SPLIT_PATTERN
