"""Accelerator kernels (Pallas/Mosaic for TPU)."""
