"""Shared kernel runtime helpers."""

from __future__ import annotations

import jax


def interpret_mode() -> bool:
    """Pallas TPU kernels run in interpret mode on non-TPU backends
    (CPU tests, debugging); compiled Mosaic otherwise."""
    return jax.default_backend() != "tpu"
