"""Pallas (Mosaic) TPU kernels for the hot ops."""

from bpe_transformer_tpu.kernels.pallas.decode_attention import (
    decode_attention,
    paged_decode_attention,
)
from bpe_transformer_tpu.kernels.pallas.flash_attention import (
    flash_attention,
    flash_attention_with_rope,
)
from bpe_transformer_tpu.kernels.pallas.gelu import gelu, gelu_reference
from bpe_transformer_tpu.kernels.pallas.quant_matmul import quant_matmul
from bpe_transformer_tpu.kernels.pallas.sample import (
    fused_head_sample,
    fused_verify_head,
)

__all__ = [
    "decode_attention",
    "paged_decode_attention",
    "flash_attention",
    "flash_attention_with_rope",
    "fused_head_sample",
    "fused_verify_head",
    "gelu",
    "gelu_reference",
    "quant_matmul",
]
