"""Flash attention: blockwise online-softmax attention as a Pallas kernel,
optionally with RoPE fused into the Q/K block loads.

TPU-native replacement for materialized S^2 attention (the reference's spec
M7, `/root/reference/tests/adapters.py:92-110`, materializes the full score
matrix; BASELINE.json config 4 demands a fused RoPE+attention kernel at seq
1k/4k/16k).

Kernel structure (classic FlashAttention on the MXU):

* grid ``(batch*heads, S/block_q, S/block_k)`` — the key axis iterates
  fastest; VMEM scratch (f32 accumulator + running max/denominator) persists
  across the key axis so each query block is normalized online, never
  materializing more than a ``(block_q, block_k)`` score tile.
* causal masking happens at block granularity: key blocks strictly above the
  diagonal are predicated off, the diagonal block gets the triangular mask,
  blocks below run unmasked.
* sequence padding to the block size is sound under causal masking (padded
  keys sit above every valid query's diagonal) and padded query rows are
  sliced off on the way out.
* RoPE fusion: Q and K are pre-permuted on the host side from the
  interleaved pair convention ``(x0, x1, x2, x3, ...)`` to a half-split
  layout ``(x0, x2, ... | x1, x3, ...)``.  Attention scores are invariant
  under any fixed permutation of the head dim applied to both Q and K, so
  in-kernel rotation becomes two dense multiply-adds against full-width
  cos/sin tiles (``rot = x * C + swap(x) * S``) with no strided access —
  the rotated Q/K never round-trip through HBM.

The backward pass is the standard FlashAttention-2 split: the forward
additionally emits the per-row logsumexp; the backward recomputes score
tiles in VMEM (never materializing S^2) in two kernels — dK/dV with the
query axis innermost (accumulators live in VMEM scratch per key block) and
dQ with the key axis innermost.  ``delta = rowsum(dO * O)`` is one cheap
elementwise XLA pass.  For the RoPE-fused variant the backward applies the
(orthogonal) rotation to Q/K outside the kernel — elementwise, O(S*d) — and
un-rotates dQ/dK with the transposed rotation, so the O(S^2) part still
never touches HBM.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bpe_transformer_tpu.ops.core import MASK_VALUE as NEG_INF
from bpe_transformer_tpu.ops.core import causal_mask, scaled_dot_product_attention

LANES = 128


def _rotate_half_layout(x, c, s, half: int):
    """RoPE rotation for inputs in the half-split feature layout.

    ``c``/``s`` are full-width cos/sin tiles ``[cos|cos|0]`` / ``[sin|sin|0]``
    so the rotation is ``x * c + swap(x) * s`` with ``swap = [-x2 | x1 | 0]``
    — two dense FMAs, no strided lane access.
    """
    x1 = x[:, :half]
    x2 = x[:, half : 2 * half]
    tail = x[:, 2 * half :]
    swapped = jnp.concatenate([-x2, x1, tail], axis=-1)
    return x * c + swapped * s


def _flash_kernel(
    *refs,
    scale: float, block_q: int, block_k: int, causal: bool, num_k_blocks: int,
    rope_half: int, with_lse: bool,
):
    refs = list(refs)
    if rope_half:
        q_ref, k_ref, v_ref, cq_ref, sq_ref, ck_ref, sk_ref = refs[:7]
        del refs[:7]
    else:
        q_ref, k_ref, v_ref = refs[:3]
        del refs[:3]
    o_ref = refs.pop(0)
    lse_ref = refs.pop(0) if with_lse else None
    acc_ref, m_ref, l_ref = refs[:3]
    qrot_ref = refs[3] if rope_half else None
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        if rope_half:
            # Rotate the query block once per (batch*head, q-block); it is
            # reused across every key block from VMEM scratch.
            qrot_ref[:] = _rotate_half_layout(
                q_ref[0].astype(jnp.float32) * scale,
                cq_ref[:].astype(jnp.float32),
                sq_ref[:].astype(jnp.float32),
                rope_half,
            )

    # Key blocks entirely above the causal diagonal contribute nothing.
    compute = (block_k * ik) <= (block_q * iq + block_q - 1) if causal else True

    @pl.when(compute)
    def _block():
        if rope_half:
            q = qrot_ref[:]
            k = _rotate_half_layout(
                k_ref[0].astype(jnp.float32),
                ck_ref[:].astype(jnp.float32),
                sk_ref[:].astype(jnp.float32),
                rope_half,
            )
        else:
            q = q_ref[0].astype(jnp.float32) * scale
            k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k)

        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + iq * block_q
            cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + ik * block_k
            s = jnp.where(rows >= cols, s, NEG_INF)

        m_prev = m_ref[:, 0:1]
        l_prev = l_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ik == num_k_blocks - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, 0:1], 1e-30)  # fully-masked rows -> 0
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)
        if with_lse:
            # Per-row logsumexp for the FA-2 backward.  Under the causal
            # mask every row sees at least its diagonal, so l > 0 and the
            # value is finite (padded rows included).
            lse = m_ref[:, 0:1] + jnp.log(denom)
            lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _xla_attention(q, k, v, causal: bool):
    """Materialized-scores oracle (parity tests + the recompute backward):
    ops.core attention with float32 accumulation."""
    mask = causal_mask(q.shape[-2]) if causal else None
    out = scaled_dot_product_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32), mask
    )
    return out.astype(q.dtype)


def _flash_impl(
    q, k, v, causal, block_q, block_k, interpret, cos=None, sin=None,
    return_lse=False,
):
    *batch, s, d = q.shape
    bh = 1
    for dim in batch:
        bh *= dim
    rope = cos is not None
    if rope and (cos.shape != (s, d // 2) or sin.shape != (s, d // 2)):
        raise ValueError(
            f"cos/sin must be position-gathered to shape (seq, d//2) = "
            f"{(s, d // 2)}, got {cos.shape} / {sin.shape}; select rows from "
            "rope_tables(...) by token position before calling"
        )

    block_q = min(block_q, s)
    block_k = min(block_k, s)
    # Pad so BOTH block sizes divide the padded length, or the grid would
    # skip trailing query/key blocks and return garbage rows.
    block = math.lcm(block_q, block_k)
    s_pad = pl.cdiv(s, block) * block
    if s_pad != s and not causal:
        raise ValueError(
            f"non-causal flash attention requires seq ({s}) divisible by the "
            f"block size ({block})"
        )
    d_pad = pl.cdiv(d, LANES) * LANES

    def prep(x):
        x = x.reshape(bh, s, d)
        return jnp.pad(x, ((0, 0), (0, s_pad - s), (0, d_pad - d)))

    if rope:
        half = d // 2
        # Scores are invariant to a fixed feature permutation applied to both
        # Q and K: move from the interleaved pair convention to a half-split
        # layout so the in-kernel rotation needs no strided access.
        to_half = lambda x: jnp.concatenate([x[..., 0::2], x[..., 1::2]], axis=-1)
        q, k = to_half(q), to_half(k)
        # Full-width tiles [cos|cos|0] / [sin|sin|0], padded to (s_pad, d_pad).
        ctile = jnp.pad(
            jnp.concatenate([cos, cos], axis=-1).astype(jnp.float32),
            ((0, s_pad - s), (0, d_pad - d)),
        )
        stile = jnp.pad(
            jnp.concatenate([sin, sin], axis=-1).astype(jnp.float32),
            ((0, s_pad - s), (0, d_pad - d)),
        )

    qp, kp, vp = prep(q), prep(k), prep(v)
    nq = s_pad // block_q
    nk = s_pad // block_k

    kernel = functools.partial(
        _flash_kernel,
        scale=1.0 / (d**0.5),  # true head dim, not the lane-padded one
        block_q=block_q,
        block_k=block_k,
        causal=causal,
        num_k_blocks=nk,
        rope_half=(d // 2) if rope else 0,
        with_lse=return_lse,
    )
    qspec = pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM)
    in_specs = [qspec, kspec, kspec]
    operands = [qp, kp, vp]
    scratch = [
        pltpu.VMEM((block_q, d_pad), jnp.float32),  # output accumulator
        pltpu.VMEM((block_q, LANES), jnp.float32),  # running row max
        pltpu.VMEM((block_q, LANES), jnp.float32),  # running denominator
    ]
    if rope:
        tile_q = pl.BlockSpec((block_q, d_pad), lambda b, i, j: (i, 0), memory_space=pltpu.VMEM)
        tile_k = pl.BlockSpec((block_k, d_pad), lambda b, i, j: (j, 0), memory_space=pltpu.VMEM)
        in_specs += [tile_q, tile_q, tile_k, tile_k]
        operands += [ctile, stile, ctile, stile]
        scratch.append(pltpu.VMEM((block_q, d_pad), jnp.float32))  # rotated Q

    out_shape = jax.ShapeDtypeStruct(qp.shape, qp.dtype)
    out_spec = pl.BlockSpec(
        (1, block_q, d_pad), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM
    )
    if return_lse:
        # lse is written lane-broadcast (LANES copies per row) so both the
        # forward store and the backward loads stay plain (8,128)-tiled
        # VMEM traffic — same layout trick as the m/l scratch above.
        out_shape = (
            out_shape,
            jax.ShapeDtypeStruct((bh, s_pad, LANES), jnp.float32),
        )
        out_spec = (
            out_spec,
            pl.BlockSpec(
                (1, block_q, LANES), lambda b, i, j: (b, i, 0),
                memory_space=pltpu.VMEM,
            ),
        )

    out = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=out_spec,
        scratch_shapes=scratch,
        interpret=interpret,
    )(*operands)

    if return_lse:
        out, lse = out
        return out[:, :s, :d].reshape(*batch, s, d), lse[:, :, 0]
    return out[:, :s, :d].reshape(*batch, s, d)


# ------------------------------------------------- FlashAttention-2 backward


def _bwd_score_block(q_ref, k_ref, lse_ref, scale, block_q, block_k, causal, i, j):
    """Recompute one (block_q, block_k) probability tile from VMEM refs."""
    qs = q_ref[0].astype(jnp.float32) * scale
    kb = k_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(
        qs, kb, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + i * block_q
        cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * block_k
        s = jnp.where(rows >= cols, s, NEG_INF)
    # exp(NEG_INF - lse) underflows to exactly 0, so masked entries drop out.
    p = jnp.exp(s - lse_ref[0][:, 0:1])
    return qs, p


def _flash_bwd_dkdv_kernel(
    q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
    dk_ref, dv_ref, dk_acc, dv_acc,
    *, scale, block_q, block_k, causal, num_q_blocks,
):
    """Grid (batch*heads, S/block_k, S/block_q): the query axis iterates
    fastest; dK/dV accumulate in VMEM scratch per key block."""
    j = pl.program_id(1)  # key block
    i = pl.program_id(2)  # query block (innermost)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    compute = (block_k * j) <= (block_q * i + block_q - 1) if causal else True

    @pl.when(compute)
    def _block():
        qs, p = _bwd_score_block(
            q_ref, k_ref, lse_ref, scale, block_q, block_k, causal, i, j
        )
        do = do_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        # dV += P^T dO ; dS = P * (dO V^T - delta) ; dK += dS^T (Q * scale)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[0][:, 0:1])
        dk_acc[:] += jax.lax.dot_general(
            ds, qs, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(i == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(
    q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
    dq_ref, dq_acc,
    *, scale, block_q, block_k, causal, num_k_blocks,
):
    """Grid (batch*heads, S/block_q, S/block_k): the key axis iterates
    fastest; dQ accumulates in VMEM scratch per query block."""
    i = pl.program_id(1)  # query block
    j = pl.program_id(2)  # key block (innermost)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    compute = (block_k * j) <= (block_q * i + block_q - 1) if causal else True

    @pl.when(compute)
    def _block():
        _, p = _bwd_score_block(
            q_ref, k_ref, lse_ref, scale, block_q, block_k, causal, i, j
        )
        do = do_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[0][:, 0:1])
        dq_acc[:] += jax.lax.dot_general(
            ds, k_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        # S = (Q * scale) K^T, so dQ picks up the remaining scale factor.
        dq_ref[0] = (dq_acc[:] * scale).astype(dq_ref.dtype)


def _flash_bwd_impl(q, k, v, out, lse, g, causal, block_q, block_k, interpret):
    """Blockwise dQ/dK/dV: two pallas_calls, no S^2 materialization.

    ``lse`` is the forward's per-row logsumexp, shape ``(batch*heads,
    s_pad)`` in the padded sequence length.
    """
    *batch, s, d = q.shape
    bh = 1
    for dim in batch:
        bh *= dim

    block_q = min(block_q, s)
    block_k = min(block_k, s)
    block = math.lcm(block_q, block_k)
    s_pad = pl.cdiv(s, block) * block
    d_pad = pl.cdiv(d, LANES) * LANES
    nq = s_pad // block_q
    nk = s_pad // block_k
    scale = 1.0 / (d**0.5)

    def prep(x):
        x = x.reshape(bh, s, d)
        return jnp.pad(x, ((0, 0), (0, s_pad - s), (0, d_pad - d)))

    qp, kp, vp, dop, outp = prep(q), prep(k), prep(v), prep(g), prep(out)
    # delta = rowsum(dO * O): one elementwise pass, O(S*d).  Padded rows have
    # dO = 0, so their delta is 0 and their dS vanishes.
    delta = jnp.sum(dop.astype(jnp.float32) * outp.astype(jnp.float32), axis=-1)
    # Lane-broadcast the row statistics (see the forward's lse store).
    lane = lambda x: jnp.broadcast_to(x[:, :, None], (bh, s_pad, LANES))
    lse_b, delta_b = lane(lse), lane(delta)

    qspec = lambda im: pl.BlockSpec((1, block_q, d_pad), im, memory_space=pltpu.VMEM)
    kspec = lambda im: pl.BlockSpec((1, block_k, d_pad), im, memory_space=pltpu.VMEM)
    rowspec = lambda im: pl.BlockSpec((1, block_q, LANES), im, memory_space=pltpu.VMEM)

    # dK/dV: grid (bh, nk, nq), query axis innermost.
    by_q = lambda b, j, i: (b, i, 0)
    by_k = lambda b, j, i: (b, j, 0)
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkdv_kernel,
            scale=scale, block_q=block_q, block_k=block_k, causal=causal,
            num_q_blocks=nq,
        ),
        out_shape=(
            jax.ShapeDtypeStruct(kp.shape, k.dtype),
            jax.ShapeDtypeStruct(vp.shape, v.dtype),
        ),
        grid=(bh, nk, nq),
        in_specs=[qspec(by_q), qspec(by_q), rowspec(by_q), rowspec(by_q),
                  kspec(by_k), kspec(by_k)],
        out_specs=(kspec(by_k), kspec(by_k)),
        scratch_shapes=[
            pltpu.VMEM((block_k, d_pad), jnp.float32),
            pltpu.VMEM((block_k, d_pad), jnp.float32),
        ],
        interpret=interpret,
    )(qp, dop, lse_b, delta_b, kp, vp)

    # dQ: grid (bh, nq, nk), key axis innermost.
    by_q2 = lambda b, i, j: (b, i, 0)
    by_k2 = lambda b, i, j: (b, j, 0)
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel,
            scale=scale, block_q=block_q, block_k=block_k, causal=causal,
            num_k_blocks=nk,
        ),
        out_shape=jax.ShapeDtypeStruct(qp.shape, q.dtype),
        grid=(bh, nq, nk),
        in_specs=[qspec(by_q2), qspec(by_q2), rowspec(by_q2), rowspec(by_q2),
                  kspec(by_k2), kspec(by_k2)],
        out_specs=qspec(by_q2),
        scratch_shapes=[pltpu.VMEM((block_q, d_pad), jnp.float32)],
        interpret=interpret,
    )(qp, dop, lse_b, delta_b, kp, vp)

    unpad = lambda x: x[:, :s, :d].reshape(*batch, s, d)
    return unpad(dq), unpad(dk), unpad(dv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
) -> jax.Array:
    """Blockwise attention over ``(..., seq, head_dim)`` inputs.

    Leading dims (batch, heads) are arbitrary; seq is padded to the block
    size internally (sound under ``causal=True``); head_dim is zero-padded
    to the 128-lane width and sliced back.
    """
    return _flash_impl(q, k, v, causal, block_q, block_k, interpret)


def flash_attention_for_config(q, k, v, config, *, causal: bool = True) -> jax.Array:
    """Config-driven plain-flash dispatch: block size from
    ``config.flash_block_size``, interpret mode from the backend.  The ONE
    call shared by the training attention (`models/transformer.py`), the
    decode prefill (`models/decode.py`), and future sites — so the call
    signature and interpret-mode policy can't drift between copies."""
    from bpe_transformer_tpu.kernels.pallas.runtime import interpret_mode

    block = config.flash_block_size
    return flash_attention(q, k, v, causal, block, block, interpret_mode())


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_impl(
        q, k, v, causal, block_q, block_k, interpret, return_lse=True
    )
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, residuals, g):
    q, k, v, out, lse = residuals
    return _flash_bwd_impl(
        q, k, v, out, lse, g, causal, block_q, block_k, interpret
    )


flash_attention.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------- ring / context-parallel interface


def flash_attention_with_lse(
    q, k, v, causal, block_q, block_k, interpret
) -> tuple[jax.Array, jax.Array]:
    """Forward + per-row logsumexp ``(..., seq)`` — the statistic a
    ring/context-parallel caller needs to merge partial attention outputs
    across visiting K/V shards (log-sum-exp combine).  Forward only; the
    ring caller owns the custom VJP.
    """
    *batch, s, d = q.shape
    out, lse = _flash_impl(
        q, k, v, causal, block_q, block_k, interpret, return_lse=True
    )
    return out, lse[:, :s].reshape(*batch, s)


def flash_attention_block_bwd(
    q, k, v, out, lse, g, causal, block_q, block_k, interpret
):
    """Partial (dq, dk, dv) of ONE visiting K/V block, given the GLOBAL
    forward output and logsumexp.

    With ``lse``/``out`` computed over ALL keys, the recomputed block
    probabilities ``exp(s_blk - lse)`` are the true global attention
    weights of this block, so the returned grads are exactly this block's
    additive contributions (the standard ring-flash backward).  ``q`` and
    ``k``/``v`` must share the (square) shard length, divisible by the
    block sizes.
    """
    *batch, s, d = q.shape
    if s % math.lcm(min(block_q, s), min(block_k, s)):
        raise ValueError(
            f"block backward needs seq ({s}) divisible by the block sizes"
        )
    bh = 1
    for dim in batch:
        bh *= dim
    return _flash_bwd_impl(
        q, k, v, out, lse.reshape(bh, s), g, causal, block_q, block_k, interpret
    )


# ------------------------------------------------- fused RoPE + attention


def _xla_rope_attention(q, k, v, cos, sin, causal: bool):
    """XLA oracle for the fused kernel: interleaved-pair RoPE on Q/K, then
    materialized-scores attention (used for parity tests and the recompute
    backward)."""
    from bpe_transformer_tpu.ops.rope import apply_rope

    positions = jnp.arange(q.shape[-2])
    qr = apply_rope(q.astype(jnp.float32), positions, cos, sin)
    kr = apply_rope(k.astype(jnp.float32), positions, cos, sin)
    return _xla_attention(qr, kr, v.astype(jnp.float32), causal).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def flash_attention_with_rope(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
) -> jax.Array:
    """Flash attention with RoPE applied to Q/K inside the kernel.

    ``cos``/``sin`` are position-gathered tables of shape ``(seq, d//2)``
    (interleaved-pair convention, `ops.rope.rope_tables` rows selected by
    token position) — the rotated Q/K exist only in VMEM, saving one full
    read+write of Q and K through HBM versus rope-then-attention
    (BASELINE.json config 4: fused RoPE+attention at seq 1k/4k/16k).
    """
    return _flash_impl(q, k, v, causal, block_q, block_k, interpret, cos, sin)


def _flash_rope_fwd(q, k, v, cos, sin, causal, block_q, block_k, interpret):
    out, lse = _flash_impl(
        q, k, v, causal, block_q, block_k, interpret, cos, sin, return_lse=True
    )
    return out, (q, k, v, cos, sin, out, lse)


def _flash_rope_bwd(causal, block_q, block_k, interpret, residuals, g):
    """FA-2 backward through the rotation: RoPE is orthogonal per (position,
    pair), so rotate Q/K forward (elementwise, O(S*d)), run the blockwise
    backward on the rotated values — scores and lse are invariant to the
    layout permutation the forward kernel uses — then apply the transposed
    rotation (angle negated) to dQ/dK.  cos/sin grads are computed exactly
    from the elementwise rotation (they are non-trainable tables in the
    model, but the vjp stays honest)."""
    from bpe_transformer_tpu.ops.rope import apply_rope

    q, k, v, cos, sin, out, lse = residuals
    positions = jnp.arange(q.shape[-2])
    f32 = jnp.float32
    qr = apply_rope(q.astype(f32), positions, cos, sin).astype(q.dtype)
    kr = apply_rope(k.astype(f32), positions, cos, sin).astype(k.dtype)
    dqr, dkr, dv = _flash_bwd_impl(
        qr, kr, v, out, lse, g, causal, block_q, block_k, interpret
    )
    dq = apply_rope(dqr.astype(f32), positions, cos, -sin).astype(q.dtype)
    dk = apply_rope(dkr.astype(f32), positions, cos, -sin).astype(k.dtype)

    def table_grads(x, dxr):
        # x_rot_even = x_e*c - x_o*s ; x_rot_odd = x_e*s + x_o*c  (per pair)
        x, dxr = x.astype(f32), dxr.astype(f32)
        xe, xo = x[..., 0::2], x[..., 1::2]
        ge, go = dxr[..., 0::2], dxr[..., 1::2]
        bdims = tuple(range(x.ndim - 2))
        dcos = jnp.sum(ge * xe + go * xo, axis=bdims)
        dsin = jnp.sum(go * xe - ge * xo, axis=bdims)
        return dcos, dsin

    dcq, dsq = table_grads(q, dqr)
    dck, dsk = table_grads(k, dkr)
    return dq, dk, dv, (dcq + dck).astype(cos.dtype), (dsq + dsk).astype(sin.dtype)


flash_attention_with_rope.defvjp(_flash_rope_fwd, _flash_rope_bwd)
