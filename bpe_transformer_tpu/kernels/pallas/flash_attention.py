"""Flash attention: blockwise online-softmax attention as a Pallas kernel,
optionally with RoPE fused into the Q/K block loads.

TPU-native replacement for materialized S^2 attention (the reference's spec
M7, `/root/reference/tests/adapters.py:92-110`, materializes the full score
matrix; BASELINE.json config 4 demands a fused RoPE+attention kernel at seq
1k/4k/16k).

Kernel structure (classic FlashAttention on the MXU):

* grid ``(batch*heads, S/block_q, S/block_k)`` — the key axis iterates
  fastest; VMEM scratch (f32 accumulator + running max/denominator) persists
  across the key axis so each query block is normalized online, never
  materializing more than a ``(block_q, block_k)`` score tile.
* causal masking happens at block granularity: key blocks strictly above the
  diagonal are predicated off, the diagonal block gets the triangular mask,
  blocks below run unmasked.
* sequence padding to the block size is sound under causal masking (padded
  keys sit above every valid query's diagonal) and padded query rows are
  sliced off on the way out.
* RoPE fusion: Q and K are pre-permuted on the host side from the
  interleaved pair convention ``(x0, x1, x2, x3, ...)`` to a half-split
  layout ``(x0, x2, ... | x1, x3, ...)``.  Attention scores are invariant
  under any fixed permutation of the head dim applied to both Q and K, so
  in-kernel rotation becomes two dense multiply-adds against full-width
  cos/sin tiles (``rot = x * C + swap(x) * S``) with no strided access —
  the rotated Q/K never round-trip through HBM.

The backward pass recomputes attention with plain XLA ops (memory-bound but
correct); a Pallas backward kernel is the natural next optimization.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bpe_transformer_tpu.ops.core import MASK_VALUE as NEG_INF
from bpe_transformer_tpu.ops.core import causal_mask, scaled_dot_product_attention

LANES = 128


def _rotate_half_layout(x, c, s, half: int):
    """RoPE rotation for inputs in the half-split feature layout.

    ``c``/``s`` are full-width cos/sin tiles ``[cos|cos|0]`` / ``[sin|sin|0]``
    so the rotation is ``x * c + swap(x) * s`` with ``swap = [-x2 | x1 | 0]``
    — two dense FMAs, no strided lane access.
    """
    x1 = x[:, :half]
    x2 = x[:, half : 2 * half]
    tail = x[:, 2 * half :]
    swapped = jnp.concatenate([-x2, x1, tail], axis=-1)
    return x * c + swapped * s


def _flash_kernel(
    *refs,
    scale: float, block_q: int, block_k: int, causal: bool, num_k_blocks: int,
    rope_half: int,
):
    if rope_half:
        q_ref, k_ref, v_ref, cq_ref, sq_ref, ck_ref, sk_ref = refs[:7]
        o_ref, acc_ref, m_ref, l_ref, qrot_ref = refs[7:]
    else:
        q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        if rope_half:
            # Rotate the query block once per (batch*head, q-block); it is
            # reused across every key block from VMEM scratch.
            qrot_ref[:] = _rotate_half_layout(
                q_ref[0].astype(jnp.float32) * scale,
                cq_ref[:].astype(jnp.float32),
                sq_ref[:].astype(jnp.float32),
                rope_half,
            )

    # Key blocks entirely above the causal diagonal contribute nothing.
    compute = (block_k * ik) <= (block_q * iq + block_q - 1) if causal else True

    @pl.when(compute)
    def _block():
        if rope_half:
            q = qrot_ref[:]
            k = _rotate_half_layout(
                k_ref[0].astype(jnp.float32),
                ck_ref[:].astype(jnp.float32),
                sk_ref[:].astype(jnp.float32),
                rope_half,
            )
        else:
            q = q_ref[0].astype(jnp.float32) * scale
            k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, block_k)

        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + iq * block_q
            cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + ik * block_k
            s = jnp.where(rows >= cols, s, NEG_INF)

        m_prev = m_ref[:, 0:1]
        l_prev = l_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ik == num_k_blocks - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, 0:1], 1e-30)  # fully-masked rows -> 0
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def _xla_attention(q, k, v, causal: bool):
    """Materialized-scores oracle (parity tests + the recompute backward):
    ops.core attention with float32 accumulation."""
    mask = causal_mask(q.shape[-2]) if causal else None
    out = scaled_dot_product_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32), mask
    )
    return out.astype(q.dtype)


def _flash_impl(q, k, v, causal, block_q, block_k, interpret, cos=None, sin=None):
    *batch, s, d = q.shape
    bh = 1
    for dim in batch:
        bh *= dim
    rope = cos is not None
    if rope and (cos.shape != (s, d // 2) or sin.shape != (s, d // 2)):
        raise ValueError(
            f"cos/sin must be position-gathered to shape (seq, d//2) = "
            f"{(s, d // 2)}, got {cos.shape} / {sin.shape}; select rows from "
            "rope_tables(...) by token position before calling"
        )

    block_q = min(block_q, s)
    block_k = min(block_k, s)
    # Pad so BOTH block sizes divide the padded length, or the grid would
    # skip trailing query/key blocks and return garbage rows.
    block = math.lcm(block_q, block_k)
    s_pad = pl.cdiv(s, block) * block
    if s_pad != s and not causal:
        raise ValueError(
            f"non-causal flash attention requires seq ({s}) divisible by the "
            f"block size ({block})"
        )
    d_pad = pl.cdiv(d, LANES) * LANES

    def prep(x):
        x = x.reshape(bh, s, d)
        return jnp.pad(x, ((0, 0), (0, s_pad - s), (0, d_pad - d)))

    if rope:
        half = d // 2
        # Scores are invariant to a fixed feature permutation applied to both
        # Q and K: move from the interleaved pair convention to a half-split
        # layout so the in-kernel rotation needs no strided access.
        to_half = lambda x: jnp.concatenate([x[..., 0::2], x[..., 1::2]], axis=-1)
        q, k = to_half(q), to_half(k)
        # Full-width tiles [cos|cos|0] / [sin|sin|0], padded to (s_pad, d_pad).
        ctile = jnp.pad(
            jnp.concatenate([cos, cos], axis=-1).astype(jnp.float32),
            ((0, s_pad - s), (0, d_pad - d)),
        )
        stile = jnp.pad(
            jnp.concatenate([sin, sin], axis=-1).astype(jnp.float32),
            ((0, s_pad - s), (0, d_pad - d)),
        )

    qp, kp, vp = prep(q), prep(k), prep(v)
    nq = s_pad // block_q
    nk = s_pad // block_k

    kernel = functools.partial(
        _flash_kernel,
        scale=1.0 / (d**0.5),  # true head dim, not the lane-padded one
        block_q=block_q,
        block_k=block_k,
        causal=causal,
        num_k_blocks=nk,
        rope_half=(d // 2) if rope else 0,
    )
    qspec = pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM)
    in_specs = [qspec, kspec, kspec]
    operands = [qp, kp, vp]
    scratch = [
        pltpu.VMEM((block_q, d_pad), jnp.float32),  # output accumulator
        pltpu.VMEM((block_q, LANES), jnp.float32),  # running row max
        pltpu.VMEM((block_q, LANES), jnp.float32),  # running denominator
    ]
    if rope:
        tile_q = pl.BlockSpec((block_q, d_pad), lambda b, i, j: (i, 0), memory_space=pltpu.VMEM)
        tile_k = pl.BlockSpec((block_k, d_pad), lambda b, i, j: (j, 0), memory_space=pltpu.VMEM)
        in_specs += [tile_q, tile_q, tile_k, tile_k]
        operands += [ctile, stile, ctile, stile]
        scratch.append(pltpu.VMEM((block_q, d_pad), jnp.float32))  # rotated Q

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(qp.shape, qp.dtype),
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, block_q, d_pad), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=scratch,
        interpret=interpret,
    )(*operands)

    return out[:, :s, :d].reshape(*batch, s, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
) -> jax.Array:
    """Blockwise attention over ``(..., seq, head_dim)`` inputs.

    Leading dims (batch, heads) are arbitrary; seq is padded to the block
    size internally (sound under ``causal=True``); head_dim is zero-padded
    to the 128-lane width and sliced back.
    """
    return _flash_impl(q, k, v, causal, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out = _flash_impl(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_bwd(causal, block_q, block_k, interpret, residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(lambda q_, k_, v_: _xla_attention(q_, k_, v_, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


# ------------------------------------------------- fused RoPE + attention


def _xla_rope_attention(q, k, v, cos, sin, causal: bool):
    """XLA oracle for the fused kernel: interleaved-pair RoPE on Q/K, then
    materialized-scores attention (used for parity tests and the recompute
    backward)."""
    from bpe_transformer_tpu.ops.rope import apply_rope

    positions = jnp.arange(q.shape[-2])
    qr = apply_rope(q.astype(jnp.float32), positions, cos, sin)
    kr = apply_rope(k.astype(jnp.float32), positions, cos, sin)
    return _xla_attention(qr, kr, v.astype(jnp.float32), causal).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def flash_attention_with_rope(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
) -> jax.Array:
    """Flash attention with RoPE applied to Q/K inside the kernel.

    ``cos``/``sin`` are position-gathered tables of shape ``(seq, d//2)``
    (interleaved-pair convention, `ops.rope.rope_tables` rows selected by
    token position) — the rotated Q/K exist only in VMEM, saving one full
    read+write of Q and K through HBM versus rope-then-attention
    (BASELINE.json config 4: fused RoPE+attention at seq 1k/4k/16k).
    """
    return _flash_impl(q, k, v, causal, block_q, block_k, interpret, cos, sin)


def _flash_rope_fwd(q, k, v, cos, sin, causal, block_q, block_k, interpret):
    out = _flash_impl(q, k, v, causal, block_q, block_k, interpret, cos, sin)
    return out, (q, k, v, cos, sin)


def _flash_rope_bwd(causal, block_q, block_k, interpret, residuals, g):
    q, k, v, cos, sin = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_, c_, s_: _xla_rope_attention(q_, k_, v_, c_, s_, causal),
        q, k, v, cos, sin,
    )
    return vjp(g)


flash_attention_with_rope.defvjp(_flash_rope_fwd, _flash_rope_bwd)
