"""Fused final-projection + sampling: the decode tick's tail as ONE kernel.

The unfused tick tail is a chain: head matmul -> (slots, vocab) f32
logits to HBM -> `filter_logits` (TWO full O(V log V) sorts for runtime
top-k/top-p) -> masked logits to HBM -> `jax.random.categorical` (gumbel
noise + argmax) — several vocab-sized HBM round trips and a pile of XLA
sort programs to emit ONE token per slot.  This kernel collapses the
whole tail: the head streams through VMEM once (int8 weights dequantize
in registers, `ops/quant.py` layout), logits accumulate in a VMEM
scratch and never reach HBM, and the filtering + sampling run in the
same program.

**Sort-free exact filtering.**  Runtime top-k/top-p need order
statistics (the k-th largest logit; the nucleus cutoff), which XLA gets
from full sorts.  Here both cutoffs come from a 32-step *radix descent
over order-preserving uint32 keys*: map each f32 logit to a uint32 whose
integer order equals the float order (sign-flip trick), then build the
threshold bit by bit from the MSB, counting (top-k) or mass-summing
(top-p) against each candidate prefix.  32 vectorized passes over the
VMEM-resident logits replace the sort — and the thresholds are EXACT
(they land on representable key values), so the keep sets match
`serving.engine.filter_logits`'s sorted-cutoff semantics bit for bit
(the only fp caveat: the nucleus mass comparison sums in a different
order than the sorted cumsum, so a logit sitting within one ulp of the
nucleus boundary can flip — measure-zero for real logits).

**Sampling.**  ``jax.random.categorical(key, masked)`` IS
``argmax(masked + gumbel(key, shape))`` — so the caller draws the gumbel
noise from the very key the unfused path would hand to ``categorical``
and passes it in; the kernel adds it to the masked logits and takes the
argmax (first occurrence, matching ``jnp.argmax``).  Greedy rows
(temp 0) take the raw-logits argmax, exactly as `sample_tokens`.

Two entry points share the machinery:

* :func:`fused_head_sample` — the tick tail: one token per row.
* :func:`fused_verify_head` — the speculative-decoding verify tail
  (`serving/spec/engine.py`): per scored row, the greedy token, the
  filtered target probability of the judged draft token (the ``p(d)`` of
  the Leviathan accept rule), and a residual-distribution sample
  (``max(p − q, 0)`` normalized, the rejection bonus) — so the verify
  program's only vocab-sized tensors outside the kernel are the draft's
  own ``q`` (which the propose program materialized anyway) and the
  gumbel noise.

Forward-only inference kernels, like the decode-attention siblings.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bpe_transformer_tpu.ops.core import MASK_VALUE as NEG_INF

SUBLANES = 8
LANE = 128


def _pick_block(n: int, target: int, step: int) -> int:
    """Largest multiple-of-``step`` divisor of ``n`` up to ``target``;
    falls back to ``n`` itself when no aligned divisor exists."""
    best = 0
    b = step
    while b <= min(target, n):
        if n % b == 0:
            best = b
        b += step
    return best or n


def _pick_block_v(v: int, target: int = 2048) -> int:
    """Vocab tile: multiple-of-128 (lane alignment for the
    dynamic-offset scratch stores); vocabularies with no aligned divisor
    run as a single whole-V head block — fine for the shipped configs
    (10k x d int8 is a ~2.5 MB tile) but a large unaligned vocab at the
    activation width can exceed VMEM on TPU; pick a 128-multiple vocab
    (or serve unfused) there."""
    return _pick_block(v, target, LANE)


def _okey(x):
    """f32 -> uint32 whose unsigned integer order equals the float order
    (IEEE sign-flip trick; NaN-free inputs assumed)."""
    b = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    return jnp.where(
        (b >> jnp.uint32(31)) > 0, ~b, b | jnp.uint32(0x80000000)
    )


def _argmax_first(x):
    """Row-wise argmax, first occurrence (``jnp.argmax`` semantics)."""
    v = x.shape[-1]
    m = jnp.max(x, axis=-1, keepdims=True)
    iota = jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
    return jnp.min(jnp.where(x == m, iota, v), axis=-1, keepdims=True)


def _topk_threshold(keys, kk):
    """Per row, the uint32 key of the ``kk``-th largest entry (ties give
    the shared key): radix descent for the largest ``t`` with
    ``count(keys >= t) >= kk``.  ``keys`` (R, V) uint32, ``kk`` (R, 1)
    int32 in [1, V]."""
    t = jnp.zeros(kk.shape, jnp.uint32)
    for bit in range(31, -1, -1):
        cand = t | jnp.uint32(1 << bit)
        cnt = jnp.sum(
            (keys >= cand).astype(jnp.int32), axis=-1, keepdims=True
        )
        t = jnp.where(cnt >= kk, cand, t)
    return t


def _nucleus_threshold(keys, e, p_mass):
    """Per row, the smallest uint32 ``t`` whose strictly-above mass
    ``sum(e[keys > t])`` is below ``p_mass`` — the value-space nucleus
    cutoff (an entry x is kept iff the mass strictly above it is < p,
    which is exactly the sorted-cumsum keep rule of ``filter_logits``).
    ``e`` must be 0 at already-dropped entries."""
    t = jnp.zeros(p_mass.shape, jnp.uint32)
    for bit in range(31, -1, -1):
        # Max completion with this bit still 0: if even it satisfies the
        # predicate, the minimum does too with bit 0; else the bit is 1.
        trial = t | jnp.uint32((1 << bit) - 1)
        g = jnp.sum(
            jnp.where(keys > trial, e, 0.0), axis=-1, keepdims=True
        )
        t = jnp.where(g < p_mass, t, t | jnp.uint32(1 << bit))
    return t


def _filter_rows(logits, temps, top_ks, top_ps):
    """The `filter_logits` keep-set + masked logits for (R, V) rows with
    per-row runtime knobs, sort-free (see module docstring).  Returns
    ``(masked, keep, e_kept, greedy)``: the -inf-masked scaled logits,
    the boolean keep set, the kept entries' ``exp(x - rowmax)`` weights
    (softmax numerators), and the raw-logits argmax."""
    v = logits.shape[-1]
    greedy = _argmax_first(logits)
    scaled = logits / jnp.maximum(temps, 1e-6)
    keys = _okey(scaled)

    kk_raw = top_ks.astype(jnp.int32)
    kk = jnp.where(kk_raw > 0, jnp.clip(kk_raw, 1, v), v)
    tk = _topk_threshold(keys, kk)
    keep_k = keys >= tk
    masked1 = jnp.where(keep_k, scaled, NEG_INF)

    m2 = jnp.max(masked1, axis=-1, keepdims=True)
    e = jnp.where(keep_k, jnp.exp(masked1 - m2), 0.0)
    z = jnp.sum(e, axis=-1, keepdims=True)
    tp = _nucleus_threshold(keys, e, top_ps * z)
    # The max (and its value-ties) always survives, as in filter_logits'
    # keep[..., 0] = True — value-based masking keeps every tie.
    keep = keep_k & ((keys >= tp) | (masked1 == m2))
    masked = jnp.where(keep, masked1, NEG_INF)
    return masked, keep, jnp.where(keep, e, 0.0), greedy


def _accumulate_logits(x_ref, h_ref, s_ref, acc_ref, *, block_v, quantized):
    """Grid step ``(i, j)``: head tile ``j``'s logit columns for row tile
    ``i`` into the scratch.  int8 tiles dequantize in registers —
    per-output-channel scale applied AFTER the f32-accumulated dot, so
    the weight bytes that cross HBM are the int8 payload."""
    j = pl.program_id(1)
    x = x_ref[...].astype(jnp.float32)           # (block_r, d)
    h = h_ref[...].astype(jnp.float32)           # (block_v, d)
    out = jax.lax.dot_general(
        x, h, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )                                             # (block_r, block_v)
    if quantized:
        out = out * s_ref[...].reshape(1, -1)
    acc_ref[:, pl.ds(j * block_v, block_v)] = out


def _sample_kernel(
    x_ref, h_ref, *refs, block_v, num_v_blocks, quantized,
):
    if quantized:
        s_ref, knobs_ref, g_ref, tok_ref, acc_ref = refs
    else:
        s_ref = None
        knobs_ref, g_ref, tok_ref, acc_ref = refs
    _accumulate_logits(
        x_ref, h_ref, s_ref, acc_ref, block_v=block_v, quantized=quantized
    )

    @pl.when(pl.program_id(1) == num_v_blocks - 1)
    def _finalize():
        logits = acc_ref[...]
        temps = knobs_ref[:, 0:1]
        masked, _, _, greedy = _filter_rows(
            logits, temps, knobs_ref[:, 1:2], knobs_ref[:, 2:3]
        )
        sampled = _argmax_first(masked + g_ref[...])
        tok_ref[...] = jnp.where(temps > 0.0, sampled, greedy).astype(
            jnp.int32
        )


def _verify_kernel(
    x_ref, h_ref, *refs, block_v, num_v_blocks, quantized,
):
    if quantized:
        (s_ref, knobs_ref, judge_ref, q_ref, g_ref,
         greedy_ref, pd_ref, bonus_ref, acc_ref) = refs
    else:
        s_ref = None
        (knobs_ref, judge_ref, q_ref, g_ref,
         greedy_ref, pd_ref, bonus_ref, acc_ref) = refs
    _accumulate_logits(
        x_ref, h_ref, s_ref, acc_ref, block_v=block_v, quantized=quantized
    )

    @pl.when(pl.program_id(1) == num_v_blocks - 1)
    def _finalize():
        logits = acc_ref[...]
        v = logits.shape[-1]
        temps = knobs_ref[:, 0:1]
        _, _, e_kept, greedy = _filter_rows(
            logits, temps, knobs_ref[:, 1:2], knobs_ref[:, 2:3]
        )
        iota = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        # Filtered target distribution p: softmax over the keep set for
        # sampled rows, the EXACT raw-argmax one-hot for greedy rows (the
        # Leviathan rule then collapses to argmax agreement).
        p_soft = e_kept / jnp.maximum(
            jnp.sum(e_kept, axis=-1, keepdims=True), 1e-30
        )
        onehot = (iota == greedy).astype(jnp.float32)
        p = jnp.where(temps > 0.0, p_soft, onehot)
        pd_ref[...] = jnp.sum(
            jnp.where(iota == judge_ref[...], p, 0.0),
            axis=-1, keepdims=True,
        )
        # Residual max(p - q, 0) with the all-mass-gone fallback to p
        # itself; the bonus/correction token is its gumbel-argmax sample
        # (sampled rows) or plain argmax (greedy rows) — exactly the
        # `_spec_verify_program` math, one sample per candidate row.
        res = jnp.maximum(p - q_ref[...].astype(jnp.float32), 0.0)
        has_mass = jnp.sum(res, axis=-1, keepdims=True) > 0
        res = jnp.where(has_mass, res, p)
        logres = jnp.where(res > 0, jnp.log(jnp.maximum(res, 1e-38)), NEG_INF)
        bonus_s = _argmax_first(logres + g_ref[...])
        bonus_g = _argmax_first(res)
        greedy_ref[...] = greedy.astype(jnp.int32)
        bonus_ref[...] = jnp.where(temps > 0.0, bonus_s, bonus_g).astype(
            jnp.int32
        )


def _head_operands(head, v, d):
    """Normalize the head argument: a raw ``(V, d)`` array or the int8
    quantized dict — returns ``(inputs, in_specs, quantized)`` for the
    blocked head tile (+ per-row scale tile when quantized)."""
    quantized = isinstance(head, dict)
    if quantized:
        q, scale = head["q"], head["scale"]
        if q.shape != (v, d) or scale.shape != (v,):
            raise ValueError(
                f"quantized head q {q.shape} / scale {scale.shape} must be "
                f"({v}, {d}) / ({v},)"
            )
        return [q, scale.reshape(v, 1)], quantized
    if head.shape != (v, d):
        raise ValueError(f"head {head.shape} must be ({v}, {d})")
    return [head], quantized


def _run(kernel_body, hidden, head, knobs, extra_inputs, out_shapes,
         *, vocab, interpret):
    """Shared pallas_call assembly for both entry points: grid =
    ``(row tiles, vocab tiles)`` with the vocab axis innermost, so each
    row tile's logits fully accumulate in the ``(block_r, vocab)``
    scratch before its finalize fires, then the next row tile reuses the
    scratch (the grid iterates sequentially, last axis fastest — the
    decode-attention kernels' accumulator pattern).  Row-tiling bounds
    VMEM: the scratch and every vocab-sized per-row operand (gumbel, the
    verify ``q``) live at ``block_r`` rows, not the full batch — the
    spec verify's rows = slots·(K+1) must not ride whole."""
    if interpret is None:
        from bpe_transformer_tpu.kernels.pallas.runtime import interpret_mode

        interpret = interpret_mode()
    r, d = hidden.shape
    r_pad = pl.cdiv(r, SUBLANES) * SUBLANES
    pad = lambda a: (
        a if a.shape[0] == r_pad
        else jnp.pad(a, ((0, r_pad - a.shape[0]),) + ((0, 0),) * (a.ndim - 1))
    )
    head_inputs, quantized = _head_operands(head, vocab, d)
    bv = _pick_block_v(vocab)
    nv = vocab // bv
    br = _pick_block(r_pad, 32, SUBLANES)

    rowspec = lambda minor: pl.BlockSpec(
        (br, minor), lambda i, j: (i, 0), memory_space=pltpu.VMEM
    )
    in_specs = [rowspec(d)]
    in_specs.append(
        pl.BlockSpec((bv, d), lambda i, j: (j, 0), memory_space=pltpu.VMEM)
    )
    if quantized:
        in_specs.append(
            pl.BlockSpec((bv, 1), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM)
        )
    in_specs.append(rowspec(knobs.shape[1]))
    inputs = [pad(hidden), *head_inputs, pad(knobs)]
    for arr in extra_inputs:
        inputs.append(pad(arr))
        in_specs.append(rowspec(arr.shape[1]))

    kernel = functools.partial(
        kernel_body, block_v=bv, num_v_blocks=nv, quantized=quantized
    )
    outs = pl.pallas_call(
        kernel,
        grid=(r_pad // br, nv),
        in_specs=in_specs,
        out_specs=[rowspec(1) for _ in out_shapes],
        out_shape=[
            jax.ShapeDtypeStruct((r_pad, 1), dt) for dt in out_shapes
        ],
        scratch_shapes=[pltpu.VMEM((br, vocab), jnp.float32)],
        interpret=interpret,
    )(*inputs)
    return [o[:r, 0] for o in outs]


def fused_head_sample(
    hidden: jax.Array,
    head,
    temps: jax.Array,
    top_ks: jax.Array,
    top_ps: jax.Array,
    gumbel: jax.Array,
    *,
    interpret: bool | None = None,
) -> jax.Array:
    """One fused tick tail: ``hidden (rows, d)`` -> sampled token ids
    ``(rows,)`` int32 under per-row runtime knobs.

    ``head`` is the LM head — a ``(vocab, d)`` array or the int8
    quantized dict.  ``gumbel (rows, vocab)`` is the caller's noise,
    drawn from the same key the unfused path would give
    ``jax.random.categorical`` (which is literally gumbel + argmax), so
    fused and unfused sampling agree token-for-token whenever the logits
    agree bitwise; greedy rows (temp 0) are argmax and agree always.
    """
    rows, _ = hidden.shape
    vocab = gumbel.shape[-1]
    knobs = jnp.stack(
        [
            temps.astype(jnp.float32),
            top_ks.astype(jnp.float32),
            top_ps.astype(jnp.float32),
        ],
        axis=1,
    )
    (tok,) = _run(
        _sample_kernel, hidden, head, knobs,
        [gumbel.astype(jnp.float32)], [jnp.int32],
        vocab=vocab, interpret=interpret,
    )
    return tok


def fused_verify_head(
    hidden: jax.Array,
    head,
    temps: jax.Array,
    top_ks: jax.Array,
    top_ps: jax.Array,
    judge_tokens: jax.Array,
    q_probs: jax.Array,
    gumbel: jax.Array,
    *,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """The speculative-verify tail for ``hidden (rows, d)`` scored rows
    (rows = slots * (K+1), row-major): returns ``(greedy, p_d, bonus)``
    each ``(rows,)`` — the raw-argmax token, the filtered target
    probability of ``judge_tokens`` (the accept rule's ``p(d)``; greedy
    rows handle it outside via argmax agreement), and a sample from the
    residual ``max(p − q_probs, 0)`` (fallback ``p``).  ``q_probs``/
    ``gumbel`` are ``(rows, vocab)``; all knobs per row.
    """
    rows, _ = hidden.shape
    vocab = q_probs.shape[-1]
    knobs = jnp.stack(
        [
            temps.astype(jnp.float32),
            top_ks.astype(jnp.float32),
            top_ps.astype(jnp.float32),
        ],
        axis=1,
    )
    greedy, p_d, bonus = _run(
        _verify_kernel, hidden, head, knobs,
        [
            judge_tokens.astype(jnp.int32).reshape(rows, 1),
            q_probs.astype(jnp.float32),
            gumbel.astype(jnp.float32),
        ],
        [jnp.int32, jnp.float32, jnp.int32],
        vocab=vocab, interpret=interpret,
    )
    return greedy, p_d, bonus
