"""Single-query (decode-step) attention against the KV cache as a Pallas
kernel.

The decode hot loop attends ONE new token per sequence against the whole
cached context (`models/decode.py:decode_step`) — a capability the
reference never implements (its contract stops at training logits,
`/root/reference/tests/adapters.py:282-361`).  Per token the XLA path
materializes a (B, KV, G, 1, ctx) score tensor, runs a separate f32
softmax pass, then a second contraction — three HBM round trips over
score-sized intermediates for what is fundamentally a bandwidth-bound
streaming reduction over the cache.  This kernel is the flash-decoding
formulation: the cache is streamed block-by-block through VMEM exactly
once, scores never leave VMEM, and the online-softmax accumulator
(`kernels/pallas/flash_attention.py`'s, specialized to a single query
position) produces the normalized output in the same pass.

Shapes (GQA-native — queries arrive grouped per KV head so the kernel
reads the COMPACT cache, preserving decode's GQA bandwidth win):

* ``q``        (batch, num_heads, d_head)     — the one new token's queries,
                                                RoPE already applied
* ``k_cache``  (batch, kv_heads, ctx, d_head) — written positions <= pos
* ``v_cache``  (batch, kv_heads, ctx, d_head)
* ``pos``      scalar int32 (traced)          — attend to cache[0..pos];
               or (batch,) for per-sequence frontiers (serving slot pool)
* returns      (batch, num_heads, d_head)

Grid ``(batch*kv_heads, ctx/block_k)``, key axis innermost; ``pos`` rides
scalar prefetch (SMEM) so the causal frontier is a traced value — the
generation loop's ``lax.scan`` carries it — while the program stays a
single compilation.  Key blocks entirely beyond ``pos`` are predicated
off AND their K/V index maps clamp to the frontier block, so the dead
tail of the cache is neither computed on nor fetched (the pipeline elides
the repeated-block DMAs) — early decode steps stream only the live
prefix.

The kernel is forward-only by design: decoding is inference.  Training
gradients flow through the training attention paths (flash/ring), never
through this one.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bpe_transformer_tpu.ops.core import MASK_VALUE as NEG_INF

LANES = 128
SUBLANES = 8


def _decode_kernel(
    pos_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
    *, scale: float, block_k: int, num_k_blocks: int, kv_heads: int,
):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # One frontier per batch row (grid axis 0 walks batch-major over
    # batch*kv_heads): a scalar pos is pre-broadcast to (batch,) by the
    # caller, so the per-sequence ragged case costs nothing extra.
    pos = pos_ref[pl.program_id(0) // kv_heads]
    # Blocks whose first key index is beyond the causal frontier contribute
    # nothing (pos >= 0 always leaves block 0 live, so l > 0 at finalize).
    @pl.when(j * block_k <= pos)
    def _block():
        q = q_ref[0].astype(jnp.float32) * scale  # (G_pad, d)
        k = k_ref[0].astype(jnp.float32)          # (block_k, d)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (G_pad, block_k)
        cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * block_k
        s = jnp.where(cols <= pos, s, NEG_INF)

        m_prev = m_ref[:, 0:1]
        l_prev = l_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        # eps guards the division only; padded (zero) query rows score 0
        # everywhere visible and emit a harmless uniform average of v —
        # the caller's out[:, :group] slice discards them.
        denom = jnp.maximum(l_ref[:, 0:1], 1e-30)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def decode_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    pos: jax.Array | int,
    *,
    block_k: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """One decode step of attention: ``softmax(q k^T / sqrt(d)) v`` over
    cache positions ``<= pos``, streamed blockwise (see module docstring).

    ``interpret=None`` resolves via ``runtime.interpret_mode()`` (compiled
    Mosaic on TPU, interpreter elsewhere), like the sibling kernels.
    """
    if interpret is None:
        from bpe_transformer_tpu.kernels.pallas.runtime import interpret_mode

        interpret = interpret_mode()
    batch, num_heads, d = q.shape
    b2, kv_heads, ctx, d2 = k_cache.shape
    if (b2, d2) != (batch, d) or v_cache.shape != k_cache.shape:
        raise ValueError(
            f"shape mismatch: q {q.shape}, k_cache {k_cache.shape}, "
            f"v_cache {v_cache.shape}"
        )
    if num_heads % kv_heads:
        raise ValueError(
            f"num_heads={num_heads} not divisible by kv_heads={kv_heads}"
        )
    group = num_heads // kv_heads
    # Shrink block_k (sublane-aligned) to a divisor of ctx when one exists,
    # so the no-copy fast path below covers every aligned context — e.g.
    # ctx=384 runs at block 128 instead of padding to 512.  Contexts with
    # no multiple-of-8 divisor (ragged test shapes) take the explicit
    # padded fallback with a sublane-aligned block.
    bk = min(block_k, ctx) - (min(block_k, ctx) % SUBLANES)
    while bk >= SUBLANES and ctx % bk:
        bk -= SUBLANES
    if bk >= SUBLANES:
        block_k = bk
    else:
        block_k = min(block_k, pl.cdiv(ctx, SUBLANES) * SUBLANES)

    # The CACHE is never copied: its head dim passes through the BlockSpec
    # at the true width (XLA's TPU layout already lane-pads the minor dim
    # physically, so block reads at d < 128 move the same tiles) and the
    # context axis is blocked in place.  A per-step jnp.pad of the whole
    # cache would materialize a padded HBM copy of every layer's cache on
    # every generated token — timing the copy, not the kernel (review r5).
    # Only the per-step operands are padded: the one-token query tile
    # (rows to the sublane width — padded G rows normalize against the eps
    # denominator and are sliced off) and, for ragged standalone contexts
    # only, the cache's trailing partial block (decode.py caches are always
    # context_length, a multiple of any shipped block_k).
    g_pad = pl.cdiv(group, SUBLANES) * SUBLANES
    ctx_pad = pl.cdiv(ctx, block_k) * block_k
    nk = ctx_pad // block_k
    bkv = batch * kv_heads

    qg = q.reshape(batch, kv_heads, group, d).reshape(bkv, group, d)
    qg = jnp.pad(qg, ((0, 0), (0, g_pad - group), (0, 0)))
    prep = lambda c: (
        c.reshape(bkv, ctx, d)
        if ctx_pad == ctx
        else jnp.pad(c.reshape(bkv, ctx, d), ((0, 0), (0, ctx_pad - ctx), (0, 0)))
    )
    kp, vp = prep(k_cache), prep(v_cache)
    # Scalar and per-batch frontiers share one program: broadcast to
    # (batch,) so the prefetch array's shape never varies.
    pos_arr = jnp.broadcast_to(
        jnp.asarray(pos, jnp.int32).reshape(-1), (batch,)
    )

    kernel = functools.partial(
        _decode_kernel,
        scale=1.0 / (d**0.5),  # true head dim, not the lane-padded one
        block_k=block_k,
        num_k_blocks=nk,
        kv_heads=kv_heads,
    )
    # Scalar-prefetch index maps receive the scalar ref as a trailing arg.
    # The K/V index CLAMPS to the causal frontier's block: grid steps beyond
    # ``pos`` are compute-predicated off in the kernel, and re-requesting
    # the frontier block instead of a dead one lets the pipeline elide the
    # DMA (same block index -> no refetch) — early decode steps would
    # otherwise stream the entire dead tail of the cache every token.
    qspec = pl.BlockSpec(
        (1, g_pad, d), lambda b, j, p: (b, 0, 0), memory_space=pltpu.VMEM
    )
    kvspec = pl.BlockSpec(
        (1, block_k, d),
        lambda b, j, p: (b, jnp.minimum(j, p[b // kv_heads] // block_k), 0),
        memory_space=pltpu.VMEM,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bkv, nk),
        in_specs=[qspec, kvspec, kvspec],
        out_specs=qspec,
        scratch_shapes=[
            pltpu.VMEM((g_pad, d), jnp.float32),      # output accumulator
            pltpu.VMEM((g_pad, LANES), jnp.float32),  # running row max
            pltpu.VMEM((g_pad, LANES), jnp.float32),  # running denominator
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((bkv, g_pad, d), q.dtype),
        interpret=interpret,
    )(pos_arr, qg, kp, vp)
    return out[:, :group, :].reshape(batch, num_heads, d)


def xla_decode_attention(q, k_cache, v_cache, pos):
    """Materialized-scores formulation: the grouped einsum straight against
    the compact GQA cache (the per-token hot path reads only
    ``kv_heads * ctx`` values — no head expansion), f32 scores + softmax.
    This IS `models/decode.py:decode_step`'s xla attention (that path calls
    here — single implementation) and the kernel's parity oracle.
    """
    batch, num_heads, d = q.shape
    kv_heads, ctx = k_cache.shape[1], k_cache.shape[2]
    qg = q.reshape(batch, kv_heads, num_heads // kv_heads, 1, d)
    # f32 scale promotes the scores out of bf16 before masking/softmax,
    # matching the kernel's f32 score accumulation.
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    scores = jnp.einsum("bkgqd,bkcd->bkgqc", qg, k_cache) * scale
    # pos is a scalar (whole batch at one depth) or (batch,) — per-sequence
    # causal frontiers for the serving engine's ragged slot pool.
    pos = jnp.asarray(pos)
    if pos.ndim == 0:
        visible = (jnp.arange(ctx) <= pos)[None, None, None, None, :]
    else:
        visible = (jnp.arange(ctx)[None, :] <= pos[:, None])[
            :, None, None, None, :
        ]
    scores = jnp.where(visible, scores, -jnp.inf)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    att = jnp.einsum("bkgqc,bkcd->bkgqd", probs, v_cache)
    return att.reshape(batch, num_heads, d)
