"""Single-query (decode-step) attention against the KV cache as a Pallas
kernel.

The decode hot loop attends ONE new token per sequence against the whole
cached context (`models/decode.py:decode_step`) — a capability the
reference never implements (its contract stops at training logits,
`/root/reference/tests/adapters.py:282-361`).  Per token the XLA path
materializes a (B, KV, G, 1, ctx) score tensor, runs a separate f32
softmax pass, then a second contraction — three HBM round trips over
score-sized intermediates for what is fundamentally a bandwidth-bound
streaming reduction over the cache.  This kernel is the flash-decoding
formulation: the cache is streamed block-by-block through VMEM exactly
once, scores never leave VMEM, and the online-softmax accumulator
(`kernels/pallas/flash_attention.py`'s, specialized to a single query
position) produces the normalized output in the same pass.

Shapes (GQA-native — queries arrive grouped per KV head so the kernel
reads the COMPACT cache, preserving decode's GQA bandwidth win):

* ``q``        (batch, num_heads, d_head)     — the one new token's queries,
                                                RoPE already applied
* ``k_cache``  (batch, kv_heads, ctx, d_head) — written positions <= pos
* ``v_cache``  (batch, kv_heads, ctx, d_head)
* ``pos``      scalar int32 (traced)          — attend to cache[0..pos];
               or (batch,) for per-sequence frontiers (serving slot pool)
* returns      (batch, num_heads, d_head)

Grid ``(batch*kv_heads, ctx/block_k)``, key axis innermost; ``pos`` rides
scalar prefetch (SMEM) so the causal frontier is a traced value — the
generation loop's ``lax.scan`` carries it — while the program stays a
single compilation.  Key blocks entirely beyond ``pos`` are predicated
off AND their K/V index maps clamp to the frontier block, so the dead
tail of the cache is neither computed on nor fetched (the pipeline elides
the repeated-block DMAs) — early decode steps stream only the live
prefix.

The kernel is forward-only by design: decoding is inference.  Training
gradients flow through the training attention paths (flash/ring), never
through this one.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bpe_transformer_tpu.ops.core import MASK_VALUE as NEG_INF

LANES = 128
SUBLANES = 8


def _decode_kernel(
    pos_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
    *, scale: float, block_k: int, num_k_blocks: int, kv_heads: int,
):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # One frontier per batch row (grid axis 0 walks batch-major over
    # batch*kv_heads): a scalar pos is pre-broadcast to (batch,) by the
    # caller, so the per-sequence ragged case costs nothing extra.
    pos = pos_ref[pl.program_id(0) // kv_heads]
    # Blocks whose first key index is beyond the causal frontier contribute
    # nothing (pos >= 0 always leaves block 0 live, so l > 0 at finalize).
    @pl.when(j * block_k <= pos)
    def _block():
        q = q_ref[0].astype(jnp.float32) * scale  # (G_pad, d)
        k = k_ref[0].astype(jnp.float32)          # (block_k, d)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (G_pad, block_k)
        cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * block_k
        s = jnp.where(cols <= pos, s, NEG_INF)

        m_prev = m_ref[:, 0:1]
        l_prev = l_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        # eps guards the division only; padded (zero) query rows score 0
        # everywhere visible and emit a harmless uniform average of v —
        # the caller's out[:, :group] slice discards them.
        denom = jnp.maximum(l_ref[:, 0:1], 1e-30)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def decode_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    pos: jax.Array | int,
    *,
    block_k: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """One decode step of attention: ``softmax(q k^T / sqrt(d)) v`` over
    cache positions ``<= pos``, streamed blockwise (see module docstring).

    ``interpret=None`` resolves via ``runtime.interpret_mode()`` (compiled
    Mosaic on TPU, interpreter elsewhere), like the sibling kernels.
    """
    if interpret is None:
        from bpe_transformer_tpu.kernels.pallas.runtime import interpret_mode

        interpret = interpret_mode()
    batch, num_heads, d = q.shape
    b2, kv_heads, ctx, d2 = k_cache.shape
    if (b2, d2) != (batch, d) or v_cache.shape != k_cache.shape:
        raise ValueError(
            f"shape mismatch: q {q.shape}, k_cache {k_cache.shape}, "
            f"v_cache {v_cache.shape}"
        )
    if num_heads % kv_heads:
        raise ValueError(
            f"num_heads={num_heads} not divisible by kv_heads={kv_heads}"
        )
    group = num_heads // kv_heads
    # Shrink block_k (sublane-aligned) to a divisor of ctx when one exists,
    # so the no-copy fast path below covers every aligned context — e.g.
    # ctx=384 runs at block 128 instead of padding to 512.  Contexts with
    # no multiple-of-8 divisor (ragged test shapes) take the explicit
    # padded fallback with a sublane-aligned block.
    bk = min(block_k, ctx) - (min(block_k, ctx) % SUBLANES)
    while bk >= SUBLANES and ctx % bk:
        bk -= SUBLANES
    if bk >= SUBLANES:
        block_k = bk
    else:
        block_k = min(block_k, pl.cdiv(ctx, SUBLANES) * SUBLANES)

    # The CACHE is never copied: its head dim passes through the BlockSpec
    # at the true width (XLA's TPU layout already lane-pads the minor dim
    # physically, so block reads at d < 128 move the same tiles) and the
    # context axis is blocked in place.  A per-step jnp.pad of the whole
    # cache would materialize a padded HBM copy of every layer's cache on
    # every generated token — timing the copy, not the kernel (review r5).
    # Only the per-step operands are padded: the one-token query tile
    # (rows to the sublane width — padded G rows normalize against the eps
    # denominator and are sliced off) and, for ragged standalone contexts
    # only, the cache's trailing partial block (decode.py caches are always
    # context_length, a multiple of any shipped block_k).
    g_pad = pl.cdiv(group, SUBLANES) * SUBLANES
    ctx_pad = pl.cdiv(ctx, block_k) * block_k
    nk = ctx_pad // block_k
    bkv = batch * kv_heads

    qg = q.reshape(batch, kv_heads, group, d).reshape(bkv, group, d)
    qg = jnp.pad(qg, ((0, 0), (0, g_pad - group), (0, 0)))
    prep = lambda c: (
        c.reshape(bkv, ctx, d)
        if ctx_pad == ctx
        else jnp.pad(c.reshape(bkv, ctx, d), ((0, 0), (0, ctx_pad - ctx), (0, 0)))
    )
    kp, vp = prep(k_cache), prep(v_cache)
    # Scalar and per-batch frontiers share one program: broadcast to
    # (batch,) so the prefetch array's shape never varies.
    pos_arr = jnp.broadcast_to(
        jnp.asarray(pos, jnp.int32).reshape(-1), (batch,)
    )

    kernel = functools.partial(
        _decode_kernel,
        scale=1.0 / (d**0.5),  # true head dim, not the lane-padded one
        block_k=block_k,
        num_k_blocks=nk,
        kv_heads=kv_heads,
    )
    # Scalar-prefetch index maps receive the scalar ref as a trailing arg.
    # The K/V index CLAMPS to the causal frontier's block: grid steps beyond
    # ``pos`` are compute-predicated off in the kernel, and re-requesting
    # the frontier block instead of a dead one lets the pipeline elide the
    # DMA (same block index -> no refetch) — early decode steps would
    # otherwise stream the entire dead tail of the cache every token.
    qspec = pl.BlockSpec(
        (1, g_pad, d), lambda b, j, p: (b, 0, 0), memory_space=pltpu.VMEM
    )
    kvspec = pl.BlockSpec(
        (1, block_k, d),
        lambda b, j, p: (b, jnp.minimum(j, p[b // kv_heads] // block_k), 0),
        memory_space=pltpu.VMEM,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bkv, nk),
        in_specs=[qspec, kvspec, kvspec],
        out_specs=qspec,
        scratch_shapes=[
            pltpu.VMEM((g_pad, d), jnp.float32),      # output accumulator
            pltpu.VMEM((g_pad, LANES), jnp.float32),  # running row max
            pltpu.VMEM((g_pad, LANES), jnp.float32),  # running denominator
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((bkv, g_pad, d), q.dtype),
        interpret=interpret,
    )(pos_arr, qg, kp, vp)
    return out[:, :group, :].reshape(batch, num_heads, d)


def _paged_decode_kernel(
    tables_ref, pos_ref, q_ref, k_ref, v_ref, *refs,
    scale: float, block_size: int, num_blocks_per_slot: int, kv_heads: int,
    quantized: bool,
):
    """Block-table flash decode: grid axis 1 walks a slot's KV BLOCKS (the
    block table was already consumed by the BlockSpec index maps, so
    ``k_ref``/``v_ref`` hold one pool block each) with the same online
    softmax as :func:`_decode_kernel`."""
    if quantized:
        kscale_ref, vscale_ref, o_ref, acc_ref, m_ref, l_ref = refs
    else:
        o_ref, acc_ref, m_ref, l_ref = refs
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    pos = pos_ref[pl.program_id(0) // kv_heads]
    # This program's kv head (hoisted: program_id is a top-level-only
    # primitive under the interpreter) — used to select the dequant scale.
    head = pl.program_id(0) % kv_heads

    @pl.when(j * block_size <= pos)
    def _block():
        q = q_ref[0].astype(jnp.float32) * scale  # (G_pad, d)
        k = k_ref[0, 0].astype(jnp.float32)       # (block_size, d)
        v = v_ref[0, 0].astype(jnp.float32)
        if quantized:
            # Per-block-per-head dequant IN REGISTERS: the scale row is
            # (1, kv_heads) f32; this program's head is selected by lane
            # mask (dynamic lane indexing is not a TPU vector primitive).
            lane = jax.lax.broadcasted_iota(jnp.int32, (1, kv_heads), 1)
            k = k * jnp.sum(jnp.where(lane == head, kscale_ref[...], 0.0))
            v = v * jnp.sum(jnp.where(lane == head, vscale_ref[...], 0.0))
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (G_pad, block_size)
        cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + j * block_size
        s = jnp.where(cols <= pos, s, NEG_INF)

        m_prev = m_ref[:, 0:1]
        l_prev = l_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(j == num_blocks_per_slot - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[:, 0:1], 1e-30)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def paged_decode_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    tables: jax.Array,
    pos: jax.Array,
    *,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Paged-NATIVE flash decode: one decode step of attention read straight
    out of the KV block pool — no contiguous per-slot gather ever exists.

    The serving block pool (`models/decode.init_kv_pool`) stores KV as
    ``(num_blocks, kv_heads, block_size, d_head)``; each slot's cache is a
    chain of block ids in ``tables`` ``(slots, blocks_per_slot)``.  Where
    `gather_paged_kv` materializes a ``(slots, blocks_per_slot*block_size)``
    transient per layer per tick before any kernel runs, here the grid is
    ``(slots*kv_heads, blocks_per_slot)`` and the BLOCK TABLE IS CONSUMED
    INSIDE THE K/V BlockSpec INDEX MAPS: ``tables``/``pos`` ride scalar
    prefetch (SMEM), so grid step ``(b, j)`` DMAs pool block
    ``tables[slot, min(j, pos[slot] // block_size)]`` directly into VMEM.
    HBM traffic per tick drops to one streaming read of the LIVE blocks —
    the gather's extra write+read round trip of the whole transient is
    gone, and (as in :func:`decode_attention`) blocks beyond the causal
    frontier clamp to the frontier block so their DMAs are elided.

    ``k_scale``/``v_scale`` ``(num_blocks, kv_heads)`` f32 must be given
    exactly when the pool is int8-quantized (per-block-per-head scales, the
    serving pool's ``kv_dtype="int8"`` layout); the kernel dequantizes each
    block in registers, so the HBM side of the stream stays 1 byte/value.
    TPU note: int8 tiles want ``block_size`` >= 32 (sublane alignment at 8
    bits); the interpreter path (CPU tests) has no such constraint.

    ``pos`` is the per-slot causal frontier ``(slots,)`` (scalar broadcast
    accepted).  Returns ``(slots, num_heads, d_head)`` like
    :func:`decode_attention`.
    """
    if interpret is None:
        from bpe_transformer_tpu.kernels.pallas.runtime import interpret_mode

        interpret = interpret_mode()
    slots, num_heads, d = q.shape
    num_blocks, kv_heads, block_size, d2 = k_pool.shape
    if d2 != d or v_pool.shape != k_pool.shape:
        raise ValueError(
            f"shape mismatch: q {q.shape}, k_pool {k_pool.shape}, "
            f"v_pool {v_pool.shape}"
        )
    if tables.ndim != 2 or tables.shape[0] != slots:
        raise ValueError(
            f"tables {tables.shape} must be (slots={slots}, blocks_per_slot)"
        )
    if num_heads % kv_heads:
        raise ValueError(
            f"num_heads={num_heads} not divisible by kv_heads={kv_heads}"
        )
    quantized = k_scale is not None
    if quantized != (v_scale is not None) or (
        quantized != (k_pool.dtype == jnp.int8)
    ):
        raise ValueError(
            "k_scale/v_scale must both be given exactly for int8 pools"
        )
    if quantized and k_scale.shape != (num_blocks, kv_heads):
        raise ValueError(
            f"k_scale {k_scale.shape} must be (num_blocks={num_blocks}, "
            f"kv_heads={kv_heads})"
        )
    group = num_heads // kv_heads
    g_pad = pl.cdiv(group, SUBLANES) * SUBLANES
    nbs = tables.shape[1]
    skv = slots * kv_heads

    qg = q.reshape(slots, kv_heads, group, d).reshape(skv, group, d)
    qg = jnp.pad(qg, ((0, 0), (0, g_pad - group), (0, 0)))
    pos_arr = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1), (slots,))
    tables = jnp.asarray(tables, jnp.int32)

    kernel = functools.partial(
        _paged_decode_kernel,
        scale=1.0 / (d**0.5),
        block_size=block_size,
        num_blocks_per_slot=nbs,
        kv_heads=kv_heads,
        quantized=quantized,
    )
    # Index maps receive the scalar-prefetch refs as trailing args: the
    # block-table lookup happens HERE, steering each grid step's DMA to
    # its pool block.  Steps beyond the frontier clamp to the frontier
    # block (same id -> the pipeline elides the refetch) and are
    # compute-predicated off in the kernel, exactly like the dense kernel.
    qspec = pl.BlockSpec(
        (1, g_pad, d), lambda b, j, t, p: (b, 0, 0), memory_space=pltpu.VMEM
    )

    def kv_index(b, j, t, p):
        s = b // kv_heads
        return (t[s, jnp.minimum(j, p[s] // block_size)], b % kv_heads, 0, 0)

    kvspec = pl.BlockSpec(
        (1, 1, block_size, d), kv_index, memory_space=pltpu.VMEM
    )
    in_specs = [qspec, kvspec, kvspec]
    inputs = [qg, k_pool, v_pool]
    if quantized:

        def scale_index(b, j, t, p):
            s = b // kv_heads
            return (t[s, jnp.minimum(j, p[s] // block_size)], 0)

        sspec = pl.BlockSpec(
            (1, kv_heads), scale_index, memory_space=pltpu.VMEM
        )
        in_specs += [sspec, sspec]
        inputs += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(skv, nbs),
        in_specs=in_specs,
        out_specs=qspec,
        scratch_shapes=[
            pltpu.VMEM((g_pad, d), jnp.float32),      # output accumulator
            pltpu.VMEM((g_pad, LANES), jnp.float32),  # running row max
            pltpu.VMEM((g_pad, LANES), jnp.float32),  # running denominator
        ],
    )
    out_dtype = q.dtype
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((skv, g_pad, d), out_dtype),
        interpret=interpret,
    )(tables, pos_arr, *inputs)
    return out[:, :group, :].reshape(slots, num_heads, d)


def xla_decode_attention(q, k_cache, v_cache, pos):
    """Materialized-scores formulation: the grouped einsum straight against
    the compact GQA cache (the per-token hot path reads only
    ``kv_heads * ctx`` values — no head expansion), f32 scores + softmax.
    This IS `models/decode.py:decode_step`'s xla attention (that path calls
    here — single implementation) and the kernel's parity oracle.
    """
    batch, num_heads, d = q.shape
    kv_heads, ctx = k_cache.shape[1], k_cache.shape[2]
    qg = q.reshape(batch, kv_heads, num_heads // kv_heads, 1, d)
    # f32 scale promotes the scores out of bf16 before masking/softmax,
    # matching the kernel's f32 score accumulation.
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    scores = jnp.einsum("bkgqd,bkcd->bkgqc", qg, k_cache) * scale
    # pos is a scalar (whole batch at one depth) or (batch,) — per-sequence
    # causal frontiers for the serving engine's ragged slot pool.
    pos = jnp.asarray(pos)
    if pos.ndim == 0:
        visible = (jnp.arange(ctx) <= pos)[None, None, None, None, :]
    else:
        visible = (jnp.arange(ctx)[None, :] <= pos[:, None])[
            :, None, None, None, :
        ]
    scores = jnp.where(visible, scores, -jnp.inf)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    att = jnp.einsum("bkgqc,bkcd->bkgqd", probs, v_cache)
    return att.reshape(batch, num_heads, d)
