"""Int8-weight matmul with in-register dequantization.

The serving engines store matmul weights as ``{"q": int8 (d_out, d_in),
"scale": f32 (d_out,)}`` (`ops/quant.py`): 1 byte per value in HBM, one
f32 scale per output channel.  This kernel is the read path — the weight
twin of the PR 9 paged decode kernel's KV dequant: each grid step DMAs
one int8 row tile into VMEM, converts it to f32 **in registers**, runs
the dot at f32 accumulation, and applies the per-row scale to the tile's
output columns.  A dequantized copy of the weight never exists in HBM,
so the decode tick's weight stream is the int8 bytes — the ~2x-vs-bf16
cut the quantization exists for.

Because the scale is per OUTPUT channel the matmul factors exactly
(``y[.., o] = scale[o] * sum_i x[.., i] q[o, i]``), so dequantization is
one multiply per output element *after* the reduction — the MXU sees a
plain f32 dot over the converted tile.

Shapes: ``x (m, d_in)`` activations (any dtype; converted to f32 for the
accumulation), ``q (d_out, d_in)`` int8, ``scale (d_out,)`` f32; returns
``(m, d_out)`` **f32** (callers cast down; `head_logits` keeps the f32 —
logits stay float32-clean).  The grid tiles BOTH axes: ``d_out`` row
tiles (the weight stream) and ``m`` row tiles — the same dispatch serves
the 1-token decode tick (m = slots, one tile) and full prefill buckets
(m = bucket length), so the activation block must never assume
decode-sized m or a long bucket would blow the VMEM budget.  TPU note:
int8 weight tiles want 32-sublane alignment, so the d_out tile prefers
multiples of 32; dimensions with no aligned divisor fall back to a
single whole-axis tile (physically lane/sublane-padded by the layout,
like the decode kernel's narrow head dims).  Interpret mode runs
everywhere else (CPU tests), as with the sibling kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

SUBLANES = 8
#: Preferred int8 sublane alignment (min int8 tile is (32, 128)).
INT8_SUBLANES = 32


def _pick_block(n: int, target: int = 512, step: int = INT8_SUBLANES) -> int:
    """Largest divisor of ``n`` that is a multiple of ``step`` and <=
    ``target``; falls back to ``n`` itself (whole-axis tile) when no
    aligned divisor exists."""
    best = 0
    b = step
    while b <= min(target, n):
        if n % b == 0:
            best = b
        b += step
    return best or n


def _quant_matmul_kernel(x_ref, q_ref, s_ref, o_ref):
    x = x_ref[...].astype(jnp.float32)          # (block_m, d_in)
    w = q_ref[...].astype(jnp.float32)          # (block_n, d_in) — in regs
    out = jax.lax.dot_general(
        x, w, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )                                            # (block_m, block_n)
    # scale rides as (block_n, 1); transpose to broadcast over rows.
    o_ref[...] = out * s_ref[...].reshape(1, -1)


def quant_matmul(
    x: jax.Array,
    q: jax.Array,
    scale: jax.Array,
    *,
    block_n: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """``x @ (q * scale[:, None]).T`` with the dequant in registers (see
    module docstring).  ``x`` may have any leading shape; returns f32
    ``(*leading, d_out)``."""
    if interpret is None:
        from bpe_transformer_tpu.kernels.pallas.runtime import interpret_mode

        interpret = interpret_mode()
    *lead, d_in = x.shape
    n, d_in2 = q.shape
    if d_in2 != d_in or scale.shape != (n,):
        raise ValueError(
            f"shape mismatch: x {x.shape}, q {q.shape}, scale {scale.shape}"
        )
    m = 1
    for dim in lead:
        m *= dim
    x2 = x.reshape(m, d_in)
    m_pad = pl.cdiv(max(m, 1), SUBLANES) * SUBLANES
    if m_pad != m:
        x2 = jnp.pad(x2, ((0, m_pad - m), (0, 0)))
    bn = block_n or _pick_block(n)
    if n % bn:
        raise ValueError(f"block_n={bn} must divide d_out={n}")
    # Tile m too: a full prefill bucket's activations must not ride VMEM
    # whole (m_pad is a SUBLANES multiple, so a divisor always exists).
    bm = _pick_block(m_pad, target=256, step=SUBLANES)

    out = pl.pallas_call(
        _quant_matmul_kernel,
        grid=(m_pad // bm, n // bn),
        in_specs=[
            pl.BlockSpec((bm, d_in), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, d_in), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bn, 1), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m_pad, n), jnp.float32),
        interpret=interpret,
    )(x2, q, scale.reshape(n, 1))
    return out[:m].reshape(*lead, n)
