"""Pallas GeLU kernel (tanh approximation).

TPU-native equivalent of the reference's only accelerator kernel, the Triton
GeLU (`/root/reference/bpe_transformer/kernels/triton/gelu.py:33-64`), with
the same tanh-approximation constants (sqrt(2/pi) ~ 0.79788456, c=0.044715).

Where the Triton kernel tiles a flat pointer over 1024-thread blocks, the
TPU version tiles a (rows, 128)-lane layout over the VPU: the wrapper pads
and reshapes any input to lane-aligned 2-D tiles, and the kernel body is pure
elementwise VPU work per (ROWS_PER_TILE, 128) block.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bpe_transformer_tpu.kernels.pallas.runtime import interpret_mode

LANES = 128
ROWS_PER_TILE = 256  # (256, 128) f32 tile = 128 KB of VMEM per buffer

_SQRT_2_OVER_PI = 0.79788456
_C = 0.044715


def _gelu_kernel(x_ref, y_ref):
    x = x_ref[:]
    inner = _SQRT_2_OVER_PI * (x + _C * x * x * x)
    # tanh via exp, as the reference kernel computes it — but clamped: exp of
    # ~2*44 overflows float32 to inf (NaN after the divide), while tanh has
    # saturated to 1.0 long before that.
    e = jnp.exp(jnp.minimum(2.0 * inner, 30.0))
    tanh = (e - 1.0) / (e + 1.0)
    y_ref[:] = 0.5 * x * (1.0 + tanh)


@jax.custom_jvp
def gelu(x: jax.Array) -> jax.Array:
    """Elementwise tanh-approx GeLU for arrays of any shape/float dtype.

    Differentiable: the backward uses the closed-form derivative in XLA (the
    forward Pallas kernel itself is not traced by autodiff).  On non-TPU
    backends the kernel runs in Pallas interpret mode.
    """
    interpret = interpret_mode()
    original_shape = x.shape
    flat = x.reshape(-1)
    n = flat.shape[0]

    tile_elems = ROWS_PER_TILE * LANES
    padded = pl.cdiv(n, tile_elems) * tile_elems
    if padded != n:
        flat = jnp.pad(flat, (0, padded - n))
    tiled = flat.reshape(-1, LANES)
    num_tiles = tiled.shape[0] // ROWS_PER_TILE

    out = pl.pallas_call(
        _gelu_kernel,
        out_shape=jax.ShapeDtypeStruct(tiled.shape, tiled.dtype),
        grid=(num_tiles,),
        in_specs=[
            pl.BlockSpec(
                (ROWS_PER_TILE, LANES),
                lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (ROWS_PER_TILE, LANES),
            lambda i: (i, 0),
            memory_space=pltpu.VMEM,
        ),
        interpret=interpret,
    )(tiled)

    return out.reshape(-1)[:n].reshape(original_shape)


@gelu.defjvp
def _gelu_jvp(primals, tangents):
    (x,), (dx,) = primals, tangents
    u = _SQRT_2_OVER_PI * (x + _C * x * x * x)
    t = jnp.tanh(u)
    du = _SQRT_2_OVER_PI * (1.0 + 3.0 * _C * x * x)
    grad = 0.5 * (1.0 + t) + 0.5 * x * (1.0 - t * t) * du
    return gelu(x), grad * dx


def gelu_reference(x: jax.Array) -> jax.Array:
    """Plain-XLA tanh-approx GeLU with identical constants (parity oracle)."""
    inner = _SQRT_2_OVER_PI * (x + _C * x * x * x)
    return 0.5 * x * (1.0 + jnp.tanh(inner))
