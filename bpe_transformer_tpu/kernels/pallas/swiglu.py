"""Fused Pallas SwiGLU FFN kernel.

Computes ``y = (silu(x @ w1.T) * (x @ w3.T)) @ w2.T`` in one kernel — the
gated hidden activation ``(tokens, d_ff)`` never round-trips to HBM (the
extension SURVEY §2.2 M5 anticipates beyond the XLA swiglu).

Tiling: grid ``(token_tiles, ff_tiles)``; each step loads an ``x`` tile and
one ``d_ff`` slice of w1/w3/w2 into VMEM, runs both up-projections + gate on
the MXU/VPU, and accumulates the down-projection into the output tile
(initialized on the first ``ff`` step).  ``d_model`` stays resident per tile.

Backward: closed-form VJP in plain XLA (recomputes the two up-projections —
same rematerialization trade as flash attention's backward).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bpe_transformer_tpu.kernels.pallas.runtime import interpret_mode

BLOCK_M = 256  # token-tile rows
BLOCK_F = 512  # d_ff slice per grid step


def _swiglu_kernel(x_ref, w1_ref, w3_ref, w2_ref, y_ref):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        y_ref[:] = jnp.zeros_like(y_ref)

    x = x_ref[:]
    up = jax.lax.dot_general(
        x, w1_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    lin = jax.lax.dot_general(
        x, w3_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    h = (up * jax.nn.sigmoid(up) * lin).astype(x.dtype)
    y_ref[:] += jax.lax.dot_general(
        h, w2_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(y_ref.dtype)


def _swiglu_impl(x2d, w1, w3, w2, block_m, block_f, interpret):
    m, d = x2d.shape
    ff = w1.shape[0]
    grid = (pl.cdiv(m, block_m), pl.cdiv(ff, block_f))
    return pl.pallas_call(
        _swiglu_kernel,
        out_shape=jax.ShapeDtypeStruct((m, d), x2d.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, d), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block_f, d), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block_f, d), lambda i, j: (j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((d, block_f), lambda i, j: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (block_m, d), lambda i, j: (i, 0), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(x2d, w1, w3, w2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def swiglu_fused(
    x: jax.Array,
    w1: jax.Array,
    w2: jax.Array,
    w3: jax.Array,
    block_m: int = BLOCK_M,
    block_f: int = BLOCK_F,
    interpret: bool | None = None,
) -> jax.Array:
    """Fused SwiGLU: ``x (..., d_model)``, ``w1/w3 (d_ff, d_model)``,
    ``w2 (d_model, d_ff)`` -> ``(..., d_model)``.

    Same argument order/layout as ``ops.core.swiglu`` (the XLA baseline and
    parity oracle).  Runs in Pallas interpret mode off-TPU.
    """
    if interpret is None:
        interpret = interpret_mode()
    orig_shape = x.shape
    d = orig_shape[-1]
    ff = w1.shape[0]
    x2d = x.reshape(-1, d)
    m = x2d.shape[0]

    # Pad every tiled dimension up to its block multiple — Pallas blocks must
    # cover the arrays exactly, and zero padding is algebraically inert here
    # (silu(0) * 0 contributes nothing; zero w2 rows produce zero columns).
    pad_m = pl.cdiv(m, block_m) * block_m - m
    pad_f = pl.cdiv(ff, block_f) * block_f - ff
    lane = 128
    pad_d = pl.cdiv(d, lane) * lane - d
    if pad_m or pad_d:
        x2d = jnp.pad(x2d, ((0, pad_m), (0, pad_d)))
    if pad_f or pad_d:
        w1 = jnp.pad(w1, ((0, pad_f), (0, pad_d)))
        w3 = jnp.pad(w3, ((0, pad_f), (0, pad_d)))
        w2 = jnp.pad(w2, ((0, pad_d), (0, pad_f)))
    out = _swiglu_impl(x2d, w1, w3, w2, block_m, block_f, interpret)
    if pad_m or pad_d:
        out = out[:m, :d]
    return out.reshape(orig_shape)


def _swiglu_fwd(x, w1, w2, w3, block_m, block_f, interpret):
    return swiglu_fused(x, w1, w2, w3, block_m, block_f, interpret), (x, w1, w2, w3)


def _swiglu_bwd(block_m, block_f, interpret, residuals, g):
    x, w1, w2, w3 = residuals
    orig_shape = x.shape
    d = orig_shape[-1]
    x2d = x.reshape(-1, d).astype(jnp.float32)
    g2d = g.reshape(-1, d).astype(jnp.float32)
    w1f, w2f, w3f = (w.astype(jnp.float32) for w in (w1, w2, w3))

    up = x2d @ w1f.T          # (m, ff)
    lin = x2d @ w3f.T
    sig = jax.nn.sigmoid(up)
    silu = up * sig
    h = silu * lin            # gated hidden

    gh = g2d @ w2f            # dL/dh, (m, ff)
    d_lin = gh * silu
    d_up = gh * lin * (sig + silu * (1.0 - sig))  # silu' = sig + silu(1-sig)

    dx = (d_up @ w1f + d_lin @ w3f).astype(x.dtype).reshape(orig_shape)
    dw1 = (d_up.T @ x2d).astype(w1.dtype)
    dw3 = (d_lin.T @ x2d).astype(w3.dtype)
    dw2 = (g2d.T @ h).astype(w2.dtype)
    return dx, dw1, dw2, dw3


swiglu_fused.defvjp(_swiglu_fwd, _swiglu_bwd)
