"""Parameter partition specs: DP (replicated), FSDP, and tensor parallelism.

The rules map the transformer's param pytree onto mesh axes:

* **dp** — every parameter replicated; only the batch is split.  Gradients
  are averaged over the ``data`` axis (psum over ICI).
* **fsdp** — each parameter's largest divisible dimension is sharded along
  the ``data`` axis (ZeRO-3-style); XLA all-gathers weights per layer and
  reduce-scatters gradients.
* **tp** — attention heads and FFN hidden columns split along the ``model``
  axis (Megatron-style pairings: row-parallel up-projections, column-parallel
  down-projections, vocab-parallel embeddings/head).

Specs compose: ``fsdp_tp`` applies TP first, then shards a remaining
dimension along ``data``.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

#: Minimum leaf size worth sharding under FSDP (tiny norms stay replicated).
FSDP_MIN_SIZE = 4096


def _path_str(path) -> str:
    parts = []
    for entry in path:
        if hasattr(entry, "key"):
            parts.append(str(entry.key))
        elif hasattr(entry, "idx"):
            parts.append(str(entry.idx))
        else:
            parts.append(str(entry))
    return "/".join(parts)


def _tp_spec(name: str, ndim: int) -> list:
    """Tensor-parallel assignment for one parameter (list of axis names/None)."""
    spec: list = [None] * ndim
    if ndim != 2:
        return spec
    # Row-parallel (split d_out): QKV head blocks, FFN up-projections,
    # vocab-parallel embedding and LM head.
    if any(
        key in name
        for key in ("q_proj", "k_proj", "v_proj", "w1", "w3", "token_embeddings", "lm_head")
    ):
        spec[0] = "model"
    # Column-parallel (split d_in, contracted away -> psum): attention output
    # projection and FFN down-projection.
    elif any(key in name for key in ("output_proj", "w2")):
        spec[1] = "model"
    return spec


def _fsdp_extend(spec: list, shape: tuple, axis_size: int, fsdp_axis: str) -> list:
    """Shard the largest still-unsharded, divisible dim along the FSDP axis."""
    candidates = [
        (dim_size, i)
        for i, dim_size in enumerate(shape)
        if spec[i] is None and dim_size % axis_size == 0
    ]
    if candidates:
        _, best = max(candidates)
        spec[best] = fsdp_axis
    return spec


def param_specs(
    params,
    mesh: Mesh,
    strategy: str = "dp",
    *,
    fsdp_axis: str = "data",
    tp_axis: str = "model",
    ep_axis: str = "expert",
):
    """Pytree of ``PartitionSpec`` for ``params`` under a strategy.

    ``strategy`` is an underscore-joined set of tokens from
    ``{dp, fsdp, tp, ep}`` (e.g. ``"fsdp_tp"``, ``"dp_ep"``).  ``ep`` shards
    the leading expert dim of MoE leaves (router + stacked expert weights)
    along ``ep_axis``; the dispatch einsums then lower to all-to-alls.
    """
    tokens = set(strategy.split("_")) if strategy else set()
    unknown = tokens - {"dp", "fsdp", "tp", "ep"}
    if not tokens or unknown:
        raise ValueError(f"unknown parallel strategy: {strategy!r}")
    use_tp = "tp" in tokens and tp_axis in mesh.shape
    use_fsdp = "fsdp" in tokens and fsdp_axis in mesh.shape
    use_ep = "ep" in tokens and ep_axis in mesh.shape
    fsdp_size = mesh.shape.get(fsdp_axis, 1)
    tp_size = mesh.shape.get(tp_axis, 1)
    ep_size = mesh.shape.get(ep_axis, 1)

    def rule(path, leaf):
        name = _path_str(path)
        spec: list = [None] * leaf.ndim
        is_moe = "ffn" in name and ("router" in name or leaf.ndim == 3)
        if use_ep and is_moe and leaf.shape[0] % ep_size == 0:
            spec[0] = ep_axis
        if use_tp and not is_moe:
            spec = _tp_spec(name, leaf.ndim)
            # Drop TP assignments that don't divide evenly.
            spec = [
                a if (a != tp_axis or leaf.shape[i] % tp_size == 0) else None
                for i, a in enumerate(spec)
            ]
        if use_fsdp and leaf.size >= FSDP_MIN_SIZE:
            spec = _fsdp_extend(spec, leaf.shape, fsdp_size, fsdp_axis)
        return P(*spec)

    return jax.tree_util.tree_map_with_path(rule, params)


def zero1_opt_specs(
    params, mesh: Mesh, strategy: str = "dp", *, fsdp_axis: str = "data"
):
    """ZeRO-1 partition specs for optimizer-moment leaves under GSPMD.

    Starts from the strategy's param specs and additionally shards each
    leaf's largest still-unsharded divisible dim along the data axis — so
    AdamW m/v live 1/N per chip even when the params themselves are
    replicated (plain dp) or only model-sharded (tp).  Annotating the
    opt-state in/out shardings with these specs is all GSPMD needs: XLA
    derives the reduce-scatter (grads → owned shard) and all-gather
    (fresh params) schedule from the annotations, the Xu et al.
    arXiv:2004.13336 weight-update sharding expressed declaratively.
    Under ``fsdp`` the extension is a no-op (moments already shard with
    the params).  Tiny leaves (< FSDP_MIN_SIZE) stay replicated.
    """
    base = param_specs(params, mesh, strategy, fsdp_axis=fsdp_axis)
    size = mesh.shape.get(fsdp_axis, 1)

    def extend(leaf, spec):
        s = list(spec) + [None] * (leaf.ndim - len(spec))
        if size <= 1 or fsdp_axis in s or leaf.size < FSDP_MIN_SIZE:
            return P(*s)
        return P(*_fsdp_extend(s, leaf.shape, size, fsdp_axis))

    return jax.tree_util.tree_map(
        extend, params, base,
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )


def zero1_opt_shardings(
    params, mesh: Mesh, strategy: str = "dp", *, fsdp_axis: str = "data"
):
    """Pytree of ``NamedSharding`` for ZeRO-1 optimizer moments."""
    specs = zero1_opt_specs(params, mesh, strategy, fsdp_axis=fsdp_axis)
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        specs,
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )


def param_shardings(params, mesh: Mesh, strategy: str = "dp", **kwargs):
    """Pytree of ``NamedSharding`` for ``params``."""
    specs = param_specs(params, mesh, strategy, **kwargs)
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec),
        specs,
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )


def shard_params(params, mesh: Mesh, strategy: str = "dp", **kwargs):
    """Place ``params`` on the mesh under the strategy's shardings."""
    shardings = param_shardings(params, mesh, strategy, **kwargs)
    return jax.device_put(params, shardings)
