"""Ulysses-style all-to-all sequence parallelism (DeepSpeed-Ulysses).

The second canonical long-context scheme next to ring attention
(`parallel/ring_attention.py`): instead of rotating K/V shards around the
mesh, ONE ``all_to_all`` re-partitions Q/K/V from sequence-sharded to
HEAD-sharded — every device then runs ordinary dense (or Pallas flash)
attention over the FULL sequence for its slice of the heads, and a second
``all_to_all`` restores the sequence sharding for the token-local rest of
the block.  No reference counterpart exists (max context there is 16
tokens, SURVEY §2.4); built because long-context is first-class here.

Trade-off vs the ring (why both exist):

* communication: Ulysses moves each Q/K/V/O tensor once (4 all-to-alls of
  O(B·S_local·d) per layer) regardless of mesh size; the ring moves K/V
  ``n-1`` times (2·(n-1) ppermutes of the same volume).  On all-to-all-
  friendly fabrics (TPU ICI is a torus — XLA lowers all_to_all to near-
  optimal bisection traffic) Ulysses wins at larger ``n``.
* constraint: the head count must be a multiple of the mesh axis size
  (heads are the scatter dimension); the ring has no head constraint.
* memory: per-device attention is (H/n heads, FULL S) — O(S²·H/n) scores
  if materialized, so pair with ``attention_impl="flash"`` at long S; the
  ring never materializes more than a shard-sized block.

Gradients need no custom VJP: the transpose of an ``all_to_all`` is the
reverse ``all_to_all``, so ``jax.grad`` derives the backward schedule.

All functions run INSIDE ``shard_map`` over a mesh with ``axis_name``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from bpe_transformer_tpu.models.config import ModelConfig
from bpe_transformer_tpu.ops.core import causal_mask, scaled_dot_product_attention


def _heads_to_seq(x, axis_name):
    """(B, H, S_local, d) seq-sharded -> (B, H/n, S_global, d) head-sharded.

    ``tiled=True`` keeps the split/concat in device order, and device order
    along the seq axis IS global sequence order for the contiguous layout
    (`shard_sp_batch` without zigzag), so the gathered sequence is in true
    token order and causal masking stays the plain triangular mask.
    """
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)


def _seq_to_heads(x, axis_name):
    """Inverse of :func:`_heads_to_seq`."""
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    config: ModelConfig | None = None,
) -> jax.Array:
    """Causal attention over sequence-sharded Q/K/V via head scattering.

    ``q/k/v``: (batch, heads, S_local, d_head), RoPE already applied with
    GLOBAL positions (the sp loss does this), KV heads already expanded
    (`ops.core.multihead_self_attention` broadcasts GQA before calling any
    attention_fn).  Returns the attended values in the same seq-sharded
    layout.

    GQA: when ``config.num_kv_heads`` divides the axis too, the K/V
    all_to_alls ship the COMPACT kv heads (the broadcast is undone by a
    strided slice and re-applied after the exchange) — group× less K/V
    communication, which is the scheme's whole currency.  The slice is
    exact because `multihead_self_attention` expands with ``jnp.repeat``,
    so every group of ``group`` consecutive heads is one kv head.

    With ``config.attention_impl == "flash"`` the full-sequence inner
    attention runs the Pallas flash kernel (no O(S²) score buffer);
    otherwise the materialized XLA oracle.
    """
    n = lax.axis_size(axis_name)
    heads = q.shape[-3]
    if heads % n:
        raise ValueError(
            f"Ulysses scatters heads over the mesh axis: num_heads={heads} "
            f"must be a multiple of the {axis_name!r} axis size {n} (use "
            "the ring schedule for head counts that aren't)"
        )
    kv_heads = (config.num_kv_heads or heads) if config is not None else heads
    group = heads // kv_heads
    compact_kv = group > 1 and kv_heads % n == 0
    if compact_kv:
        k = k[:, ::group]
        v = v[:, ::group]
    qh = _heads_to_seq(q, axis_name)
    kh = _heads_to_seq(k, axis_name)
    vh = _heads_to_seq(v, axis_name)
    if compact_kv:
        # Device i's query heads [i·H/n, (i+1)·H/n) map exactly onto its kv
        # shard [i·KV/n, (i+1)·KV/n) (H/n = group·KV/n), so re-expanding
        # locally reproduces the expanded-path pairing.
        kh = jnp.repeat(kh, group, axis=1)
        vh = jnp.repeat(vh, group, axis=1)
    if config is not None and config.attention_impl == "flash":
        from bpe_transformer_tpu.kernels.pallas.flash_attention import (
            flash_attention_for_config,
        )

        out = flash_attention_for_config(qh, kh, vh, config)
    else:
        mask = causal_mask(qh.shape[-2])
        out = scaled_dot_product_attention(
            qh.astype(jnp.float32), kh.astype(jnp.float32),
            vh.astype(jnp.float32), mask,
        ).astype(q.dtype)
    return _seq_to_heads(out, axis_name)
