"""Device meshes: the substrate for all multi-chip execution.

TPU-native scaling happens by laying a logical ``jax.sharding.Mesh`` over
the chip slice and annotating arrays with ``NamedSharding`` partition specs;
XLA then inserts the collectives (psum/all-gather/reduce-scatter) and routes
them over ICI.  The reference has no distributed backend at all (SURVEY §2.4
— host multiprocessing only), so this subsystem is designed TPU-first rather
than ported.

Axis conventions used across the framework:

* ``data``  — batch (data-parallel) axis; gradients are psum'd over it, and
  FSDP parameter shards also live along it.
* ``model`` — tensor-parallel axis for attention heads / FFN columns.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def make_mesh(
    axes: dict[str, int] | None = None,
    devices: list | None = None,
) -> Mesh:
    """Build a mesh from ``{axis_name: size}`` (defaults to 1-D data axis).

    ``mesh_utils.create_device_mesh`` picks a device ordering that keeps
    neighboring mesh coordinates physically adjacent on the ICI torus.
    """
    if devices is None:
        devices = jax.devices()
    if axes is None:
        axes = {"data": len(devices)}
    names = tuple(axes)
    shape = tuple(axes.values())
    if int(np.prod(shape)) != len(devices):
        raise ValueError(
            f"mesh {dict(axes)} needs {int(np.prod(shape))} devices, "
            f"have {len(devices)}"
        )
    if len(devices) == 1:
        device_array = np.asarray(devices).reshape(shape)
    else:
        device_array = mesh_utils.create_device_mesh(shape, devices=devices)
    return Mesh(device_array, names)


def make_hybrid_mesh(
    ici_axes: dict[str, int],
    dcn_axes: dict[str, int] | None = None,
    devices: list | None = None,
) -> Mesh:
    """Multi-slice mesh: ``ici_axes`` partition within a slice (fast ICI),
    ``dcn_axes`` replicate that layout across slices (slower DCN links).

    Each axis's total size is ``ici * dcn``; axes absent from ``dcn_axes``
    span a single slice.  Convention: put data parallelism (gradient
    all-reduce, the most DCN-tolerant collective) on the DCN axis and keep
    model/tensor/expert/sequence axes inside a slice.

    Example — 2 slices of a v4-16 with FSDP inside each slice::

        mesh = make_hybrid_mesh({"data": 8, "model": 2}, {"data": 2})
        # mesh.shape == {"data": 16, "model": 2}
    """
    if devices is None:
        devices = jax.devices()
    dcn_axes = dcn_axes or {}
    unknown = set(dcn_axes) - set(ici_axes)
    if unknown:
        raise ValueError(
            f"dcn axes {sorted(unknown)} not present in ici_axes "
            f"{sorted(ici_axes)}"
        )
    names = tuple(ici_axes)
    ici_shape = tuple(ici_axes.values())
    dcn_shape = tuple(dcn_axes.get(name, 1) for name in names)
    total = int(np.prod(ici_shape)) * int(np.prod(dcn_shape))
    if total != len(devices):
        raise ValueError(
            f"hybrid mesh ici={dict(ici_axes)} x dcn={dict(dcn_axes)} needs "
            f"{total} devices, have {len(devices)}"
        )
    if all(size == 1 for size in dcn_shape):
        return make_mesh(ici_axes, devices=devices)
    device_array = mesh_utils.create_hybrid_device_mesh(
        ici_shape, dcn_shape, devices=devices
    )
    return Mesh(device_array, names)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard the leading (batch) dimension along the data axis."""
    return NamedSharding(mesh, PartitionSpec(axis))


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Multi-host bring-up: join the jax.distributed cluster.

    On Cloud TPU pods the arguments are auto-detected from the metadata
    server; explicit values support other launchers.  After this returns,
    ``jax.devices()`` spans every host's chips and meshes built from it
    communicate over ICI within a slice and DCN across slices.
    """
    kwargs = {}
    if coordinator_address is not None:
        kwargs.update(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    jax.distributed.initialize(**kwargs)
