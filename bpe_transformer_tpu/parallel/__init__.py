"""Multi-chip parallelism: meshes, shardings, and collective train steps."""

from bpe_transformer_tpu.parallel.mesh import (
    batch_sharding,
    initialize_distributed,
    make_mesh,
    replicated,
)
from bpe_transformer_tpu.parallel.sharding import (
    param_shardings,
    param_specs,
    shard_params,
)
from bpe_transformer_tpu.parallel.train_step import (
    make_dp_train_step,
    make_gspmd_train_step,
    shard_batch,
)

__all__ = [
    "batch_sharding",
    "initialize_distributed",
    "make_dp_train_step",
    "make_gspmd_train_step",
    "make_mesh",
    "param_shardings",
    "param_specs",
    "replicated",
    "shard_batch",
    "shard_params",
]
