"""Multi-chip parallelism: meshes, shardings, and collective train steps."""

from bpe_transformer_tpu.compat.shardmap import ensure_shard_map

# Every module below (and their callers) uses jax.shard_map; on jax 0.4.x
# that name only exists under jax.experimental — alias it before anything
# can call it.
ensure_shard_map()

from bpe_transformer_tpu.parallel.mesh import (  # noqa: E402
    batch_sharding,
    initialize_distributed,
    make_hybrid_mesh,
    make_mesh,
    replicated,
)
from bpe_transformer_tpu.parallel.sharding import (
    param_shardings,
    param_specs,
    shard_params,
    zero1_opt_shardings,
    zero1_opt_specs,
)
from bpe_transformer_tpu.parallel.pp import (
    init_pp_opt_state,
    make_pp_train_step,
    shard_pp_params,
    stack_pipeline_params,
    unstack_pipeline_params,
)
from bpe_transformer_tpu.parallel.ring_attention import (
    make_ring_attention,
    ring_self_attention,
)
from bpe_transformer_tpu.parallel.ulysses import ulysses_attention
from bpe_transformer_tpu.parallel.sp import (
    make_sp_train_step,
    shard_sp_batch,
    sp_forward,
)
from bpe_transformer_tpu.parallel.train_step import (
    make_dp_train_step,
    make_gspmd_train_step,
    shard_batch,
)

__all__ = [
    "batch_sharding",
    "init_pp_opt_state",
    "make_pp_train_step",
    "shard_pp_params",
    "stack_pipeline_params",
    "unstack_pipeline_params",
    "make_ring_attention",
    "make_sp_train_step",
    "ring_self_attention",
    "shard_sp_batch",
    "sp_forward",
    "ulysses_attention",
    "initialize_distributed",
    "make_dp_train_step",
    "make_gspmd_train_step",
    "make_hybrid_mesh",
    "make_mesh",
    "param_shardings",
    "param_specs",
    "replicated",
    "shard_batch",
    "shard_params",
    "zero1_opt_shardings",
    "zero1_opt_specs",
]
