"""Multi-chip train steps: explicit-collective DP and GSPMD FSDP/TP.

Two complementary executions of the same update body
(`training.train_step.train_step_fn`):

* :func:`make_dp_train_step` — ``jax.shard_map`` over a 1-D ``data`` mesh.
  Every chip holds full replicas; the batch is split along ``data``; each
  chip computes local gradients and a single ``lax.pmean`` all-reduce (ICI)
  makes them global before the identical AdamW update runs everywhere.
  This is the BASELINE.json north-star collective, written explicitly.

* :func:`make_gspmd_train_step` — ``jax.jit`` with ``NamedSharding``
  in/out shardings for ``dp`` / ``fsdp`` / ``tp`` / ``fsdp_tp`` strategies
  (specs from `parallel.sharding`).  XLA's SPMD partitioner derives the
  all-gather / reduce-scatter / psum schedule from the annotations — the
  idiomatic TPU path that scales from v4-8 data parallelism to
  GPT-2-medium FSDP on v5p-16 (BASELINE configs 2/3/5).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from bpe_transformer_tpu.models.config import ModelConfig
from bpe_transformer_tpu.optim.adamw import AdamWState
from bpe_transformer_tpu.parallel.sharding import param_shardings
from bpe_transformer_tpu.training.train_step import (
    TrainHParams,
    grad_accum_step_fn,
    scanned_step_fn,
    train_step_fn,
)

P = PartitionSpec


def _multi_step_body(
    config: ModelConfig,
    hparams: TrainHParams,
    accum_steps: int,
    inner_steps: int,
    reduce_axis: str | None,
    health: bool = False,
    dynamics: bool = False,
    zero1_shards: int | None = None,
) -> tuple[Callable, bool]:
    """(body, stacked): the per-shard update body for the requested
    accumulation/scan mode, and whether batches carry a leading stacked dim
    (``(accum|inner, micro_batch, seq)`` instead of ``(batch, seq)``).

    ``health`` and ``dynamics`` thread through to the shared update bodies
    (see ``training.train_step.train_step_fn``): the device-side health/
    dynamics stats compile inside the same sharded program, so their
    reductions reuse the step's collectives and nothing new crosses the
    host boundary.

    ``zero1_shards`` swaps the AdamW update for the ZeRO-1 sharded one
    (`optim.sharded`): reduce-scatter grads, shard-local moment update,
    all-gather params — composed with the same accum/inner stacking."""
    if accum_steps > 1 and inner_steps > 1:
        raise ValueError("accum_steps and inner_steps cannot both exceed 1")
    if accum_steps > 1:
        return (
            grad_accum_step_fn(
                config, hparams, accum_steps, reduce_axis, health=health,
                dynamics=dynamics, zero1_shards=zero1_shards,
            ),
            True,
        )
    if inner_steps > 1:
        return (
            scanned_step_fn(
                config, hparams, inner_steps, reduce_axis, health=health,
                dynamics=dynamics, zero1_shards=zero1_shards,
            ),
            True,
        )
    return (
        train_step_fn(
            config, hparams, reduce_axis, health=health, dynamics=dynamics,
            zero1_shards=zero1_shards,
        ),
        False,
    )


def make_dp_train_step(
    config: ModelConfig,
    hparams: TrainHParams,
    mesh: Mesh,
    axis: str = "data",
    accum_steps: int = 1,
    inner_steps: int = 1,
    health: bool = False,
    dynamics: bool = False,
    opt_sharding: str | None = None,
) -> Callable:
    """Data-parallel step with an explicit gradient all-reduce over ``axis``.

    Batch arrays must be sharded (or shardable) along their leading dim;
    params/opt-state are replicated.  The global batch size must divide the
    mesh axis size.

    ``accum_steps > 1``: each chip scans its local microbatches and the
    all-reduce runs ONCE per update (after local accumulation); batches are
    ``(accum_steps, micro_batch, seq)`` with the micro batch split on
    ``axis``.  ``inner_steps > 1``: several full updates per dispatch, each
    with its own all-reduce; batches are ``(inner_steps, batch, seq)``.

    ``opt_sharding="zero1"`` replaces the pmean + replicated AdamW with the
    ZeRO-1 sharded update (`optim.sharded`): reduce-scatter grads along
    ``axis``, shard-local moment/master update, all-gather fresh params.
    The opt state must then be a ``ShardedAdamWState`` (from
    ``sharded_adamw_init``/``restore_opt_state``) whose flat ``(N, L)``
    leaves ride ``P(axis)`` in/out specs — per-chip optimizer bytes ~1/N.
    """
    if opt_sharding not in (None, "zero1"):
        raise ValueError(f"unknown opt_sharding: {opt_sharding!r}")
    n_shards = mesh.shape[axis] if opt_sharding == "zero1" else None
    body, stacked = _multi_step_body(
        config, hparams, accum_steps, inner_steps, reduce_axis=axis,
        health=health, dynamics=dynamics, zero1_shards=n_shards,
    )
    batch_spec = P(None, axis) if stacked else P(axis)
    if n_shards is not None:
        from bpe_transformer_tpu.optim.sharded import ShardedAdamWState

        # The sharded state's (N, L) leaves split their leading dim over
        # the dp axis — each replica's body sees its own (1, L) block.
        opt_spec = ShardedAdamWState(
            step=P(), m=P(axis), v=P(axis), master=P(axis)
        )
    else:
        opt_spec = P()
    # out_specs are pytree PREFIXES: the final P() covers the whole metrics
    # dict, whatever keys (health sub-dicts included) the body emits.
    mapped = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(), opt_spec, batch_spec, batch_spec),
        out_specs=(P(), opt_spec, P()),
        check_vma=False,
    )
    return jax.jit(mapped, donate_argnums=(0, 1))


def make_gspmd_train_step(
    config: ModelConfig,
    hparams: TrainHParams,
    mesh: Mesh,
    strategy: str = "fsdp",
    example_params=None,
    accum_steps: int = 1,
    inner_steps: int = 1,
    health: bool = False,
    dynamics: bool = False,
    opt_sharding: str | None = None,
) -> Callable:
    """Sharding-annotated jit step; XLA derives the collective schedule.

    ``example_params`` (an abstract or concrete params pytree) is needed to
    build per-leaf shardings.  Returns a step with donated params/opt-state.

    ``accum_steps``/``inner_steps`` compile the accumulation/multi-update
    ``lax.scan`` INSIDE the sharded program (batches gain a leading stacked
    dim, split on ``data`` along their second axis); XLA still derives all
    collectives from the annotations, so FSDP's gather/scatter schedule
    composes with accumulation without any manual communication.

    ``opt_sharding="zero1"`` annotates the AdamW m/v leaves with
    ``zero1_opt_specs`` (each leaf's largest divisible dim split along
    ``data`` on top of the strategy's param spec): the update body is
    UNCHANGED — the in/out sharding constraints alone make XLA keep the
    moments 1/N per chip and derive the reduce-scatter/all-gather around
    the weight update.  A no-op under ``fsdp`` (moments already shard with
    the params).
    """
    if example_params is None:
        raise ValueError("example_params is required to derive shardings")
    if opt_sharding not in (None, "zero1"):
        raise ValueError(f"unknown opt_sharding: {opt_sharding!r}")
    body, stacked = _multi_step_body(
        config, hparams, accum_steps, inner_steps, reduce_axis=None,
        health=health, dynamics=dynamics,
    )
    p_sh = param_shardings(example_params, mesh, strategy)
    replicated = NamedSharding(mesh, P())
    if opt_sharding == "zero1":
        from bpe_transformer_tpu.parallel.sharding import zero1_opt_shardings

        moment_sh = zero1_opt_shardings(example_params, mesh, strategy)
    else:
        moment_sh = p_sh
    opt_sh = AdamWState(step=replicated, m=moment_sh, v=moment_sh)
    data_spec = (P(None, "data") if stacked else P("data"))
    batch_sh = (
        NamedSharding(mesh, data_spec) if "data" in mesh.shape else replicated
    )

    # The metrics out-sharding is a pytree PREFIX: one replicated sharding
    # covers the whole dict regardless of which keys (health sub-dicts
    # included) the body emits — all metrics are scalars.
    return jax.jit(
        body,
        in_shardings=(p_sh, opt_sh, batch_sh, batch_sh),
        out_shardings=(p_sh, opt_sh, replicated),
        donate_argnums=(0, 1),
    )


def shard_batch(batch, mesh: Mesh, axis: str = "data", stacked: bool = False):
    """Place a host batch on the mesh, split along the data axis.

    ``stacked=True`` places ``(accum|inner, batch, seq)`` arrays with the
    LEADING dim unsharded and the batch dim split on ``axis`` (the
    grad-accum / scanned-step layouts).  On meshes without that axis (e.g.
    pure tensor parallelism) the batch is replicated instead, matching
    make_gspmd_train_step's fallback."""
    if axis in mesh.shape:
        spec = P(None, axis) if stacked else P(axis)
    else:
        spec = P()
    return jax.device_put(batch, NamedSharding(mesh, spec))
