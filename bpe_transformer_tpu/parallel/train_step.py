"""Multi-chip train steps: explicit-collective DP and GSPMD FSDP/TP.

Two complementary executions of the same update body
(`training.train_step.train_step_fn`):

* :func:`make_dp_train_step` — ``jax.shard_map`` over a 1-D ``data`` mesh.
  Every chip holds full replicas; the batch is split along ``data``; each
  chip computes local gradients and a single ``lax.pmean`` all-reduce (ICI)
  makes them global before the identical AdamW update runs everywhere.
  This is the BASELINE.json north-star collective, written explicitly.

* :func:`make_gspmd_train_step` — ``jax.jit`` with ``NamedSharding``
  in/out shardings for ``dp`` / ``fsdp`` / ``tp`` / ``fsdp_tp`` strategies
  (specs from `parallel.sharding`).  XLA's SPMD partitioner derives the
  all-gather / reduce-scatter / psum schedule from the annotations — the
  idiomatic TPU path that scales from v4-8 data parallelism to
  GPT-2-medium FSDP on v5p-16 (BASELINE configs 2/3/5).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from bpe_transformer_tpu.models.config import ModelConfig
from bpe_transformer_tpu.optim.adamw import AdamWState
from bpe_transformer_tpu.parallel.sharding import param_shardings
from bpe_transformer_tpu.training.train_step import TrainHParams, train_step_fn

P = PartitionSpec


def make_dp_train_step(
    config: ModelConfig,
    hparams: TrainHParams,
    mesh: Mesh,
    axis: str = "data",
) -> Callable:
    """Data-parallel step with an explicit gradient all-reduce over ``axis``.

    Batch arrays must be sharded (or shardable) along their leading dim;
    params/opt-state are replicated.  The global batch size must divide the
    mesh axis size.
    """
    mapped = jax.shard_map(
        train_step_fn(config, hparams, reduce_axis=axis),
        mesh=mesh,
        in_specs=(P(), P(), P(axis), P(axis)),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    return jax.jit(mapped, donate_argnums=(0, 1))


def make_gspmd_train_step(
    config: ModelConfig,
    hparams: TrainHParams,
    mesh: Mesh,
    strategy: str = "fsdp",
    example_params=None,
) -> Callable:
    """Sharding-annotated jit step; XLA derives the collective schedule.

    ``example_params`` (an abstract or concrete params pytree) is needed to
    build per-leaf shardings.  Returns a step with donated params/opt-state.
    """
    if example_params is None:
        raise ValueError("example_params is required to derive shardings")
    p_sh = param_shardings(example_params, mesh, strategy)
    replicated = NamedSharding(mesh, P())
    opt_sh = AdamWState(step=replicated, m=p_sh, v=p_sh)
    batch_sh = NamedSharding(mesh, P("data")) if "data" in mesh.shape else replicated
    metrics_sh = {"loss": replicated, "lr": replicated, "grad_norm": replicated}

    return jax.jit(
        train_step_fn(config, hparams),
        in_shardings=(p_sh, opt_sh, batch_sh, batch_sh),
        out_shardings=(p_sh, opt_sh, metrics_sh),
        donate_argnums=(0, 1),
    )


def shard_batch(batch, mesh: Mesh, axis: str = "data"):
    """Place a host batch on the mesh, split along the data axis.

    On meshes without that axis (e.g. pure tensor parallelism) the batch is
    replicated instead, matching make_gspmd_train_step's fallback."""
    spec = P(axis) if axis in mesh.shape else P()
    return jax.device_put(batch, NamedSharding(mesh, spec))
