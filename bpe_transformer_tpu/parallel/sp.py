"""Sequence-parallel (context-parallel) training: ring attention in the loop.

Long sequences are sharded along a ``seq`` mesh axis (in addition to the
``data`` batch axis): every chip holds a slice of every sequence, activation
memory scales as O(S / n_seq), and attention runs as the ring schedule from
`parallel.ring_attention` (K/V shards rotating over ICI).  Everything else
in the block (norms, FFN, projections) is token-local, so only attention and
the loss/grad reductions touch collectives:

* attention: ``ppermute`` ring over ``seq``;
* loss and gradients: ``pmean`` over both ``data`` and ``seq``.

This subsystem has no reference counterpart at all (max context there is 16
tokens) — it exists because long-context is first-class in the TPU build.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from bpe_transformer_tpu.models.config import ModelConfig
from bpe_transformer_tpu.models.transformer import forward
from bpe_transformer_tpu.ops.grad import clip_by_global_norm
from bpe_transformer_tpu.optim.adamw import AdamWState, adamw_update
from bpe_transformer_tpu.optim.schedule import cosine_schedule_jax
from bpe_transformer_tpu.parallel.ring_attention import (
    ring_flash_attention,
    ring_self_attention,
    zigzag_indices,
    zigzag_positions,
    zigzag_ring_flash_attention,
    zigzag_ring_self_attention,
)
from bpe_transformer_tpu.parallel.ulysses import ulysses_attention
from bpe_transformer_tpu.training.train_step import (
    TrainHParams,
    accumulate_grads,
    scanned_step_fn,
)

P = PartitionSpec

_FLASH_RING_KV_CHUNK_ERROR = (
    'attention_impl="flash" does not honor ring_kv_chunk inside the ring '
    "(the Pallas kernel tiles each visiting shard by flash_block_size "
    'instead); unset ring_kv_chunk or use the XLA ring (attention_impl="xla")'
)


def sp_forward(
    params,
    local_token_ids: jax.Array,
    config: ModelConfig,
    seq_axis: str,
    ulysses: bool = False,
) -> jax.Array:
    """Forward over a local sequence shard; call INSIDE shard_map.

    Positions are global (shard offset + local index) so RoPE sees the true
    token positions; attention is the exact ring schedule over ``seq_axis``
    (or the Ulysses all-to-all head-scatter with ``ulysses=True``).
    """
    s_local = local_token_ids.shape[-1]
    offset = jax.lax.axis_index(seq_axis) * s_local
    positions = offset + jnp.arange(s_local)
    attention_fn = _sp_attention_fn(config, seq_axis, ulysses=ulysses)
    return forward(
        params, local_token_ids, config, positions=positions, attention_fn=attention_fn
    )


def _sp_attention_fn(
    config: ModelConfig,
    seq_axis: str,
    zigzag: bool = False,
    ulysses: bool = False,
):
    """Per-shard attention for the sp schedules, per the config:
    ``ulysses=True`` is the all-to-all head scatter (`parallel/ulysses.py`);
    otherwise ``attention_impl="flash"`` runs the Pallas kernel inside every
    ring shard (ring-flash / zig-zag ring-flash), anything else the XLA
    online-softmax ring (optionally kv-chunked; zig-zag has no chunk knob —
    its sub-blocks are already half-size)."""
    if ulysses:
        return partial(ulysses_attention, axis_name=seq_axis, config=config)
    if config.attention_impl == "flash":
        from bpe_transformer_tpu.kernels.pallas.runtime import interpret_mode

        if config.ring_kv_chunk:
            raise ValueError(_FLASH_RING_KV_CHUNK_ERROR)
        block = config.flash_block_size
        fn = zigzag_ring_flash_attention if zigzag else ring_flash_attention
        return partial(
            fn,
            axis_name=seq_axis,
            block_q=block,
            block_k=block,
            interpret=interpret_mode(),
        )
    if zigzag:
        return partial(zigzag_ring_self_attention, axis_name=seq_axis)
    return partial(
        ring_self_attention,
        axis_name=seq_axis,
        causal=True,
        kv_chunk=config.ring_kv_chunk,
    )


def make_sp_train_step(
    config: ModelConfig,
    hparams: TrainHParams,
    mesh: Mesh,
    data_axis: str = "data",
    seq_axis: str = "seq",
    zigzag: bool = False,
    ulysses: bool = False,
    accum_steps: int = 1,
    inner_steps: int = 1,
) -> Callable:
    """Train step over a 2-D (data x seq) mesh: batch split on ``data``,
    every sequence split on ``seq``; params/opt-state replicated.

    ``ulysses=True`` swaps the ring schedule for the all-to-all head
    scatter (`parallel/ulysses.py`): one all_to_all re-partitions Q/K/V to
    head-sharded, dense/flash attention runs over the FULL sequence per
    head slice, and the inverse all_to_all restores sequence sharding.
    Requires ``num_heads`` to be a multiple of the seq axis size;
    contiguous layout
    (mutually exclusive with ``zigzag`` — Ulysses has no load imbalance to
    fix, every device already does identical full-sequence work).

    The global batch must divide the data axis and ``context_length`` must
    divide the seq axis.  With ``zigzag=True`` the causal ring runs the
    balanced striped schedule (~2x less attention work at large mesh sizes);
    feed batches through :func:`shard_sp_batch` with ``zigzag=True`` so the
    on-device layout matches, and note positions/loss are permutation-
    consistent (targets ride the same permutation as inputs).

    ``accum_steps > 1``: gradient accumulation INSIDE the sharded program —
    each chip scans its local microbatch shards (``lax.scan``, so peak
    activation memory stays one microbatch even though sp exists precisely
    because long-context activations are HBM-limited), and the grad/loss
    ``pmean`` over (data, seq) runs ONCE per update, after accumulation.
    Batches become ``(accum_steps, micro_batch, seq)``; feed them through
    :func:`shard_sp_batch` with ``stacked=True``.

    ``inner_steps > 1``: several FULL updates per dispatch (``lax.scan``
    over the whole local update incl. its per-update pmean), amortizing
    host launch latency exactly like the dp/GSPMD scanned steps; batches
    are ``(inner_steps, batch, seq)``, also via ``stacked=True``.  Metrics
    report the last inner update.  Mutually exclusive with accumulation.
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    if inner_steps < 1:
        raise ValueError(f"inner_steps must be >= 1, got {inner_steps}")
    if accum_steps > 1 and inner_steps > 1:
        raise ValueError("accum_steps and inner_steps cannot both exceed 1")
    if zigzag and ulysses:
        raise ValueError(
            "zigzag and ulysses are mutually exclusive (the all-to-all "
            "schedule has no causal load imbalance to stripe away)"
        )
    n_seq = mesh.shape[seq_axis]
    if ulysses and config.num_heads % n_seq:
        raise ValueError(
            f"ulysses scatters heads over the seq axis: num_heads="
            f"{config.num_heads} must be a multiple of the {seq_axis!r} "
            f"axis size {n_seq} (use the ring schedule otherwise)"
        )
    if zigzag and config.ring_kv_chunk:
        raise ValueError(
            "the zig-zag schedule does not honor ring_kv_chunk (its "
            "sub-blocks are already half-size); use the contiguous ring, or "
            'unset ring_kv_chunk and set attention_impl="flash" for '
            "VMEM-tiled zig-zag"
        )
    if config.attention_impl == "flash" and config.ring_kv_chunk and not ulysses:
        # Same guard lives in _sp_attention_fn (covers sp_forward too);
        # raising here surfaces it at step-construction time.  Ulysses is
        # carved out: it never consumes ring_kv_chunk (its inner attention
        # is full-sequence flash/dense), so a ring-specific error about a
        # knob the selected schedule ignores would only mislead.
        raise ValueError(_FLASH_RING_KV_CHUNK_ERROR)

    def local_step(params, opt_state: AdamWState, x, y):
        def loss_fn(p, x, y):
            # Memory-lean loss on the LOCAL sequence shard (already seq/N
            # long); lm_loss applies the shared clamp/divisibility guard.
            from bpe_transformer_tpu.models.transformer import (
                forward_hidden,
                lm_head_weight,
            )
            from bpe_transformer_tpu.ops.losses import lm_loss

            s_local = x.shape[-1]
            if zigzag:
                positions = zigzag_positions(
                    jax.lax.axis_index(seq_axis), s_local, n_seq
                )
            else:
                offset = jax.lax.axis_index(seq_axis) * s_local
                positions = offset + jnp.arange(s_local)
            attention_fn = _sp_attention_fn(
                config, seq_axis, zigzag=zigzag, ulysses=ulysses
            )
            hidden, aux = forward_hidden(
                p, x, config, positions=positions, attention_fn=attention_fn
            )
            loss = lm_loss(
                hidden, lm_head_weight(p, config), y, config.loss_chunk
            )
            if config.ffn_type == "moe":
                # Load-balance aux per dispatch group (the Switch
                # convention): each shard routes its local tokens and
                # regularizes its own expert loads; the pmean below averages
                # the shard auxes (equal-size shards).
                loss = loss + config.router_aux_weight * aux
            return loss

        grad_fn = jax.value_and_grad(loss_fn)
        if accum_steps > 1:
            loss, grads = accumulate_grads(
                grad_fn, params, x, y, accum_steps, context="sp grad-accum step"
            )
        else:
            loss, grads = grad_fn(params, x, y)
        # Equal-size shards: the global mean is the mean of shard means —
        # ONE collective per update, after any local accumulation.  Under
        # grads_dtype="bfloat16" the tree crosses the (data, seq)
        # all-reduce at half width (train_step._reduce_grads semantics);
        # clip/AdamW below stay f32.
        narrow = jnp.dtype(hparams.grads_dtype)
        if narrow != jnp.float32:
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(narrow), grads
            )
        grads = jax.lax.pmean(grads, (data_axis, seq_axis))
        if narrow != jnp.float32:
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), grads
            )
        loss = jax.lax.pmean(loss, (data_axis, seq_axis))

        grads, grad_norm = clip_by_global_norm(grads, hparams.grad_clip_norm)
        lr = cosine_schedule_jax(
            opt_state.step,
            hparams.max_learning_rate,
            hparams.min_learning_rate,
            hparams.warmup_iters,
            hparams.cosine_cycle_iters,
        )
        params, opt_state = adamw_update(
            params, grads, opt_state, lr,
            betas=hparams.betas, eps=hparams.eps,
            weight_decay=hparams.weight_decay,
        )
        metrics = {
            "loss": loss.astype(jnp.float32),
            "lr": lr.astype(jnp.float32),
            "grad_norm": grad_norm,
        }
        return params, opt_state, metrics

    if inner_steps > 1:
        local_step = scanned_step_fn(config, hparams, inner_steps, body=local_step)

    stacked = accum_steps > 1 or inner_steps > 1
    batch_spec = (
        P(None, data_axis, seq_axis) if stacked else P(data_axis, seq_axis)
    )
    mapped = jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), P(), batch_spec, batch_spec),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    return jax.jit(mapped, donate_argnums=(0, 1))


def shard_sp_batch(
    batch,
    mesh: Mesh,
    data_axis: str = "data",
    seq_axis: str = "seq",
    zigzag: bool = False,
    stacked: bool = False,
):
    """Place ``(B, S)`` batch arrays split over (data, seq).

    ``zigzag=True`` permutes the sequence axis into the striped layout
    (shard ``i`` gets global chunks ``(i, 2n-1-i)``) before placement, for
    :func:`make_sp_train_step`'s balanced schedule.  ``stacked=True``
    places ``(accum_steps, micro_batch, S)`` arrays with the leading dim
    unsharded (the grad-accum layout; zigzag permutes the last axis either
    way).
    """
    if zigzag:
        n = mesh.shape[seq_axis]
        perm = zigzag_indices(jax.tree_util.tree_leaves(batch)[0].shape[-1], n)
        batch = jax.tree_util.tree_map(lambda a: a[..., perm], batch)
    spec = P(None, data_axis, seq_axis) if stacked else P(data_axis, seq_axis)
    sharding = NamedSharding(mesh, spec)
    return jax.device_put(batch, sharding)
