"""Pipeline parallelism: GPipe-style microbatched stages over a ``pp`` axis.

No reference precedent (SURVEY §2.4 lists PP as absent); built TPU-first:

* the layer stack is split into ``pp`` contiguous stages, one per mesh rank
  along the ``pp`` axis; each rank holds ONLY its stage's block parameters
  (leading stage dim sharded via ``shard_map``);
* activations flow rank -> rank+1 through ``lax.ppermute`` (neighbor
  exchange over ICI) inside a ``lax.scan`` over ``num_micro + pp - 1``
  pipeline ticks — microbatch ``t`` enters stage 0 at tick ``t`` and leaves
  the last stage at tick ``t + pp - 1``;
* the backward pipeline is not hand-written: ``jax.value_and_grad``
  differentiates through the scan + ppermute (the transpose of a ppermute is
  the reverse ppermute), yielding the reverse-order schedule automatically;
* composes with data parallelism on a 2-D ``(data, pp)`` mesh — batch split
  over ``data``, gradients pmean'd over ``data``.

Embeddings / final norm / LM head are replicated on every rank ("shared"):
only rank 0 reads the embedding and only the last rank applies the head, so
their gradients are psum'd over ``pp`` to become global.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from bpe_transformer_tpu.models.config import ModelConfig
from bpe_transformer_tpu.models.transformer import (
    Params,
    policy_block,
)
from bpe_transformer_tpu.ops.core import embedding, rmsnorm
from bpe_transformer_tpu.ops.rope import rope_tables
from bpe_transformer_tpu.optim.adamw import AdamWState, adamw_init, adamw_update
from bpe_transformer_tpu.optim.schedule import cosine_schedule_jax
from bpe_transformer_tpu.training.train_step import TrainHParams

P = PartitionSpec


# ------------------------------------------------------------ param layout


def stack_pipeline_params(params: Params, pp: int) -> dict:
    """Re-layout a transformer param pytree for ``pp`` pipeline stages.

    Returns ``{"stages": ..., "shared": ...}`` where every ``stages`` leaf is
    stacked to ``(pp, layers_per_stage, ...)`` (dim 0 shards over the ``pp``
    mesh axis) and ``shared`` holds the replicated embedding / final norm /
    LM head.
    """
    layers = params["layers"]
    if len(layers) % pp:
        raise ValueError(
            f"num_layers={len(layers)} not divisible by pipeline size {pp}"
        )
    per_stage = len(layers) // pp
    stage_groups = [
        layers[s * per_stage : (s + 1) * per_stage] for s in range(pp)
    ]
    # blocks-within-stage stacked on dim 0, then stages stacked on a new dim 0.
    stages = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves),
        *[
            jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *group)
            for group in stage_groups
        ],
    )
    shared = {
        "token_embeddings": params["token_embeddings"],
        "ln_final": params["ln_final"],
    }
    if "lm_head" in params:  # absent under tie_embeddings
        shared["lm_head"] = params["lm_head"]
    return {"stages": stages, "shared": shared}


def unstack_pipeline_params(pp_params: dict) -> Params:
    """Inverse of :func:`stack_pipeline_params` (for checkpoint interop)."""
    stages = pp_params["stages"]
    leaves = jax.tree_util.tree_leaves(stages)
    pp, per_stage = leaves[0].shape[0], leaves[0].shape[1]
    layers = [
        jax.tree_util.tree_map(lambda l: l[s, i], stages)
        for s in range(pp)
        for i in range(per_stage)
    ]
    out = {
        "token_embeddings": pp_params["shared"]["token_embeddings"],
        "layers": layers,
        "ln_final": pp_params["shared"]["ln_final"],
    }
    if "lm_head" in pp_params["shared"]:
        out["lm_head"] = pp_params["shared"]["lm_head"]
    return out


# ------------------------------------------------------------- loss (local)


def _pp_loss_fn(
    config: ModelConfig,
    num_micro: int,
    pp_axis: str,
    pp_size: int,
) -> Callable:
    """Per-rank pipelined forward+loss: ``(pp_params, x, y) -> mean CE``.

    Runs under ``shard_map``; ``pp_params["stages"]`` leaves arrive shaped
    ``(1, layers_per_stage, ...)`` (this rank's stage).
    """

    def loss_fn(pp_params, x, y):
        stages, shared = pp_params["stages"], pp_params["shared"]
        rank = lax.axis_index(pp_axis)
        batch, seq = x.shape
        if batch % num_micro:
            raise ValueError(
                f"per-rank batch {batch} not divisible by "
                f"num_microbatches {num_micro}"
            )
        mb = batch // num_micro
        x_mb = x.reshape(num_micro, mb, seq)
        y_mb = y.reshape(num_micro, mb, seq)

        act_dtype = jnp.dtype(config.activation_dtype)
        positions = jnp.arange(seq)
        rope_cos_sin = None
        if not config.remove_rope:
            cos, sin = rope_tables(
                config.d_head, config.context_length, config.rope_theta
            )
            rope_cos_sin = (cos.astype(act_dtype), sin.astype(act_dtype))

        embed_w = shared["token_embeddings"].astype(act_dtype)
        per_stage = jax.tree_util.tree_leaves(stages)[0].shape[1]

        def apply_stage(act):
            aux_sum = jnp.zeros((), jnp.float32)
            # Graduated remat policy (PR 13): the same policy dispatch as
            # the single-program forward — full/dots_saveable checkpoint
            # the block (in_scan: the tick scan already bars CSE),
            # save_attn keeps the attention kernel's residuals and remats
            # only the FFN tail.  The deprecated remat bool maps to full.
            block = policy_block(config, in_scan=True)
            for i in range(per_stage):
                block_params = jax.tree_util.tree_map(
                    lambda l: l[0, i].astype(act_dtype), stages
                )
                act, aux = block(
                    act, block_params, config, rope_cos_sin, positions, None
                )
                aux_sum = aux_sum + aux
            return act, aux_sum

        def head_loss(act, targets):
            if not config.remove_rmsnorm:
                act = rmsnorm(act, shared["ln_final"].astype(act_dtype))
            from bpe_transformer_tpu.ops.losses import lm_loss

            head_w = shared.get("lm_head", shared["token_embeddings"])
            return lm_loss(act, head_w, targets, config.loss_chunk)

        fwd_perm = [(i, (i + 1) % pp_size) for i in range(pp_size)]
        ticks = num_micro + pp_size - 1

        def tick(carry, t):
            recv, loss_sum, aux_total = carry
            # Only rank 0 pays for the embedding lookup; other ranks take the
            # ppermute'd activation (lax.cond executes a single branch).
            enter = jnp.clip(t, 0, num_micro - 1)
            act_in = lax.cond(
                rank == 0,
                lambda: embedding(
                    embed_w,
                    lax.dynamic_index_in_dim(x_mb, enter, 0, keepdims=False),
                ).astype(act_dtype),
                lambda: recv,
            )
            act_out, aux = apply_stage(act_in)
            # MoE router aux: count only the ticks where THIS rank holds a
            # real microbatch (warmup/drain ticks process garbage
            # activations whose routing must not leak into the loss or its
            # gradient).  Each rank contributes its own stages' aux to its
            # LOCAL loss — the sum over ranks seeds exactly once per term,
            # same argument as the head loss below.
            valid = (t >= rank) & (t - rank < num_micro)
            aux_total = aux_total + jnp.where(valid, aux, 0.0)

            # Only the last rank pays for the full-vocab head matmul + CE.
            done = t - (pp_size - 1)
            done_idx = jnp.clip(done, 0, num_micro - 1)
            take = (rank == pp_size - 1) & (done >= 0)
            mb_loss = lax.cond(
                take,
                lambda: head_loss(
                    act_out,
                    lax.dynamic_index_in_dim(y_mb, done_idx, 0, keepdims=False),
                ),
                lambda: jnp.zeros((), jnp.float32),
            )
            loss_sum = loss_sum + mb_loss

            recv_next = lax.ppermute(act_out, pp_axis, fwd_perm)
            return (recv_next, loss_sum, aux_total), None

        d = config.d_model
        init = (
            jnp.zeros((mb, seq, d), act_dtype),
            jnp.zeros((), jnp.float32),
            jnp.zeros((), jnp.float32),
        )
        (_, loss_sum, aux_total), _ = lax.scan(tick, init, jnp.arange(ticks))
        # LOCAL loss: CE is nonzero only on the last rank; each rank adds
        # its own stages' router aux.  Deliberately NOT psum'd here —
        # differentiating a psum inside shard_map would seed one cotangent
        # per rank and overcount stage gradients pp times; with local
        # losses the total = sum of rank-local terms, each seeded exactly
        # once, and the ppermute transposes route every rank its true
        # gradient.  The caller psums the VALUE for metrics.
        local = loss_sum
        if config.ffn_type == "moe":
            local = local + config.router_aux_weight * aux_total
        return local / num_micro

    return loss_fn


# --------------------------------------------------------------- train step


def make_pp_train_step(
    config: ModelConfig,
    hparams: TrainHParams,
    mesh: Mesh,
    *,
    num_microbatches: int = 4,
    pp_axis: str = "pp",
    dp_axis: str = "data",
    accum_steps: int = 1,
    inner_steps: int = 1,
) -> Callable:
    """Jitted pipeline(+data)-parallel step over ``mesh``.

    Signature: ``(pp_params, opt_state, x, y) -> (pp_params, opt_state,
    metrics)`` where ``pp_params`` comes from :func:`stack_pipeline_params`
    (placed with :func:`shard_pp_params`) and ``opt_state`` from
    :func:`jax.eval_shape`-compatible :func:`~bpe_transformer_tpu.optim.
    adamw.adamw_init` over it.

    ``accum_steps > 1``: gradient accumulation around the pipeline — each
    accumulation slice runs the FULL GPipe schedule (all
    ``num_microbatches`` ticks), gradients sum in f32 via the shared
    :func:`~bpe_transformer_tpu.training.train_step.accumulate_grads`
    (same numerics as the dp/sp/GSPMD paths) and the optimizer updates
    once.  This stacks a second, memory-motivated microbatching level on
    top of the pipeline's own (which exists to fill the bubble, not to
    shrink activations): peak activation memory is one accum slice's
    pipeline.  Batches become ``(accum_steps, batch, seq)`` — feed through
    ``shard_batch(..., stacked=True)``.

    ``inner_steps > 1``: several FULL updates per dispatch (``lax.scan``
    over the whole update body inside the pipelined program, via the
    shared :func:`~bpe_transformer_tpu.training.train_step.
    scanned_step_fn`); batches ``(inner_steps, batch, seq)``, also
    ``stacked=True``.  Metrics report the last update.
    """
    if pp_axis not in mesh.shape:
        raise ValueError(f"mesh {dict(mesh.shape)} lacks axis {pp_axis!r}")
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    if inner_steps < 1:
        raise ValueError(f"inner_steps must be >= 1, got {inner_steps}")
    if accum_steps > 1 and inner_steps > 1:
        raise ValueError("accum_steps and inner_steps cannot both exceed 1")
    pp_size = mesh.shape[pp_axis]
    use_dp = dp_axis in mesh.shape and mesh.shape[dp_axis] > 1
    loss_fn = _pp_loss_fn(config, num_microbatches, pp_axis, pp_size)

    def step(pp_params, opt_state: AdamWState, x, y):
        if accum_steps > 1:
            from bpe_transformer_tpu.training.train_step import accumulate_grads

            local_loss, grads = accumulate_grads(
                jax.value_and_grad(loss_fn), pp_params, x, y, accum_steps,
                context="pp grad-accum step",
            )
        else:
            local_loss, grads = jax.value_and_grad(loss_fn)(pp_params, x, y)
        loss = lax.psum(local_loss, pp_axis)  # loss lives on the last rank
        # Shared params saw real gradients on one rank only (embed on rank 0,
        # head/final-norm on the last): psum over pp makes them global.
        grads["shared"] = lax.psum(grads["shared"], pp_axis)
        if use_dp:
            # The dp gradient all-reduce optionally crosses at bf16
            # (train_step._reduce_grads semantics; the pp-axis psums above
            # are correctness sums of DISJOINT partials and stay f32).
            narrow = jnp.dtype(hparams.grads_dtype)
            if narrow != jnp.float32:
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(narrow), grads
                )
            grads = lax.pmean(grads, dp_axis)
            if narrow != jnp.float32:
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32), grads
                )
            loss = lax.pmean(loss, dp_axis)

        # Global grad-norm: stage grads live on distinct pp ranks (sum their
        # squares across pp); shared grads are identical on every rank.
        stage_sq = sum(
            jnp.sum(jnp.square(g.astype(jnp.float32)))
            for g in jax.tree_util.tree_leaves(grads["stages"])
        )
        shared_sq = sum(
            jnp.sum(jnp.square(g.astype(jnp.float32)))
            for g in jax.tree_util.tree_leaves(grads["shared"])
        )
        global_norm = jnp.sqrt(lax.psum(stage_sq, pp_axis) + shared_sq)
        scale = jnp.minimum(
            1.0, hparams.grad_clip_norm / (global_norm + 1e-6)
        )
        grads = jax.tree_util.tree_map(lambda g: g * scale, grads)

        lr = cosine_schedule_jax(
            opt_state.step,
            hparams.max_learning_rate,
            hparams.min_learning_rate,
            hparams.warmup_iters,
            hparams.cosine_cycle_iters,
        )
        pp_params_new, opt_state = adamw_update(
            pp_params,
            grads,
            opt_state,
            lr,
            betas=hparams.betas,
            eps=hparams.eps,
            weight_decay=hparams.weight_decay,
        )
        metrics = {"loss": loss, "lr": lr, "grad_norm": global_norm}
        return pp_params_new, opt_state, metrics

    if inner_steps > 1:
        from bpe_transformer_tpu.training.train_step import scanned_step_fn

        step = scanned_step_fn(config, hparams, inner_steps, body=step)

    param_specs = {"stages": P(pp_axis), "shared": P()}
    opt_specs = AdamWState(step=P(), m=param_specs, v=param_specs)
    stacked = accum_steps > 1 or inner_steps > 1
    if use_dp:
        batch_spec = P(None, dp_axis) if stacked else P(dp_axis)
    else:
        batch_spec = P()
    metric_specs = {"loss": P(), "lr": P(), "grad_norm": P()}

    mapped = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(param_specs, opt_specs, batch_spec, batch_spec),
        out_specs=(param_specs, opt_specs, metric_specs),
        check_vma=False,
    )
    return jax.jit(mapped, donate_argnums=(0, 1))


def shard_pp_params(pp_params: dict, mesh: Mesh, pp_axis: str = "pp"):
    """Place stacked pipeline params: stages split over ``pp``, shared replicated."""
    stage_sh = NamedSharding(mesh, P(pp_axis))
    repl = NamedSharding(mesh, P())
    return {
        "stages": jax.device_put(pp_params["stages"], stage_sh),
        "shared": jax.device_put(pp_params["shared"], repl),
    }


def init_pp_opt_state(pp_params: dict, mesh: Mesh, pp_axis: str = "pp") -> AdamWState:
    """AdamW state over stacked pipeline params, sharded to match."""
    state = adamw_init(pp_params)
    stage_sh = NamedSharding(mesh, P(pp_axis))
    repl = NamedSharding(mesh, P())

    def place(tree):
        return {
            "stages": jax.device_put(tree["stages"], stage_sh),
            "shared": jax.device_put(tree["shared"], repl),
        }

    return AdamWState(
        step=jax.device_put(state.step, repl),
        m=place(state.m),
        v=place(state.v),
    )
