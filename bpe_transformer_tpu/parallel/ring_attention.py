"""Ring attention: exact causal attention over sequence-sharded inputs.

Long-context / context-parallelism subsystem (first-class here; entirely
absent from the reference, whose max context is 16 tokens — SURVEY §5).

Each device holds a contiguous sequence shard of Q, K, V.  K/V blocks rotate
around the mesh axis with ``jax.lax.ppermute`` (nearest-neighbor ICI hops —
the collective XLA lowers to an ICI ring); every device folds each visiting
K/V block into its queries' online-softmax state (running max, denominator,
f32 accumulator — the same math as the Pallas flash kernel, at shard
granularity).  After ``axis_size`` steps every query has attended to every
key with O(S_local) memory per device: sequence length scales linearly with
the number of chips.

Two schedules:

* :func:`ring_self_attention` — contiguous shards.  Causal masking uses
  global positions; blocks strictly above a query shard's diagonal are
  folded in as no-ops via a predicated select, so under causal masking the
  ring is load-imbalanced (device 0 needs 1 block, device n-1 needs n) and
  every device still computes every visiting block.  ``kv_chunk`` bounds
  per-device score memory at O(S_local * chunk) by sub-chunking each
  visiting block in a rematerialized scan (blockwise/flash math at shard
  granularity — set ``ModelConfig.ring_kv_chunk`` to enable in sp
  training).
* :func:`ring_flash_attention` — the contiguous ring with the Pallas flash
  kernel INSIDE each shard: per-step score memory rides VMEM tiles, partial
  outputs merge by log-sum-exp, and the custom backward re-runs the
  blockwise kernel per visiting shard against the GLOBAL lse/out, routing
  each shard's dK/dV home around the ring (select with
  ``attention_impl="flash"`` under sp training).
* :func:`zigzag_ring_flash_attention` — the striped schedule AND the
  Pallas kernel per sub-block: both long-context optimizations at once
  (balanced causal load + VMEM-tiled scores), with the lse-merge forward
  and ring-routed blockwise backward of the flash ring.
* :func:`zigzag_ring_self_attention` — striped ("zig-zag") shards: the
  sequence is cut into ``2n`` chunks and device ``i`` holds chunks
  ``(i, 2n-1-i)``, giving every device exactly ``2n+1`` visible
  chunk-pair sub-blocks.  Each ring step then computes two half-size
  products instead of one full block: per-device causal FLOPs drop from
  ``n`` blocks to ``(2n+1)/4`` block-equivalents (~2x at large n) and the
  work is identical on every device, so no one waits on the last rank.
  Callers lay data out with :func:`zigzag_indices` and position tables
  with :func:`zigzag_positions` (RoPE must see true global positions).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from bpe_transformer_tpu.ops.core import MASK_VALUE as NEG_INF

P = PartitionSpec


def _fold_visiting_block(
    q, k_blk, v_blk, state, row_base, col_base, causal, kv_chunk, scale
):
    """Fold one visiting K/V block into the online-softmax ``state``.

    ``kv_chunk`` (dividing the block's key length) processes the block in
    sub-chunks inside a rematerialized ``lax.scan``: peak per-device score
    memory drops from O(S_local^2) to O(S_local * kv_chunk) — the blockwise
    (flash) trick at shard granularity, with the chunk body recomputed on
    the backward pass instead of storing its scores.

    Matmuls take COMPUTE-dtype inputs with f32 accumulation
    (``preferred_element_type``): bf16 shards keep full MXU rate — f32
    inputs run the systolic array at ~1/4 speed — while the online-softmax
    statistics stay f32.  The softmax scale is applied to the f32 scores.
    """
    s_q = q.shape[-2]
    s_kv = k_blk.shape[-2]
    rows = jnp.arange(s_q)[:, None]

    def fold(state, k_c, v_c, col0, width):
        m, l, acc = state
        scores = (
            jnp.einsum(
                "...qd,...kd->...qk", q, k_c,
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        if causal:
            cols = jnp.arange(width)[None, :]
            keep = (row_base + rows) >= (col_base + col0 + cols)
            scores = jnp.where(keep, scores, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "...qk,...kv->...qv", p.astype(v_c.dtype), v_c,
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    if not kv_chunk or kv_chunk >= s_kv:
        return fold(state, k_blk, v_blk, 0, s_kv)

    if s_kv % kv_chunk:
        raise ValueError(
            f"kv_chunk {kv_chunk} must divide the shard length {s_kv}"
        )
    n_chunks = s_kv // kv_chunk
    d = k_blk.shape[-1]
    # Chunk axis must lead for lax.scan.
    to_chunks = lambda x: jnp.moveaxis(
        x.reshape(*x.shape[:-2], n_chunks, kv_chunk, d), -3, 0
    )

    @jax.checkpoint
    def body(state, inp):
        k_c, v_c, col0 = inp
        return fold(state, k_c, v_c, col0, kv_chunk), None

    col0s = jnp.arange(n_chunks) * kv_chunk
    state, _ = jax.lax.scan(body, state, (to_chunks(k_blk), to_chunks(v_blk), col0s))
    return state


def ring_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
    kv_chunk: int | None = None,
) -> jax.Array:
    """Attention on sequence shards; call INSIDE shard_map over ``axis_name``.

    Shapes (per device): ``q, k, v: (..., S_local, D)``; the global sequence
    is the concatenation of shards in mesh-axis order.  ``kv_chunk`` bounds
    per-device score memory at O(S_local * kv_chunk) (blockwise
    online-softmax within each visiting shard, rematerialized on backward);
    ``None`` materializes one full (S_local, S_local) block per ring step.
    """
    n = jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    s_local = q.shape[-2]
    d = q.shape[-1]
    scale = 1.0 / (d**0.5)

    stat_shape = (*q.shape[:-1], 1)
    m = jnp.full(stat_shape, NEG_INF, jnp.float32)
    l = jnp.zeros(stat_shape, jnp.float32)
    acc = jnp.zeros(q.shape, jnp.float32)

    k_cur, v_cur = k, v
    for step in range(n):
        src = (me - step) % n  # which shard's K/V we hold this step

        m_new, l_new, acc_new = _fold_visiting_block(
            q,
            k_cur,
            v_cur,
            (m, l, acc),
            me * s_local,
            src * s_local,
            causal,
            kv_chunk,
            scale,
        )

        if causal:
            # Blocks fully above our diagonal fold in as no-ops.  step 0 is
            # our own (diagonal) block, so state is always seeded validly.
            visible = src <= me
            m = jnp.where(visible, m_new, m)
            l = jnp.where(visible, l_new, l)
            acc = jnp.where(visible, acc_new, acc)
        else:
            m, l, acc = m_new, l_new, acc_new

        if step < n - 1:
            perm = [(i, (i + 1) % n) for i in range(n)]
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)

    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


# ------------------------------------------------- ring + Pallas flash


def _merge_partials(out_acc, lse_acc, out_blk, lse_blk):
    """Log-sum-exp combine of two partial attention results (f32)."""
    lse_new = jnp.logaddexp(lse_acc, lse_blk)
    w_acc = jnp.exp(lse_acc - lse_new)[..., None]
    w_blk = jnp.exp(lse_blk - lse_new)[..., None]
    return out_acc * w_acc + out_blk * w_blk, lse_new


def _ring_flash_fwd_impl(q, k, v, axis_name, block_q, block_k, interpret):
    from bpe_transformer_tpu.kernels.pallas.flash_attention import (
        flash_attention_with_lse,
    )

    n = jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # Step 0 — the diagonal block (own K/V) — is the only one needing a
    # causal mask, and it is static: src == me exactly when step == 0.
    out, lse = flash_attention_with_lse(q, k, v, True, block_q, block_k, interpret)
    out = out.astype(jnp.float32)

    k_cur, v_cur = k, v
    for step in range(1, n):
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        src = (me - step) % n
        o_blk, l_blk = flash_attention_with_lse(
            q, k_cur, v_cur, False, block_q, block_k, interpret
        )
        merged_out, merged_lse = _merge_partials(
            out, lse, o_blk.astype(jnp.float32), l_blk
        )
        # Shards strictly after ours are fully masked under causality —
        # fold as a no-op (same predicated-select pattern as the XLA ring).
        visible = src < me
        out = jnp.where(visible, merged_out, out)
        lse = jnp.where(visible, merged_lse, lse)

    return out.astype(q.dtype), lse


def _ring_flash_vjp_fwd(q, k, v, axis_name, block_q, block_k, interpret):
    out, lse = _ring_flash_fwd_impl(
        q, k, v, axis_name, block_q, block_k, interpret
    )
    return out, (q, k, v, out, lse)


def _ring_flash_vjp_bwd(axis_name, block_q, block_k, interpret, residuals, g):
    from bpe_transformer_tpu.kernels.pallas.flash_attention import (
        flash_attention_block_bwd,
    )

    q, k, v, out, lse = residuals
    n = jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # Diagonal block: causal, own K/V.
    dq, dk_acc, dv_acc = flash_attention_block_bwd(
        q, k, v, out, lse, g, True, block_q, block_k, interpret
    )
    dq = dq.astype(jnp.float32)
    dk_acc = dk_acc.astype(jnp.float32)
    dv_acc = dv_acc.astype(jnp.float32)

    k_cur, v_cur = k, v
    for step in range(1, n):
        # The grad accumulators travel WITH the K/V shard they belong to:
        # after a full cycle (loop permutes + the final one below) each
        # shard's dK/dV arrives back at its home device with every visiting
        # device's contribution added.
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
        src = (me - step) % n
        dq_blk, dk_blk, dv_blk = flash_attention_block_bwd(
            q, k_cur, v_cur, out, lse, g, False, block_q, block_k, interpret
        )
        visible = src < me
        zero = jnp.zeros((), jnp.float32)
        dq = dq + jnp.where(visible, dq_blk.astype(jnp.float32), zero)
        dk_acc = dk_acc + jnp.where(visible, dk_blk.astype(jnp.float32), zero)
        dv_acc = dv_acc + jnp.where(visible, dv_blk.astype(jnp.float32), zero)

    dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
    dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
    return (
        dq.astype(q.dtype),
        dk_acc.astype(k.dtype),
        dv_acc.astype(v.dtype),
    )


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
) -> jax.Array:
    """Causal ring attention with the Pallas flash kernel INSIDE each shard.

    Call inside shard_map over ``axis_name`` with per-device
    ``(..., S_local, D)`` shards (contiguous layout, like
    :func:`ring_self_attention`).  Per ring step the visiting K/V block runs
    through the flash kernel (O(S_local * block) score memory on the VMEM
    path) and partial outputs merge by log-sum-exp; the backward re-runs the
    blockwise kernel per visiting shard with the GLOBAL lse/out — the
    standard ring-flash decomposition — and routes each shard's dK/dV home
    around the ring.  ``S_local`` must divide by the block sizes.
    """
    out, _ = _ring_flash_fwd_impl(q, k, v, axis_name, block_q, block_k, interpret)
    return out


ring_flash_attention.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


# ------------------------------------------- zig-zag ring + Pallas flash


def _zz_flash_fwd_impl(q, k, v, axis_name, block_q, block_k, interpret):
    from bpe_transformer_tpu.kernels.pallas.flash_attention import (
        flash_attention_with_lse,
    )

    if q.shape[-2] % 2:
        raise ValueError(
            f"zig-zag local length must be even, got {q.shape[-2]}"
        )
    n = jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    c = q.shape[-2] // 2
    split = lambda x: (x[..., :c, :], x[..., c:, :])
    qa, qb = split(q)

    def call(qq, kk, vv, causal):
        o, lse = flash_attention_with_lse(
            qq, kk, vv, causal, block_q, block_k, interpret
        )
        return o.astype(jnp.float32), lse

    # Step 0 — own K/V; the only step with causal masking (both diagonal
    # sub-blocks), and statically so.
    ka, kb = split(k)
    va, vb = split(v)
    out_a, lse_a = call(qa, ka, va, True)
    o2, l2 = call(qb, ka, va, False)
    o3, l3 = call(qb, kb, vb, True)
    out_b, lse_b = _merge_partials(o2, l2, o3, l3)

    k_cur, v_cur = k, v
    perm = [(i, (i + 1) % n) for i in range(n)]
    for step in range(1, n):
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        src = (me - step) % n
        early = src < me  # visiting shard's low chunk precedes ours
        ka, kb = split(k_cur)
        va, vb = split(v_cur)

        # Product 1: (early ? qa : qb) @ ka — one kernel call, operand
        # selects route the state in/out (same trick as the XLA zig-zag).
        q_sel = jnp.where(early, qa, qb)
        o1, l1 = call(q_sel, ka, va, False)
        in_out = jnp.where(early, out_a, out_b)
        in_lse = jnp.where(early, lse_a, lse_b)
        m_out, m_lse = _merge_partials(in_out, in_lse, o1, l1)
        out_a = jnp.where(early, m_out, out_a)
        lse_a = jnp.where(early, m_lse, lse_a)
        out_b = jnp.where(early, out_b, m_out)
        lse_b = jnp.where(early, lse_b, m_lse)

        # Product 2: qb @ (early ? ka : kb).
        k_sel = jnp.where(early, ka, kb)
        v_sel = jnp.where(early, va, vb)
        o2, l2 = call(qb, k_sel, v_sel, False)
        out_b, lse_b = _merge_partials(out_b, lse_b, o2, l2)

    out = jnp.concatenate([out_a, out_b], axis=-2).astype(q.dtype)
    lse = jnp.concatenate([lse_a, lse_b], axis=-1)
    return out, lse


def _zz_flash_vjp_fwd(q, k, v, axis_name, block_q, block_k, interpret):
    out, lse = _zz_flash_fwd_impl(q, k, v, axis_name, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _zz_flash_vjp_bwd(axis_name, block_q, block_k, interpret, residuals, g):
    from bpe_transformer_tpu.kernels.pallas.flash_attention import (
        flash_attention_block_bwd,
    )

    q, k, v, out, lse = residuals
    n = jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    c = q.shape[-2] // 2
    split = lambda x: (x[..., :c, :], x[..., c:, :])
    splitl = lambda x: (x[..., :c], x[..., c:])
    qa, qb = split(q)
    ga, gb = split(g)
    out_a, out_b = split(out)
    lse_a, lse_b = splitl(lse)

    def bwd(qq, kk, vv, oo, ll, gg, causal):
        dq, dk, dv = flash_attention_block_bwd(
            qq, kk, vv, oo, ll, gg, causal, block_q, block_k, interpret
        )
        return (
            dq.astype(jnp.float32),
            dk.astype(jnp.float32),
            dv.astype(jnp.float32),
        )

    # Step 0: same three sub-blocks as the forward.
    ka, kb = split(k)
    va, vb = split(v)
    dq1, dka1, dva1 = bwd(qa, ka, va, out_a, lse_a, ga, True)
    dq2, dka2, dva2 = bwd(qb, ka, va, out_b, lse_b, gb, False)
    dq3, dkb3, dvb3 = bwd(qb, kb, vb, out_b, lse_b, gb, True)
    dq_a = dq1
    dq_b = dq2 + dq3
    # dK/dV accumulators travel with the visiting K/V shard (see
    # ring_flash_attention) — one final permute delivers them home.
    dk_acc = jnp.concatenate([dka1 + dka2, dkb3], axis=-2)
    dv_acc = jnp.concatenate([dva1 + dva2, dvb3], axis=-2)

    k_cur, v_cur = k, v
    perm = [(i, (i + 1) % n) for i in range(n)]
    for step in range(1, n):
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
        dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
        src = (me - step) % n
        early = src < me
        ka, kb = split(k_cur)
        va, vb = split(v_cur)

        q_sel = jnp.where(early, qa, qb)
        o_sel = jnp.where(early, out_a, out_b)
        l_sel = jnp.where(early, lse_a, lse_b)
        g_sel = jnp.where(early, ga, gb)
        dq1, dk1, dv1 = bwd(q_sel, ka, va, o_sel, l_sel, g_sel, False)
        dq_a = dq_a + jnp.where(early, dq1, 0.0)
        dq_b = dq_b + jnp.where(early, 0.0, dq1)

        k_sel = jnp.where(early, ka, kb)
        v_sel = jnp.where(early, va, vb)
        dq2, dk2, dv2 = bwd(qb, k_sel, v_sel, out_b, lse_b, gb, False)
        dq_b = dq_b + dq2

        dk_acc = dk_acc + jnp.concatenate(
            [dk1 + jnp.where(early, dk2, 0.0), jnp.where(early, 0.0, dk2)],
            axis=-2,
        )
        dv_acc = dv_acc + jnp.concatenate(
            [dv1 + jnp.where(early, dv2, 0.0), jnp.where(early, 0.0, dv2)],
            axis=-2,
        )

    dk_acc = jax.lax.ppermute(dk_acc, axis_name, perm)
    dv_acc = jax.lax.ppermute(dv_acc, axis_name, perm)
    dq = jnp.concatenate([dq_a, dq_b], axis=-2)
    return dq.astype(q.dtype), dk_acc.astype(k.dtype), dv_acc.astype(v.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def zigzag_ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
) -> jax.Array:
    """The balanced zig-zag ring WITH the Pallas flash kernel per sub-block.

    Combines both long-context optimizations: the striped schedule's ~2x
    causal load balance AND flash's VMEM-tiled score memory.  Per ring step
    each device runs two half-size kernel calls (three on the diagonal
    step) and merges partials by log-sum-exp; the custom backward re-runs
    the blockwise kernel per sub-block against the GLOBAL per-chunk
    out/lse, routing dK/dV home around the ring.  Use the zig-zag data
    layout (:func:`zigzag_indices` / :func:`zigzag_positions`); the local
    chunk length ``S_local/2`` must divide by the block sizes.
    """
    out, _ = _zz_flash_fwd_impl(q, k, v, axis_name, block_q, block_k, interpret)
    return out


zigzag_ring_flash_attention.defvjp(_zz_flash_vjp_fwd, _zz_flash_vjp_bwd)


# ----------------------------------------------------- zig-zag schedule


def zigzag_indices(seq_len: int, n_shards: int) -> jnp.ndarray:
    """Global token order for zig-zag sharding.

    Returns ``perm`` such that ``x[..., perm, :]`` (or ``ids[..., perm]``)
    laid out contiguously gives shard ``i`` the chunks ``(i, 2n-1-i)`` of
    the original sequence.  ``seq_len`` must divide by ``2 * n_shards``.
    """
    if seq_len % (2 * n_shards):
        raise ValueError(
            f"zig-zag needs seq_len ({seq_len}) divisible by 2*n_shards "
            f"({2 * n_shards})"
        )
    c = seq_len // (2 * n_shards)
    parts = []
    for i in range(n_shards):
        parts.append(jnp.arange(i * c, (i + 1) * c))
        parts.append(jnp.arange((2 * n_shards - 1 - i) * c, (2 * n_shards - i) * c))
    return jnp.concatenate(parts)


def zigzag_inverse_indices(seq_len: int, n_shards: int) -> jnp.ndarray:
    """Inverse permutation: maps zig-zag layout back to global order."""
    perm = zigzag_indices(seq_len, n_shards)
    return jnp.argsort(perm)


def zigzag_positions(axis_index, s_local: int, n_shards: int) -> jnp.ndarray:
    """Global positions of this shard's tokens (for RoPE), inside shard_map."""
    c = s_local // 2
    lo = axis_index * c + jnp.arange(c)
    hi = (2 * n_shards - 1 - axis_index) * c + jnp.arange(c)
    return jnp.concatenate([lo, hi])


def zigzag_ring_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
) -> jax.Array:
    """Causal ring attention over zig-zag shards; call INSIDE shard_map.

    Per-device layout: ``(..., S_local, D)`` where the first ``S_local/2``
    rows are global chunk ``me`` and the rest chunk ``2n-1-me`` (produce it
    with :func:`zigzag_indices`).  Exact same math as the contiguous ring,
    but per step each device computes two half-size score blocks that are
    both fully visible by construction:

    * step 0 (own K/V): ``qa@ka`` (triangular), ``qb@ka`` (full),
      ``qb@kb`` (triangular) — the only step with any masking;
    * step s>0 with source shard ``src``: if ``src < me`` the visible work
      is ``(qa+qb) @ ka``, else ``qb @ (ka+kb)`` — either way two ``(c, c)``
      products, selected by operand (same SPMD program on every device).
    """
    n = jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    s_local = q.shape[-2]
    if s_local % 2:
        raise ValueError(f"zig-zag local length must be even, got {s_local}")
    c = s_local // 2
    d = q.shape[-1]
    scale = 1.0 / (d**0.5)

    split = lambda x: (x[..., :c, :], x[..., c:, :])
    # Compute-dtype matmul inputs, f32 accumulation/stats (same dtype rule
    # as _fold_visiting_block): bf16 shards keep full MXU rate.
    qa, qb = split(q)
    stat = lambda: (
        jnp.full((*qa.shape[:-1], 1), NEG_INF, jnp.float32),
        jnp.zeros((*qa.shape[:-1], 1), jnp.float32),
        jnp.zeros(qa.shape, jnp.float32),
    )
    # Independent online-softmax state per local chunk.
    state_a, state_b = stat(), stat()

    def fold(state, scores, v_blk):
        m, l, acc = state
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "...qk,...kv->...qv", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    dots = lambda qq, kk: (
        jnp.einsum(
            "...qd,...kd->...qk", qq, kk, preferred_element_type=jnp.float32
        )
        * scale
    )
    tri = jnp.arange(c)[:, None] >= jnp.arange(c)[None, :]

    # Step 0: own K/V — the diagonal step.
    ka, kb = split(k)
    va, vb = split(v)
    state_a = fold(state_a, jnp.where(tri, dots(qa, ka), NEG_INF), va)
    state_b = fold(state_b, dots(qb, ka), va)
    state_b = fold(state_b, jnp.where(tri, dots(qb, kb), NEG_INF), vb)

    k_cur, v_cur = k, v
    perm = [(i, (i + 1) % n) for i in range(n)]
    for step in range(1, n):
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        src = (me - step) % n
        early = src < me  # the visiting shard's low chunk precedes ours
        ka, kb = split(k_cur)
        va, vb = split(v_cur)

        # Product 1: (early ? qa : qb) @ ka — select the state in, fold
        # once, scatter back (selects are elementwise; the fold's two
        # matmuls run once).
        q_sel = jnp.where(early, qa, qb)
        st_in = tuple(jnp.where(early, a_, b_) for a_, b_ in zip(state_a, state_b))
        folded = fold(st_in, dots(q_sel, ka), va)
        state_a = tuple(jnp.where(early, f_, a_) for f_, a_ in zip(folded, state_a))
        state_b = tuple(jnp.where(early, b_, f_) for f_, b_ in zip(folded, state_b))

        # Product 2: qb @ (early ? ka : kb).
        k_sel = jnp.where(early, ka, kb)
        v_sel = jnp.where(early, va, vb)
        state_b = fold(state_b, dots(qb, k_sel), v_sel)

    finish = lambda st: st[2] / jnp.maximum(st[1], 1e-30)
    out = jnp.concatenate([finish(state_a), finish(state_b)], axis=-2)
    return out.astype(q.dtype)


def make_ring_attention(mesh: Mesh, axis: str = "data", causal: bool = True):
    """Wrap :func:`ring_self_attention` for callers outside shard_map.

    Returns ``fn(q, k, v)`` over global ``(B, H, S, D)`` arrays; S is split
    along ``axis``.
    """
    spec = P(None, None, axis, None)
    mapped = jax.shard_map(
        partial(ring_self_attention, axis_name=axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return mapped
