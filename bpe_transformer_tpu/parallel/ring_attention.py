"""Ring attention: exact causal attention over sequence-sharded inputs.

Long-context / context-parallelism subsystem (first-class here; entirely
absent from the reference, whose max context is 16 tokens — SURVEY §5).

Each device holds a contiguous sequence shard of Q, K, V.  K/V blocks rotate
around the mesh axis with ``jax.lax.ppermute`` (nearest-neighbor ICI hops —
the collective XLA lowers to an ICI ring); every device folds each visiting
K/V block into its queries' online-softmax state (running max, denominator,
f32 accumulator — the same math as the Pallas flash kernel, at shard
granularity).  After ``axis_size`` steps every query has attended to every
key with O(S_local) memory per device: sequence length scales linearly with
the number of chips.

Causal masking uses global positions; blocks strictly above a query shard's
diagonal are folded in as no-ops via a predicated select (the classic ring
load-imbalance — a zig-zag schedule is the known follow-up optimization).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from bpe_transformer_tpu.ops.core import MASK_VALUE as NEG_INF

P = PartitionSpec


def ring_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
) -> jax.Array:
    """Attention on sequence shards; call INSIDE shard_map over ``axis_name``.

    Shapes (per device): ``q, k, v: (..., S_local, D)``; the global sequence
    is the concatenation of shards in mesh-axis order.
    """
    n = jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    s_local = q.shape[-2]
    d = q.shape[-1]
    scale = 1.0 / (d**0.5)

    q32 = q.astype(jnp.float32) * scale
    stat_shape = (*q.shape[:-1], 1)
    m = jnp.full(stat_shape, NEG_INF, jnp.float32)
    l = jnp.zeros(stat_shape, jnp.float32)
    acc = jnp.zeros(q.shape, jnp.float32)

    rows = jnp.arange(s_local)[:, None]
    cols = jnp.arange(s_local)[None, :]

    k_cur, v_cur = k, v
    for step in range(n):
        src = (me - step) % n  # which shard's K/V we hold this step

        scores = jnp.einsum(
            "...qd,...kd->...qk", q32, k_cur.astype(jnp.float32)
        )
        if causal:
            # global row index = me*S+r, global col = src*S+c
            keep = (me * s_local + rows) >= (src * s_local + cols)
            scores = jnp.where(keep, scores, NEG_INF)

        m_new = jnp.maximum(m, jnp.max(scores, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "...qk,...kv->...qv", p, v_cur.astype(jnp.float32)
        )

        if causal:
            # Blocks fully above our diagonal fold in as no-ops.  step 0 is
            # our own (diagonal) block, so state is always seeded validly.
            visible = src <= me
            m = jnp.where(visible, m_new, m)
            l = jnp.where(visible, l_new, l)
            acc = jnp.where(visible, acc_new, acc)
        else:
            m, l, acc = m_new, l_new, acc_new

        if step < n - 1:
            perm = [(i, (i + 1) % n) for i in range(n)]
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)

    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def make_ring_attention(mesh: Mesh, axis: str = "data", causal: bool = True):
    """Wrap :func:`ring_self_attention` for callers outside shard_map.

    Returns ``fn(q, k, v)`` over global ``(B, H, S, D)`` arrays; S is split
    along ``axis``.
    """
    spec = P(None, None, axis, None)
    mapped = jax.shard_map(
        partial(ring_self_attention, axis_name=axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return mapped
