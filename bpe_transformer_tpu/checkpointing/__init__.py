"""Checkpoint/resume: host-side pytree serialization."""

from bpe_transformer_tpu.checkpointing.checkpoint import load_checkpoint, save_checkpoint

__all__ = ["load_checkpoint", "save_checkpoint"]
