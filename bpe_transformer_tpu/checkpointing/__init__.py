"""Checkpoint/resume: host-side pytree serialization."""

from bpe_transformer_tpu.checkpointing.checkpoint import (
    AsyncCheckpointer,
    load_checkpoint,
    load_checkpoint_sharded,
    save_checkpoint,
    save_checkpoint_sharded,
)

__all__ = [
    "AsyncCheckpointer",
    "load_checkpoint",
    "load_checkpoint_sharded",
    "save_checkpoint",
    "save_checkpoint_sharded",
]
