"""Checkpoint/resume: host-side pytree serialization."""

from bpe_transformer_tpu.checkpointing.checkpoint import (
    AsyncCheckpointer,
    CheckpointCorruptionError,
    load_checkpoint,
    load_checkpoint_sharded,
    load_checkpoint_with_fallback,
    save_checkpoint,
    save_checkpoint_sharded,
)

__all__ = [
    "AsyncCheckpointer",
    "CheckpointCorruptionError",
    "load_checkpoint",
    "load_checkpoint_sharded",
    "load_checkpoint_with_fallback",
    "save_checkpoint",
    "save_checkpoint_sharded",
]
