"""Pytree checkpointing: params + optimizer state + iteration, host-side.

Reference contract: `run_save_checkpoint` / `run_load_checkpoint`
(`/root/reference/tests/adapters.py:505-542`) — serialize (model, optimizer,
iteration) to a path or file-like object; loading restores both and returns
the iteration (roundtrip incl. optimizer internals pinned by
`test_serialization.py:57-121`).

Format: a pickled dict of numpy arrays (leaves pulled off-device with
``jax.device_get``) plus the pytree structure, so any params/opt-state shape
this framework produces roundtrips exactly.  Preemption-safe: writes go to a
temp file and rename into place when given a path.
"""

from __future__ import annotations

import os
import pickle
import tempfile
from pathlib import Path
from typing import Any, BinaryIO

import jax
import numpy as np

_FORMAT_VERSION = 1


def _to_host(tree):
    return jax.tree_util.tree_map(lambda x: np.asarray(jax.device_get(x)), tree)


def save_checkpoint(
    out: str | os.PathLike | BinaryIO,
    *,
    params: Any,
    opt_state: Any = None,
    iteration: int = 0,
    extra: dict | None = None,
) -> None:
    """Serialize a training state snapshot to ``out`` (path or file-like)."""
    payload = {
        "format_version": _FORMAT_VERSION,
        "params": _to_host(params),
        "opt_state": _to_host(opt_state) if opt_state is not None else None,
        "iteration": int(iteration),
        "extra": extra or {},
    }
    if hasattr(out, "write"):
        pickle.dump(payload, out)
        return
    path = Path(out)
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp_name = tempfile.mkstemp(dir=path.parent, prefix=path.name + ".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            pickle.dump(payload, f)
        os.replace(tmp_name, path)
    except BaseException:
        if os.path.exists(tmp_name):
            os.unlink(tmp_name)
        raise


def load_checkpoint(src: str | os.PathLike | BinaryIO) -> dict:
    """Load a snapshot; returns the payload dict (params, opt_state,
    iteration, extra)."""
    if hasattr(src, "read"):
        payload = pickle.load(src)
    else:
        with open(src, "rb") as f:
            payload = pickle.load(f)
    version = payload.get("format_version")
    if version != _FORMAT_VERSION:
        raise ValueError(f"unsupported checkpoint format version: {version}")
    return payload
