"""Pytree checkpointing: params + optimizer state + iteration, host-side.

Reference contract: `run_save_checkpoint` / `run_load_checkpoint`
(`/root/reference/tests/adapters.py:505-542`) — serialize (model, optimizer,
iteration) to a path or file-like object; loading restores both and returns
the iteration (roundtrip incl. optimizer internals pinned by
`test_serialization.py:57-121`).

Two formats:

* **single-file** (`save_checkpoint`): a pickled dict of numpy arrays
  (leaves pulled off-device with ``jax.device_get``) — fine at small scale
  and required for the reference's file-like-object contract;
* **sharded directory** (`save_checkpoint_sharded`): one ``.npy`` file per
  device shard of every leaf, streamed one shard at a time, plus a JSON
  manifest — peak host memory is one *shard*, not the full tree, which is
  what FSDP-scale states need.  Loading reassembles leaf by leaf and can
  place each leaf directly onto a target sharding (resume re-placement)
  without ever holding the whole tree in a single buffer.

Both are preemption-safe: writes go to a temp file/directory and rename into
place.  :func:`load_checkpoint` auto-detects the format.

Integrity (resilience/integrity.py): every save stamps per-file CRC32
checksums — into a ``checksums`` map of the sharded manifest, or an atomic
JSON sidecar next to a dense ``.ckpt`` — so corruption is detectable by a
cheap jax-free scan (``bpe-tpu verify-checkpoint``) instead of an opaque
unpickling crash.  :func:`load_checkpoint_with_fallback` acts on it:
quarantine the corrupt snapshot (``.corrupt`` suffix) and fall back to the
newest prior valid one in the same directory.
"""

from __future__ import annotations

import json
import os
import pickle
import threading
import shutil
import sys
import tempfile
from pathlib import Path
from typing import Any, BinaryIO

import jax
import numpy as np

from bpe_transformer_tpu.resilience.integrity import (
    Crc32Writer,
    candidate_snapshots,
    quarantine,
    sidecar_path,
    snapshot_step,
    verify_checkpoint,
    write_sidecar,
)

_FORMAT_VERSION = 1
_SHARDED_FORMAT_VERSION = 2
_MANIFEST = "manifest.json"
#: Marker file dropped in displaced-checkpoint temp dirs so recovery/reclaim
#: only ever touches directories THIS code created (a user's manual
#: ``cp -r x.ckpt x.ckpt.old`` backup carries no marker and is left alone).
_DISPLACED_MARKER = ".bt_displaced"


def _stranded_orphans(path: Path) -> list[Path]:
    """Displaced-checkpoint dirs a crashed save stranded next to ``path``,
    oldest first.  Matched by name prefix via listdir (no glob — checkpoint
    names may contain glob metacharacters) and required to carry both the
    ownership marker and a complete manifest."""
    parent = path.parent
    if not parent.is_dir():
        return []
    prefix = path.name + ".old"
    orphans = [
        parent / entry
        for entry in os.listdir(parent)
        if entry.startswith(prefix)
        and (parent / entry / _DISPLACED_MARKER).exists()
        and (parent / entry / "d" / _MANIFEST).exists()
    ]
    return sorted(orphans, key=lambda p: (p / "d").stat().st_mtime)


def _to_host(tree):
    return jax.tree_util.tree_map(lambda x: np.asarray(jax.device_get(x)), tree)


def save_checkpoint(
    out: str | os.PathLike | BinaryIO,
    *,
    params: Any,
    opt_state: Any = None,
    iteration: int = 0,
    extra: dict | None = None,
) -> None:
    """Serialize a training state snapshot to ``out`` (path or file-like)."""
    payload = {
        "format_version": _FORMAT_VERSION,
        "params": _to_host(params),
        "opt_state": _to_host(opt_state) if opt_state is not None else None,
        "iteration": int(iteration),
        "extra": extra or {},
    }
    if hasattr(out, "write"):
        pickle.dump(payload, out)
        return
    path = Path(out)
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp_name = tempfile.mkstemp(dir=path.parent, prefix=path.name + ".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            # CRC32 computed in the same pass as the write: the sidecar
            # (written AFTER the rename, so it never describes a file that
            # isn't in place yet) makes corruption detectable by a cheap
            # jax-free scan instead of an unpickling crash at resume.
            writer = Crc32Writer(f)
            pickle.dump(payload, writer)
        os.replace(tmp_name, path)
        write_sidecar(path, writer.crc, writer.size)
    except BaseException:
        if os.path.exists(tmp_name):
            os.unlink(tmp_name)
        raise


def sharded_checkpoint_exists(path: str | os.PathLike) -> bool:
    """True when ``path`` is a loadable sharded checkpoint: it has a
    manifest, or a crash-stranded ``<name>.old*/d`` sibling does (the
    recovery case :func:`load_checkpoint_sharded` handles)."""
    p = Path(path)
    return (p / _MANIFEST).exists() or bool(_stranded_orphans(p))


def load_checkpoint(src: str | os.PathLike | BinaryIO) -> dict:
    """Load a snapshot; returns the payload dict (params, opt_state,
    iteration, extra).  Accepts a single-file checkpoint, a file-like
    object, or a sharded checkpoint directory (auto-detected, including
    the crash-stranded-orphan recovery case)."""
    if not hasattr(src, "read") and (
        Path(src).is_dir()
        or (not Path(src).exists() and sharded_checkpoint_exists(src))
    ):
        return load_checkpoint_sharded(src)
    if hasattr(src, "read"):
        payload = pickle.load(src)
    else:
        with open(src, "rb") as f:
            payload = pickle.load(f)
    version = payload.get("format_version")
    if version != _FORMAT_VERSION:
        raise ValueError(f"unsupported checkpoint format version: {version}")
    return payload


# ------------------------------------------------- sharded directory format


def _distinct_shards(leaf) -> list[tuple[list[list[int]], Any]]:
    """(index, shard) for each DISTINCT index range of a leaf.

    A leaf replicated over one mesh axis and sharded over another has
    multiple addressable shards per index range; writing one per range
    keeps the checkpoint at exactly one copy of the data.
    """
    seen = set()
    out = []
    for shard in leaf.addressable_shards:
        index = []
        for sl, dim in zip(shard.index, leaf.shape):
            start = 0 if sl.start is None else int(sl.start)
            stop = dim if sl.stop is None else int(sl.stop)
            index.append([start, stop])
        key = tuple(tuple(r) for r in index)
        if key not in seen:
            seen.add(key)
            out.append((index, shard))
    return out


def _leaf_snapshots(leaves, eager: bool):
    """Per-leaf ``(record, [(filename, get_array)])`` write plan.

    ``eager=False`` defers every ``np.asarray`` to write time (the sync
    path streams one shard at a time); ``eager=True`` materializes numpy
    copies NOW so the caller may hand writing to a background thread while
    the live device buffers get donated by the next train step.
    """
    plan = []
    for i, leaf in enumerate(leaves):
        name = f"leaf_{i:05d}"
        # Multi-host guard: on a multi-process mesh each process addresses
        # only its local shards, so this single-writer format would record a
        # fraction of the leaf and a later load would silently restore
        # uninitialized memory in the gaps.  Refuse rather than corrupt —
        # multi-host saves need a per-process manifest (or orbax).
        if (
            isinstance(leaf, jax.Array)
            and hasattr(leaf, "is_fully_addressable")
            and not leaf.is_fully_addressable
        ):
            raise ValueError(
                f"leaf {i} is not fully addressable from this process "
                "(multi-process mesh); the sharded single-writer checkpoint "
                "format cannot record it completely. Gather to host or use a "
                "per-process checkpoint scheme."
            )
        is_sharded = (
            isinstance(leaf, jax.Array)
            and hasattr(leaf, "addressable_shards")
            and len(leaf.addressable_shards) > 1
            and not leaf.is_fully_replicated
        )
        record = {
            "name": name,
            "shape": list(np.shape(leaf)),
            "dtype": str(np.asarray(jax.device_get(leaf)).dtype)
            if np.ndim(leaf) == 0
            else str(leaf.dtype),
        }
        if is_sharded:
            distinct = _distinct_shards(leaf)
            record["shards"] = [{"index": index} for index, _ in distinct]
            files = []
            for j, (_, shard) in enumerate(distinct):
                get = (lambda s: lambda: np.asarray(s.data))(shard)
                if eager:
                    arr = get()
                    get = (lambda a: lambda: a)(arr)
                files.append((f"{name}.{j:03d}.npy", get))
        else:
            get = (lambda l: lambda: np.asarray(jax.device_get(l)))(leaf)
            if eager:
                arr = get()
                get = (lambda a: lambda: a)(arr)
            files = [(f"{name}.npy", get)]
        plan.append((record, files))
    return plan


def _write_sharded_dir(
    out_dir: Path, treedef, plan, iteration: int, extra: dict | None
) -> None:
    """Write a snapshot plan into ``out_dir`` (tmp-dir build + rename)."""
    out_dir.parent.mkdir(parents=True, exist_ok=True)
    tmp_dir = Path(
        tempfile.mkdtemp(dir=out_dir.parent, prefix=out_dir.name + ".tmp")
    )
    try:
        # Per-file CRC32s stamped into the manifest (computed during the
        # write, never by re-reading): the integrity layer verifies shards
        # without loading them, and resume can fall back past a corrupt
        # snapshot instead of crashing in np.load.
        checksums: dict[str, dict] = {}

        def _write_checksummed(fname: str, dump) -> None:
            with open(tmp_dir / fname, "wb") as f:
                writer = Crc32Writer(f)
                dump(writer)
            checksums[fname] = {"crc32": writer.crc, "size": writer.size}

        _write_checksummed("treedef.pkl", lambda w: pickle.dump(treedef, w))
        for record, files in plan:
            for fname, get_array in files:
                _write_checksummed(
                    fname,
                    (lambda get: lambda w: np.save(w, get()))(get_array),
                )
        manifest = {
            "format_version": _SHARDED_FORMAT_VERSION,
            "iteration": int(iteration),
            "extra": extra or {},
            "leaves": [record for record, _ in plan],
            "checksums": checksums,
        }
        with open(tmp_dir / _MANIFEST, "w") as f:
            json.dump(manifest, f)
        # os.replace cannot atomically swap non-empty directories; displace
        # any existing checkpoint with a RENAME (cheap), put the new one in
        # place, and only then rmtree the displaced copy.  An EXCEPTION in
        # the displace->replace window renames the old checkpoint back.  A
        # hard crash (SIGKILL/power) in that window can still strand the old
        # copy in a ``<name>.old*`` sibling — load_checkpoint_sharded probes
        # for exactly that and recovers it, so a resume always finds either
        # the old or the new checkpoint.
        displaced = None
        try:
            if out_dir.exists():
                displaced = Path(
                    tempfile.mkdtemp(
                        dir=out_dir.parent, prefix=out_dir.name + ".old"
                    )
                )
                (displaced / _DISPLACED_MARKER).touch()
                os.rename(out_dir, displaced / "d")
            os.replace(tmp_dir, out_dir)
        except BaseException:
            if (
                displaced is not None
                and not out_dir.exists()
                and (displaced / "d").exists()
            ):
                os.rename(displaced / "d", out_dir)
                shutil.rmtree(displaced, ignore_errors=True)
            raise
        if displaced is not None:
            shutil.rmtree(displaced, ignore_errors=True)
        # Reclaim marked orphans stranded by EARLIER crashed saves of this
        # checkpoint (each would otherwise leak a full checkpoint copy).
        # Only marker-carrying dirs are touched — see _DISPLACED_MARKER.
        prefix = out_dir.name + ".old"
        for entry in os.listdir(out_dir.parent):
            stale = out_dir.parent / entry
            if entry.startswith(prefix) and (stale / _DISPLACED_MARKER).exists():
                shutil.rmtree(stale, ignore_errors=True)
    except BaseException:
        if tmp_dir.exists():
            shutil.rmtree(tmp_dir, ignore_errors=True)
        raise


def save_checkpoint_sharded(
    out_dir: str | os.PathLike,
    *,
    params: Any,
    opt_state: Any = None,
    iteration: int = 0,
    extra: dict | None = None,
) -> None:
    """Stream a training state into a checkpoint DIRECTORY, shard by shard.

    Every pytree leaf is written as one ``.npy`` per DISTINCT device shard
    (a leaf on N devices under FSDP yields N files, each 1/N of the leaf);
    replicated or host leaves yield a single file.  Peak host memory is
    therefore one shard, never the assembled tree.  The pytree structure
    goes to ``treedef.pkl`` (structure only, no array data) and shard
    geometry to ``manifest.json``.  The directory is built under a temp
    name and renamed into place, so a preempted save never leaves a partial
    checkpoint at ``out_dir``.
    """
    tree = {"params": params, "opt_state": opt_state}
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    plan = _leaf_snapshots(leaves, eager=False)
    _write_sharded_dir(Path(out_dir), treedef, plan, iteration, extra)


def _check_shards_tile(record: dict) -> None:
    """Verify a manifest leaf's shard index boxes exactly tile its shape.

    The reassembly below fills an ``np.empty`` buffer from the manifest's
    index ranges; a manifest that covers only part of the leaf (e.g. written
    by one process of a multi-process mesh before the save-side guard
    existed) would otherwise restore uninitialized memory silently.
    Axis-aligned boxes tile a volume iff they are pairwise disjoint and
    their volumes sum to the total.
    """
    shape = record["shape"]
    total = int(np.prod(shape)) if shape else 1
    boxes = [s["index"] for s in record["shards"]]
    covered = 0
    for box in boxes:
        vol = 1
        for (start, stop), dim in zip(box, shape):
            if not (0 <= start <= stop <= dim):
                raise ValueError(
                    f"checkpoint leaf {record['name']}: shard index {box} "
                    f"out of bounds for shape {shape}"
                )
            vol *= stop - start
        covered += vol
    disjoint = all(
        any(a1 >= b2 or b1 >= a2 for (a1, a2), (b1, b2) in zip(pa, pb))
        for i, pa in enumerate(boxes)
        for pb in boxes[i + 1 :]
    )
    if covered != total or not disjoint:
        raise ValueError(
            f"checkpoint leaf {record['name']}: shard files cover "
            f"{covered}/{total} elements"
            + ("" if disjoint else " with overlapping ranges")
            + f" of shape {shape} — incomplete or corrupt manifest "
            "(possibly written from one process of a multi-process mesh)"
        )


def load_checkpoint_sharded(
    src_dir: str | os.PathLike, shardings: Any | None = None
) -> dict:
    """Load a sharded checkpoint directory; returns the same payload dict as
    :func:`load_checkpoint`.

    Leaves are reassembled ONE AT A TIME from their shard files; with
    ``shardings`` (a pytree of `jax.sharding.Sharding` matching
    ``{"params": ..., "opt_state": ...}``) each leaf is placed onto its
    target devices as soon as it is assembled, so resume re-placement never
    stages the whole tree on host.
    """
    src_dir = Path(src_dir)
    if not (src_dir / _MANIFEST).exists():
        # A hard crash inside _write_sharded_dir's displace->replace window
        # leaves the previous (complete) checkpoint stranded in a marked
        # ``<name>.old*/d`` sibling; PROMOTE the newest such copy back to
        # ``src_dir`` (so the recovery is visible on disk and the orphan
        # doesn't leak or resurrect after an intentional delete) and load it.
        orphans = _stranded_orphans(src_dir)
        if orphans:
            import sys

            print(
                f"checkpoint {src_dir} missing; recovering the copy a "
                f"crashed save stranded in {orphans[-1]}",
                file=sys.stderr,
            )
            try:
                os.rename(orphans[-1] / "d", src_dir)
            except OSError:
                # Concurrent loader won the promotion race; fine as long as
                # the checkpoint is now in place.
                if not (src_dir / _MANIFEST).exists():
                    raise
            for leftover in orphans:
                shutil.rmtree(leftover, ignore_errors=True)
    with open(src_dir / _MANIFEST) as f:
        manifest = json.load(f)
    if manifest.get("format_version") != _SHARDED_FORMAT_VERSION:
        raise ValueError(
            "unsupported sharded checkpoint format version: "
            f"{manifest.get('format_version')}"
        )
    with open(src_dir / "treedef.pkl", "rb") as f:
        treedef = pickle.load(f)

    placement_leaves = None
    if shardings is not None:
        placement_leaves = jax.tree_util.tree_flatten(shardings)[0]
        if len(placement_leaves) != len(manifest["leaves"]):
            raise ValueError(
                f"shardings tree has {len(placement_leaves)} leaves, "
                f"checkpoint has {len(manifest['leaves'])}"
            )

    leaves = []
    for i, record in enumerate(manifest["leaves"]):
        name = record["name"]
        if "shards" in record:
            _check_shards_tile(record)
            value = np.empty(record["shape"], dtype=np.dtype(record["dtype"]))
            for j, shard in enumerate(record["shards"]):
                idx = tuple(slice(start, stop) for start, stop in shard["index"])
                value[idx] = np.load(src_dir / f"{name}.{j:03d}.npy")
        else:
            value = np.load(src_dir / f"{name}.npy")
            if not record["shape"]:  # 0-d leaf saved from a python scalar
                value = value[()]
        if placement_leaves is not None:
            value = jax.device_put(value, placement_leaves[i])
        leaves.append(value)

    tree = jax.tree_util.tree_unflatten(treedef, leaves)
    return {
        "format_version": _FORMAT_VERSION,
        "params": tree["params"],
        "opt_state": tree["opt_state"],
        "iteration": manifest["iteration"],
        "extra": manifest["extra"],
    }


# ----------------------------------------------- corruption-tolerant loading


class CheckpointCorruptionError(RuntimeError):
    """No loadable checkpoint: the requested snapshot AND every prior
    sibling failed verification or loading.  Carries the per-snapshot
    failure list in ``.failures``."""

    def __init__(self, message: str, failures: list[str]):
        super().__init__(message)
        self.failures = failures


def _quarantine_snapshot(path: Path) -> Path | None:
    """Quarantine a corrupt snapshot with the ``.corrupt`` suffix.  A
    symlink (``latest.ckpt`` in the sharded layout) quarantines its TARGET
    and removes the dangling link — the evidence is the data, not the
    pointer."""
    if path.is_symlink():
        try:
            target = path.resolve(strict=False)
        except OSError:
            target = None
        path.unlink()
        if target is not None and (target.exists() or target.is_symlink()):
            return quarantine(target)
        return None
    if path.exists():
        return quarantine(path)
    return None


def load_checkpoint_with_fallback(
    src: str | os.PathLike, loader=None
) -> tuple[dict, Path]:
    """Load ``src``, falling back to the newest prior VALID snapshot in the
    same directory when it is corrupt — quarantining (never deleting) every
    snapshot that fails on the way.  Returns ``(payload, used_path)``.

    ``loader`` defaults to :func:`load_checkpoint` (auto-detecting); the
    training loop passes its mesh-placement-aware loader so GSPMD resumes
    get the same protection.  Verification is the cheap jax-free pass
    (checksums + manifest shapes).  Two deliberate limits on the fallback:

    * only snapshots with a step number STRICTLY BELOW the requested one
      are candidates — a user who explicitly resumes from an old snapshot
      (re-branching before a divergence) must never be silently fast-
      forwarded to a newer state;
    * a snapshot whose bytes are PROVABLY intact (checksums verified) but
      whose load still raises is a caller/config or environment error
      (wrong mesh, NFS timeout, OOM), not corruption — the error is
      re-raised untouched instead of quarantining valid multi-GB
      snapshots one by one.  Only unverifiable (pre-integrity) snapshots
      get the quarantine-on-load-failure treatment.
    """
    loader = loader or load_checkpoint
    src = Path(src)
    try:
        exclude = {src.resolve()}
    except OSError:
        exclude = set()
    siblings = candidate_snapshots(src.parent, exclude=exclude)
    src_step = snapshot_step(src.name)
    if src_step is not None:
        siblings = [
            p
            for p in siblings
            if (snapshot_step(p.name) or 0) < src_step
        ]
    attempts = [src] + siblings
    failures: list[str] = []
    for path in attempts:
        result = verify_checkpoint(path)
        if not result.ok:
            failures.append(
                f"{path}: {'; '.join(result.problems) or 'invalid'}"
            )
            quarantined = _quarantine_snapshot(path)
            print(
                f"checkpoint {path} failed integrity verification"
                + (f" (quarantined as {quarantined})" if quarantined else "")
                + f": {'; '.join(result.problems)}",
                file=sys.stderr,
            )
            continue
        # ok + no warnings == every byte matched a recorded checksum.
        bytes_verified = not result.warnings
        try:
            payload = loader(path)
        except Exception as exc:  # noqa: BLE001 - triaged below
            if bytes_verified:
                # Intact bytes that won't load: the problem is the caller
                # or the environment, never this snapshot — surface it.
                raise
            failures.append(f"{path}: load failed ({exc})")
            quarantined = _quarantine_snapshot(path)
            print(
                f"checkpoint {path} failed to load ({exc})"
                + (f"; quarantined as {quarantined}" if quarantined else ""),
                file=sys.stderr,
            )
            continue
        if failures:
            print(
                f"resumed from fallback snapshot {path} after "
                f"{len(failures)} corrupt candidate(s)",
                file=sys.stderr,
            )
        return payload, path
    raise CheckpointCorruptionError(
        f"no loadable checkpoint at {src} or among its siblings "
        f"({len(failures)} candidate(s) failed — corrupt snapshots were "
        "quarantined with a .corrupt suffix)",
        failures,
    )


# --------------------------------------------------------- async checkpoints


class AsyncCheckpointer:
    """Overlap checkpoint writing with training.

    ``save()`` snapshots every leaf to host numpy SYNCHRONOUSLY (so the
    live device buffers can be donated by the next train step) and hands
    serialization + file IO to a background thread — the training loop
    resumes after the device→host copy instead of waiting on disk.  At most
    one write is in flight: the next ``save()`` (or ``close()``) joins the
    previous one first and re-raises any error it hit.

    Host-memory note: the eager snapshot stages one full copy of the state
    in RAM for the duration of the write — the price of overlap.  Use the
    plain ``save_checkpoint*`` functions where host memory is tighter than
    step time.
    """

    def __init__(self):
        self._thread: threading.Thread | None = None
        self._error: BaseException | None = None

    def wait(self) -> None:
        """Block until the in-flight write (if any) finishes."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def save(
        self,
        out: str | os.PathLike,
        *,
        params: Any,
        opt_state: Any = None,
        iteration: int = 0,
        extra: dict | None = None,
        sharded: bool = False,
        on_complete=None,
    ) -> None:
        """Snapshot now, write in the background (single- or sharded-format).

        ``on_complete()`` runs in the worker thread after a SUCCESSFUL
        write — e.g. to update a ``latest.ckpt`` pointer only once the
        checkpoint actually exists on disk.
        """
        self.wait()
        if sharded:
            tree = {"params": params, "opt_state": opt_state}
            leaves, treedef = jax.tree_util.tree_flatten(tree)
            plan = _leaf_snapshots(leaves, eager=True)

            def write():
                _write_sharded_dir(Path(out), treedef, plan, iteration, extra)

        else:
            host_params = _to_host(params)
            host_opt = _to_host(opt_state) if opt_state is not None else None

            def write():
                save_checkpoint(
                    out,
                    params=host_params,
                    opt_state=host_opt,
                    iteration=iteration,
                    extra=extra,
                )

        def work():
            try:
                write()
                if on_complete is not None:
                    on_complete()
            except BaseException as exc:  # noqa: BLE001 - rethrown in wait()
                self._error = exc

        self._thread = threading.Thread(target=work, daemon=True)
        self._thread.start()

    def close(self) -> None:
        self.wait()
