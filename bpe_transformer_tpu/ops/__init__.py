"""Device-side tensor ops (pure jnp / XLA)."""

from bpe_transformer_tpu.ops.core import (
    causal_mask,
    embedding,
    linear,
    merge_heads,
    multihead_self_attention,
    rmsnorm,
    scaled_dot_product_attention,
    silu,
    softmax,
    split_heads,
    swiglu,
)
from bpe_transformer_tpu.ops.grad import clip_by_global_norm, global_norm
from bpe_transformer_tpu.ops.losses import cross_entropy
from bpe_transformer_tpu.ops.quant import (
    is_quantized,
    quantize_params,
    quantize_weight,
)
from bpe_transformer_tpu.ops.rope import apply_rope, rope, rope_tables

__all__ = [
    "apply_rope",
    "causal_mask",
    "clip_by_global_norm",
    "cross_entropy",
    "embedding",
    "global_norm",
    "is_quantized",
    "linear",
    "quantize_params",
    "quantize_weight",
    "merge_heads",
    "multihead_self_attention",
    "rmsnorm",
    "rope",
    "rope_tables",
    "scaled_dot_product_attention",
    "silu",
    "softmax",
    "split_heads",
    "swiglu",
]
