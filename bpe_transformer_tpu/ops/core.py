"""Core tensor ops: the building blocks of the transformer, as pure jnp.

Each op mirrors a spec-only component of the reference test contract
(`/root/reference/tests/adapters.py`): linear (M1), embedding (M2), rmsnorm
(M3), silu (M4), swiglu (M5), softmax (M6), scaled-dot-product attention
(M7), multi-head self-attention with/without RoPE (M8).

TPU notes: weights follow the torch ``(d_out, d_in)`` row-major layout so
reference checkpoints map 1:1; matmuls are einsums the XLA TPU backend tiles
onto the MXU; normalization/softmax accumulate in float32 regardless of the
activation dtype (bf16-safe); masks are boolean with True = keep.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import Array

from bpe_transformer_tpu.ops.rope import apply_rope, rope_tables

#: Large negative filler for masked attention scores.  Finite (not -inf) so
#: fully-masked rows produce a uniform distribution instead of NaNs.
MASK_VALUE = -1e30


def linear(x: Array, weight: Array) -> Array:
    """``y = x @ W.T`` with torch-layout ``W: (d_out, d_in)``; no bias.

    ``weight`` may also be an int8-quantized dict (``ops/quant.py``, the
    serving path's per-channel weights) — dispatched to the
    dequant-in-register Pallas matmul.  Training params are plain arrays,
    so the hot path is untouched.
    """
    if isinstance(weight, dict):
        from bpe_transformer_tpu.ops.quant import quant_linear

        return quant_linear(x, weight)
    return jnp.einsum("...i,oi->...o", x, weight)


def head_logits(hidden: Array, head_w: Array) -> Array:
    """Vocab projection ``hidden (..., d) @ head_w (vocab, d).T`` in the
    HIDDEN's dtype with float32 accumulation/output.

    The one dtype rule for every logits site (train loss, chunked CE,
    decode sampling): on the bf16 perf path the step's most expensive
    matmul keeps full MXU rate (f32 inputs run the systolic array at ~1/4
    speed on v5e) while the f32 output preserves logsumexp/sampling
    stability; on f32 paths it is bit-identical to an f32 matmul.

    An int8-quantized ``head_w`` dict (serving path) dispatches to the
    dequant-in-register kernel; its accumulator is already f32, so the
    float32-clean logits contract holds unchanged.
    """
    if isinstance(head_w, dict):
        from bpe_transformer_tpu.ops.quant import quant_linear

        return quant_linear(hidden, head_w, preserve_f32=True)
    return jax.lax.dot_general(
        hidden, head_w.astype(hidden.dtype),
        (((hidden.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def embedding(weight: Array, token_ids: Array) -> Array:
    """Row gather from ``(vocab_size, d_model)``."""
    return jnp.take(weight, token_ids, axis=0)


def rmsnorm(x: Array, weight: Array, eps: float = 1e-5) -> Array:
    """Root-mean-square norm with affine scale; accumulates in float32."""
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * scale).astype(x.dtype) * weight


def silu(x: Array) -> Array:
    """``x * sigmoid(x)``."""
    return x * jax.nn.sigmoid(x)


def swiglu(x: Array, w1: Array, w2: Array, w3: Array) -> Array:
    """SwiGLU FFN: ``w2(silu(w1 x) * (w3 x))``.

    ``w1, w3: (d_ff, d_model)``, ``w2: (d_model, d_ff)``.
    """
    return linear(silu(linear(x, w1)) * linear(x, w3), w2)


def softmax(x: Array, axis: int = -1) -> Array:
    """Shift-stabilized softmax along ``axis``; float32 accumulation."""
    x32 = x.astype(jnp.float32)
    shifted = x32 - jax.lax.stop_gradient(x32.max(axis=axis, keepdims=True))
    exp = jnp.exp(shifted)
    return (exp / exp.sum(axis=axis, keepdims=True)).astype(x.dtype)


def scaled_dot_product_attention(
    q: Array,
    k: Array,
    v: Array,
    mask: Array | None = None,
) -> Array:
    """Attention over the last two axes; boolean ``mask`` keeps True entries.

    Shapes: ``q (..., Sq, d)``, ``k (..., Sk, d)``, ``v (..., Sk, dv)``,
    ``mask (..., Sq, Sk)`` broadcastable.
    """
    d_k = q.shape[-1]
    scores = jnp.einsum("...qd,...kd->...qk", q, k) / jnp.sqrt(
        jnp.asarray(d_k, dtype=q.dtype)
    )
    if mask is not None:
        scores = jnp.where(mask, scores, MASK_VALUE)
    weights = softmax(scores, axis=-1)
    return jnp.einsum("...qk,...kv->...qv", weights, v)


def causal_mask(seq_len: int, dtype=bool) -> Array:
    """Lower-triangular ``(seq, seq)`` keep-mask."""
    return jnp.tril(jnp.ones((seq_len, seq_len), dtype=dtype))


def attention_entropy(q: Array, k: Array, causal: bool = True) -> Array:
    """Mean Shannon entropy (nats) of the softmax attention distribution.

    ``q (..., Sq, d)``, ``k (..., Sk, d)`` — the same tensors an
    ``attention_fn`` receives; scores/log-softmax accumulate in float32.
    Averaged over every leading axis and query position: ~0 means the
    heads collapsed onto single keys, ~log(Sk) means uniform (no learned
    structure).  The telemetry dynamics tap (`telemetry.dynamics`) calls
    this on a batch slice — it re-materializes the (Sq, Sk) score matrix,
    which fused attention kernels exist to avoid.
    """
    q32, k32 = q.astype(jnp.float32), k.astype(jnp.float32)
    scores = jnp.einsum("...qd,...kd->...qk", q32, k32) / jnp.sqrt(
        jnp.asarray(q.shape[-1], jnp.float32)
    )
    if causal:
        scores = jnp.where(
            causal_mask(scores.shape[-1])[: scores.shape[-2]], scores, MASK_VALUE
        )
    logp = jax.nn.log_softmax(scores, axis=-1)
    # exp(logp) is exactly 0 at masked entries, so p * logp contributes -0.0
    # there (never NaN).
    return -jnp.mean(jnp.sum(jnp.exp(logp) * logp, axis=-1))


def split_heads(x: Array, num_heads: int) -> Array:
    """``(..., S, H*dh) -> (..., H, S, dh)`` with head-major row layout.

    Matches the reference weight convention where projection rows are the
    concatenation of per-head blocks (`adapters.py:237-251`).
    """
    *batch, seq, dm = x.shape
    x = x.reshape(*batch, seq, num_heads, dm // num_heads)
    return jnp.moveaxis(x, -2, -3)


def merge_heads(x: Array) -> Array:
    """``(..., H, S, dh) -> (..., S, H*dh)``."""
    x = jnp.moveaxis(x, -3, -2)
    *batch, seq, h, dh = x.shape
    return x.reshape(*batch, seq, h * dh)


def multihead_self_attention(
    x: Array,
    q_w: Array,
    k_w: Array,
    v_w: Array,
    o_w: Array,
    num_heads: int,
    *,
    num_kv_heads: int | None = None,
    positions: Array | None = None,
    rope_theta: float | None = None,
    max_seq_len: int | None = None,
    rope_cos_sin: tuple[Array, Array] | None = None,
    causal: bool = True,
    attention_fn=None,
) -> Array:
    """Causal multi-head self-attention, optionally with RoPE on Q/K.

    All four projections are single fused matmuls over the head-concat
    weight layout.  RoPE (when enabled) is applied per head at
    ``d_head = d_model // num_heads``.  ``attention_fn(q, k, v)`` swaps the
    materialized-scores attention for a fused kernel (e.g. Pallas flash
    attention); the callable owns its own (causal) masking.

    ``num_kv_heads < num_heads`` is grouped-query attention: K/V project to
    fewer heads (``k_w``/``v_w`` have ``num_kv_heads * d_head`` rows) and
    each KV head serves ``num_heads // num_kv_heads`` query heads — the
    projections and the KV cache shrink by that factor while scores/output
    math is unchanged (KV heads broadcast up before the attention call, so
    every ``attention_fn`` works untouched).
    """
    seq_len = x.shape[-2]
    kv_heads = num_kv_heads or num_heads
    q = split_heads(linear(x, q_w), num_heads)
    k = split_heads(linear(x, k_w), kv_heads)
    v = split_heads(linear(x, v_w), kv_heads)

    if rope_cos_sin is not None or rope_theta is not None:
        if positions is None:
            positions = jnp.arange(seq_len)
        if rope_cos_sin is None:
            d_head = q.shape[-1]
            rope_cos_sin = rope_tables(
                d_head, max_seq_len or seq_len, rope_theta, dtype=jnp.float32
            )
        cos, sin = rope_cos_sin
        # positions broadcast over the head axis: (..., S) -> (..., 1, S)
        pos = jnp.expand_dims(positions, axis=-2)
        q = apply_rope(q, pos, cos, sin)
        k = apply_rope(k, pos, cos, sin)

    if kv_heads != num_heads:
        group = num_heads // kv_heads
        k = jnp.repeat(k, group, axis=-3)
        v = jnp.repeat(v, group, axis=-3)

    if attention_fn is not None:
        attended = attention_fn(q, k, v)
    else:
        mask = causal_mask(seq_len) if causal else None
        attended = scaled_dot_product_attention(q, k, v, mask)
    return linear(merge_heads(attended), o_w)
