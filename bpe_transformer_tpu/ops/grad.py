"""Gradient utilities: global-norm clipping over parameter pytrees.

Reference contract: `run_gradient_clipping` (`/root/reference/tests/
adapters.py:458-467`) — combined L2 over all grads, scale applied when the
norm exceeds the budget, matching ``torch.nn.utils.clip_grad_norm_``
(eps 1e-6 in the denominator).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import Array


def global_norm(tree) -> Array:
    """L2 norm over every array in a pytree, accumulated in float32."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.float32(0.0)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(leaf.astype(jnp.float32))) for leaf in leaves)
    )


def clip_by_global_norm(grads, max_norm: float, eps: float = 1e-6):
    """Scale ``grads`` so their combined L2 norm is at most ``max_norm``.

    Returns ``(clipped_grads, pre_clip_norm)``.  The scale factor
    ``max_norm / (norm + eps)`` is only applied when the norm exceeds the
    budget — identical semantics to torch's ``clip_grad_norm_``.
    """
    norm = global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / (norm + eps))
    return jax.tree_util.tree_map(lambda g: (g * scale).astype(g.dtype), grads), norm
