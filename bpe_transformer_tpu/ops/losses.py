"""Training losses as pure XLA ops (optax-free).

Reference contract: `run_cross_entropy` (`/root/reference/tests/
adapters.py:440-455`) — mean cross-entropy over examples, stable at 1000x
logit scale (pinned by `test_nn_utils.py:27-59`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import Array, lax
from jax.scipy.special import logsumexp

from bpe_transformer_tpu.ops.core import head_logits


def cross_entropy(logits: Array, targets: Array) -> Array:
    """Mean negative log-likelihood of ``targets`` under ``logits``.

    ``logits: (..., vocab)``, ``targets: (...)`` integer class ids.  Uses
    logsumexp (float32 accumulation) so arbitrarily scaled logits stay
    finite.
    """
    logits32 = logits.astype(jnp.float32)
    target_logit = jnp.take_along_axis(
        logits32, targets[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    nll = logsumexp(logits32, axis=-1) - target_logit
    return nll.mean()


def chunked_lm_cross_entropy(
    hidden: Array,
    lm_head_w: Array,
    targets: Array,
    chunk_size: int,
) -> Array:
    """Mean LM cross-entropy WITHOUT materializing full logits.

    ``hidden: (batch, seq, d_model)``, ``lm_head_w: (vocab, d_model)``,
    ``targets: (batch, seq)``.  The sequence axis is processed in
    ``chunk_size`` slices inside a ``lax.map``; each chunk projects to the
    vocab, reduces to its NLL, and is rematerialized on the backward pass —
    peak activation memory drops from ``O(seq * vocab)`` to
    ``O(chunk * vocab)``, the enabling trick for 32k-vocab configs at long
    context.  Numerically identical to
    ``cross_entropy(hidden @ lm_head.T, targets)``.
    """
    batch, seq, d = hidden.shape
    if seq % chunk_size:
        raise ValueError(
            f"seq {seq} not divisible by loss chunk_size {chunk_size}"
        )
    n_chunks = seq // chunk_size
    h = hidden.reshape(batch, n_chunks, chunk_size, d).swapaxes(0, 1)
    t = targets.reshape(batch, n_chunks, chunk_size).swapaxes(0, 1)

    @jax.checkpoint
    def chunk_nll(args):
        hc, tc = args  # (batch, chunk, d), (batch, chunk)
        # head_logits: activation-dtype matmul, f32 accumulation — full MXU
        # rate on the bf16 path, f32 logsumexp stability either way.
        logits = head_logits(hc, lm_head_w)
        target_logit = jnp.take_along_axis(
            logits, tc[..., None].astype(jnp.int32), axis=-1
        )[..., 0]
        return (logsumexp(logits, axis=-1) - target_logit).sum()

    total = lax.map(chunk_nll, (h, t)).sum()
    return total / (batch * seq)


def lm_loss(
    hidden: Array,
    lm_head_w: Array,
    targets: Array,
    chunk_size: int | None,
) -> Array:
    """LM cross-entropy from final hidden states, chunking when possible.

    The one shared guard for every loss path (single-device train/eval,
    pipeline head loss, sequence-parallel shards): clamp ``chunk_size`` to
    the actual sequence — callers may evaluate truncated inputs — and fall
    back to full logits when the chunk doesn't divide it.
    """
    seq = hidden.shape[-2]
    chunk = min(chunk_size, seq) if chunk_size else None
    if chunk and seq % chunk == 0:
        return chunked_lm_cross_entropy(hidden, lm_head_w, targets, chunk)
    return cross_entropy(head_logits(hidden, lm_head_w), targets)
