"""Training losses as pure XLA ops (optax-free).

Reference contract: `run_cross_entropy` (`/root/reference/tests/
adapters.py:440-455`) — mean cross-entropy over examples, stable at 1000x
logit scale (pinned by `test_nn_utils.py:27-59`).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import Array
from jax.scipy.special import logsumexp


def cross_entropy(logits: Array, targets: Array) -> Array:
    """Mean negative log-likelihood of ``targets`` under ``logits``.

    ``logits: (..., vocab)``, ``targets: (...)`` integer class ids.  Uses
    logsumexp (float32 accumulation) so arbitrarily scaled logits stay
    finite.
    """
    logits32 = logits.astype(jnp.float32)
    target_logit = jnp.take_along_axis(
        logits32, targets[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    nll = logsumexp(logits32, axis=-1) - target_logit
    return nll.mean()
