"""Rotary position embeddings (RoPE), interleaved-pair convention.

Matches the reference contract pinned by `test_rope.npz` (verified to
~5e-7): for each adjacent feature pair ``(x[2k], x[2k+1])`` at position
``p``, rotate by angle ``p * theta^(-2k/d)``.

TPU-first shape discipline: the sin/cos tables are precomputed once for
``max_seq_len`` (host constant, becomes an XLA constant under jit), and
application is a pure elementwise op that XLA fuses into the surrounding
attention matmuls.  The table gather by ``positions`` keeps shapes static so
the whole attention stack stays jit-compatible at any prompt length.

Reference spec: `/root/reference/tests/adapters.py:187-206` (run_rope),
`bpe_transformer/embeddings/rope.py` (empty placeholder in the reference).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import Array


def rope_tables(
    d_k: int, max_seq_len: int, theta: float = 10000.0, dtype=jnp.float32
) -> tuple[Array, Array]:
    """Precompute ``(cos, sin)`` tables of shape ``(max_seq_len, d_k // 2)``."""
    if d_k % 2:
        raise ValueError(f"RoPE feature dim must be even, got {d_k}")
    inv_freq = theta ** (-jnp.arange(0, d_k, 2, dtype=jnp.float32) / d_k)
    angles = jnp.arange(max_seq_len, dtype=jnp.float32)[:, None] * inv_freq[None, :]
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def apply_rope(
    x: Array,
    positions: Array,
    cos: Array,
    sin: Array,
) -> Array:
    """Rotate ``x`` (``..., seq, d_k``) by position-dependent angles.

    ``positions`` has shape ``(..., seq)`` (leading dims broadcast against
    ``x``'s batch dims) and indexes into the precomputed tables.
    """
    cos_p = cos[positions]  # (..., seq, d_k//2)
    sin_p = sin[positions]
    x_even = x[..., 0::2]
    x_odd = x[..., 1::2]
    rot_even = x_even * cos_p - x_odd * sin_p
    rot_odd = x_even * sin_p + x_odd * cos_p
    # Re-interleave: stack pairs on a trailing axis and flatten.
    out = jnp.stack([rot_even, rot_odd], axis=-1)
    return out.reshape(x.shape)


def rope(
    x: Array,
    positions: Array,
    *,
    theta: float = 10000.0,
    max_seq_len: int | None = None,
) -> Array:
    """One-shot convenience: build tables and apply (test/reference seam)."""
    if max_seq_len is None:
        max_seq_len = int(positions.max()) + 1
    cos, sin = rope_tables(x.shape[-1], max_seq_len, theta, dtype=x.dtype)
    return apply_rope(x, positions, cos, sin)
