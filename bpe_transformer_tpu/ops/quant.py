"""Per-channel int8 weight quantization for the serving path.

At serving batch sizes (batch ≲ slots) the decode tick is
weight-bandwidth-bound: every emitted token pays one full HBM sweep of
the matmul weights (T-REX, arXiv:2503.00322, builds an accelerator
around exactly this "reduce external memory access" bottleneck).  This
module shrinks that sweep ~2x under bf16 (4x under f32) by storing the
matmul weights as int8 with one f32 scale per OUTPUT channel:

    W[o, i]  ~=  q[o, i] * scale[o],      q int8, scale = amax_i|W[o,:]|/127

and dequantizing **in registers** at matmul time (the Pallas kernel in
`kernels/pallas/quant_matmul.py`, the weight twin of the PR 9 paged
decode kernel's KV dequant) — a dequantized f32/bf16 copy of the weight
never exists in HBM.  Because the scale is per output row, the matmul
factors exactly:

    y[..., o] = scale[o] * sum_i x[..., i] * q[o, i]

so the inner product runs over the int8 tile and ONE multiply per output
element applies the scale — no per-element dequant tensor at all.

A quantized weight is a plain dict ``{"q": int8 (d_out, d_in),
"scale": f32 (d_out,)}`` — a pytree, so it flows through jit/scan/vmap
unchanged — and `ops.core.linear` / `ops.core.head_logits` dispatch on
it, which is what lets every serving program (decode tick, chunked
prefill, spec verify, draft propose over a truncated view) run quantized
without a second code path.  Training never constructs one: quantization
happens once, at engine build / ``warmup`` time
(:func:`quantize_params`), on the already-compute-dtype-cast tree.

What is quantized: the attention projections (q/k/v/output), the dense
FFN matrices (w1/w2[/w3]), and the LM head — the tensors a decode tick
streams.  What is NOT: token embeddings (a row *gather*, not a matmul —
int8 rows would quantize activations, not traffic), norm gains (tiny),
and MoE expert stacks (the gather-dispatch layout is not covered;
engines refuse ``weight_dtype="int8"`` for MoE configs up front).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import Array

from bpe_transformer_tpu.models.config import ModelConfig

__all__ = [
    "dequantize",
    "is_quantized",
    "quant_linear",
    "quant_linear_xla",
    "quantize_params",
    "quantize_weight",
    "tree_bytes",
]

#: Keys of a quantized-weight dict — the dispatch tag `ops.core.linear`
#: checks.  Kept minimal so the dict stays a transparent pytree.
_QKEYS = frozenset({"q", "scale"})


def is_quantized(w) -> bool:
    """True for a quantized-weight dict (works on tracers too — the check
    is structural, never touches array values)."""
    return isinstance(w, dict) and _QKEYS.issubset(w.keys())


def quantize_weight(w: Array) -> dict:
    """Per-output-channel symmetric int8 quantization of a ``(d_out,
    d_in)`` matmul weight: ``scale[o] = max_i |w[o, i]| / 127`` (f32),
    ``q = round(w / scale)`` clipped to ``[-127, 127]``.  An all-zero row
    keeps scale 0 and dequantizes to exact zeros."""
    if w.ndim != 2:
        raise ValueError(
            f"quantize_weight expects a 2D (d_out, d_in) matrix, got "
            f"{w.shape}"
        )
    w32 = w.astype(jnp.float32)
    scale = jnp.max(jnp.abs(w32), axis=1) / 127.0  # (d_out,)
    safe = jnp.maximum(scale, 1e-30)
    q = jnp.clip(jnp.round(w32 / safe[:, None]), -127, 127).astype(jnp.int8)
    return {"q": q, "scale": scale}


def dequantize(w: dict, dtype=jnp.float32) -> Array:
    """Materialize the approximate weight (tests/debugging only — the
    serving path never calls this)."""
    return (
        w["q"].astype(jnp.float32) * w["scale"][:, None]
    ).astype(dtype)


def quant_linear_xla(x: Array, w: dict) -> Array:
    """XLA reference for the quantized matmul: f32 accumulation over the
    int8 tile, ONE scale multiply per output element, output back at
    ``x``'s dtype.  The Pallas kernel's parity oracle (and the fallback
    where Pallas is unavailable)."""
    out = jax.lax.dot_general(
        x.astype(jnp.float32), w["q"].astype(jnp.float32),
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return (out * w["scale"]).astype(x.dtype)


def quant_linear(x: Array, w: dict, *, preserve_f32: bool = False) -> Array:
    """``y = x @ (q * scale).T`` without materializing the dequantized
    weight: the Pallas kernel streams int8 tiles through VMEM and
    dequantizes in registers (interpret mode off-TPU, like every kernel
    here).  ``preserve_f32=True`` returns the f32 accumulator itself —
    the `head_logits` contract (logits stay float32-clean)."""
    from bpe_transformer_tpu.kernels.pallas.quant_matmul import quant_matmul

    out = quant_matmul(x, w["q"], w["scale"])  # f32
    return out if preserve_f32 else out.astype(x.dtype)


def _quantize_ffn(ffn: dict) -> dict:
    """Quantize a dense FFN param dict (swiglu w1/w2/w3 or silu/gelu
    w1/w2) — every 2D leaf is a matmul weight by construction."""
    return {name: quantize_weight(w) for name, w in ffn.items()}


def quantize_params(params: dict, config: ModelConfig) -> dict:
    """Quantize the serving param tree's matmul weights in place of the
    originals: attention projections, dense FFN matrices, and the
    ``lm_head`` leaf when present.  Embeddings and norm gains pass
    through untouched (see module docstring).  Raises for MoE configs —
    the expert stacks' gather-dispatch layout is not covered."""
    if config.ffn_type == "moe":
        raise ValueError(
            'weight_dtype="int8" does not cover MoE expert stacks; '
            "serve MoE configs at the activation width"
        )
    out = {
        "token_embeddings": params["token_embeddings"],
        "ln_final": params["ln_final"],
        "layers": [
            {
                "attn": {
                    name: quantize_weight(w)
                    for name, w in layer["attn"].items()
                },
                "ln1": layer["ln1"],
                "ln2": layer["ln2"],
                "ffn": _quantize_ffn(layer["ffn"]),
            }
            for layer in params["layers"]
        ],
    }
    if "lm_head" in params:
        out["lm_head"] = quantize_weight(params["lm_head"])
    return out


def tree_bytes(tree) -> int:
    """Resident bytes of every array leaf (quantized dicts count their
    int8 payload + f32 scales — the honest footprint)."""
    return sum(
        int(leaf.size) * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "dtype")
    )
