"""JAX persistent compilation cache wiring (`--compile-cache DIR`).

Every train/serve start pays a full XLA compile per program (visible in
the PR-3 compile counters) — 20-40 s each on the real chip — which taxes
exactly the respawn loop the supervisor runs and every rolling-restart of
a serve replica.  JAX ships a content-addressed persistent cache keyed on
the lowered program + compile options + backend version; pointing it at a
directory that outlives the process turns all of those into disk reads.

One function so the CLI, bench queue (``tpu_queue.sh`` exports
``JAX_COMPILATION_CACHE_DIR`` the env-var way), and tests share the exact
config-knob set.

Stability caveat (jax 0.4.x): with the VIRTUAL multi-device CPU platform
(``--xla_force_host_platform_device_count=N``, the test mesh) the cache
has been observed aborting the process under donated sharded executions —
which is why the test suite does not enable it globally and the
warm-restart test runs single-device subprocesses.  Real single-device
CPU and TPU backends (where the bench queue has exported the env var for
rounds) are unaffected.
"""

from __future__ import annotations

from pathlib import Path


def enable_compile_cache(cache_dir: str | Path) -> Path:
    """Enable JAX's persistent compilation cache rooted at ``cache_dir``.

    Creates the directory, points ``jax_compilation_cache_dir`` at it, and
    zeroes the min-compile-time / min-entry-size thresholds so even the
    fast-compiling programs of the test/serve ladder are cached (the
    defaults skip sub-second compiles, which is every program on the CPU
    test platform).  Threshold knobs that this jax version doesn't have
    are skipped — the cache still works with its defaults.

    Safe to call after compiles have already happened: jax latches the
    cache-disabled state at the first compile of the process, so the
    latched cache object is reset (best-effort, private API) to pick the
    new directory up.  Programs compiled before the call are simply not
    cached.  Returns the cache directory.
    """
    import jax

    cache_dir = Path(cache_dir)
    cache_dir.mkdir(parents=True, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    for option, value in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(option, value)
        except Exception:
            pass
    try:
        from jax._src import compilation_cache

        compilation_cache.reset_cache()
    except Exception:
        pass
    return cache_dir
