"""Back-compat shim: the metrics sinks moved into the unified telemetry
subsystem (``bpe_transformer_tpu.telemetry.sinks``)."""

from bpe_transformer_tpu.telemetry.sinks import MetricsLogger

__all__ = ["MetricsLogger"]
