from bpe_transformer_tpu.telemetry.sinks import MetricsLogger
from bpe_transformer_tpu.telemetry.timing import StepTimer, profile_trace, time_fn
from bpe_transformer_tpu.utils.compile_cache import enable_compile_cache
from bpe_transformer_tpu.utils.debug import check_finite, nan_checks

__all__ = [
    "MetricsLogger",
    "StepTimer",
    "check_finite",
    "enable_compile_cache",
    "nan_checks",
    "profile_trace",
    "time_fn",
]
