from bpe_transformer_tpu.utils.debug import check_finite, nan_checks
from bpe_transformer_tpu.utils.metrics import MetricsLogger
from bpe_transformer_tpu.utils.profiling import StepTimer, profile_trace, time_fn

__all__ = [
    "MetricsLogger",
    "StepTimer",
    "check_finite",
    "nan_checks",
    "profile_trace",
    "time_fn",
]
