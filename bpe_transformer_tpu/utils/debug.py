"""Debug toggles: NaN checking and finite-ness assertions.

The reference has no sanitizers and no races by construction (share-nothing
multiprocessing, SURVEY §5). The JAX-native analogue of a sanitizer is
``jax_debug_nans`` (recompiles jitted fns with NaN checks on every op) plus
explicit finite checks at step boundaries; both live here.
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp


@contextlib.contextmanager
def nan_checks(enabled: bool = True):
    """Enable ``jax_debug_nans`` within the block.

    Under this flag XLA de-optimizes jitted functions so every primitive's
    output is checked; a NaN raises ``FloatingPointError`` at the producing
    op instead of surfacing steps later in the loss. Expensive — use for
    debugging runs, not production training.
    """
    previous = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", enabled)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", previous)


def check_finite(tree, name: str = "tree") -> None:
    """Host-side assertion that every leaf of a pytree is finite.

    All per-leaf ``isfinite`` reductions are dispatched first and fetched
    with a single ``jax.device_get``, so the host sync cost is one round
    trip regardless of tree size; intended at checkpoint boundaries so a
    corrupted state is never serialized.
    """
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    finite = jax.device_get(
        [jnp.all(jnp.isfinite(leaf)) for _, leaf in leaves]
    )
    bad = [
        jax.tree_util.keystr(path)
        for (path, _), ok in zip(leaves, finite)
        if not bool(ok)
    ]
    if bad:
        raise FloatingPointError(f"non-finite values in {name}: {bad}")
