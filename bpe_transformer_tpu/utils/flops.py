"""Model-FLOPs accounting and MFU (model FLOPs utilization).

The reference publishes no FLOPs math (its perf numbers are notebook
wall-clocks, SURVEY §6); on TPU the meaningful single-chip metric is
MFU = achieved model FLOPs/sec / peak chip FLOPs/sec.  This module provides
the standard decoder-transformer estimate (the "6ND + attention" rule):

    forward FLOPs   = 2 * N_matmul * tokens  +  4 * L * S * d_model * tokens
    training FLOPs  = 3 * forward            (backward ~ 2x forward)

where ``N_matmul`` counts parameters that participate in dense matmuls
(attention/FFN projections and the LM head; the embedding gather is
bandwidth, not FLOPs) and the second term is the attention score/value
einsums (QK^T and AV, 2 matmuls of 2*S*d FLOPs per token per layer).
"""

from __future__ import annotations

import warnings

from bpe_transformer_tpu.models.config import ModelConfig

#: Peak dense FLOPs/sec per chip, bf16, by device_kind substring.  Sources:
#: public TPU spec sheets (v4 275 TF, v4i 138 TF, v5e 197 TF, v5p 459 TF,
#: v6e/Trillium 918 TF, v3 123 TF per chip).  Matching is substring-based
#: on ``jax.devices()[0].device_kind`` (e.g. "TPU v4") with the longest/
#: most-specific patterns first, so "v5p" never falls through to "v5".
_PEAK_FLOPS_BY_KIND: tuple[tuple[str, float], ...] = (
    ("trillium", 918e12),
    ("v6e", 918e12),
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v5litepod", 197e12),
    ("v4i", 138e12),
    ("v4 lite", 138e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
)

#: Peak HBM bandwidth per chip, bytes/sec, same spec sheets (v2 700 GB/s,
#: v3 900, v4 1228, v4i 614, v5e 819, v5p 2765, v6e 1640) — the second
#: axis of the roofline `telemetry.attribution` classifies compiled
#: programs against (ridge point = peak FLOPs / peak bytes).
_PEAK_HBM_BW_BY_KIND: tuple[tuple[str, float], ...] = (
    ("trillium", 1640e9),
    ("v6e", 1640e9),
    ("v6", 1640e9),
    ("v5p", 2765e9),
    ("v5e", 819e9),
    ("v5 lite", 819e9),
    ("v5litepod", 819e9),
    ("v4i", 614e9),
    ("v4 lite", 614e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
)

#: device_kinds already warned about — warn ONCE per kind per process, not
#: once per logged step (a training loop asks every log boundary).
_warned_unknown_kinds: set[str] = set()


def _lookup_peak(
    table: tuple[tuple[str, float], ...], device_kind: str, what: str
) -> float | None:
    kind = device_kind.lower()
    for pattern, peak in table:
        if pattern in kind:
            return peak
    non_tpu = any(s in kind for s in ("cpu", "gpu", "cuda", "nvidia", "rocm"))
    if device_kind not in _warned_unknown_kinds and not non_tpu:
        # CPU/GPU backends legitimately have no TPU peak entry (MFU is a
        # TPU-first metric here); an unrecognized TPU generation, though,
        # silently disables MFU/roofline — say so once instead.
        _warned_unknown_kinds.add(device_kind)
        warnings.warn(
            f"no {what} table entry for device_kind {device_kind!r}; "
            "MFU/roofline accounting disabled for it — extend "
            "bpe_transformer_tpu/utils/flops.py",
            stacklevel=3,
        )
    return None


def matmul_param_count(config: ModelConfig) -> int:
    """Parameters participating in dense matmuls (excludes embedding gather)."""
    d, ff, L = config.d_model, config.d_ff, config.num_layers
    # q + output are (d, d); GQA shrinks k/v to (num_kv_heads * d_head, d).
    d_kv = (config.num_kv_heads or config.num_heads) * config.d_head
    attn = 2 * d * d + 2 * d * d_kv
    if config.ffn_type == "moe":
        # Per-token compute is router_top_k experts' SwiGLU FFNs (w1/w2/w3,
        # models/moe.py init_moe_params) + the router projection.
        ffn = config.router_top_k * 3 * d * ff + d * config.n_experts
    elif config.ffn_type in ("silu", "gelu"):
        ffn = 2 * d * ff
    else:  # SwiGLU: w1, w3 (d->ff) and w2 (ff->d)
        ffn = 3 * d * ff
    lm_head = d * config.vocab_size
    return L * (attn + ffn) + lm_head


def train_step_flops(config: ModelConfig, batch: int, seq: int | None = None) -> float:
    """Model FLOPs of one full training step (fwd + bwd) at the given shape."""
    S = seq or config.context_length
    tokens = batch * S
    matmul = 2.0 * matmul_param_count(config) * tokens
    attention = 4.0 * config.num_layers * S * config.d_model * tokens
    return 3.0 * (matmul + attention)


def decode_tick_flops(
    config: ModelConfig, n_tokens: int, kv_positions: int
) -> float:
    """Model FLOPs of ONE serving decode tick: ``n_tokens`` single-token
    forwards (each sweeps the matmul weights once) plus attention against
    ``kv_positions`` total visible cache positions (summed over the active
    slots — per token the QK^T and AV contractions cost ``4 * d_model``
    FLOPs per visible key per layer, the decode slice of the training
    estimate above).  The numerator of the decode-tick roofline
    (`telemetry.attribution.decode_tick_roofline`)."""
    matmul = 2.0 * matmul_param_count(config) * n_tokens
    attention = 4.0 * config.num_layers * config.d_model * kv_positions
    return matmul + attention


def peak_flops_per_chip(device_kind: str) -> float | None:
    """Peak bf16 FLOPs/sec for a TPU device_kind string, or None if unknown
    (warned once per kind — a silent None quietly disables MFU)."""
    return _lookup_peak(_PEAK_FLOPS_BY_KIND, device_kind, "peak-FLOPs")


def peak_hbm_bytes_per_sec(device_kind: str) -> float | None:
    """Peak HBM bandwidth in bytes/sec for a TPU device_kind string, or
    None if unknown (warned once per kind, shared with the FLOPs lookup)."""
    return _lookup_peak(_PEAK_HBM_BW_BY_KIND, device_kind, "HBM-bandwidth")


def mfu(
    config: ModelConfig,
    batch: int,
    step_time_s: float,
    device_kind: str,
    n_chips: int = 1,
    seq: int | None = None,
) -> float | None:
    """Model FLOPs utilization in [0, 1], or None when the peak is unknown."""
    peak = peak_flops_per_chip(device_kind)
    if peak is None or step_time_s <= 0:
        return None
    achieved = train_step_flops(config, batch, seq) / step_time_s
    return achieved / (peak * max(n_chips, 1))
