"""Back-compat shim: the profiling/timing harness moved into the unified
telemetry subsystem (``bpe_transformer_tpu.telemetry.timing``)."""

from bpe_transformer_tpu.telemetry.timing import StepTimer, profile_trace, time_fn

__all__ = ["StepTimer", "profile_trace", "time_fn"]
