"""Pre-tokenization demo: serial vs parallel throughput.

Script equivalent of the reference's `notebooks/1_pretokenization.ipynb`
(which timed serial vs multiprocessing pre-tokenization of TinyStories on a
laptop — SURVEY §6). Runs both paths on a text file and reports tokens/sec.

Usage:
    python examples/1_pretokenization.py [--input PATH] [--workers N]
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

import argparse
import time

from bpe_transformer_tpu.tokenization.pretokenization import (
    parallel_pretokenization,
    serial_pretokenization,
)

DEFAULT_INPUT = Path("/root/reference/tests/fixtures/tinystories_sample.txt")


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--input", type=Path, default=DEFAULT_INPUT)
    parser.add_argument("--workers", type=int, default=None)
    args = parser.parse_args()

    special_tokens = ["<|endoftext|>"]

    start = time.perf_counter()
    serial_counts = serial_pretokenization(args.input, special_tokens=special_tokens)
    serial_s = time.perf_counter() - start
    n_tokens = sum(serial_counts.values())
    print(f"serial:   {serial_s:6.2f}s  ({n_tokens / serial_s:,.0f} pretokens/s)")

    start = time.perf_counter()
    parallel_counts = parallel_pretokenization(
        args.input, n_workers=args.workers, special_tokens=special_tokens
    )
    parallel_s = time.perf_counter() - start
    print(f"parallel: {parallel_s:6.2f}s  ({n_tokens / parallel_s:,.0f} pretokens/s)")

    assert parallel_counts == serial_counts, "parallel != serial pretokenization"
    print(f"{len(serial_counts):,} distinct pretokens, {n_tokens:,} total — paths agree")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
