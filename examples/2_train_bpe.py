"""BPE training demo: learn a vocabulary and inspect it.

Script equivalent of the reference's `notebooks/2_bpe_tokenization_training
.ipynb` (BPE training with timing/memory measurement — SURVEY §6). Trains a
BPE tokenizer on a text file, saves vocab/merges artifacts, and prints the
longest learned token.

Usage:
    python examples/2_train_bpe.py [--input PATH] [--vocab-size N] [--out DIR]
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

import argparse
import time
import tracemalloc

from bpe_transformer_tpu import BPETrainer

DEFAULT_INPUT = Path("/root/reference/tests/fixtures/tinystories_sample.txt")


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--input", type=Path, default=DEFAULT_INPUT)
    parser.add_argument("--vocab-size", type=int, default=1000)
    parser.add_argument("--out", type=Path, default=Path("bpe_artifacts"))
    args = parser.parse_args()

    tracemalloc.start()
    start = time.perf_counter()
    trainer = BPETrainer(
        vocab_size=args.vocab_size, special_tokens=["<|endoftext|>"]
    )
    trainer.train(args.input)
    elapsed = time.perf_counter() - start
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()

    vocab, merges = trainer.vocab, trainer.merges
    print(f"trained vocab {len(vocab):,} ({len(merges):,} merges) "
          f"in {elapsed:.2f}s, peak traced memory {peak / 2**20:.1f} MB")

    longest = max(vocab.values(), key=len)
    print(f"longest learned token: {longest!r} ({len(longest)} bytes)")

    trainer.save_trainer(args.out)
    print(f"saved vocab.pkl / merges.pkl under {args.out}/")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
