"""Continuous-batching serving: the offline batch mode on CPU.

The ROADMAP's "serve heavy traffic" leg (`bpe_transformer_tpu/serving/`):
a fixed pool of KV-cache slots decodes many requests through ONE jitted
step per tick, prefill pads prompts into power-of-two length buckets so
the engine compiles a bounded set of programs, and a FIFO scheduler feeds
free slots as requests arrive.  This demo walks the offline batch mode —
prompts file in, completions JSONL out — and checks the two properties
that make the engine trustworthy:

* **parity**: at temperature=0 every batched completion is byte-identical
  to a sequential `sampling.generate_ids` run of the same prompt;
* **bounded compilation**: after serving ragged prompt lengths, the
  compile counter stays at (prefill buckets used) + 1 — no per-request
  recompiles.

A byte-level model (vocab 256 + one stop token) keeps the demo
self-contained; the weights are random — the point is the serving
machinery, not the prose.

Usage:
    python examples/10_serving.py [--input PATH] [--new-tokens N]
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

import argparse
import json

DEFAULT_INPUT = Path("/root/reference/tests/fixtures/tinystories_sample.txt")


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--input", type=Path, default=DEFAULT_INPUT)
    parser.add_argument("--new-tokens", type=int, default=12)
    args = parser.parse_args()

    import jax

    from bpe_transformer_tpu.models import init_params
    from bpe_transformer_tpu.models.config import ModelConfig
    from bpe_transformer_tpu.serving import ServingEngine
    from bpe_transformer_tpu.tokenization import BPETokenizer
    from bpe_transformer_tpu.training.sampling import generate_ids

    config = ModelConfig(
        vocab_size=257,  # bytes + the stop token
        context_length=128,
        d_model=64,
        num_layers=2,
        num_heads=4,
        d_ff=128,
    )
    params = init_params(jax.random.PRNGKey(0), config)
    tokenizer = BPETokenizer(
        vocab={i: bytes([i]) for i in range(256)},
        merges=[],
        special_tokens=["<|endoftext|>"],  # id 256: the serving stop id
    )

    # Ragged prompts from the input text -> a prompts file, one per line.
    text = args.input.read_text(encoding="utf-8", errors="ignore")
    lines = [ln.strip() for ln in text.splitlines() if ln.strip()]
    prompts = [lines[i % len(lines)][: 6 + 13 * i] for i in range(6)]
    prompts_path = Path("serving_prompts.txt")
    prompts_path.write_text("\n".join(prompts) + "\n", encoding="utf-8")

    out_path = Path("serving_completions.jsonl")
    with ServingEngine(
        params,
        config,
        tokenizer=tokenizer,
        slots=3,  # fewer slots than prompts: retirement + re-admission
        min_bucket=16,
        default_stop_id=256,
    ) as serving:
        results = serving.serve_batch_file(
            prompts_path, out_path,
            max_new_tokens=args.new_tokens, temperature=0.0,
        )
        stats = serving.stats()

    rows = [json.loads(ln) for ln in out_path.read_text().splitlines()]
    print(
        f"served {len(rows)} prompts through {stats['slots']} slots: "
        f"{stats['tokens_emitted']} tokens in {stats['ticks']} ticks, "
        f"buckets={stats['prefill_buckets']}, "
        f"compiled {stats['compiled_programs']} programs "
        f"(bound: {len(stats['prefill_buckets']) + 1})"
    )
    assert stats["compiled_programs"] <= len(stats["prefill_buckets"]) + 1

    # Batched-vs-sequential parity at temperature 0.
    for prompt, result in zip(prompts, results):
        expected = generate_ids(
            params, config, tokenizer.encode(prompt),
            max_new_tokens=args.new_tokens, temperature=0.0, stop_id=256,
        )
        assert list(result.token_ids) == expected, "parity violated"
    print(
        "every batched completion matches its sequential generate_ids run "
        "(temperature=0, byte-identical)"
    )
    print(f"first completion: {rows[0]['completion']!r}")
    print("serving demo OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
