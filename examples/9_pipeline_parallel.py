"""Pipeline parallelism: GPipe stages over a (data, pp) mesh, autodiff'd.

The layer stack splits into contiguous stages, one per rank along the
``pp`` mesh axis; microbatched activations flow rank -> rank+1 through
``lax.ppermute`` inside a scanned schedule, and the BACKWARD pipeline is
not hand-written — ``jax.grad`` differentiates through the scan (the
transpose of a ppermute is the reverse ppermute), producing the reverse
schedule automatically (`parallel/pp.py`; no reference counterpart,
SURVEY §2.4 lists PP as absent).

This demo runs a 4-stage pipeline x 2-way data parallelism on the
8-device virtual CPU mesh, verifies the update equals the single-device
step, then runs a second update with gradient accumulation AROUND the
pipeline (each accumulation slice runs the full GPipe schedule — the
round-5 addition).

Usage:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/9_pipeline_parallel.py
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp

from bpe_transformer_tpu.models import TS_TEST_CONFIG, init_params
from bpe_transformer_tpu.optim import adamw_init
from bpe_transformer_tpu.parallel import (
    init_pp_opt_state,
    make_mesh,
    make_pp_train_step,
    shard_batch,
    shard_pp_params,
    stack_pipeline_params,
    unstack_pipeline_params,
)
from bpe_transformer_tpu.training.train_step import TrainHParams, make_train_step


def main() -> int:
    if len(jax.devices()) < 8:
        print(
            "need 8 devices (run with JAX_PLATFORMS=cpu "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8)"
        )
        return 1

    pp = 4
    config = dataclasses.replace(
        TS_TEST_CONFIG, vocab_size=512, context_length=32, num_layers=pp
    )  # one layer per stage
    hparams = TrainHParams(warmup_iters=2, cosine_cycle_iters=10)
    rng = np.random.default_rng(0)
    x = rng.integers(0, config.vocab_size, size=(16, 32), dtype=np.int64)
    y = np.roll(x, -1, axis=1)

    mesh = make_mesh({"data": 2, "pp": pp})
    params = init_params(jax.random.PRNGKey(0), config)
    pp_params = shard_pp_params(stack_pipeline_params(params, pp), mesh)
    pp_opt = init_pp_opt_state(pp_params, mesh)
    step = make_pp_train_step(config, hparams, mesh, num_microbatches=4)
    xb, yb = shard_batch((jnp.asarray(x), jnp.asarray(y)), mesh)
    new_pp, new_opt, metrics = step(pp_params, pp_opt, xb, yb)
    print(
        f"GPipe update: {pp} stages x 2-way dp, 4 microbatches, "
        f"loss {float(metrics['loss']):.4f}"
    )

    # Oracle: the identical update as ONE single-device step.
    ref_step = make_train_step(config, hparams)
    ref_params = init_params(jax.random.PRNGKey(0), config)
    ref_new, _, ref_metrics = ref_step(
        ref_params, adamw_init(ref_params), jnp.asarray(x), jnp.asarray(y)
    )
    np.testing.assert_allclose(
        float(metrics["loss"]), float(ref_metrics["loss"]), rtol=1e-5
    )
    restored = unstack_pipeline_params(jax.device_get(new_pp))
    np.testing.assert_allclose(
        np.asarray(restored["lm_head"]), np.asarray(ref_new["lm_head"]), atol=2e-5
    )
    print("matches the single-device update (atol 2e-5)")

    # Round 5: gradient accumulation AROUND the pipeline — two slices, each
    # running the full GPipe schedule, one optimizer update.
    accum_step = make_pp_train_step(
        config, hparams, mesh, num_microbatches=2, accum_steps=2
    )
    xs = jnp.asarray(x).reshape(2, 8, -1)
    ys = jnp.asarray(y).reshape(2, 8, -1)
    xs, ys = shard_batch((xs, ys), mesh, stacked=True)
    _, _, metrics2 = accum_step(new_pp, new_opt, xs, ys)
    print(
        f"pp + grad-accum update: loss {float(metrics2['loss']):.4f} "
        "(2 accumulation slices x full pipeline each)"
    )
    print("pipeline parallel OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
