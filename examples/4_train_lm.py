"""End-to-end LM pretraining demo: text -> BPE -> memmap -> TPU train -> sample.

The reference has no training loop at all (SURVEY §3.5); this is the full
pipeline its adapters imply, TPU-native: train a BPE tokenizer on the host,
stream-encode the corpus to a uint16 memmap, run jitted training steps on
whatever accelerator JAX finds, and sample text from the result.

Usage:
    python examples/4_train_lm.py [--input PATH] [--steps N] [--out DIR]
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

import argparse
import dataclasses

from bpe_transformer_tpu import BPETokenizer, BPETrainer
from bpe_transformer_tpu.data.dataset import tokenize_to_memmap
from bpe_transformer_tpu.models import TINYSTORIES_4L
from bpe_transformer_tpu.training.loop import LoopConfig, train
from bpe_transformer_tpu.training.sampling import generate_text
from bpe_transformer_tpu.training.train_step import TrainHParams

DEFAULT_INPUT = Path("/root/reference/tests/fixtures/tinystories_sample.txt")
SPECIALS = ["<|endoftext|>"]


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--input", type=Path, default=DEFAULT_INPUT)
    parser.add_argument("--steps", type=int, default=300)
    parser.add_argument("--vocab-size", type=int, default=2000)
    parser.add_argument("--out", type=Path, default=Path("lm_demo"))
    args = parser.parse_args()
    args.out.mkdir(parents=True, exist_ok=True)

    print("1/4  training BPE tokenizer ...")
    trainer = BPETrainer(vocab_size=args.vocab_size, special_tokens=SPECIALS)
    trainer.train(args.input)
    trainer.save_trainer(args.out / "tokenizer")
    tokenizer = BPETokenizer(trainer.vocab, trainer.merges, SPECIALS)

    print("2/4  encoding corpus to memmap ...")
    tokens = tokenize_to_memmap(tokenizer, args.input, args.out / "tokens.bin")
    print(f"     {tokens.shape[0]:,} tokens")

    print("3/4  training LM ...")
    config = dataclasses.replace(
        TINYSTORIES_4L, vocab_size=args.vocab_size, context_length=128
    )
    n_val = max(tokens.shape[0] // 20, config.context_length + 1)
    summary = train(
        model_config=config,
        hparams=TrainHParams(
            max_learning_rate=3e-3,
            warmup_iters=max(args.steps // 20, 1),
            cosine_cycle_iters=args.steps,
        ),
        loop=LoopConfig(
            steps=args.steps,
            batch_size=32,
            log_every=max(args.steps // 10, 1),
            eval_every=args.steps,
            checkpoint_every=args.steps,
            checkpoint_dir=str(args.out / "checkpoints"),
            metrics_jsonl=str(args.out / "metrics.jsonl"),
        ),
        train_data=tokens[:-n_val],
        val_data=tokens[-n_val:],
    )
    print(f"     final train loss {summary['final_train_loss']:.3f}, "
          f"val loss {summary['final_val_loss']:.3f}")

    print("4/4  sampling ...")
    from bpe_transformer_tpu.checkpointing import load_checkpoint

    params = load_checkpoint(args.out / "checkpoints" / "latest.ckpt")["params"]
    text = generate_text(
        params, config, tokenizer,
        prompt="Once upon a time", max_new_tokens=64, temperature=0.8, top_k=40,
    )
    print(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
