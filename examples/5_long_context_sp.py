"""Long-context training demo: ring-attention sequence parallelism.

``--grad-accum N`` adds gradient accumulation INSIDE the ring program (the
round-4 combo: activation memory capped at one microbatch while every
sequence stays sharded over the ring; one (data, seq) pmean per update).

The reference tops out at a 16-token context (SURVEY §5 — its
`model_config.json`); this demo trains a context window LARGER than any
single chip would hold activations for, by sharding every sequence over a
``seq`` mesh axis and running attention as the ring schedule
(`parallel/ring_attention.py`, K/V shards rotating over ICI).  On a real
TPU slice the mesh axes map to chips; here it runs the same program on the
8-device virtual CPU mesh (set up below) so the demo works anywhere.

Usage:
    python examples/5_long_context_sp.py [--input PATH] [--steps N]
        [--context 512] [--zigzag | --ulysses] [--grad-accum N]
"""

from __future__ import annotations

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

# Force the virtual 8-device CPU mesh BEFORE jax initializes (on a real TPU
# slice, drop these two lines and the mesh axes bind to chips).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import argparse
import dataclasses

from bpe_transformer_tpu import BPETokenizer, BPETrainer
from bpe_transformer_tpu.data.dataset import tokenize_to_memmap
from bpe_transformer_tpu.models import TINYSTORIES_4L
from bpe_transformer_tpu.training.loop import LoopConfig, train
from bpe_transformer_tpu.training.train_step import TrainHParams

DEFAULT_INPUT = Path("/root/reference/tests/fixtures/tinystories_sample.txt")
SPECIALS = ["<|endoftext|>"]


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--input", type=Path, default=DEFAULT_INPUT)
    parser.add_argument("--steps", type=int, default=40)
    parser.add_argument("--vocab-size", type=int, default=512)
    parser.add_argument("--context", type=int, default=512)
    parser.add_argument("--ulysses", action="store_true",
                        help="Ulysses all-to-all head scatter instead of the "
                        "ring (one all_to_all to head-sharded, full-seq "
                        "attention per head slice, inverse all_to_all back)")
    parser.add_argument("--zigzag", action="store_true",
                        help="balanced striped ring schedule (~2x less causal work)")
    parser.add_argument("--grad-accum", type=int, default=1,
                        help="microbatches per update, scanned INSIDE the ring "
                        "program (long-context HBM relief; one pmean per update)")
    parser.add_argument("--out", type=Path, default=Path("sp_demo"))
    args = parser.parse_args()
    if args.zigzag and args.ulysses:
        parser.error("--zigzag and --ulysses are mutually exclusive")
    args.out.mkdir(parents=True, exist_ok=True)

    import jax

    n_dev = len(jax.devices())
    if args.ulysses and 128 % n_dev:  # 128 = the demo model's d_model below
        # The demo model uses d_model=128 and (under --ulysses) one head
        # per seq-axis device; an awkward device count would crash deep in
        # ModelConfig instead of here.
        parser.error(
            f"--ulysses in this demo needs a device count that divides "
            f"d_model=128 (one head per device); have {n_dev}"
        )
    mesh_axes = {"data": 1, "seq": n_dev}
    print(f"1/3  mesh {mesh_axes} on {jax.devices()[0].platform}; "
          f"context {args.context} -> {args.context // n_dev} tokens/device")

    print("2/3  tokenizer + memmap ...")
    trainer = BPETrainer(vocab_size=args.vocab_size, special_tokens=SPECIALS)
    trainer.train(args.input)
    tokenizer = BPETokenizer(trainer.vocab, trainer.merges, SPECIALS)
    tokens = tokenize_to_memmap(tokenizer, args.input, args.out / "tokens.bin")
    print(f"     {tokens.shape[0]:,} tokens")

    print("3/3  sequence-parallel training ...")
    config = dataclasses.replace(
        TINYSTORIES_4L,
        vocab_size=args.vocab_size,
        context_length=args.context,
        d_model=128,
        num_layers=2,
        # Ulysses scatters heads over the seq axis, so the head count must
        # be a multiple of it (the ring has no such constraint) — and
        # d_model must stay divisible by the head count, checked above.
        num_heads=n_dev if args.ulysses else 4,
        d_ff=256,
    )
    summary = train(
        model_config=config,
        hparams=TrainHParams(
            max_learning_rate=3e-3,
            warmup_iters=max(args.steps // 10, 1),
            cosine_cycle_iters=args.steps,
        ),
        loop=LoopConfig(
            steps=args.steps,
            batch_size=8,
            log_every=max(args.steps // 5, 1),
            eval_every=args.steps,
            checkpoint_every=args.steps,
            checkpoint_dir=str(args.out / "checkpoints"),
            parallel="sp",
            mesh_axes=mesh_axes,
            sp_zigzag=args.zigzag,
            sp_ulysses=args.ulysses,
            grad_accum_steps=args.grad_accum,
        ),
        train_data=tokens,
    )
    first, last = summary["history"][0]["loss"], summary["history"][-1]["loss"]
    schedule = (
        "Ulysses all-to-all" if args.ulysses
        else "zig-zag striped ring" if args.zigzag else "contiguous ring"
    )
    accum_note = (
        f", {args.grad_accum} scanned microbatches/update" if args.grad_accum > 1 else ""
    )
    print(f"     loss {first:.3f} -> {last:.3f} over {args.steps} steps "
          f"(seq {args.context} sharded {n_dev}-way, {schedule}{accum_note})")
    print("long-context sp OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
