"""Mixture-of-experts training demo: top-2 routing + expert parallelism.

No reference counterpart (SURVEY §2.4 lists EP as absent) — this shows the
framework's MoE family end to end: a Switch/GShard-style routed FFN
(`models/moe.py`) trained with the GSPMD ``dp_ep`` strategy, where the
stacked expert weights shard over an ``expert`` mesh axis and XLA lowers the
dispatch/combine einsums to all-to-alls.  Runs on the 8-device virtual CPU
mesh anywhere; on a TPU slice the axes bind to chips.

Usage:
    python examples/6_moe_expert_parallel.py [--input PATH] [--steps N]
        [--experts 4] [--top-k 2]
"""

from __future__ import annotations

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import argparse
import dataclasses

from bpe_transformer_tpu import BPETokenizer, BPETrainer
from bpe_transformer_tpu.data.dataset import tokenize_to_memmap
from bpe_transformer_tpu.models import TINYSTORIES_4L
from bpe_transformer_tpu.training.loop import LoopConfig, train
from bpe_transformer_tpu.training.sampling import generate_text
from bpe_transformer_tpu.training.train_step import TrainHParams

DEFAULT_INPUT = Path("/root/reference/tests/fixtures/tinystories_sample.txt")
SPECIALS = ["<|endoftext|>"]


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--input", type=Path, default=DEFAULT_INPUT)
    parser.add_argument("--steps", type=int, default=60)
    parser.add_argument("--vocab-size", type=int, default=512)
    parser.add_argument("--experts", type=int, default=4)
    parser.add_argument("--top-k", type=int, default=2)
    parser.add_argument("--out", type=Path, default=Path("moe_demo"))
    args = parser.parse_args()
    args.out.mkdir(parents=True, exist_ok=True)

    import jax

    n_dev = len(jax.devices())
    if n_dev % args.experts:
        parser.error(
            f"--experts {args.experts} must divide the device count ({n_dev})"
        )
    mesh_axes = {"data": n_dev // args.experts, "expert": args.experts}
    print(f"1/3  mesh {mesh_axes}: experts shard over the expert axis, "
          f"dispatch einsums lower to all-to-alls")

    print("2/3  tokenizer + memmap ...")
    trainer = BPETrainer(vocab_size=args.vocab_size, special_tokens=SPECIALS)
    trainer.train(args.input)
    tokenizer = BPETokenizer(trainer.vocab, trainer.merges, SPECIALS)
    tokens = tokenize_to_memmap(tokenizer, args.input, args.out / "tokens.bin")
    print(f"     {tokens.shape[0]:,} tokens")

    print(f"3/3  MoE training (top-{args.top_k} of {args.experts} experts) ...")
    config = dataclasses.replace(
        TINYSTORIES_4L,
        vocab_size=args.vocab_size,
        context_length=128,
        d_model=128,
        num_layers=2,
        num_heads=4,
        d_ff=256,
        ffn_type="moe",
        n_experts=args.experts,
        router_top_k=args.top_k,
        capacity_factor=2.0,
    )
    summary = train(
        model_config=config,
        hparams=TrainHParams(
            max_learning_rate=3e-3,
            warmup_iters=max(args.steps // 10, 1),
            cosine_cycle_iters=args.steps,
        ),
        loop=LoopConfig(
            steps=args.steps,
            batch_size=16,
            log_every=max(args.steps // 5, 1),
            eval_every=args.steps,
            checkpoint_every=args.steps,
            checkpoint_dir=str(args.out / "checkpoints"),
            parallel="dp_ep",
            mesh_axes=mesh_axes,
        ),
        train_data=tokens,
    )
    first, last = summary["history"][0]["loss"], summary["history"][-1]["loss"]
    print(f"     loss {first:.3f} -> {last:.3f} over {args.steps} steps")

    from bpe_transformer_tpu.checkpointing import load_checkpoint

    params = load_checkpoint(args.out / "checkpoints" / "latest.ckpt")["params"]
    text = generate_text(
        params, config, tokenizer,
        prompt="Once", max_new_tokens=24, temperature=0.8, top_k=20,
    )
    print("     sample:", text[:120].replace("\n", " "))
    print("moe expert-parallel OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
