"""KV-cached autoregressive decoding: cache anatomy, GQA shrink, sampling.

The reference's contract stops at training logits (it ships no sampler,
no cache, no generation loop — `/root/reference/tests/adapters.py`
defines the model purely through training-side functions).  This demo
walks the TPU-native decode stack built on top of that architecture:

* a static-shape KV cache (one compiled program per generation, the token
  loop a `lax.scan` — no per-token recompilation, no shape growth);
* grouped-query attention shrinking the cache (decode's HBM footprint and
  per-token read traffic) by the query-group factor;
* the flash-decoding Pallas kernel (`decode_attention_impl="pallas"`)
  streaming the cache through VMEM once per token;
* flash-attention prefill (`attention_impl="flash"`) so long prompts
  never materialize an O(plen^2) score buffer;
* temperature / top-k / top-p sampling, all inside the jitted program.

A byte-level model (vocab 256) keeps the demo self-contained — the point
is the decode machinery, not the (randomly initialized) weights.

Usage:
    python examples/8_kv_cache_decode.py [--input PATH] [--new-tokens N]
"""

from __future__ import annotations

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

import argparse
import dataclasses

import numpy as np

DEFAULT_INPUT = Path("/root/reference/tests/fixtures/tinystories_sample.txt")


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--input", type=Path, default=DEFAULT_INPUT)
    parser.add_argument("--new-tokens", type=int, default=32)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    from bpe_transformer_tpu.models import init_params
    from bpe_transformer_tpu.models.config import ModelConfig
    from bpe_transformer_tpu.models.decode import generate_cached, init_kv_cache

    base = ModelConfig(
        vocab_size=256,  # byte-level: any text is already tokens
        context_length=128,
        d_model=128,
        num_layers=4,
        num_heads=4,
        d_ff=256,
    )
    gqa = dataclasses.replace(base, num_kv_heads=2)

    # --- cache anatomy -----------------------------------------------------
    def cache_bytes(cfg, batch=1):
        cache = init_kv_cache(cfg, batch)
        return sum(
            leaf.size * leaf.dtype.itemsize
            for leaf in jax.tree_util.tree_leaves(cache)
        )

    mha_b, gqa_b = cache_bytes(base), cache_bytes(gqa)
    print(
        f"KV cache @ ctx={base.context_length}: MHA {mha_b / 1024:.0f} KiB "
        f"-> GQA(kv={gqa.num_kv_heads}) {gqa_b / 1024:.0f} KiB "
        f"({mha_b / gqa_b:.0f}x smaller, and the same factor off every "
        "per-token cache read)"
    )

    # --- one compiled program per generation -------------------------------
    text = args.input.read_text(encoding="utf-8", errors="ignore")[:64]
    prompt = jnp.asarray([list(text.encode("utf-8"))], jnp.int32)
    cfg = dataclasses.replace(
        gqa,
        attention_impl="flash",          # prefill: no O(plen^2) buffer
        decode_attention_impl="pallas",  # per-token: flash-decoding kernel
    )
    params = init_params(jax.random.PRNGKey(0), cfg)

    t0 = time.perf_counter()
    out = generate_cached(
        params, prompt, jax.random.PRNGKey(1), config=cfg,
        max_new_tokens=args.new_tokens, temperature=0.9, top_k=50, top_p=0.95,
    )
    jax.block_until_ready(out)
    t_compile = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = generate_cached(
        params, prompt, jax.random.PRNGKey(2), config=cfg,
        max_new_tokens=args.new_tokens, temperature=0.9, top_k=50, top_p=0.95,
    )
    jax.block_until_ready(out)
    t_run = time.perf_counter() - t0
    print(
        f"generated {args.new_tokens} tokens: compile+run {t_compile:.2f}s, "
        f"cached re-run {t_run:.3f}s "
        f"({args.new_tokens / t_run:,.0f} tok/s on {jax.devices()[0].platform}) "
        "— one XLA program, prefill + scanned token loop"
    )

    # Same program, different sampling knobs — all static args of the jit.
    greedy = generate_cached(
        params, prompt, jax.random.PRNGKey(0), config=cfg,
        max_new_tokens=8, temperature=0.0,
    )
    again = generate_cached(
        params, prompt, jax.random.PRNGKey(9), config=cfg,
        max_new_tokens=8, temperature=0.0,
    )
    assert (np.asarray(greedy) == np.asarray(again)).all(), "greedy must be deterministic"
    print(f"greedy continuation bytes: {np.asarray(greedy[0]).tolist()}")

    # The pallas and xla decode paths agree (parity pinned in tests/).
    xla_cfg = dataclasses.replace(cfg, decode_attention_impl="xla")
    a = generate_cached(
        params, prompt, jax.random.PRNGKey(3), config=cfg,
        max_new_tokens=8, temperature=0.0,
    )
    b = generate_cached(
        params, prompt, jax.random.PRNGKey(3), config=xla_cfg,
        max_new_tokens=8, temperature=0.0,
    )
    assert (np.asarray(a) == np.asarray(b)).all()
    print("pallas and xla decode paths agree; decode demo OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
