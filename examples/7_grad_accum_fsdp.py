"""Large-batch training on a memory budget: gradient accumulation under FSDP.

BASELINE configs 3/5 (GPT-2-small/medium) want batch sizes whose activations
don't fit one chip's HBM.  The standard answer is ZeRO-style parameter
sharding (FSDP) PLUS gradient accumulation — and in this framework the
accumulation ``lax.scan`` compiles INSIDE the sharded program, so XLA's
derived all-gather/reduce-scatter schedule composes with the microbatch loop
with no manual communication (``parallel/train_step.py:make_gspmd_train_step``,
new in round 3; the reference has no training loop at all, SURVEY §3.5).

This demo runs a tiny model on the 8-device virtual CPU mesh: one optimizer
update from 4 microbatches, each microbatch split across the ``data`` axis,
then verifies the update equals a single full-batch step to float tolerance.

Usage:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/7_grad_accum_fsdp.py
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp

from bpe_transformer_tpu.models import TS_TEST_CONFIG, init_params
from bpe_transformer_tpu.optim import adamw_init
from bpe_transformer_tpu.parallel import (
    make_gspmd_train_step,
    make_mesh,
    shard_batch,
    shard_params,
)
from bpe_transformer_tpu.training.train_step import TrainHParams, make_train_step


def main() -> int:
    if len(jax.devices()) < 8:
        print(
            "need 8 devices (run with JAX_PLATFORMS=cpu "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8)"
        )
        return 1

    config = dataclasses.replace(TS_TEST_CONFIG, vocab_size=512, context_length=32)
    hparams = TrainHParams(warmup_iters=2, cosine_cycle_iters=10)
    accum, micro = 4, 8  # effective batch 32, one microbatch's memory
    rng = np.random.default_rng(0)
    x = rng.integers(0, config.vocab_size, size=(accum * micro, 32), dtype=np.int64)
    y = np.roll(x, -1, axis=1)

    mesh = make_mesh({"data": 8})
    params = shard_params(init_params(jax.random.PRNGKey(0), config), mesh, "fsdp")
    opt_state = adamw_init(params)
    step = make_gspmd_train_step(
        config, hparams, mesh, "fsdp", example_params=params, accum_steps=accum
    )
    xs = jnp.asarray(x).reshape(accum, micro, -1)
    ys = jnp.asarray(y).reshape(accum, micro, -1)
    xs, ys = shard_batch((xs, ys), mesh, stacked=True)

    new_params, _, metrics = step(params, opt_state, xs, ys)
    print(
        f"fsdp + grad-accum update: loss {float(metrics['loss']):.4f}, "
        f"effective batch {accum * micro} as {accum} microbatches of {micro}"
    )

    # Oracle: the identical update as ONE full-batch single-device step.
    ref_params = init_params(jax.random.PRNGKey(0), config)
    ref_step = make_train_step(config, hparams)
    ref_new, _, ref_metrics = ref_step(
        ref_params, adamw_init(ref_params), jnp.asarray(x), jnp.asarray(y)
    )
    np.testing.assert_allclose(
        float(metrics["loss"]), float(ref_metrics["loss"]), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(jax.device_get(new_params["lm_head"])),
        np.asarray(ref_new["lm_head"]),
        atol=1e-5,
    )
    print("matches the single-device full-batch update (atol 1e-5)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
