"""Encode/decode demo: load artifacts, roundtrip text, stream a file.

Script equivalent of the reference's `notebooks/3_bpe_tokenization_encode_
decode.ipynb` (encode/decode with a cProfile/tracemalloc performance report
— SURVEY §6). Trains a small tokenizer if no artifacts are given, then
demonstrates exact roundtrips and bounded-memory streaming encode.

Usage:
    python examples/3_encode_decode.py [--artifacts DIR] [--input PATH]
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

import argparse
import time

from bpe_transformer_tpu import BPETokenizer, BPETrainer

DEFAULT_INPUT = Path("/root/reference/tests/fixtures/tinystories_sample.txt")
SPECIALS = ["<|endoftext|>"]


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--artifacts", type=Path, default=None,
                        help="dir with vocab.pkl/merges.pkl; trains one if absent")
    parser.add_argument("--input", type=Path, default=DEFAULT_INPUT)
    args = parser.parse_args()

    if args.artifacts is not None:
        tokenizer = BPETokenizer.from_files(
            args.artifacts / "vocab.pkl", args.artifacts / "merges.pkl", SPECIALS
        )
    else:
        trainer = BPETrainer(vocab_size=1000, special_tokens=SPECIALS)
        trainer.train(args.input)
        tokenizer = BPETokenizer(trainer.vocab, trainer.merges, SPECIALS)

    sample = "Once upon a time, there was a pretty girl named Lily.<|endoftext|>"
    ids = tokenizer.encode(sample)
    assert tokenizer.decode(ids) == sample
    print(f"roundtrip OK: {len(sample)} chars -> {len(ids)} tokens")
    print("ids:", ids[:16], "...")
    print("tokens:", [tokenizer.vocab[i] for i in ids[:8]], "...")

    # Streaming encode never materializes the file (SURVEY T6: the reference
    # pins this with a 1 MB rlimit test on a 5 MB corpus).
    start = time.perf_counter()
    with open(args.input, encoding="utf-8") as f:
        n = sum(1 for _ in tokenizer.encode_iterable(f))
    elapsed = time.perf_counter() - start
    print(f"streamed {args.input.name}: {n:,} tokens in {elapsed:.2f}s "
          f"({n / elapsed:,.0f} tok/s)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
