"""Run the reference repo's own test files VERBATIM against this framework.

The reference's compatibility seam is that its tests import only
`tests/adapters.py` (`/root/reference/tests/test_model.py:6-18`; the
assignment design, `/root/reference/tests/README.md`).  This runner stages
the reference suite with every test file, conftest, snapshot, and fixture
**byte-identical** (symlinked read-only), swapping in exactly one file —
`tests/adapters.py`, re-exporting `bpe_transformer_tpu.compat.adapters` —
which is the swap the suite was designed for.

Environment shims live in an OUTER conftest (rootdir level, ours), never in
the reference files:
  * `tiktoken.get_encoding("gpt2")` downloads its vocab from the network;
    this container has no egress, so the shim rebuilds the identical
    encoding offline from the reference's own fixture artifacts
    (`gpt2_vocab.json`, 50,257 entries) — same ids, same regex, same
    special token.
  * Tests whose fixtures are the repo's missing large blobs
    (`/root/reference/.MISSING_LARGE_BLOBS`: `ts_tests/model.pt`,
    `tinystories_sample_5M.txt`) are SKIPPED with an explicit reason —
    nobody, including the reference itself, can run those from this mount.

Usage:
    python tools/run_reference_suite.py [extra pytest args]

Exit code is pytest's.  A summary line (collected/passed/skipped) prints at
the end; PARITY.md records the certified result.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
REF_TESTS = Path("/root/reference/tests")

ADAPTERS_SHIM = '''\
"""The one swapped file: the reference suite's designed seam.

Everything else in this staged tree is a byte-identical symlink into
/root/reference/tests; this module re-exports the framework's adapter
implementations (bpe_transformer_tpu/compat/adapters.py) under the import
path the reference tests use (`from .adapters import ...`).
"""

from bpe_transformer_tpu.compat.adapters import *  # noqa: F401,F403
'''

OUTER_CONFTEST = '''\
"""Environment shims for running the reference suite offline (ours; the
reference's own tests/conftest.py is staged unmodified next to the tests).

1. tiktoken.get_encoding("gpt2") normally downloads the GPT-2 vocab; this
   container has no egress.  Rebuild the identical encoding from the
   reference's committed fixture artifacts instead (same trick as the
   framework's own tests/test_tokenizer.py).
2. Skip tests whose fixtures are the repo's missing large blobs
   (.MISSING_LARGE_BLOBS) — unrunnable from this mount by anyone.
"""

import pytest

_OFFLINE_ENCODINGS = {}


def _install_offline_tiktoken():
    import tiktoken

    from bpe_transformer_tpu.tokenization.gpt2 import load_gpt2_vocab

    real_get_encoding = tiktoken.get_encoding

    def offline_get_encoding(name):
        if name != "gpt2":
            return real_get_encoding(name)
        if "gpt2" not in _OFFLINE_ENCODINGS:
            vocab = load_gpt2_vocab(
                "/root/reference/tests/fixtures/gpt2_vocab.json"
            )
            mergeable = {
                tok: idx for idx, tok in vocab.items() if tok != b"<|endoftext|>"
            }
            _OFFLINE_ENCODINGS["gpt2"] = tiktoken.Encoding(
                name="gpt2",
                pat_str=(
                    r"""'(?:[sdmt]|ll|ve|re)| ?\\p{L}+| ?\\p{N}+|"""
                    r""" ?[^\\s\\p{L}\\p{N}]+|\\s+(?!\\S)|\\s+"""
                ),
                mergeable_ranks=mergeable,
                special_tokens={"<|endoftext|>": 50256},
            )
        return _OFFLINE_ENCODINGS["gpt2"]

    tiktoken.get_encoding = offline_get_encoding


_install_offline_tiktoken()

#: Tests that read tinystories_sample_5M.txt by path (the ts_state_dict
#: model.pt dependents are caught by fixture name instead).
_5M_TESTS = {
    "test_train_bpe_special_tokens",
    "test_encode_iterable_memory_usage",
    "test_encode_memory_usage",
}


def pytest_collection_modifyitems(config, items):
    skip_blob = pytest.mark.skip(
        reason="fixture is a missing large blob (see "
        "/root/reference/.MISSING_LARGE_BLOBS); unrunnable from "
        "this mount by the reference itself"
    )
    for item in items:
        if "ts_state_dict" in getattr(item, "fixturenames", ()):
            item.add_marker(skip_blob)
        elif item.name.split("[")[0] in _5M_TESTS:
            item.add_marker(skip_blob)
'''


def stage() -> Path:
    """Build a fresh staging tree; a per-run tempdir, so concurrent
    invocations (the in-suite certification test vs a manual run) can never
    rmtree each other's tree mid-run."""
    stage_root = Path(tempfile.mkdtemp(prefix="refsuite-"))
    tests = stage_root / "tests"
    tests.mkdir(parents=True)
    (stage_root / "conftest.py").write_text(OUTER_CONFTEST)
    for entry in REF_TESTS.iterdir():
        if entry.name == "adapters.py":
            continue  # the designed swap point
        if entry.name == "__pycache__":
            continue
        (tests / entry.name).symlink_to(entry)
    (tests / "adapters.py").write_text(ADAPTERS_SHIM)
    return stage_root


def main() -> int:
    stage_root = stage()
    cmd = [
        sys.executable,
        "-m",
        "pytest",
        "tests/",
        "-q",
        "-p",
        "no:cacheprovider",  # rootdir may be torn down between runs
        *sys.argv[1:],
    ]
    env = dict(os.environ)
    # The reference suite is torch-vs-adapter numerics on the host — force
    # the CPU backend UNCONDITIONALLY: the container boot exports
    # JAX_PLATFORMS=axon, whose backend init SLEEPS in a connect-retry loop
    # when the tunnel is down (a setdefault here silently inherits that and
    # the first jax-using test hangs forever), and a TPU has no role in
    # this parity run anyway.
    env["JAX_PLATFORMS"] = "cpu"
    print(
        f"running reference suite: {' '.join(cmd)} (cwd={stage_root})",
        file=sys.stderr,
    )
    try:
        return subprocess.call(cmd, cwd=stage_root, env=env)
    finally:
        shutil.rmtree(stage_root, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
