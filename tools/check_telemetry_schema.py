#!/usr/bin/env python
"""Gate: every telemetry record kind emitted anywhere in the package must be
documented in ``telemetry/schema.py`` (and its prose table in the docs).

Three checks, all static/jax-free (wired into tier-1 via
``tests/test_telemetry.py``, runnable standalone):

1. **Source sweep** — grep ``bpe_transformer_tpu/`` (every subpackage: the
   ``resilience/`` emitters' preemption/recovery kinds included, plus
   ``bench.py``, ``benchmarks/`` and ``tools/``) for every
   ``"kind": "..."`` / ``kind="..."`` literal an emitter writes; each must
   be a key of ``RECORD_SCHEMAS``.  A new record kind cannot ship
   undocumented.
2. **Docs sweep** — every documented kind must appear in the
   ``ARCHITECTURE.md`` and ``README.md`` record-kind tables.
3. **Fixture validation** — every record in the committed
   ``tests/fixtures/*.jsonl`` streams must validate against its kind's
   required-field schema (the fixtures are the pinned wire format).
4. **Trace-exporter assumptions** — every field ``telemetry/trace.py``
   reads (its ``TRACE_ASSUMPTIONS``) must be a required field of the
   corresponding kind, so a schema change cannot silently break the
   Chrome trace export.
5. **Fixture coverage** — every registered kind must appear in at least
   one committed ``tests/fixtures/*.jsonl`` stream (the pinned wire
   format): a kind nobody pins is a kind whose renderers regress
   silently.  (``metric`` is exempt from the literal-kind grep — it is
   the pseudo-kind of the kind-less step records, matched by a bare
   ``"step"`` + ``"loss"`` record instead.)

Exit 0 when clean; 1 with one line per violation otherwise.
"""

from __future__ import annotations

import json
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from bpe_transformer_tpu.telemetry.schema import (  # noqa: E402
    RECORD_SCHEMAS,
    validate_record,
)

#: ``{"kind": "span"}`` / ``dict(...)["kind"] = "x"`` string-literal record
#: kinds.  Kwarg spellings (``run_manifest(kind="serve")``) are deliberately
#: NOT swept: in this codebase they name run kinds (train/serve/bench), not
#: record kinds — every record-kind emitter writes the dict-literal form.
_KIND_DICT = re.compile(r'["\']kind["\']\s*:\s*["\'](\w+)["\']')


def emitted_kinds() -> dict[str, list[str]]:
    """record kind -> source locations that emit it."""
    kinds: dict[str, list[str]] = {}
    roots = [REPO / "bpe_transformer_tpu", REPO / "benchmarks", REPO / "tools"]
    files = [p for root in roots for p in sorted(root.rglob("*.py"))]
    files += [REPO / "bench.py"]
    for path in files:
        if path == Path(__file__).resolve():
            continue
        try:
            text = path.read_text(encoding="utf-8")
        except OSError:
            continue
        for match in _KIND_DICT.finditer(text):
            line = text[: match.start()].count("\n") + 1
            kinds.setdefault(match.group(1), []).append(
                f"{path.relative_to(REPO)}:{line}"
            )
    return kinds


def check_source() -> list[str]:
    problems = []
    for kind, where in sorted(emitted_kinds().items()):
        if kind not in RECORD_SCHEMAS:
            problems.append(
                f"undocumented record kind {kind!r} emitted at "
                f"{', '.join(where[:3])} — add it to "
                "bpe_transformer_tpu/telemetry/schema.py and the docs tables"
            )
    return problems


def check_docs() -> list[str]:
    problems = []
    for doc in ("ARCHITECTURE.md", "README.md"):
        try:
            text = (REPO / doc).read_text(encoding="utf-8")
        except OSError:
            problems.append(f"{doc} missing — the schema table lives there")
            continue
        for kind in RECORD_SCHEMAS:
            if f"`{kind}`" not in text and f'"{kind}"' not in text:
                problems.append(
                    f"{doc} does not document record kind {kind!r} "
                    "(record-kind table out of date)"
                )
    return problems


def check_fixtures() -> list[str]:
    problems = []
    for path in sorted((REPO / "tests" / "fixtures").glob("*.jsonl")):
        for lineno, line in enumerate(
            path.read_text(encoding="utf-8").splitlines(), start=1
        ):
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                problems.append(f"{path.name}:{lineno}: unparseable JSON")
                continue
            if not isinstance(record, dict):
                problems.append(f"{path.name}:{lineno}: not a JSON object")
                continue
            for problem in validate_record(record):
                problems.append(f"{path.name}:{lineno}: {problem}")
    return problems


def check_fixture_coverage() -> list[str]:
    """Every registered record kind is exercised by a committed fixture."""
    from bpe_transformer_tpu.telemetry.schema import record_kind

    seen: set[str] = set()
    for path in sorted((REPO / "tests" / "fixtures").glob("*.jsonl")):
        for line in path.read_text(encoding="utf-8").splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(record, dict):
                seen.add(record_kind(record))
    problems = []
    for kind in RECORD_SCHEMAS:
        if kind not in seen:
            problems.append(
                f"record kind {kind!r} appears in no tests/fixtures/*.jsonl "
                "stream — add a fixture record so its renderers are pinned"
            )
    return problems


def check_trace_assumptions() -> list[str]:
    from bpe_transformer_tpu.telemetry.trace import TRACE_ASSUMPTIONS

    problems = []
    for kind, fields in sorted(TRACE_ASSUMPTIONS.items()):
        schema = RECORD_SCHEMAS.get(kind)
        if schema is None:
            problems.append(
                f"trace exporter assumes record kind {kind!r}, which is "
                "not in the schema registry"
            )
            continue
        missing = sorted(fields - schema)
        if missing:
            problems.append(
                f"trace exporter reads {kind!r} field(s) "
                f"{', '.join(missing)} that the schema does not require — "
                "align telemetry/trace.py TRACE_ASSUMPTIONS with "
                "telemetry/schema.py"
            )
    return problems


def main() -> int:
    problems = (
        check_source()
        + check_docs()
        + check_fixtures()
        + check_trace_assumptions()
        + check_fixture_coverage()
    )
    for problem in problems:
        print(f"telemetry-schema: {problem}", file=sys.stderr)
    if not problems:
        kinds = ", ".join(sorted(RECORD_SCHEMAS))
        print(f"telemetry schema clean ({kinds})")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
