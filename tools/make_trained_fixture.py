"""Regenerate the trained 3L/64d model fixture the reference lost.

The reference's trained-weights test family (everything consuming its
``ts_state_dict`` fixture) is unrunnable from this mount: its input weights
`tests/fixtures/ts_tests/model.pt` are a missing large blob
(`/root/reference/.MISSING_LARGE_BLOBS`), while the snapshot outputs
they produced remain.  Those snapshots can never be replayed without the
original weights, so this script regenerates the equivalent artifact —
a BRIEFLY TRAINED model at the exact `model_config.json` shape
(`/root/reference/tests/fixtures/ts_tests/model_config.json:1-13`: vocab
10k, ctx 16, 3L/64d, 4 heads, d_ff 128, RoPE θ=10⁴) — and pins ITS outputs
in this repo's suite, so the trained-weights family runs somewhere, forever
(tests/test_trained_fixture.py).

Fixture contents (tests/fixtures/trained_3l64d.npz):
  * the trained state dict under the reference's torch-style key schema
    (`adapters.py:307-353`);
  * ``pin/input_ids`` + ``pin/logits`` — a fixed forward;
  * ``pin/traj_lm_head`` + ``pin/traj_losses`` — a 5-step AdamW trajectory
    (cosine-warmup ``TrainHParams`` defaults) continuing from the trained
    state on seeded batches.

Training corpus: benchmarks/northstar_tokens.npz (corpus.en BPE-tokenized
at vocab 10k — the same id space as the model).  Deterministic end to end;
re-running reproduces the committed file bit-for-bit on the same stack.

Usage:  JAX_PLATFORMS=cpu python tools/make_trained_fixture.py
"""

from __future__ import annotations

import sys
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

TRAIN_STEPS = 150
BATCH = 32
FIXTURE = REPO / "tests" / "fixtures" / "trained_3l64d.npz"
TOKENS = REPO / "benchmarks" / "northstar_tokens.npz"


def batches(tokens: np.ndarray, seq: int, n_steps: int, seed: int):
    rng = np.random.default_rng(seed)
    for _ in range(n_steps):
        starts = rng.integers(0, len(tokens) - seq - 1, size=BATCH)
        x = np.stack([tokens[s : s + seq] for s in starts])
        y = np.stack([tokens[s + 1 : s + seq + 1] for s in starts])
        yield x.astype(np.int32), y.astype(np.int32)


def main() -> int:
    import jax
    import jax.numpy as jnp

    from bpe_transformer_tpu.models import TS_TEST_CONFIG, init_params
    from bpe_transformer_tpu.models.transformer import forward, state_dict_from_params
    from bpe_transformer_tpu.optim import adamw_init
    from bpe_transformer_tpu.training.train_step import TrainHParams, make_train_step

    cfg = TS_TEST_CONFIG
    tokens = np.load(TOKENS)["tokens"]

    params = init_params(jax.random.PRNGKey(0), cfg)
    opt_state = adamw_init(params)
    step = make_train_step(cfg, TrainHParams())
    for x, y in batches(tokens, cfg.context_length, TRAIN_STEPS, seed=1):
        params, opt_state, m = step(params, opt_state, jnp.asarray(x), jnp.asarray(y))
    print(f"trained {TRAIN_STEPS} steps, final loss {float(m['loss']):.4f}",
          file=sys.stderr)

    out: dict[str, np.ndarray] = {
        k: np.asarray(v, dtype=np.float32)
        for k, v in state_dict_from_params(params).items()
    }

    # Pinned forward: fixed ids -> logits from the trained weights.
    rng = np.random.default_rng(7)
    ids = rng.integers(0, cfg.vocab_size, size=(4, cfg.context_length))
    logits = jax.jit(lambda p, t: forward(p, t, cfg))(params, jnp.asarray(ids))
    out["pin/input_ids"] = ids.astype(np.int32)
    out["pin/logits"] = np.asarray(logits, dtype=np.float32)

    # Pinned 5-step AdamW trajectory from the trained params with a FRESH
    # optimizer state (the fixture stores weights only, so the replaying
    # test can reconstruct the exact same starting point).
    opt_state = adamw_init(params)
    traj_losses = []
    for x, y in batches(tokens, cfg.context_length, 5, seed=2):
        params, opt_state, m = step(params, opt_state, jnp.asarray(x), jnp.asarray(y))
        traj_losses.append(float(m["loss"]))
    out["pin/traj_lm_head"] = np.asarray(params["lm_head"], dtype=np.float32)
    out["pin/traj_losses"] = np.asarray(traj_losses, dtype=np.float32)

    FIXTURE.parent.mkdir(parents=True, exist_ok=True)
    np.savez_compressed(FIXTURE, **out)
    print(f"wrote {FIXTURE} ({FIXTURE.stat().st_size / 1e6:.2f} MB, "
          f"{len(out)} arrays)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
