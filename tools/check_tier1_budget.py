#!/usr/bin/env python
"""Guard: the tier-1 (``-m 'not slow'``) suite must stay inside its wall
budget.

The driver gives tier-1 a hard 870 s timeout (ROADMAP "Tier-1 verify");
PR 9 already had to sweep 27 heavy tests behind ``slow`` to fit it, and
every PR since has grown the suite.  A suite that silently creeps past
the budget doesn't fail gracefully — it gets KILLED mid-run and reports
whatever happened to finish.  This tool makes the creep loud *before*
that happens, two jax-free ways:

* **Log mode** (default, given a pytest log file): parse the summary
  trailer (``... passed ... in 612.34s``) of a finished tier-1 run —
  e.g. the ``/tmp/_t1.log`` the ROADMAP verify command tees — and fail
  when the measured wall exceeds ``--budget`` (default 800 s, a ~8%
  margin under the 870 s kill).
* **Count mode** (``--collect``): run ``pytest --collect-only -q -m 'not
  slow'`` and fail when the tier-1 test COUNT exceeds ``--max-tests``
  (default 520).  A proxy, not a measurement — but it runs in seconds,
  so it can gate a commit that adds a pile of unmarked tests without
  re-running the suite.  When the ceiling is hit legitimately (cheap
  tests), raise it here *in the same commit* that adds them — the point
  is that growth is a decision, not an accident.

Exit 0 within budget; 1 over budget (or unparseable log); 2 usage.
"""

from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

#: Wall budget for a finished tier-1 run (seconds) — under the driver's
#: 870 s timeout with margin for runner variance.
DEFAULT_BUDGET_S = 800.0

#: Tier-1 test-count ceiling for --collect mode.  ~430 tests ran in
#: ~640 s at PR 10 on a 2-cpu runner (~1.5 s/test amortized); the ceiling
#: keeps headroom while catching a silent 20%+ jump.  Raised 520 -> 545
#: in PR 13 (deliberately, per the policy above) for the 11 tier-1
#: MFU-push tests (tests/test_mfu_push.py — remat-policy parity/ordering,
#: bf16 collective bytes, donation audit, peak-HBM gate).  Raised
#: 545 -> 570 in PR 17 for the self-healing control plane
#: (tests/test_controller.py decide/breaker/spawner pins, migration wire
#: v2 CRC+codec, router suspect quarantine, serving fault hooks, import
#: idempotency); its heavy fleet chaos e2e is marked slow.
DEFAULT_MAX_TESTS = 570

#: Pytest summary trailer: "== 398 passed, 27 deselected in 612.34s =="
#: (also plain "in 612.34s (0:10:12)" forms).
_TRAILER = re.compile(r"\bin\s+(\d+(?:\.\d+)?)s\b")
_COUNTS = re.compile(r"(\d+)\s+(passed|failed|errors?|skipped)")


def check_log(path: Path, budget_s: float) -> int:
    try:
        text = path.read_text(encoding="utf-8", errors="replace")
    except OSError as exc:
        print(f"tier1-budget: cannot read {path}: {exc}", file=sys.stderr)
        return 1
    wall = None
    counts: dict[str, int] = {}
    for line in text.splitlines():
        m = _TRAILER.search(line)
        if m and _COUNTS.search(line):
            wall = float(m.group(1))
            counts = {k: int(n) for n, k in _COUNTS.findall(line)}
    if wall is None:
        print(
            f"tier1-budget: no pytest summary trailer in {path} "
            "(run interrupted or not a pytest log?)",
            file=sys.stderr,
        )
        return 1
    verdict = "within" if wall <= budget_s else "OVER"
    print(
        f"tier1 wall {wall:.1f}s — {verdict} budget {budget_s:.0f}s "
        f"({', '.join(f'{v} {k}' for k, v in counts.items()) or 'no counts'})"
    )
    if wall > budget_s:
        print(
            "tier1-budget: the 'not slow' suite is over budget — move "
            "heavy tests behind the slow marker (PR 9 precedent) before "
            "the driver's 870s timeout starts killing runs",
            file=sys.stderr,
        )
        return 1
    return 0


def check_collect(max_tests: int) -> int:
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest", "tests/", "-q",
            "--collect-only", "-m", "not slow",
            "--continue-on-collection-errors",
            "-p", "no:cacheprovider",
        ],
        cwd=REPO, capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        timeout=600,
    )
    m = re.search(
        r"(\d+)(?:/\d+)? tests? (?:collected|selected)",
        proc.stdout + proc.stderr,
    )
    if m is None:
        # "N deselected, M selected" / "N tests collected" variants.
        m = re.search(r"(\d+) selected", proc.stdout + proc.stderr)
    if m is None:
        print(
            "tier1-budget: could not parse collected-test count from "
            "pytest --collect-only output",
            file=sys.stderr,
        )
        print(proc.stdout[-2000:], file=sys.stderr)
        return 1
    n = int(m.group(1))
    verdict = "within" if n <= max_tests else "OVER"
    print(f"tier1 collects {n} tests — {verdict} ceiling {max_tests}")
    if n > max_tests:
        print(
            "tier1-budget: tier-1 test count jumped past the ceiling — "
            "either mark the new heavy tests slow, or raise "
            "DEFAULT_MAX_TESTS in this tool in the same commit (growth "
            "should be a decision, not an accident)",
            file=sys.stderr,
        )
        return 1
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument(
        "log", nargs="?", default=None,
        help="pytest log of a finished tier-1 run (e.g. /tmp/_t1.log)",
    )
    parser.add_argument("--budget", type=float, default=DEFAULT_BUDGET_S,
                        help="wall budget in seconds for log mode")
    parser.add_argument("--collect", action="store_true",
                        help="count tier-1 tests via pytest --collect-only "
                        "instead of parsing a log")
    parser.add_argument("--max-tests", type=int, default=DEFAULT_MAX_TESTS,
                        help="test-count ceiling for --collect mode")
    args = parser.parse_args(argv)
    if args.collect:
        return check_collect(args.max_tests)
    if not args.log:
        parser.print_usage(sys.stderr)
        print(
            "tier1-budget: give a pytest log path, or --collect",
            file=sys.stderr,
        )
        return 2
    return check_log(Path(args.log), args.budget)


if __name__ == "__main__":
    sys.exit(main())
