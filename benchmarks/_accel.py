"""Shared accelerator-gate for benchmark scripts (ADVICE r3: the probe was
duplicated in bench_decode/bench_attention/bench_moe_dispatch and
near-duplicated in bench_breakdown; one copy here so it can't drift).

The container's axon backend HANGS on init when its tunnel is down rather
than raising, so the probe must be a subprocess with a timeout — a direct
``jax.devices()`` call would burn the caller's whole queue timeout.
"""

from __future__ import annotations

import os
import subprocess
import sys


def probe_platform(timeout_s: float = 60.0) -> tuple[str | None, str]:
    """Probe what platform a fresh interpreter reaches.

    Returns ``(platform, note)``: platform is e.g. ``"tpu"`` when a non-CPU
    backend answered, else ``None`` with ``note`` explaining why (timeout,
    CPU-only, stderr tail) — callers that annotate their output (bench.py's
    RESULT note) need the reason, not just the boolean.
    """
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.devices()[0].platform)"],
            capture_output=True,
            timeout=timeout_s,
        )
        out = probe.stdout.decode().strip().splitlines()
        if probe.returncode == 0 and out and out[-1] not in ("", "cpu"):
            return out[-1], ""
        if probe.returncode == 0:
            return None, "backend resolved to host CPU"
        return None, (probe.stderr or b"").decode(errors="replace")[-200:]
    except subprocess.TimeoutExpired:
        return None, f"backend init exceeded {timeout_s:.0f}s"
    except Exception as exc:  # noqa: BLE001 - a probe failure is just "down"
        return None, repr(exc)


def accelerator_up(timeout_s: float = 60.0) -> bool:
    """True when a fresh interpreter reaches a non-CPU backend."""
    return probe_platform(timeout_s)[0] is not None


def require_accelerator(name: str = "benchmark", timeout_s: float = 60.0) -> None:
    """Exit rc=3 (the queue's "retry later" code) when the tunnel is down.

    An explicit ``JAX_PLATFORMS=cpu`` run (dev/CI smoke on hosts with no
    accelerator) skips the probe — the caller asked for CPU, so CPU numbers
    are what they expect.
    """
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        return
    if accelerator_up(timeout_s):
        return
    print(
        f"{name}: accelerator unreachable; exiting for fast queue retry "
        "(set JAX_PLATFORMS=cpu for an explicit CPU smoke run)",
        file=sys.stderr,
    )
    raise SystemExit(3)
