"""Pre-measure torch-CPU baselines for the large bench configs.

GPT-2-class torch-CPU steps take minutes each on this host, so measuring
them INSIDE a TPU-tunnel recovery window wastes the window.  This script
measures them ahead of time (run it while the host is otherwise idle — a
loaded host deflates the baseline and inflates every later ratio) and
seeds partial capture files (``benchmarks/captures/tpu_capture_<config>.json``
holding ONLY ``torch_cpu_tokens_per_sec`` + shape) that bench.py reuses
directly (and carries into real captures).  A partial seed can never
replay (no ``value``), never blocks a real capture (no ``measure_steps``),
and is shape-checked before reuse (same ``batch``).  ``BENCH_REMEASURE_TORCH=1``
makes bench.py ignore stored baselines and measure live again.

Run niced in the background: ``nice -n 19 python
benchmarks/seed_torch_baselines.py``.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench
from bench import BENCH_CONFIGS

CAPTURE_DIR = Path(__file__).resolve().parent / "captures"

#: config -> measure steps (1 is enough at GPT-2 scale; eager torch has no
#: compile, the warmup step only warms the allocator).
TARGETS = {"tinystories-12l": 2, "gpt2-small-32k": 1, "gpt2-medium": 1}


def _read(path: Path) -> dict:
    try:
        return json.loads(path.read_text())
    except (OSError, json.JSONDecodeError):
        return {}


def main() -> int:
    for name, steps in TARGETS.items():
        batch = BENCH_CONFIGS[name][1]
        seq = BENCH_CONFIGS[name][4]
        path = CAPTURE_DIR / f"tpu_capture_{name}.json"
        existing = _read(path)
        if existing.get("torch_cpu_tokens_per_sec") or existing.get("value"):
            print(f"{name}: capture already has data, skipping", flush=True)
            continue

        # Reuse bench.py's own measurement path (one methodology).
        bench.ARGS.config, bench.ARGS.batch = name, batch
        print(f"{name}: measuring ({steps} step(s) + warmup)...", flush=True)
        start = time.perf_counter()
        tps = bench.bench_torch_cpu(measure_steps=steps)
        elapsed = time.perf_counter() - start

        # Re-check + atomic write: the recovery watcher may have landed a
        # REAL capture while we were measuring — never clobber it.
        existing = _read(path)
        if existing.get("value") or existing.get("torch_cpu_tokens_per_sec"):
            print(f"{name}: capture appeared during measurement, keeping it", flush=True)
            continue
        CAPTURE_DIR.mkdir(parents=True, exist_ok=True)
        payload = {
            "config": name,
            "batch": batch,
            "seq": seq,
            "torch_cpu_tokens_per_sec": round(tps, 1),
            "torch_cpu_seconds_per_step": round(batch * seq / tps, 2),
            "note": (
                "partial seed: torch-CPU baseline only, measured ahead of "
                "the TPU window; bench.py reuses it (cannot replay — no "
                "value/platform)"
            ),
        }
        tmp = path.with_suffix(".tmp")
        tmp.write_text(json.dumps(payload, indent=2) + "\n")
        os.replace(tmp, path)
        print(
            f"{name}: {tps:,.0f} tok/s ({batch * seq / tps:.1f}s/step, "
            f"wall {elapsed:.0f}s) -> {path.name}",
            flush=True,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
