"""MoE dispatch formulations head-to-head: dense one-hot einsum vs gather.

Same routing semantics (pinned by tests/test_moe.py equivalence tests);
this measures the cost difference.  The one-hot dispatch/combine einsums
cost ``2·n·e·cap·d`` flops EACH — at training shapes that exceeds the
expert FFN compute itself — while the gather formulation moves rows by
index.

On the TPU the numbers are wall-clock evidence; for a host-CPU run
(relative formulation arithmetic, like the ring-schedule comparison) set
``JAX_PLATFORMS=cpu`` explicitly — without it the accelerator gate exits
rc=3 when the tunnel is down, producing no output (ADVICE r3).

    python benchmarks/bench_moe_dispatch.py [--tokens N] [--d D] [--ff F]
    JAX_PLATFORMS=cpu python benchmarks/bench_moe_dispatch.py   # CPU smoke
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from _accel import require_accelerator  # noqa: E402  (benchmarks/_accel.py)

import numpy as np

import bpe_transformer_tpu  # noqa: F401  (re-asserts JAX_PLATFORMS before backend init)
import jax
import jax.numpy as jnp




def main() -> int:
    require_accelerator(Path(__file__).stem)
    parser = argparse.ArgumentParser()
    # Defaults: the tinystories-moe bench shape on accelerators, a scaled
    # shape (same n/(3*ff) dispatch:FFN flop ratio regime) on host CPU.
    on_accel = jax.default_backend() != "cpu"
    parser.add_argument("--tokens", type=int, default=8192 if on_accel else 2048)
    parser.add_argument("--d", type=int, default=512 if on_accel else 256)
    parser.add_argument("--ff", type=int, default=1365 if on_accel else 683)
    parser.add_argument("--experts", type=int, default=8)
    parser.add_argument("--top-k", type=int, default=2)
    parser.add_argument("--iters", type=int, default=10 if on_accel else 3)
    args = parser.parse_args()

    from bpe_transformer_tpu.models import TS_TEST_CONFIG
    from bpe_transformer_tpu.models.moe import init_moe_params, switch_ffn

    base = dataclasses.replace(
        TS_TEST_CONFIG,
        d_model=args.d,
        d_ff=args.ff,
        ffn_type="moe",
        n_experts=args.experts,
        router_top_k=args.top_k,
    )
    dtype = jnp.bfloat16 if on_accel else jnp.float32
    params = init_moe_params(jax.random.PRNGKey(0), base, dtype=dtype)
    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.standard_normal((args.tokens, args.d)), dtype=dtype
    )

    def timed(config):
        def loss(p, x):
            out, aux = switch_ffn(x, p, config)
            return jnp.sum(out.astype(jnp.float32) ** 2) + aux

        fn = jax.jit(jax.value_and_grad(loss))
        val, _ = fn(params, x)
        float(jax.device_get(val))  # compile + barrier
        start = time.perf_counter()
        for _ in range(args.iters):
            val, _ = fn(params, x)
        float(jax.device_get(val))
        return (time.perf_counter() - start) / args.iters * 1e3

    t_einsum = timed(dataclasses.replace(base, moe_dispatch="einsum"))
    t_gather = timed(dataclasses.replace(base, moe_dispatch="gather"))
    device = jax.devices()[0]
    print(
        json.dumps(
            {
                "metric": (
                    f"moe switch_ffn fwd+bwd (n={args.tokens}, e={args.experts}, "
                    f"top{args.top_k}, d={args.d}, ff={args.ff}, {np.dtype(dtype).name})"
                ),
                "einsum_ms": round(t_einsum, 3),
                "gather_ms": round(t_gather, 3),
                "speedup": round(t_einsum / t_gather, 2),
                "platform": device.platform,
                "device": str(device),
            }
        ),
        flush=True,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
