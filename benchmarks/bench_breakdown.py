"""Per-component timing breakdown of the training step on the real chip.

The headline bench (bench.py) reports one number for the whole update; this
script decomposes it so an MFU gap can be attributed to a specific stage
(forward, backward, optimizer, attention impl, CE chunking) instead of
guessed at.  Measurement is `telemetry.attribution`'s shared path —
``time_call`` (value-fetch barrier, warm first) for the sub-stage jits and
``StepProbe`` (non-donating AOT step copies + XLA cost analysis) for the
full update — so these bench rows and the loop's ``kind="attribution"``
telemetry records can never disagree about method.  Full-step rows carry
the static roofline verdict (flops, bytes moved, arithmetic intensity,
compute- vs memory-bound) alongside the measured ms.

Rows (one JSON line each, stdout):
    {"stage": "full_step" | "forward" | "value_and_grad" | ..., "ms": N,
     "config": ..., "platform": "tpu", ...}

Refuses to record CPU-fallback numbers: if the accelerator probe fails the
script exits(3) without output (the TPU queue treats that as a retry).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from _accel import accelerator_up  # noqa: E402  (benchmarks/_accel.py)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--config", default="gpt2-small-32k")
    parser.add_argument("--batch", type=int, default=32)
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument(
        "--decode",
        action="store_true",
        help="decompose the DECODE path instead of training: prefill(+1) "
        "and per-token scan cost, for each decode_attention_impl — the "
        "attribution the gpt2 decode-cell timeouts need (compile vs "
        "prefill vs token loop)",
    )
    parser.add_argument(
        "--remat-policy", default=None,
        choices=["none", "full", "dots_saveable", "save_attn"],
        help="remat policy for the measured step (default: config's)",
    )
    parser.add_argument(
        "--scan-layers", action="store_true",
        help="measure the scan-over-layers step",
    )
    parser.add_argument(
        "--grads-dtype", default="float32",
        choices=["float32", "bfloat16"],
        help="gradient width at the reduction boundary",
    )
    parser.add_argument(
        "--mfu-push", action="store_true",
        help="training-MFU knob matrix (ISSUE 13): one full_step row per "
        "(remat_policy, grads_dtype, scan_layers) combination with "
        "implied tok/s + mfu + peak_hbm_bytes, so the tpu_queue "
        "self-report can diff each knob against the BENCH_r04 headline",
    )
    args = parser.parse_args()

    # BREAKDOWN_ALLOW_CPU=1 is a functional smoke for the script itself
    # (CI/dev); rows it emits carry platform "cpu" and the queue's run_job
    # discards them, so they can never pollute TPU evidence.
    if os.environ.get("BREAKDOWN_ALLOW_CPU") != "1" and not accelerator_up():
        print("accelerator unreachable; refusing to record CPU numbers", file=sys.stderr)
        return 3

    import jax
    import jax.numpy as jnp

    import bpe_transformer_tpu.models as models
    from bpe_transformer_tpu.models import init_params
    from bpe_transformer_tpu.optim import adamw_init
    from bpe_transformer_tpu.telemetry.attribution import (
        StepProbe,
        time_call,
    )
    from bpe_transformer_tpu.training.train_step import (
        TrainHParams,
        make_loss_fn,
    )

    name_to_attr = {
        "tinystories-4l": "TINYSTORIES_4L",
        "tinystories-12l": "TINYSTORIES_12L",
        "gpt2-small-32k": "GPT2_SMALL_32K",
        "gpt2-medium": "GPT2_MEDIUM",
    }
    base = getattr(models, name_to_attr[args.config])
    base = dataclasses.replace(
        base, activation_dtype="bfloat16",
        attention_impl="flash" if base.context_length >= 1024 else "xla",
    )
    if args.remat_policy:
        base = dataclasses.replace(
            base, remat_policy=args.remat_policy, remat=False
        )
    if args.scan_layers:
        base = dataclasses.replace(base, scan_layers=True)
    device = jax.devices()[0]
    rng = np.random.default_rng(0)

    def emit(stage: str, ms: float, **extra) -> None:
        print(
            json.dumps(
                {
                    "stage": stage,
                    "ms": round(ms, 3),
                    "config": args.config,
                    "batch": args.batch,
                    "platform": device.platform,
                    **extra,
                }
            ),
            flush=True,
        )

    def step_row(config, grads_dtype: str | None = None) -> tuple[float, dict]:
        # The shared attribution probe: a NON-donating AOT copy of the
        # update (no state threading needed — the loop's buffers stay
        # valid) timed with the same fenced path the telemetry records
        # use, plus the program's XLA cost-model roofline verdict and
        # peak-HBM envelope, labelled with the execution knobs that
        # produced them (the ISSUE 13 attribution contract: every knob's
        # win or regression names its cause).
        params = init_params(jax.random.PRNGKey(0), config)
        opt_state = adamw_init(params)
        hparams = TrainHParams(grads_dtype=grads_dtype or args.grads_dtype)
        probe = StepProbe(
            config, hparams, batch_size=args.batch, iters=args.iters
        )
        cost = probe.program_costs(params, opt_state)[0]
        memory = probe.memory_stats(params, opt_state)
        measured = probe.measure(params, opt_state)
        return measured["device_step_s"] * 1e3, {
            "flops": cost["flops"],
            "bytes_accessed": cost["bytes_accessed"],
            "arithmetic_intensity": cost["arithmetic_intensity"],
            "bound": cost["bound"],
            "peak_hbm_bytes": memory.get("peak_hbm_bytes"),
            "remat_policy": config.resolved_remat_policy,
            "grads_dtype": hparams.grads_dtype,
            "scan_layers": config.scan_layers,
        }

    if args.mfu_push:
        # Training-MFU knob matrix: the graduated remat ladder at f32
        # grads, then the bf16-collective and scan-layers combinations on
        # the selective-recompute point.  Each row carries implied tok/s +
        # mfu so the queue's jax-free self-report can diff it against the
        # BENCH_r04 headline capture without re-deriving geometry.
        from bpe_transformer_tpu.utils.flops import mfu as mfu_of

        matrix = [
            ("none", "float32", False),
            ("dots_saveable", "float32", False),
            ("full", "float32", False),
            ("save_attn", "float32", False),
            ("save_attn", "bfloat16", False),
            ("save_attn", "bfloat16", True),
        ]
        for policy, grads_dtype, scan in matrix:
            cfg = dataclasses.replace(
                base, remat_policy=policy, remat=False, scan_layers=scan
            )
            ms, cost = step_row(cfg, grads_dtype=grads_dtype)
            tokens_per_sec = args.batch * cfg.context_length / (ms / 1e3)
            emit(
                "mfu_push", ms,
                attention=cfg.attention_impl,
                loss_chunk=cfg.loss_chunk,
                tokens_per_sec=round(tokens_per_sec, 1),
                mfu=(
                    round(m, 4)
                    if (m := mfu_of(cfg, args.batch, ms / 1e3,
                                    device.device_kind)) is not None
                    else None
                ),
                **cost,
            )
        return 0

    if args.decode:
        from bench_decode import PROMPT_LEN  # shared geometry: these rows
        # must stay comparable with the decode.jsonl cells they explain

        from bpe_transformer_tpu.models.decode import generate_cached

        params = init_params(jax.random.PRNGKey(0), base)
        prompt = jnp.asarray(
            rng.integers(0, base.vocab_size, size=(args.batch, PROMPT_LEN)),
            jnp.int32,
        )
        key = jax.random.PRNGKey(1)
        n_long = 33  # per-token cost = (t(33) - t(1)) / 32
        # Honesty marker for the compile row: the queue's persistent
        # compile cache means a RETRY measures a warm "compile" — record
        # how many cache entries existed so the row is self-describing.
        cache_dir = Path(os.environ.get("JAX_COMPILATION_CACHE_DIR", ""))
        ccache_entries = (
            len(list(cache_dir.iterdir())) if cache_dir.is_dir() else 0
        )
        for impl in ("xla", "pallas"):
            cfg_d = dataclasses.replace(base, decode_attention_impl=impl)

            def gen(n, cfg_d=cfg_d):
                return generate_cached(
                    params, prompt, key, config=cfg_d,
                    max_new_tokens=n, temperature=0.0,
                )

            t0 = time.perf_counter()
            jax.device_get(gen(1))  # compile + first run
            emit(
                "decode_compile_plus_first(new=1)",
                (time.perf_counter() - t0) * 1e3,
                dec=impl,
                ccache_entries_at_start=ccache_entries,
            )
            t1 = time_call(lambda: gen(1), iters=args.iters)
            emit("decode_prefill_plus_1", t1, dec=impl, prompt=PROMPT_LEN)
            t_long = time_call(lambda: gen(n_long), iters=max(args.iters // 2, 3))
            emit(
                "decode_per_token",
                (t_long - t1) / (n_long - 1),
                dec=impl,
                measured_new=n_long,
            )
        return 0

    ids = rng.integers(0, base.vocab_size, size=(args.batch, base.context_length))
    x = jnp.asarray(ids)
    y = jnp.asarray(np.roll(ids, -1, axis=1))

    # 1. The full update as shipped.
    ms, cost = step_row(base)
    emit("full_step", ms, attention=base.attention_impl,
         flash_block=base.flash_block_size, loss_chunk=base.loss_chunk,
         **cost)

    # 2. Forward-only and grad-only splits (optimizer cost = full - valgrad).
    params = init_params(jax.random.PRNGKey(0), base)
    loss_fn = make_loss_fn(base)
    fwd = jax.jit(loss_fn)
    emit("forward", time_call(fwd, params, x, y, iters=args.iters))
    vg = jax.jit(jax.value_and_grad(loss_fn))
    emit("value_and_grad", time_call(lambda p: vg(p, x, y)[0], params, iters=args.iters))

    # 3. Attention impl / tile size at this exact shape.
    for attn, block in (("xla", None), ("flash", 256), ("flash", 512)):
        if attn == base.attention_impl and (block or 256) == base.flash_block_size:
            continue  # already row 1
        over = {"attention_impl": attn}
        if block:
            over["flash_block_size"] = block
        ms, cost = step_row(dataclasses.replace(base, **over))
        emit(
            "full_step", ms,
            attention=attn, flash_block=block, loss_chunk=base.loss_chunk,
            **cost,
        )

    # 4. CE chunking policy.  loss_chunk_size=None now resolves to the
    # AUTO chunk on these forced-bf16 configs (PR 13), so the full-logits
    # comparison point must be requested explicitly as 0; rows are
    # labelled with the RESOLVED chunk (null = full logits).
    for chunk in (0, 512):
        cfg = dataclasses.replace(base, loss_chunk_size=chunk)
        if cfg.loss_chunk == base.loss_chunk:
            continue  # already row 1
        ms, cost = step_row(cfg)
        emit(
            "full_step", ms,
            attention=base.attention_impl, flash_block=base.flash_block_size,
            loss_chunk=cfg.loss_chunk,
            **cost,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
