"""BASELINE config 1 validation-loss parity: JAX/TPU step vs torch-CPU step.

Trains the TinyStories-class 4L/256d LM with the framework's own BPE
tokenizer and training step, and the byte-identical architecture/update in
PyTorch on the host CPU (`bench.make_torch_lm`, the reference's execution
substrate — it defines the model via `/root/reference/tests/adapters.py:282-
361` but never ships a loop), under the SAME token budget, batch schedule,
and train/val split.  Writes `benchmarks/val_parity_results.json` with both
loss curves, final val losses, and throughput.

BASELINE config 1 names `tinystories_sample.txt`, but the mounted copy is
3.7 KB (~1.2k tokens — smaller than one batch); `corpus.en` (130 KB) is the
largest text the reference ships, so it is the default corpus here and the
artifact records which was used.

Usage:  python benchmarks/val_parity.py [--steps 200] [--corpus PATH]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

SEQ = 128
BATCH = 16
VOCAB = 1000
EVAL_EVERY = 25
VAL_FRACTION = 0.1
SPECIAL = "<|endoftext|>"


def tokenize_corpus(corpus: Path) -> np.ndarray:
    from bpe_transformer_tpu import BPETokenizer, train_bpe

    vocab, merges = train_bpe(str(corpus), VOCAB, [SPECIAL])
    tok = BPETokenizer(vocab, merges, [SPECIAL])
    ids = tok.encode(corpus.read_text(encoding="utf-8", errors="ignore"))
    return np.asarray(ids, dtype=np.int32)


def batches(tokens: np.ndarray, n_steps: int, seed: int):
    """The reference batch contract (D1): uniform start indices, y = x+1."""
    rng = np.random.default_rng(seed)
    for _ in range(n_steps):
        starts = rng.integers(0, len(tokens) - SEQ - 1, size=BATCH)
        x = np.stack([tokens[s : s + SEQ] for s in starts])
        y = np.stack([tokens[s + 1 : s + SEQ + 1] for s in starts])
        yield x.astype(np.int64), y.astype(np.int64)


def val_batches(tokens: np.ndarray):
    """Deterministic non-overlapping windows over the held-out split."""
    n = (len(tokens) - 1) // SEQ
    for i in range(min(n, 8)):
        s = i * SEQ
        yield (
            tokens[s : s + SEQ][None, :].astype(np.int64),
            tokens[s + 1 : s + SEQ + 1][None, :].astype(np.int64),
        )


def run_jax(cfg, train_toks, val_toks, n_steps):
    """Returns (curve, tokens_per_sec, initial_params) — the initial params
    seed the torch run so both trajectories start identically."""
    import jax
    import jax.numpy as jnp

    from bpe_transformer_tpu.models import init_params
    from bpe_transformer_tpu.optim import adamw_init
    from bpe_transformer_tpu.training.train_step import (
        TrainHParams,
        make_eval_step,
        make_train_step,
    )

    params = init_params(jax.random.PRNGKey(0), cfg)
    params0 = jax.tree_util.tree_map(np.asarray, params)
    opt_state = adamw_init(params)
    step = make_train_step(cfg, TrainHParams())
    ev = make_eval_step(cfg)

    def val_loss():
        losses = [
            float(ev(params, jnp.asarray(x), jnp.asarray(y)))
            for x, y in val_batches(val_toks)
        ]
        return sum(losses) / len(losses)

    curve = []
    start = time.perf_counter()
    for i, (x, y) in enumerate(batches(train_toks, n_steps, seed=0)):
        params, opt_state, m = step(params, opt_state, jnp.asarray(x), jnp.asarray(y))
        if (i + 1) % EVAL_EVERY == 0 or i == n_steps - 1:
            curve.append(
                {"step": i + 1, "train_loss": float(m["loss"]), "val_loss": val_loss()}
            )
            print(f"jax step {i + 1}: {curve[-1]}", file=sys.stderr)
    elapsed = time.perf_counter() - start
    return curve, n_steps * BATCH * SEQ / elapsed, params0


def _load_jax_params_into_torch(model, params):
    """Copy the JAX initialization into the torch model so both sides start
    from identical weights — the comparison then isolates the training-step
    implementations, not the initializers (neither is pinned by the
    reference, whose adapters take weights as inputs)."""
    import torch

    t = lambda a: torch.from_numpy(np.asarray(a, dtype=np.float32))
    with torch.no_grad():
        model.emb.weight.copy_(t(params["token_embeddings"]))
        model.ln_f.copy_(t(params["ln_final"]))
        model.head.weight.copy_(t(params["lm_head"]))
        for blk, lp in zip(model.blocks, params["layers"]):
            blk.q.weight.copy_(t(lp["attn"]["q_proj"]))
            blk.k.weight.copy_(t(lp["attn"]["k_proj"]))
            blk.v.weight.copy_(t(lp["attn"]["v_proj"]))
            blk.o.weight.copy_(t(lp["attn"]["output_proj"]))
            blk.w1.weight.copy_(t(lp["ffn"]["w1"]))
            blk.w2.weight.copy_(t(lp["ffn"]["w2"]))
            blk.w3.weight.copy_(t(lp["ffn"]["w3"]))
            blk.ln1.copy_(t(lp["ln1"]))
            blk.ln2.copy_(t(lp["ln2"]))


def run_torch(cfg, train_toks, val_toks, n_steps, init_params_tree=None):
    import torch

    from bench import make_torch_lm

    model, train_step, eval_loss = make_torch_lm(cfg)
    if init_params_tree is not None:
        _load_jax_params_into_torch(model, init_params_tree)

    def val_loss():
        losses = [
            eval_loss(torch.from_numpy(x), torch.from_numpy(y))
            for x, y in val_batches(val_toks)
        ]
        return sum(losses) / len(losses)

    curve = []
    start = time.perf_counter()
    for i, (x, y) in enumerate(batches(train_toks, n_steps, seed=0)):
        loss = train_step(torch.from_numpy(x), torch.from_numpy(y))
        if (i + 1) % EVAL_EVERY == 0 or i == n_steps - 1:
            curve.append({"step": i + 1, "train_loss": loss, "val_loss": val_loss()})
            print(f"torch step {i + 1}: {curve[-1]}", file=sys.stderr)
    elapsed = time.perf_counter() - start
    return curve, n_steps * BATCH * SEQ / elapsed


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument(
        "--corpus", default="/root/reference/tests/fixtures/corpus.en"
    )
    ap.add_argument("--out", default=str(REPO / "benchmarks" / "val_parity_results.json"))
    args = ap.parse_args()

    import os

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        # The container's boot hook force-selects its accelerator via
        # jax.config, trampling the env var (see training/cli.py:266-274);
        # re-assert the caller's explicit choice before backends init.
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from bpe_transformer_tpu.models import TINYSTORIES_4L

    corpus = Path(args.corpus)
    tokens = tokenize_corpus(corpus)
    n_val = max(int(len(tokens) * VAL_FRACTION), SEQ + 1)
    train_toks, val_toks = tokens[:-n_val], tokens[-n_val:]
    print(
        f"corpus {corpus.name}: {len(tokens)} tokens "
        f"({len(train_toks)} train / {len(val_toks)} val)",
        file=sys.stderr,
    )

    cfg = dataclasses.replace(
        TINYSTORIES_4L, vocab_size=VOCAB, context_length=SEQ
    )
    jax_curve, jax_tps, params0 = run_jax(cfg, train_toks, val_toks, args.steps)
    torch_curve, torch_tps = run_torch(
        cfg, train_toks, val_toks, args.steps, init_params_tree=params0
    )

    result = {
        "config": "BASELINE config 1 (4L/256d), vocab 1000, seq 128, batch 16",
        "corpus": str(corpus),
        "n_tokens": int(len(tokens)),
        "steps": args.steps,
        "platform": jax.devices()[0].platform,
        "jax": {"curve": jax_curve, "tokens_per_sec": round(jax_tps, 1)},
        "torch_cpu": {"curve": torch_curve, "tokens_per_sec": round(torch_tps, 1)},
        "final_val_loss": {
            "jax": jax_curve[-1]["val_loss"],
            "torch_cpu": torch_curve[-1]["val_loss"],
        },
        "jax_beats_or_matches_torch": jax_curve[-1]["val_loss"]
        <= torch_curve[-1]["val_loss"] + 0.02,
    }
    Path(args.out).write_text(json.dumps(result, indent=2))
    print(json.dumps(result["final_val_loss"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
