"""Sharded-optimizer + step-overlap head-to-head: plain dp vs dp+ZeRO-1.

Runs the SAME short data-parallel training job twice over every local
device — once with the replicated AdamW update (the BENCH baseline
collective), once with ``opt_sharding="zero1"`` + the double-buffered
host→device prefetch — through the real training loop, so each run emits
the production telemetry (attribution splits, per-chip state bytes) this
bench then reads back.  The JSON row it prints is the PR-7 evidence line:

* ``opt_state_bytes`` vs ``opt_state_bytes_plain`` — per-chip AdamW state
  must scale ~1/N along the dp axis,
* ``host_gap_frac`` vs ``host_gap_frac_plain`` — the prefetcher's effect
  on the measured host-gap fraction,
* ``value`` (tokens/sec/chip, zero1 run) vs ``plain_tokens_per_sec_per_chip``
  — the throughput guardrail: sharding the update must not cost speed.

On a single-device backend the dp mesh is 1-wide: the row still measures
the overlap half honestly, while the bytes ratio reads 1.0 (nothing to
shard across — the row says so via ``n_chips``).

    python benchmarks/bench_sharded_opt.py [--config tinystories-4l]
    JAX_PLATFORMS=cpu python benchmarks/bench_sharded_opt.py --steps 8  # smoke
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))

from _accel import require_accelerator  # noqa: E402  (benchmarks/_accel.py)

import numpy as np

import bpe_transformer_tpu  # noqa: F401  (re-asserts JAX_PLATFORMS before backend init)
import jax


def stream_summary(path: Path) -> dict:
    """The comparison-relevant numbers out of one run's telemetry stream
    (jax-free parse — same records ``bpe-tpu report`` reads)."""
    steps, resources, attributions = [], [], []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                continue
            kind = record.get("kind")
            if kind == "resources":
                resources.append(record)
            elif kind == "attribution":
                attributions.append(record)
            elif kind is None and "tokens_per_sec_per_chip" in record:
                steps.append(record)
    tps = [r["tokens_per_sec_per_chip"] for r in steps]
    out = {
        "tokens_per_sec_per_chip": (
            round(float(np.mean(tps)), 1) if tps else None
        ),
    }
    if resources:
        out["opt_state_bytes"] = resources[-1].get("opt_state_bytes")
        out["params_bytes"] = resources[-1].get("params_bytes")
    if attributions:
        last = attributions[-1]
        for key in ("compute_frac", "collective_frac", "host_gap_frac"):
            out[key] = last.get(key)
    return out


def run_variant(
    config, hparams, *, steps, batch, mesh_axes, zero1, prefetch, data, out_jsonl
):
    from bpe_transformer_tpu.training.loop import LoopConfig, train

    # The attribution probe fires once, at the mid-run log boundary (it
    # must be a log_every multiple that lands inside the run).
    log_every = max(steps // 4, 1)
    attribution_every = (steps // (2 * log_every)) * log_every or log_every
    loop = LoopConfig(
        steps=steps,
        batch_size=batch,
        log_every=log_every,
        eval_every=10**9,
        checkpoint_every=10**9,
        metrics_jsonl=str(out_jsonl),
        attribution_every=attribution_every,
        parallel="dp",
        mesh_axes=mesh_axes,
        opt_sharding="zero1" if zero1 else None,
        prefetch=prefetch,
        seed=0,
    )
    train(config, hparams, loop, data, log_fn=lambda *_: None)
    return stream_summary(Path(out_jsonl))


def main() -> int:
    require_accelerator(Path(__file__).stem)
    parser = argparse.ArgumentParser()
    on_accel = jax.default_backend() != "cpu"
    parser.add_argument(
        "--config", default="tinystories-4l",
        choices=["ts-test", "tinystories-4l", "tinystories-12l"],
    )
    parser.add_argument("--steps", type=int, default=60 if on_accel else 8)
    parser.add_argument("--batch", type=int, default=None)
    args = parser.parse_args()

    from bpe_transformer_tpu.models import config as model_configs
    from bpe_transformer_tpu.training.train_step import TrainHParams

    presets = {
        "ts-test": (model_configs.TS_TEST_CONFIG, 8),
        "tinystories-4l": (model_configs.TINYSTORIES_4L, 32),
        "tinystories-12l": (model_configs.TINYSTORIES_12L, 32),
    }
    config, default_batch = presets[args.config]
    batch = args.batch or default_batch
    n_chips = len(jax.devices())
    if batch % n_chips:
        batch = max(batch // n_chips, 1) * n_chips
    mesh_axes = {"data": n_chips}
    hparams = TrainHParams(warmup_iters=5, cosine_cycle_iters=args.steps)

    # Synthetic learnable stream at the config's vocab (same trick as the
    # loop tests): the bench measures throughput/memory, not convergence.
    vocab = min(config.vocab_size, 4096)
    data = np.tile(np.arange(vocab, dtype=np.int32), 200)

    scratch = Path(tempfile.mkdtemp(prefix="bench_sharded_opt_"))
    plain = run_variant(
        config, hparams, steps=args.steps, batch=batch, mesh_axes=mesh_axes,
        zero1=False, prefetch=0, data=data, out_jsonl=scratch / "plain.jsonl",
    )
    zero1 = run_variant(
        config, hparams, steps=args.steps, batch=batch, mesh_axes=mesh_axes,
        zero1=True, prefetch=2, data=data, out_jsonl=scratch / "zero1.jsonl",
    )

    device = jax.devices()[0]
    row = {
        "metric": "sharded_opt",
        "config": args.config,
        "batch": batch,
        "steps": args.steps,
        "n_chips": n_chips,
        # "value" is the headline field capture tooling sorts on: the
        # zero1 run's tokens/sec/chip.
        "value": zero1.get("tokens_per_sec_per_chip"),
        "plain_tokens_per_sec_per_chip": plain.get("tokens_per_sec_per_chip"),
        "opt_state_bytes": zero1.get("opt_state_bytes"),
        "opt_state_bytes_plain": plain.get("opt_state_bytes"),
        "params_bytes": zero1.get("params_bytes"),
        "host_gap_frac": zero1.get("host_gap_frac"),
        "host_gap_frac_plain": plain.get("host_gap_frac"),
        "compute_frac": zero1.get("compute_frac"),
        "collective_frac": zero1.get("collective_frac"),
        "platform": device.platform,
        "device": str(device),
    }
    if row["opt_state_bytes"] and row["opt_state_bytes_plain"]:
        row["opt_bytes_ratio"] = round(
            row["opt_state_bytes"] / row["opt_state_bytes_plain"], 4
        )
    print(json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
