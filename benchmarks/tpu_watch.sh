#!/bin/bash
# TPU recovery watcher (repo-resident since round 5; earlier rounds kept it
# in /tmp, which a container recycle would silently erase — VERDICT r4 #7).
#
# Probes the accelerator every 60 s; the moment a window opens, runs the
# benchmark queue (benchmarks/tpu_queue.sh, idempotent + flock-guarded).
# Separately, once an hour, re-checks whether this container has grown a
# second usable CPU core and captures the multi-worker host-tokenization
# grid the moment it does (VERDICT r4 #7 — no TPU needed for that one).
#
# Re-arm after any recycle with:
#   nohup bash /root/repo/benchmarks/tpu_watch.sh >/dev/null 2>&1 &
# Single-flight: a second invocation exits immediately (flock on the repo
# scratch, which survives recycles).
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
LOG=/tmp/tpu_watch5.log
mkdir -p "$REPO/.scratch"
exec 8> "$REPO/.scratch/watch.lock"
flock -n 8 || exit 0
last_core_check=0
while true; do
  # JAX_PLATFORMS=axon is exported by the container boot; when the tunnel is
  # down the first jax call hangs in the connect-retry loop, hence timeout.
  if timeout 90 python -c "import jax; d=jax.devices()[0]; assert 'TPU' in str(d)" >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) tpu up, running queue" >> "$LOG"
    # 8>&-: children must not inherit the watch lock — a queue pass (or a
    # 20-min northstar job inside it) outliving a killed watcher would
    # silently block re-arming (review r5).
    bash "$REPO/benchmarks/tpu_queue.sh" 8>&- >> "$LOG" 2>&1
    echo "$(date -u +%FT%TZ) queue pass done" >> "$LOG"
    # Short re-probe gap: windows have measured ~25-40 min and a completed
    # pass leaves only the always-rerun headline; a long sleep here could
    # waste the tail of the same window a new job list might use.
    sleep 120
  else
    echo "$(date -u +%FT%TZ) tpu down" >> "$LOG"
    sleep 60
  fi
  now=$(date +%s)
  HOSTTOK="$REPO/benchmarks/captures/host_tokenization.jsonl"
  if [ $((now - last_core_check)) -ge 3600 ]; then
    last_core_check=$now
    # Backgrounded subshell: the grid bench can run ~15 min and the probe
    # loop must keep watching for tunnel windows meanwhile (review r5).
    # Dedicated hosttok lock (manual bench invocations and a previous
    # still-running trap can race this); NOT queue.lock — a CPU-only bench
    # must never serialize against TPU work.  Disarm/duplicate logic lives
    # in the bench itself (--covered-file): it skips when single-core AND
    # when a grid at >= the current core count is already recorded, so the
    # trap re-fires if the container later grows more cores.
    (
      mkdir -p /tmp/tpu_results
      exec 7> /tmp/tpu_results/hosttok.lock
      flock -n 7 || exit 0
      # Buffer-then-promote (as tpu_queue.sh's run_job): a timeout-kill must
      # not leave partial/torn rows in the committed evidence file.  Failed
      # attempts keep their partial output in the scratch mirror.
      t=$(mktemp)
      if JAX_PLATFORMS=cpu timeout 900 python \
          "$REPO/benchmarks/bench_tokenization.py" --grid-if-multicore \
          --covered-file "$HOSTTOK" 8>&- > "$t" 2>> "$LOG" && [ -s "$t" ]; then
        cat "$t" >> "$HOSTTOK"
        echo "$(date -u +%FT%TZ) multicore trap fired: host tokenization grid captured" >> "$LOG"
      elif [ -s "$t" ]; then
        cat "$t" >> "$REPO/.scratch/hosttok_failed.jsonl"
      fi
      rm -f "$t"
    ) 8>&- &
  fi
done
