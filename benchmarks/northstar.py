"""BASELINE north star, demonstrated in ONE on-chip run.

Target (BASELINE.json): the TinyStories 4-layer LM reaches the PyTorch-CPU
reference validation loss at >= 10x its tokens/sec.  Prior rounds proved the
two halves separately — throughput on the chip (bench.py) and loss parity at
toy shape on CPU (val_parity.py).  This script closes the loop at the REAL
config-1 shape (`TINYSTORIES_4L`: vocab 10k, seq 256, 4L/256d) with the
training run itself on the accelerator.

Protocol (LR-matched, identical on both substrates — val_parity.py's):
same BPE-tokenized corpus, same train/val split, same pre-drawn batch
schedule, same init (the JAX init copied into torch), same warmup+cosine
AdamW schedule (`TrainHParams` defaults).  The torch side is the
reference-architecture step from ``bench.make_torch_lm`` (defined by
`/root/reference/tests/adapters.py:282-361`; the reference ships no loop).

Corpus: BASELINE config 1 names `tinystories_sample.txt`, but the mounted
copy is 3.7 KB and the 5 MB sample is a missing blob
(`/root/reference/.MISSING_LARGE_BLOBS`); `corpus.en` (130 KB) is the
largest text the reference ships, so it is the corpus here — recorded in
the artifact, as in val_parity.py.

Phases (so a short tunnel window only pays for the accelerator part):
  --phase data    tokenize the corpus at vocab 10k; cache to
                  benchmarks/northstar_tokens.npz (deterministic, committed)
  --phase torch   the torch-CPU reference run; writes
                  benchmarks/northstar_torch.json (curve, final val loss,
                  tokens/sec).  Runs offline, no accelerator needed.
  --phase jax     the accelerator run.  Checkpoints every eval to the
                  repo-local gitignored scratch (.scratch/northstar_ckpt.pkl,
                  NORTHSTAR_CKPT overrides) so a tunnel drop OR a container
                  recycle RESUMES instead of restarting; on completion writes
                  benchmarks/captures/northstar.json with both final val
                  losses, both tokens/sec, and the speedup.
  (default)       data + torch if their artifacts are missing, then jax.

Numerics: both sides train in f32; the JAX run pins
``jax.default_matmul_precision("highest")`` so the TPU trajectory tracks the
torch-f32 oracle (TPU's default f32 matmul rounds through bf16 passes and
would drift over hundreds of steps).  Even at highest precision the tiny
model clears the 10x bar by orders of magnitude — the HONEST perf numbers
live in bench.py's captures; this run is the convergence evidence.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

# Recycle-safe compile cache, same default as bench.py / tpu_queue.sh.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", str(REPO / ".scratch" / "jax_ccache")
)

from _accel import require_accelerator  # noqa: E402  (benchmarks/_accel.py)

SEQ = 256
BATCH = 16
VOCAB = 10_000
#: NORTHSTAR_STEPS is a smoke-test override; the artifacts record the value
#: used, and phase_jax refuses a torch reference run at a different length.
STEPS = int(os.environ.get("NORTHSTAR_STEPS", "200"))
EVAL_EVERY = 25
VAL_FRACTION = 0.1
SPECIAL = "<|endoftext|>"
CORPUS = "/root/reference/tests/fixtures/corpus.en"

TOKENS_NPZ = REPO / "benchmarks" / "northstar_tokens.npz"
TORCH_JSON = REPO / "benchmarks" / "northstar_torch.json"
CAPTURE = REPO / "benchmarks" / "captures" / "northstar.json"
#: The native-precision variant writes its own artifact: the parity run
#: (matmul precision=highest, per-step dispatch) is the convergence oracle;
#: the native run (TPU-default f32 matmuls, EVAL_EVERY steps per scanned
#: dispatch) is the same protocol at the precision/dispatch the framework
#: actually trains at, and is the run that demonstrates BOTH north-star
#: clauses — reference val loss AND >=10x tokens/sec — in one run.
CAPTURE_NATIVE = REPO / "benchmarks" / "captures" / "northstar_native.json"
#: Resume checkpoint lives in the repo's gitignored scratch, not /tmp: a
#: container recycle between tunnel windows must not discard mid-run
#: progress (VERDICT r4 weak #7).  Legacy /tmp checkpoints are migrated in
#: phase_jax so an in-flight resume survives this path change.
CKPT = Path(
    os.environ.get("NORTHSTAR_CKPT", str(REPO / ".scratch" / "northstar_ckpt.pkl"))
)
LEGACY_CKPT = Path("/tmp/tpu_results/northstar_ckpt.pkl")
#: Val-loss slack for the reached_reference verdict: two independent f32
#: trajectories (torch-CPU vs TPU at matmul precision=highest) drift a few
#: centinats over 200 steps; recorded in the artifact so the claim is
#: self-describing (ADVICE r4).
VAL_TOLERANCE = 0.02


def _write_json(path: Path, payload: dict) -> None:
    """tmp + os.replace, as bench.py's captures: a queue timeout landing
    mid-write must not leave a torn artifact for bench.py to half-read."""
    tmp = path.with_suffix(".tmp")
    tmp.write_text(json.dumps(payload, indent=2) + "\n")
    os.replace(tmp, path)


def phase_data() -> np.ndarray:
    """Tokenize the corpus (vocab 10k BPE trained on it) and cache the ids.

    Deterministic — the BPE trainer's tie-breaking is pinned by the
    reference's own snapshot tests — so the cache is just a time saver for
    the accelerator window, not a correctness requirement.
    """
    if TOKENS_NPZ.exists():
        return np.load(TOKENS_NPZ)["tokens"]
    from bpe_transformer_tpu import BPETokenizer, train_bpe

    corpus = Path(CORPUS)
    vocab, merges = train_bpe(str(corpus), VOCAB, [SPECIAL])
    tok = BPETokenizer(vocab, merges, [SPECIAL])
    ids = tok.encode(corpus.read_text(encoding="utf-8", errors="ignore"))
    tokens = np.asarray(ids, dtype=np.int32)
    np.savez_compressed(TOKENS_NPZ, tokens=tokens, vocab_size=len(vocab))
    print(f"tokenized {corpus.name}: {len(tokens)} tokens, "
          f"{len(vocab)} vocab entries", file=sys.stderr)
    return tokens


def split_tokens(tokens: np.ndarray):
    n_val = max(int(len(tokens) * VAL_FRACTION), SEQ + 1)
    return tokens[:-n_val], tokens[-n_val:]


def batch_schedule(n_tokens: int) -> np.ndarray:
    """All start indices drawn up front from one seed — a resumed run at
    step k sees exactly the batches the uninterrupted run would have."""
    rng = np.random.default_rng(0)
    return rng.integers(0, n_tokens - SEQ - 1, size=(STEPS, BATCH))


def gather_batch(tokens: np.ndarray, starts: np.ndarray):
    x = np.stack([tokens[s : s + SEQ] for s in starts])
    y = np.stack([tokens[s + 1 : s + SEQ + 1] for s in starts])
    return x.astype(np.int64), y.astype(np.int64)


def val_batches(val_toks: np.ndarray):
    n = (len(val_toks) - 1) // SEQ
    for i in range(min(n, 8)):
        s = i * SEQ
        yield (
            val_toks[s : s + SEQ][None, :].astype(np.int64),
            val_toks[s + 1 : s + SEQ + 1][None, :].astype(np.int64),
        )


def model_config():
    import dataclasses

    from bpe_transformer_tpu.models import TINYSTORIES_4L

    assert TINYSTORIES_4L.vocab_size == VOCAB
    assert TINYSTORIES_4L.context_length == SEQ
    return dataclasses.replace(TINYSTORIES_4L)


def init_params_np():
    """The shared starting point: JAX's deterministic init (threefry is
    platform-independent), fetched to host numpy for the torch loader."""
    import jax

    from bpe_transformer_tpu.models import init_params

    params = init_params(jax.random.PRNGKey(0), model_config())
    return jax.tree_util.tree_map(np.asarray, params)


def phase_torch() -> dict:
    if TORCH_JSON.exists():
        return json.loads(TORCH_JSON.read_text())
    import torch

    from bench import make_torch_lm
    from benchmarks.val_parity import _load_jax_params_into_torch

    tokens = phase_data()
    train_toks, val_toks = split_tokens(tokens)
    schedule = batch_schedule(len(train_toks))
    cfg = model_config()
    model, train_step, eval_loss = make_torch_lm(cfg)
    _load_jax_params_into_torch(model, init_params_np())

    def val_loss():
        losses = [
            eval_loss(torch.from_numpy(x), torch.from_numpy(y))
            for x, y in val_batches(val_toks)
        ]
        return sum(losses) / len(losses)

    curve = []
    start = time.perf_counter()
    train_s = 0.0
    for i in range(STEPS):
        x, y = gather_batch(train_toks, schedule[i])
        t0 = time.perf_counter()
        loss = train_step(torch.from_numpy(x), torch.from_numpy(y))
        train_s += time.perf_counter() - t0
        if (i + 1) % EVAL_EVERY == 0 or i == STEPS - 1:
            curve.append({"step": i + 1, "train_loss": loss, "val_loss": val_loss()})
            print(f"torch step {i + 1}: {curve[-1]}", file=sys.stderr)
    result = {
        "config": "TINYSTORIES_4L (vocab 10k, seq 256), batch 16",
        "corpus": CORPUS,
        "steps": STEPS,
        "curve": curve,
        "final_val_loss": curve[-1]["val_loss"],
        # tokens/sec over train-step time only (evals excluded on both
        # sides — the comparison is the training step, the reference's
        # contract surface).
        "tokens_per_sec": round(STEPS * BATCH * SEQ / train_s, 1),
        "wall_s": round(time.perf_counter() - start, 1),
    }
    _write_json(TORCH_JSON, result)
    print(f"torch reference: final val {result['final_val_loss']:.4f}, "
          f"{result['tokens_per_sec']:,.0f} tok/s", file=sys.stderr)
    return result


def phase_jax(allow_cpu: bool, variant: str = "parity") -> int:
    """One accelerator run of the shared protocol.

    ``variant="parity"``: f32 at matmul precision=highest, one dispatch per
    step — the trajectory tracks the torch-f32 oracle; the convergence claim.
    ``variant="native"``: TPU-default f32 matmul precision (single-pass bf16
    MXU) with the EVAL_EVERY steps between evals folded into ONE scanned
    dispatch (`make_scanned_train_step` — identical update math; the LR
    schedule rides opt_state.step, so scanning changes nothing numerically
    beyond the matmul rounding).  Same corpus/split/schedule/init; the run
    that shows val-loss AND the >=10x clause together, at the precision the
    framework actually trains at.
    """
    if variant not in ("parity", "native"):
        raise ValueError(f"unknown variant {variant!r}")
    native = variant == "native"
    capture_path = CAPTURE_NATIVE if native else CAPTURE
    ckpt_path = CKPT.with_name(f"native_{CKPT.name}") if native else CKPT
    if not allow_cpu:
        require_accelerator("northstar")
    torch_ref = json.loads(TORCH_JSON.read_text())
    if torch_ref["steps"] != STEPS:
        raise SystemExit(
            f"torch reference ran {torch_ref['steps']} steps but this run "
            f"wants {STEPS}; delete {TORCH_JSON} or match NORTHSTAR_STEPS"
        )
    if native and STEPS % EVAL_EVERY:
        raise SystemExit(
            f"native variant scans {EVAL_EVERY} steps per dispatch; "
            f"NORTHSTAR_STEPS={STEPS} must be a multiple of it"
        )

    import contextlib

    import jax
    import jax.numpy as jnp

    from bpe_transformer_tpu.checkpointing import load_checkpoint, save_checkpoint
    from bpe_transformer_tpu.models import init_params
    from bpe_transformer_tpu.optim import adamw_init
    from bpe_transformer_tpu.training.train_step import (
        TrainHParams,
        make_eval_step,
        make_scanned_train_step,
        make_train_step,
    )

    tokens = phase_data()
    train_toks, val_toks = split_tokens(tokens)
    schedule = batch_schedule(len(train_toks))
    cfg = model_config()
    device = jax.devices()[0]

    precision_ctx = (
        contextlib.nullcontext()
        if native
        else jax.default_matmul_precision("highest")
    )
    with precision_ctx:
        if native:
            step = make_scanned_train_step(cfg, TrainHParams(), EVAL_EVERY)
        else:
            step = make_train_step(cfg, TrainHParams())
        ev = make_eval_step(cfg)

        if not native and not ckpt_path.exists() and LEGACY_CKPT.exists():
            import shutil  # move, not rename: /tmp and the repo can be
                           # different filesystems (rename would EXDEV)
            ckpt_path.parent.mkdir(parents=True, exist_ok=True)
            shutil.move(str(LEGACY_CKPT), str(ckpt_path))
            print(f"migrated legacy checkpoint {LEGACY_CKPT} -> {ckpt_path}", file=sys.stderr)
        if ckpt_path.exists():
            payload = load_checkpoint(ckpt_path)
            ckpt_platform = payload["extra"].get("platform")
            ckpt_steps = payload["extra"].get("steps")
            ckpt_variant = payload["extra"].get("variant", "parity")
            if (
                ckpt_platform != device.platform
                or ckpt_steps != STEPS
                or ckpt_variant != variant
            ):
                # An interrupted --allow-cpu smoke must not seed the real
                # on-chip run (the capture would claim a trajectory trained
                # mostly on the wrong substrate), and a checkpoint from a
                # different-length protocol must not shortcut this one (a
                # stale iteration >= STEPS would skip training entirely and
                # write an inconsistent artifact); restart from scratch.
                print(
                    f"checkpoint is platform={ckpt_platform!r} steps={ckpt_steps!r} "
                    f"variant={ckpt_variant!r}; this run is "
                    f"platform={device.platform!r} steps={STEPS} variant={variant!r}; "
                    "discarding and starting fresh",
                    file=sys.stderr,
                )
                ckpt_path.unlink()
                payload = None
        else:
            payload = None
        if payload is not None:
            params, opt_state = payload["params"], payload["opt_state"]
            start_step = payload["iteration"]
            curve = payload["extra"]["curve"]
            train_s = payload["extra"]["train_s"]
            print(f"resuming from step {start_step}", file=sys.stderr)
        else:
            params = init_params(jax.random.PRNGKey(0), cfg)
            opt_state = adamw_init(params)
            start_step, curve, train_s = 0, [], 0.0

        def val_loss():
            losses = [
                float(ev(params, jnp.asarray(x), jnp.asarray(y)))
                for x, y in val_batches(val_toks)
            ]
            return sum(losses) / len(losses)

        def checkpoint(done_step: int) -> None:
            ckpt_path.parent.mkdir(parents=True, exist_ok=True)
            save_checkpoint(
                ckpt_path,
                params=params,
                opt_state=opt_state,
                iteration=done_step,
                extra={
                    "curve": curve,
                    "train_s": train_s,
                    "platform": device.platform,
                    "steps": STEPS,
                    "variant": variant,
                },
            )

        if native:
            # AOT-compile the scanned step OUTSIDE the timed loop (bench.py's
            # warmup discipline — the torch side pays no compile, so compile
            # time must not pollute the tokens/sec comparison).  lower() +
            # compile() never executes, so no donation or update happens.
            batch_aval = jax.ShapeDtypeStruct((EVAL_EVERY, BATCH, SEQ), jnp.int32)
            step = step.lower(params, opt_state, batch_aval, batch_aval).compile()
            # One dispatch per eval block: the EVAL_EVERY pre-drawn batches
            # are stacked (inner, B, S) and scanned on-device.  A resumed
            # run restarts at the block boundary its checkpoint recorded.
            for block_start in range(start_step, STEPS, EVAL_EVERY):
                xs, ys = zip(
                    *(
                        gather_batch(train_toks, schedule[i])
                        for i in range(block_start, block_start + EVAL_EVERY)
                    )
                )
                xs, ys = np.stack(xs), np.stack(ys)
                t0 = time.perf_counter()
                params, opt_state, m = step(
                    params, opt_state, jnp.asarray(xs), jnp.asarray(ys)
                )
                loss = float(jax.device_get(m["loss"]))  # execution barrier
                train_s += time.perf_counter() - t0
                done = block_start + EVAL_EVERY
                curve.append({"step": done, "train_loss": loss, "val_loss": val_loss()})
                print(f"jax step {done}: {curve[-1]}", file=sys.stderr)
                checkpoint(done)
        else:
            for i in range(start_step, STEPS):
                x, y = gather_batch(train_toks, schedule[i])
                t0 = time.perf_counter()
                params, opt_state, m = step(params, opt_state, jnp.asarray(x), jnp.asarray(y))
                loss = float(jax.device_get(m["loss"]))  # execution barrier
                train_s += time.perf_counter() - t0
                if (i + 1) % EVAL_EVERY == 0 or i == STEPS - 1:
                    curve.append({"step": i + 1, "train_loss": loss, "val_loss": val_loss()})
                    print(f"jax step {i + 1}: {curve[-1]}", file=sys.stderr)
                    checkpoint(i + 1)

    jax_tps = STEPS * BATCH * SEQ / train_s
    final_val = curve[-1]["val_loss"]
    result = {
        "metric": "north star: reference val loss on-accel at >=10x torch-CPU tok/s",
        "config": torch_ref["config"],
        "corpus": CORPUS,
        "steps": STEPS,
        "platform": device.platform,
        "device": str(device),
        "variant": variant,
        "precision": (
            "f32, TPU-default matmul precision (single-pass bf16 MXU), "
            f"{EVAL_EVERY} steps per scanned dispatch"
            if native
            else "f32, matmul precision=highest (parity with the torch-f32 oracle)"
        ),
        "steps_per_dispatch": EVAL_EVERY if native else 1,
        "curve": curve,
        "final_val_loss": {"jax": final_val, "torch_cpu": torch_ref["final_val_loss"]},
        "reached_reference": final_val <= torch_ref["final_val_loss"] + VAL_TOLERANCE,
        "reference_tolerance": VAL_TOLERANCE,
        "val_loss_delta_vs_torch": round(final_val - torch_ref["final_val_loss"], 4),
        "tokens_per_sec": {
            "jax": round(jax_tps, 1),
            "torch_cpu": torch_ref["tokens_per_sec"],
        },
        "speedup": round(jax_tps / torch_ref["tokens_per_sec"], 2),
        "captured_at_utc": time.strftime("%Y-%m-%dT%H:%M:%S+00:00", time.gmtime()),
    }
    # Self-describing artifact: embed the run manifest (git SHA, jax/device
    # versions, host) — best-effort down to the import, never at the cost
    # of the measurement.
    try:
        from bpe_transformer_tpu.telemetry.manifest import attach_manifest

        attach_manifest(
            result, kind="northstar", model_config=cfg, extra={"variant": variant}
        )
    except Exception as exc:
        print(f"manifest attach failed: {exc!r}", file=sys.stderr)
    capture_path.parent.mkdir(parents=True, exist_ok=True)
    _write_json(capture_path, result)
    print(json.dumps({k: result[k] for k in (
        "platform", "variant", "final_val_loss", "reached_reference", "speedup")}))
    # The measurement is COMPLETE either way — the artifact records the
    # verdict honestly.  Exit 0 so the queue's done-marker stops re-runs
    # (a deterministic protocol would just reproduce the same result), and
    # clear the exhausted checkpoint so a deliberate re-run starts fresh.
    ckpt_path.unlink(missing_ok=True)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--phase", choices=["data", "torch", "jax"], default=None)
    ap.add_argument(
        "--variant", choices=["parity", "native"], default="parity",
        help="parity: matmul precision=highest, per-step dispatch (tracks "
        "the torch-f32 oracle).  native: TPU-default precision, "
        "EVAL_EVERY steps per scanned dispatch — the honest-throughput "
        "run; writes northstar_native.json",
    )
    ap.add_argument(
        "--allow-cpu", action="store_true",
        help="let --phase jax run on host CPU (smoke testing only; the "
        "committed capture then records platform=cpu and bench.py ignores it)",
    )
    args = ap.parse_args()
    if args.phase == "data":
        phase_data()
        return 0
    if args.phase == "torch":
        phase_torch()
        return 0
    if args.phase == "jax":
        return phase_jax(args.allow_cpu, args.variant)
    phase_torch()  # runs data implicitly; both cached
    return phase_jax(args.allow_cpu, args.variant)


if __name__ == "__main__":
    sys.exit(main())
