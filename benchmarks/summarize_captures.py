"""One-screen summary of every persisted TPU capture + queue artifact.

Reads benchmarks/captures/*.json (bench.py per-config captures,
northstar.json) and the attention/decode/breakdown/moe-dispatch JSONL
files, and prints a compact table per group — what's measured, when, and
at what knobs.  Pure host-side file reads: safe to run any time (no jax).

    python benchmarks/summarize_captures.py
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

CAP = Path(__file__).resolve().parent / "captures"


def _rows(path: Path):
    try:
        with path.open() as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        yield json.loads(line)
                    except json.JSONDecodeError:
                        pass
    except OSError:
        return


def _manifest_line(m: dict | None) -> str | None:
    """Compact provenance from a telemetry run-manifest record (the
    ``kind="manifest"`` header every training/benchmark stream now writes,
    also embedded as ``"manifest"`` in bench.py/northstar captures)."""
    if not m:
        return None
    parts = [f"git={str(m.get('git_sha'))[:12]}"]
    if m.get("jax_version"):
        parts.append(f"jax={m['jax_version']}")
    devices = m.get("devices") or {}
    if devices:
        parts.append(
            f"{devices.get('count', '?')}x{devices.get('kind', '?')}"
            f" ({devices.get('platform', '?')})"
        )
    if m.get("mesh"):
        parts.append(f"mesh={m['mesh']}")
    if m.get("parallel"):
        parts.append(f"parallel={m['parallel']}")
    if m.get("host"):
        parts.append(f"host={m['host']}")
    return "  ".join(parts)


def main() -> int:
    if not CAP.exists():
        print("no captures directory", file=sys.stderr)
        return 1

    print("== bench.py captures (tokens/sec/chip) ==")
    for p in sorted(CAP.glob("tpu_capture_*.json")):
        try:
            c = json.loads(p.read_text())
        except (OSError, json.JSONDecodeError) as exc:
            print(f"  {p.name}: unreadable ({exc!r})")
            continue
        knobs = [f"att={c.get('attention_impl', '?')}"]
        for key in ("ffn_impl", "moe_dispatch"):
            if c.get(key) not in (None, "xla", "einsum"):
                knobs.append(f"{key}={c[key]}")
        policy = c.get("remat_policy") or ("full" if c.get("remat") else None)
        if policy and policy != "none":
            knobs.append(f"remat={policy}")
        if c.get("scan_layers"):
            knobs.append("scan_layers")
        if c.get("grads_dtype") not in (None, "float32"):
            knobs.append(f"grads={c['grads_dtype']}")
        print(
            f"  {p.name[12:-5]:28s} {c.get('value') or 0:>12,.0f} tok/s"
            f"  mfu={c.get('mfu')}  vs_torch={c.get('vs_baseline')}"
            f"  B={c.get('batch')} steps={c.get('measure_steps')}"
            # `or '?'` not a .get default: the key can be present with a JSON
            # null (ADVICE r4), and None[:16] would kill the whole summary.
            f"  @{(c.get('captured_at_utc') or '?')[:16]}  [{', '.join(knobs)}]"
        )
        provenance = _manifest_line(c.get("manifest"))
        if provenance:
            print(f"    {provenance}")

    ns = CAP / "northstar.json"
    print("== north star ==")
    if ns.exists():
        try:
            c = json.loads(ns.read_text())
            print(
                f"  platform={c.get('platform')}  "
                f"val jax={c['final_val_loss']['jax']:.4f} vs "
                f"torch={c['final_val_loss']['torch_cpu']:.4f}  "
                f"reached={c.get('reached_reference')}  "
                f"speedup={c.get('speedup')}x  @{(c.get('captured_at_utc') or '?')[:16]}"
            )
            provenance = _manifest_line(c.get("manifest"))
            if provenance:
                print(f"    {provenance}")
        except (OSError, json.JSONDecodeError, KeyError, TypeError) as exc:
            print(f"  unreadable ({exc!r})")
    else:
        print("  (not yet captured — torch half lives in northstar_torch.json)")

    for name, keys in (
        ("attention.jsonl", ("metric", "speedup", "speedup_bwd")),
        ("decode.jsonl", ("metric", "speedup")),
        ("moe_dispatch.jsonl", ("metric", "speedup")),
        ("breakdown.jsonl", ("stage", "ms", "config")),
        (
            "host_tokenization.jsonl",
            (
                "stage",
                "engine",
                "n_workers",
                "pretokens_per_s",
                "tokens_per_s",
                "speedup",
                # The trailing summary row carries the grid's provenance —
                # whether these are real multicore rows or a collapsed
                # single-core grid.
                "usable_cores",
                "captured_at_utc",
            ),
        ),
    ):
        path = CAP / name
        rows = list(_rows(path))
        # Unified-telemetry streams open with a run-manifest header (and may
        # close with a footer): surface the provenance once, keep the data
        # rows as before.
        manifests = [r for r in rows if r.get("kind") == "manifest"]
        rows = [r for r in rows if r.get("kind") not in ("manifest", "footer")]
        print(f"== {name} ({len(rows)} rows) ==")
        if manifests:
            print(f"    {_manifest_line(manifests[-1])}")
        # 20, not 12: a full multicore host-tokenization grid is 14+ rows
        # and truncating it would cut the python-engine rows the
        # native-vs-python comparison needs (review r5).
        for r in rows[-20:]:
            print("  " + "  ".join(f"{k}={r.get(k)}" for k in keys if k in r))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `... | head` closing early is fine
        sys.exit(0)
