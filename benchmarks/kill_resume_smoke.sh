#!/bin/bash
# Kill-resume smoke (resilience layer): SIGTERM a short training run midway
# and assert, ON THE REAL CHIP, the two halves of the preemption story the
# CPU chaos suite (tests/test_resilience.py) pins functionally:
#   1. the run exits with the distinct preemption code (75, EX_TEMPFAIL)
#      after writing a verifiable emergency checkpoint + a kind="preemption"
#      record;
#   2. a --resume run continues from that checkpoint and completes with
#      exit 0.
# Emits one JSON verdict line on stdout (tpu_queue.sh appends it to the
# job's outfile); any assertion failure exits nonzero so the queue marks
# the job failed instead of recording a hollow pass.
set -u
WORK=$(mktemp -d /tmp/kill_resume.XXXXXX)
trap 'rm -rf "$WORK"' EXIT
cd "$(dirname "$0")/.."

python - "$WORK" <<'EOF'
import sys
import numpy as np
from pathlib import Path
work = Path(sys.argv[1])
np.tile(np.arange(256, dtype=np.uint16), 2000).tofile(work / "tokens.bin")
EOF

TRAIN=(python -m bpe_transformer_tpu.training.cli train
  --data "$WORK/tokens.bin" --preset ts-test
  --steps 200000 --batch-size 8 --log-every 20 --eval-every 1000000
  --checkpoint-every 1000 --checkpoint-dir "$WORK/ckpt"
  --metrics-jsonl "$WORK/metrics.jsonl" --warmup 5)

"${TRAIN[@]}" > "$WORK/train.log" 2>&1 &
pid=$!
# Wait for a few logged windows so the SIGTERM lands mid-run, post-compile.
for _ in $(seq 1 120); do
  [ -e "$WORK/metrics.jsonl" ] && \
    [ "$(wc -l < "$WORK/metrics.jsonl")" -ge 6 ] && break
  kill -0 "$pid" 2>/dev/null || break
  sleep 1
done
kill -TERM "$pid" 2>/dev/null
wait "$pid"
rc=$?
if [ "$rc" -ne 75 ]; then
  echo "kill_resume: expected preemption exit 75, got $rc" >&2
  tail -5 "$WORK/train.log" >&2
  exit 1
fi
# The emergency checkpoint must verify (jax-free checksum pass).
python -m bpe_transformer_tpu.resilience.integrity "$WORK/ckpt/latest.ckpt" \
  >&2 || exit 1

# Resume to a nearby step and require a clean finish.
stop_step=$(python - "$WORK" <<'EOF'
import json, sys
from pathlib import Path
records = [json.loads(l) for l in (Path(sys.argv[1]) / "metrics.jsonl").open()]
print(next(r["step"] for r in records if r.get("kind") == "preemption"))
EOF
)
resume_steps=$((stop_step + 100))
python -m bpe_transformer_tpu.training.cli train \
  --data "$WORK/tokens.bin" --preset ts-test \
  --steps "$resume_steps" --batch-size 8 --log-every 20 --eval-every 1000000 \
  --checkpoint-every 1000 --checkpoint-dir "$WORK/ckpt" \
  --metrics-jsonl "$WORK/metrics.jsonl" --warmup 5 \
  --resume "$WORK/ckpt" > "$WORK/resume.log" 2>&1
rrc=$?
if [ "$rrc" -ne 0 ]; then
  echo "kill_resume: resume run failed (exit $rrc)" >&2
  tail -5 "$WORK/resume.log" >&2
  exit 1
fi
python - "$WORK" "$stop_step" "$resume_steps" <<'EOF'
import json, sys
from pathlib import Path
work, stop_step, resume_steps = Path(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
records = [json.loads(l) for l in (work / "metrics.jsonl").open()]
steps = [r["step"] for r in records if "kind" not in r and "loss" in r]
assert max(steps) == resume_steps, (max(steps), resume_steps)
print(json.dumps({
    "job": "kill_resume",
    "preempt_exit": 75,
    "stopped_at_step": stop_step,
    "resumed_to_step": resume_steps,
    "recovered": True,
}))
EOF
