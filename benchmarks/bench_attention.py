"""BASELINE config 4: Pallas fused attention vs XLA baseline at long seq.

Run on a TPU host:  python benchmarks/bench_attention.py
Prints one JSON line per sequence length with both timings and the speedup.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

from bpe_transformer_tpu.kernels.pallas.flash_attention import (
    _xla_attention,
    flash_attention,
    flash_attention_with_rope,
)
from bpe_transformer_tpu.ops.rope import apply_rope, rope_tables

BATCH, HEADS, D_HEAD = 1, 8, 64
SEQ_LENS = (1024, 4096, 16384)
ITERS = 20


def _sync(x) -> float:
    # Value fetch: the only reliable barrier on relayed remote backends.
    return float(jax.device_get(x.reshape(-1)[0]))


def _bench(fn, *args) -> float:
    jitted = jax.jit(fn)
    _sync(jitted(*args))
    start = time.perf_counter()
    out = None
    for _ in range(ITERS):
        out = jitted(*args)
    _sync(out)
    return (time.perf_counter() - start) / ITERS


def main() -> int:
    rng = np.random.default_rng(0)
    cos, sin = rope_tables(D_HEAD, max(SEQ_LENS))
    on_tpu = jax.default_backend() == "tpu"

    for seq in SEQ_LENS:
        shape = (BATCH, HEADS, seq, D_HEAD)
        q, k, v = (
            jnp.asarray(rng.standard_normal(shape), dtype=jnp.bfloat16)
            for _ in range(3)
        )
        pos = jnp.arange(seq)[None, None, :]

        def roped(attn):
            def fn(q, k, v):
                c, s = cos.astype(q.dtype), sin.astype(q.dtype)
                return attn(apply_rope(q, pos, c, s), apply_rope(k, pos, c, s), v)

            return fn

        cos_s, sin_s = cos[:seq], sin[:seq]
        t_xla = _bench(roped(lambda q, k, v: _xla_attention(q, k, v, True)), q, k, v)
        t_flash = _bench(
            roped(
                lambda q, k, v: flash_attention(q, k, v, True, 512, 512, not on_tpu)
            ),
            q, k, v,
        )
        t_fused = _bench(
            lambda q, k, v: flash_attention_with_rope(
                q, k, v, cos_s, sin_s, True, 512, 512, not on_tpu
            ),
            q, k, v,
        )
        print(
            json.dumps(
                {
                    "metric": f"rope+causal_attention seq={seq} (B=1,H=8,D=64,bf16)",
                    "xla_ms": round(t_xla * 1e3, 3),
                    "pallas_ms": round(t_flash * 1e3, 3),
                    "pallas_fused_rope_ms": round(t_fused * 1e3, 3),
                    "speedup": round(t_xla / t_flash, 2),
                    "speedup_fused": round(t_xla / t_fused, 2),
                    "device": str(jax.devices()[0]),
                }
            )
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
