"""BASELINE config 4: Pallas fused attention vs XLA baseline at long seq.

Run on a TPU host:  python benchmarks/bench_attention.py
Prints one JSON line per sequence length with both timings and the speedup.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from _accel import require_accelerator  # noqa: E402  (benchmarks/_accel.py)

import numpy as np

import jax
import jax.numpy as jnp

from bpe_transformer_tpu.kernels.pallas.flash_attention import (
    flash_attention,
    flash_attention_with_rope,
)
from bpe_transformer_tpu.ops.core import causal_mask, scaled_dot_product_attention
from bpe_transformer_tpu.ops.rope import apply_rope, rope_tables


def _xla_baseline(q, k, v, causal):
    """The model's OWN attention_impl="xla" math (ops/core.py): compute-
    dtype matmuls, f32 softmax.  The f32-upcast parity oracle
    (kernels/pallas/flash_attention._xla_attention) is NOT a fair speed
    baseline — f32 matmuls run the MXU at ~1/4 rate."""
    mask = causal_mask(q.shape[-2]) if causal else None
    return scaled_dot_product_attention(q, k, v, mask)

BATCH, HEADS, D_HEAD = 1, 8, 64
# Override with e.g. `--seq 16384` to split long runs across invocations;
# `--batch 8 --heads 12` measures a training-shaped grid (the default B=1
# cells are latency-dominated at short seq and noisy between runs).
SEQ_LENS = (1024, 4096, 16384)


def _sync(x) -> float:
    # Value fetch: the only reliable barrier on relayed remote backends.
    return float(jax.device_get(x.reshape(-1)[0]))


def _bench(fn, *args, label: str = "", iters: int = 10) -> float | None:
    """Mean seconds/call, or None when the case can't run (e.g. the XLA
    materialized path OOMing at seq 16k — which is the point of flash)."""
    try:
        jitted = jax.jit(fn)
        t_compile = time.perf_counter()
        _sync(jitted(*args))
        print(
            f"  {label}: compiled+first-run in "
            f"{time.perf_counter() - t_compile:.1f}s",
            file=sys.stderr,
            flush=True,
        )
        start = time.perf_counter()
        out = None
        for _ in range(iters):
            out = jitted(*args)
        _sync(out)
        return (time.perf_counter() - start) / iters
    except Exception as exc:  # noqa: BLE001 - report the case as absent
        print(f"case failed: {exc!r}"[:300], file=sys.stderr)
        return None


def _ms(t: float | None):
    return round(t * 1e3, 3) if t is not None else None


def _ratio(a: float | None, b: float | None):
    return round(a / b, 2) if a and b else None




def main() -> int:
    require_accelerator(Path(__file__).stem)
    seq_lens = SEQ_LENS
    if "--seq" in sys.argv:
        arg = sys.argv[sys.argv.index("--seq") + 1]
        seq_lens = tuple(int(s) for s in arg.split(","))
    batch, heads = BATCH, HEADS
    if "--batch" in sys.argv:
        batch = int(sys.argv[sys.argv.index("--batch") + 1])
    if "--heads" in sys.argv:
        heads = int(sys.argv[sys.argv.index("--heads") + 1])

    rng = np.random.default_rng(0)
    cos, sin = rope_tables(D_HEAD, max(seq_lens))
    on_tpu = jax.default_backend() == "tpu"

    for seq in seq_lens:
        shape = (batch, heads, seq, D_HEAD)
        q, k, v = (
            jnp.asarray(rng.standard_normal(shape), dtype=jnp.bfloat16)
            for _ in range(3)
        )
        pos = jnp.arange(seq)[None, None, :]

        def roped(attn):
            def fn(q, k, v):
                c, s = cos.astype(q.dtype), sin.astype(q.dtype)
                return attn(apply_rope(q, pos, c, s), apply_rope(k, pos, c, s), v)

            return fn

        cos_s, sin_s = cos[:seq], sin[:seq]
        iters = 10 if seq < 16384 else 3
        t_xla = _bench(
            roped(lambda q, k, v: _xla_baseline(q, k, v, True)), q, k, v,
            label=f"xla_fwd@{seq}", iters=iters,
        )
        t_flash = _bench(
            roped(
                lambda q, k, v: flash_attention(q, k, v, True, 512, 512, not on_tpu)
            ),
            q, k, v,
            label=f"flash_fwd@{seq}", iters=iters,
        )
        t_fused = _bench(
            lambda q, k, v: flash_attention_with_rope(
                q, k, v, cos_s, sin_s, True, 512, 512, not on_tpu
            ),
            q, k, v,
            label=f"fused_fwd@{seq}", iters=iters,
        )

        # Backward (training) path: grad of a scalar through attention.
        # The Pallas backward recomputes score blocks in-kernel, so peak
        # memory stays O(S) per row — the XLA backward materializes the
        # (S, S) probability matrix and its cotangent.
        def grad_of(attn):
            g = jax.grad(
                lambda q, k, v: attn(q, k, v).astype(jnp.float32).sum(),
                argnums=(0, 1, 2),
            )

            # Reduce ALL THREE grads into the timed output: syncing only dq
            # would let jit dead-code-eliminate the XLA path's separate
            # dk/dv einsums while the monolithic Pallas backward kernel
            # still computes everything — biasing the comparison.
            def timed(*a):
                dq, dk, dv = g(*a)
                return (
                    dq.astype(jnp.float32).mean()
                    + dk.astype(jnp.float32).mean()
                    + dv.astype(jnp.float32).mean()
                )

            return timed

        t_xla_bwd = _bench(
            grad_of(roped(lambda q, k, v: _xla_baseline(q, k, v, True))),
            q, k, v,
            label=f"xla_bwd@{seq}", iters=iters,
        )
        t_flash_bwd = _bench(
            grad_of(
                roped(
                    lambda q, k, v: flash_attention(
                        q, k, v, True, 512, 512, not on_tpu
                    )
                )
            ),
            q, k, v,
            label=f"flash_bwd@{seq}", iters=iters,
        )
        t_fused_bwd = _bench(
            grad_of(
                lambda q, k, v: flash_attention_with_rope(
                    q, k, v, cos_s, sin_s, True, 512, 512, not on_tpu
                )
            ),
            q, k, v,
            label=f"fused_bwd@{seq}", iters=iters,
        )
        print(
            json.dumps(
                {
                    "metric": f"rope+causal_attention seq={seq} "
                    f"(B={batch},H={heads},D=64,bf16)",
                    "xla_ms": _ms(t_xla),
                    "pallas_ms": _ms(t_flash),
                    "pallas_fused_rope_ms": _ms(t_fused),
                    "speedup": _ratio(t_xla, t_flash),
                    "speedup_fused": _ratio(t_xla, t_fused),
                    "xla_bwd_ms": _ms(t_xla_bwd),
                    "pallas_bwd_ms": _ms(t_flash_bwd),
                    "pallas_fused_rope_bwd_ms": _ms(t_fused_bwd),
                    "speedup_bwd": _ratio(t_xla_bwd, t_flash_bwd),
                    "speedup_bwd_fused": _ratio(t_xla_bwd, t_fused_bwd),
                    "device": str(jax.devices()[0]),
                }
            ),
            flush=True,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
