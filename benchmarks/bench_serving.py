"""Serving-engine throughput/latency: closed-loop concurrency sweeps and
an open-loop (target-QPS) load generator.

Two modes, one JSON row per cell:

* **closed loop** (default): for each ``--concurrency`` level the engine
  serves a fixed request load (ragged prompt lengths, shared token
  budget) and reports aggregate generated tokens/sec plus p50/p95/p99
  request latency — the tradeoff curve capacity planning reads.
* **open loop** (``--qps F``): requests arrive on a Poisson schedule at
  the target rate regardless of completions — the arrival process real
  traffic has — with an optional shared system prefix
  (``--shared-prefix-len N`` tokens on ``--shared-prefix-frac`` of
  requests).  Rows carry p50/p95/p99 end-to-end latency, achieved QPS,
  and — for the paged engine — the prefix-cache hit rate and prefill
  compute seconds, so the paged-vs-dense comparison ("prefix sharing
  buys X% of prefill back") is one jax-free diff of two rows.

``--paged`` switches the engine to the block-pool KV cache
(`serving/kvpool/`): radix prefix sharing + chunked prefill
(``--prefill-chunk``/``--prefill-budget``); ``--decode-attention paged``
runs the block-pool-NATIVE flash-decode kernel (no per-tick gather
transient) and ``--kv-dtype int8`` the quantized pool — rows carry
``kv_pool_bytes``/``kv_bytes_per_token`` so the memory-traffic claims
are machine-checkable.  Warmup (compilation of the configured ladder +
tick) happens before timing in both modes, so cells measure steady-state
serving, not XLA.

A third mode, ``--restart``, times restart-to-traffic (ROADMAP item 5):
a serve replica from process spawn to first token THROUGH the router's
rejoin path, cold versus ``bpe-tpu warmup``-warmed compile cache — one
JSON row with ``cold_s``/``warm_s``/``warmup_s``.

Run on a TPU host:  python benchmarks/bench_serving.py [--qps 8 --paged]
Prints one JSON line per cell.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from _accel import require_accelerator  # noqa: E402  (benchmarks/_accel.py)

import numpy as np

import jax

CONFIGS = {
    "tinystories-4l": "TINYSTORIES_4L",
    "gpt2-small-32k": "GPT2_SMALL_32K",
}


def _pctl(values, q):
    ordered = sorted(values)
    return ordered[min(len(ordered) - 1, max(0, round(q * len(ordered)) - 1))]


def _make_engine(params, config, *, concurrency, n_requests, args):
    from bpe_transformer_tpu.serving import ServingEngine

    draft_spec = None
    if args.speculate:
        from bpe_transformer_tpu.serving import DraftSpec

        draft_spec = DraftSpec(truncate_layers=args.draft_layers)
    return ServingEngine(
        params, config, slots=concurrency, max_queue=n_requests + 1,
        paged=args.paged, block_size=args.block_size,
        prefill_chunk=args.prefill_chunk,
        prefill_token_budget=args.prefill_budget,
        kv_dtype=None if args.kv_dtype == "act" else args.kv_dtype,
        weight_dtype=(
            None if args.weight_dtype == "act" else args.weight_dtype
        ),
        fused_sampling=args.fused_sampling,
        speculate_k=args.speculate, draft_spec=draft_spec,
    )


def _warmup(serving, config):
    """One request per distinct bucket + the tick program, so timed cells
    measure steady-state serving rather than XLA.  Prompts are DISTINCT
    per bucket: identical ones would share a radix-cache prefix on the
    paged engine, shrinking later rungs' chunks into already-compiled
    programs and leaving their cold compile inside the timed cell.
    Returns a post-warmup stats snapshot so row fields can be reported as
    deltas (warmup traffic must not pollute hit-rate/compute evidence)."""
    ctx = config.context_length
    vocab = config.vocab_size
    for b in serving.engine.buckets:
        plen = min(b, ctx - 2)
        serving.generate([(17 * b + i) % vocab for i in range(plen)],
                         max_new_tokens=2, temperature=0.0, timeout=600)
    return serving.stats()


def _parse_prompt_mix(spec: str) -> tuple[int, int, float]:
    """``--prompt-mix SHORT,LONG,LONG_FRAC`` (e.g. ``12,160,0.25``):
    bimodal prompt lengths — the disaggregated-serving workload, where a
    minority of long prompts is exactly what blows a monolithic
    replica's decode p99."""
    parts = spec.split(",")
    if len(parts) != 3:
        raise ValueError(
            f"--prompt-mix wants SHORT,LONG,LONG_FRAC, got {spec!r}"
        )
    short, long_, frac = int(parts[0]), int(parts[1]), float(parts[2])
    if short < 1 or long_ <= short or not 0.0 < frac < 1.0:
        raise ValueError(
            f"--prompt-mix needs 1 <= SHORT < LONG and 0 < LONG_FRAC < 1, "
            f"got {spec!r}"
        )
    return short, long_, frac


def _prompts_mix(rng, config, *, n_requests, new_tokens, short, long_, frac):
    """Bimodal prompts: ``frac`` of requests at ~``long_`` tokens, the
    rest at ~``short`` (±25% jitter so bucket ladders stay honest).
    Returns ``(prompts, is_long flags)``."""
    ctx = config.context_length
    vocab = config.vocab_size
    cap = max(ctx - new_tokens - 1, 2)
    prompts, is_long = [], []
    for _ in range(n_requests):
        lng = rng.random() < frac
        base = long_ if lng else short
        n = int(rng.integers(max(1, (3 * base) // 4), (5 * base) // 4 + 1))
        prompts.append(
            [int(t) for t in rng.integers(0, vocab, size=min(n, cap))]
        )
        is_long.append(lng)
    return prompts, is_long


def _bucket_fields(results, is_long) -> dict:
    """Per-bucket (short/long) and overall request + decode latency
    percentiles — the row evidence `serve_open_disagg` is judged on:
    disaggregation moves SHORT-bucket decode p99, which a monolithic mix
    lets long prefills stall."""
    out: dict = {}
    lat = [r.queue_wait_s + r.prefill_s + r.decode_s for r in results]
    dec = [r.decode_s for r in results]
    out["decode_p50_s"] = round(_pctl(dec, 0.50), 4)
    out["decode_p95_s"] = round(_pctl(dec, 0.95), 4)
    out["decode_p99_s"] = round(_pctl(dec, 0.99), 4)
    for label, flag in (("short", False), ("long", True)):
        sel = [i for i, lng in enumerate(is_long) if lng is flag]
        if not sel:
            continue
        for name, values in (
            ("latency", [lat[i] for i in sel]),
            ("decode", [dec[i] for i in sel]),
        ):
            for q, tag in ((0.50, "p50"), (0.95, "p95"), (0.99, "p99")):
                out[f"{label}_{name}_{tag}_s"] = round(
                    _pctl(values, q), 4
                )
        out[f"{label}_requests"] = len(sel)
    return out


def _prompts(rng, config, *, n_requests, new_tokens,
             shared_prefix_len=0, shared_prefix_frac=0.0):
    """Ragged prompts biased short (serving-shaped); a ``shared_prefix_len``
    system prefix rides the first ``shared_prefix_frac`` fraction of them
    (same tokens every time — the prefix-cache target)."""
    ctx = config.context_length
    vocab = config.vocab_size
    max_suffix = max(min(ctx - new_tokens - shared_prefix_len, 4 * 64), 9)
    prefix = [int(t) for t in rng.integers(0, vocab, size=shared_prefix_len)]
    prompts = []
    for i in range(n_requests):
        n = int(rng.integers(8, max_suffix))
        suffix = [int(t) for t in rng.integers(0, vocab, size=n)]
        if shared_prefix_len and i < shared_prefix_frac * n_requests:
            prompts.append(prefix + suffix)
        else:
            prompts.append(suffix)
    return prompts


def _prefill_compute_s(stats):
    return sum(
        work["seconds"]
        for work in stats.get("prefill_bucket_work", {}).values()
    )


def _paged_row_fields(serving, baseline):
    """Prefix-cache and prefill-compute evidence as DELTAS against the
    post-warmup ``baseline`` snapshot (warmup traffic excluded) —
    None-filled for the dense engine so rows stay diffable."""
    stats = serving.stats()
    hits = misses = rate = None
    if stats.get("prefix_cache_hits") is not None:
        hits = stats["prefix_cache_hits"] - baseline.get(
            "prefix_cache_hits", 0
        )
        misses = stats["prefix_cache_misses"] - baseline.get(
            "prefix_cache_misses", 0
        )
        rate = round(hits / (hits + misses), 6) if hits + misses else None
    out = {
        "engine": stats.get("engine_kind", "dense"),
        "prefill_compute_s": round(
            _prefill_compute_s(stats) - _prefill_compute_s(baseline), 4
        ),
        "prefix_hits": hits,
        "prefix_hit_rate": rate,
        "kv_blocks_free_end": stats.get("kv_blocks_free"),
        # KV-memory economics (ISSUE 9): the int8 win and the paged-native
        # kernel's traffic cut are judged against these row fields.
        "kv_dtype": stats.get("kv_dtype"),
        "kv_pool_bytes": stats.get("kv_pool_bytes"),
        "kv_bytes_per_token": stats.get("kv_bytes_per_token"),
        # Weight-quantization + fused-sampling evidence (ISSUE 11): the
        # per-tick weight sweep (int8 halves it vs bf16), the storage
        # width label, whether the tick tail ran fused, and the analytic
        # roofline's intensity/floor — machine-checkable next to the
        # compiled-program count the bounded-compile claim pins.
        "weight_dtype": stats.get("weight_dtype"),
        "params_bytes": stats.get("params_bytes"),
        "tick_weight_bytes": stats.get("tick_weight_bytes"),
        "fused_sampling": stats.get("fused_sampling"),
        "tick_arithmetic_intensity": (
            (stats.get("decode_roofline") or {}).get("arithmetic_intensity")
        ),
        "tick_projected_s": (
            (stats.get("decode_roofline") or {}).get("projected_tick_s")
        ),
        "decode_p95_s": stats["phase_p95_s"]["decode"],
    }
    if stats.get("spec_k") is not None:
        # Speculative-decoding evidence (ISSUE 10), warmup excluded: the
        # acceptance rate of the timed traffic, tokens emitted per target
        # verify pass (1.0 = non-speculative, k+1 = ceiling), and the
        # draft's share of spec-tick wall — the overhead acceptance pays.
        proposed = stats["spec_proposed_tokens"] - baseline.get(
            "spec_proposed_tokens", 0
        )
        accepted = stats["spec_accepted_tokens"] - baseline.get(
            "spec_accepted_tokens", 0
        )
        steps = stats["spec_target_steps"] - baseline.get(
            "spec_target_steps", 0
        )
        emitted = stats["spec_emitted_tokens"] - baseline.get(
            "spec_emitted_tokens", 0
        )
        draft_s = stats["spec_draft_time_s"] - baseline.get(
            "spec_draft_time_s", 0.0
        )
        tick_s = stats["spec_tick_time_s"] - baseline.get(
            "spec_tick_time_s", 0.0
        )
        out.update({
            "speculate_k": stats["spec_k"],
            "accept_rate": (
                round(accepted / proposed, 6) if proposed else None
            ),
            "tokens_per_target_step": (
                round(emitted / steps, 6) if steps else None
            ),
            "draft_overhead_frac": (
                round(draft_s / tick_s, 6) if tick_s > 0 else None
            ),
            "rewound_tokens": stats["spec_rewound_tokens"] - baseline.get(
                "spec_rewound_tokens", 0
            ),
        })
    return out


def run_cell(params, config, *, concurrency, n_requests, new_tokens, args,
             seed=0):
    """Closed loop: submit everything up front, the scheduler feeds slots."""
    from bpe_transformer_tpu.serving import Request

    rng = np.random.default_rng(seed)
    prompts = _prompts(
        rng, config, n_requests=n_requests, new_tokens=new_tokens,
        shared_prefix_len=args.shared_prefix_len,
        shared_prefix_frac=args.shared_prefix_frac,
    )

    with _make_engine(
        params, config, concurrency=concurrency, n_requests=n_requests,
        args=args,
    ) as serving:
        baseline = _warmup(serving, config)
        t0 = time.perf_counter()
        handles = [
            serving.submit(
                Request(
                    prompt_ids=tuple(p), max_new_tokens=new_tokens,
                    temperature=1.0, top_k=50, seed=i,
                )
            )
            for i, p in enumerate(prompts)
        ]
        results = [h.result(timeout=1800) for h in handles]
        wall = time.perf_counter() - t0
        latencies = [
            r.queue_wait_s + r.prefill_s + r.decode_s for r in results
        ]
        tokens = sum(len(r.token_ids) for r in results)
        compiled = serving.engine.compiled_programs()
        extra = _paged_row_fields(serving, baseline)

    return {
        "wall_s": round(wall, 3),
        "gen_tok_per_s": round(tokens / wall, 1),
        "latency_p50_s": round(_pctl(latencies, 0.50), 4),
        "latency_p95_s": round(_pctl(latencies, 0.95), 4),
        "latency_p99_s": round(_pctl(latencies, 0.99), 4),
        "compiled_programs": compiled,
        "requests": n_requests,
        "new_tokens": new_tokens,
        **extra,
    }


def run_open_loop(params, config, *, concurrency, n_requests, new_tokens,
                  qps, args, seed=0):
    """Open loop: Poisson arrivals at the target QPS — submissions never
    wait for completions, so queueing delay is measured, not hidden."""
    from bpe_transformer_tpu.serving import Request

    rng = np.random.default_rng(seed)
    prompts = _prompts(
        rng, config, n_requests=n_requests, new_tokens=new_tokens,
        shared_prefix_len=args.shared_prefix_len,
        shared_prefix_frac=args.shared_prefix_frac,
    )
    # The shared-prefix requests are interleaved with the rest (real mixes
    # are), not front-loaded: shuffle the submission order.
    order = rng.permutation(n_requests)
    arrivals = np.cumsum(rng.exponential(1.0 / qps, size=n_requests))

    with _make_engine(
        params, config, concurrency=concurrency, n_requests=n_requests,
        args=args,
    ) as serving:
        baseline = _warmup(serving, config)
        t0 = time.perf_counter()
        handles = []
        for arrival, idx in zip(arrivals, order):
            now = time.perf_counter() - t0
            if now < arrival:
                time.sleep(arrival - now)
            handles.append(
                serving.submit(
                    Request(
                        prompt_ids=tuple(prompts[int(idx)]),
                        max_new_tokens=new_tokens,
                        temperature=1.0, top_k=50, seed=int(idx),
                    )
                )
            )
        results = [h.result(timeout=1800) for h in handles]
        wall = time.perf_counter() - t0
        latencies = [
            r.queue_wait_s + r.prefill_s + r.decode_s for r in results
        ]
        tokens = sum(len(r.token_ids) for r in results)
        compiled = serving.engine.compiled_programs()
        extra = _paged_row_fields(serving, baseline)

    return {
        "wall_s": round(wall, 3),
        "qps_target": qps,
        "qps_achieved": round(n_requests / wall, 3),
        "gen_tok_per_s": round(tokens / wall, 1),
        "latency_p50_s": round(_pctl(latencies, 0.50), 4),
        "latency_p95_s": round(_pctl(latencies, 0.95), 4),
        "latency_p99_s": round(_pctl(latencies, 0.99), 4),
        "compiled_programs": compiled,
        "requests": n_requests,
        "new_tokens": new_tokens,
        "shared_prefix_len": args.shared_prefix_len,
        "shared_prefix_frac": args.shared_prefix_frac,
        **extra,
    }


def run_open_fleet(params, config, *, concurrency, n_requests, new_tokens,
                   qps, args, seed=0):
    """Open-loop Poisson arrivals against a TWO-ENGINE in-process fleet
    (equal engine count either way — the CPU smoke's stand-in for equal
    chips):

    * **monolithic** (default, ``--replicas 2``): requests round-robin
      across N ``role="both"`` engines — every replica's decode ticks
      share a worker loop with long-prompt prefills;
    * **disaggregated** (``--disagg``): one prefill-role engine + one
      decode-role engine wired through the real KV migration path —
      long prompts (>= ``--prefill-threshold``) prefill on the prefill
      engine, export as payload bytes, and graft onto the decode engine
      (`submit_import`); short prompts bypass straight to the decode
      engine.  The decode engine's ticks never wait behind a long
      prefill, which is the whole point: compare ``decode_p99_s`` (and
      the ``short_*`` bucket fields) across the two rows.

    ``--prompt-mix`` supplies the bimodal lengths; rows carry per-bucket
    p50/p95/p99 latency + decode fields.
    """
    import threading

    from bpe_transformer_tpu.serving import Request, ServingEngine

    short, long_, frac = _parse_prompt_mix(args.prompt_mix)
    threshold = args.prefill_threshold or (short + long_) // 2
    rng = np.random.default_rng(seed)
    prompts, is_long = _prompts_mix(
        rng, config, n_requests=n_requests, new_tokens=new_tokens,
        short=short, long_=long_, frac=frac,
    )
    arrivals = np.cumsum(rng.exponential(1.0 / qps, size=n_requests))

    def make(role):
        return ServingEngine(
            params, config, slots=concurrency, max_queue=n_requests + 1,
            paged=True, block_size=args.block_size,
            prefill_chunk=args.prefill_chunk,
            prefill_token_budget=args.prefill_budget,
            kv_dtype=None if args.kv_dtype == "act" else args.kv_dtype,
            weight_dtype=(
                None if args.weight_dtype == "act" else args.weight_dtype
            ),
            fused_sampling=args.fused_sampling,
            role=role,
        )

    if args.disagg:
        engines = [make("prefill"), make("decode")]
    else:
        engines = [make("both") for _ in range(args.replicas)]
    for engine in engines:
        engine.start()
    try:
        # Warm every engine's ladder so timed cells measure steady state
        # (the decode engine warms tick+import through a real migration).
        ctx = config.context_length
        vocab = config.vocab_size
        if args.disagg:
            pre, dec = engines
            for b in pre.engine.buckets:
                plen = min(b, ctx - new_tokens - 1)
                r = pre.generate(
                    [(13 * b + i) % vocab for i in range(plen)],
                    max_new_tokens=2, temperature=0.0, migrate=True,
                    timeout=600,
                )
                if r.kv_payload is not None:
                    dec.submit_import(r.kv_payload).result(timeout=600)
            for b in dec.engine.buckets:  # short prompts prefill here
                plen = min(b, ctx - new_tokens - 1)
                dec.generate(
                    [(29 * b + i) % vocab for i in range(plen)],
                    max_new_tokens=2, temperature=0.0, timeout=600,
                )
        else:
            for engine in engines:
                for b in engine.engine.buckets:
                    plen = min(b, ctx - new_tokens - 1)
                    engine.generate(
                        [(17 * b + i) % vocab for i in range(plen)],
                        max_new_tokens=2, temperature=0.0, timeout=600,
                    )

        results: list = [None] * n_requests
        errors: list = []

        def serve_one(i: int, t0: float):
            delay = arrivals[i] - (time.perf_counter() - t0)
            if delay > 0:
                time.sleep(delay)
            req = dict(
                max_new_tokens=new_tokens, temperature=1.0, top_k=50,
                seed=i,
            )
            try:
                if args.disagg:
                    pre, dec = engines
                    if len(prompts[i]) >= threshold:
                        r = pre.generate(
                            prompts[i], migrate=True, timeout=1800, **req
                        )
                        if r.finish_reason == "migrated":
                            r = dec.submit_import(r.kv_payload).result(
                                timeout=1800
                            )
                    else:
                        r = dec.generate(prompts[i], timeout=1800, **req)
                else:
                    r = engines[i % len(engines)].generate(
                        prompts[i], timeout=1800, **req
                    )
                results[i] = r
            except Exception as exc:  # noqa: BLE001 — the row reports it
                errors.append(repr(exc))

        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=serve_one, args=(i, t0), daemon=True)
            for i in range(n_requests)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=1900)
        wall = time.perf_counter() - t0
        done = [r for r in results if r is not None]
        flags = [f for r, f in zip(results, is_long) if r is not None]
        tokens = sum(len(r.token_ids) for r in done)
        lat = [r.queue_wait_s + r.prefill_s + r.decode_s for r in done]
        dec_stats = engines[-1].stats()
        migrations = sum(e.stats()["migrations_in"] for e in engines)
    finally:
        for engine in engines:
            engine.close()

    return {
        "wall_s": round(wall, 3),
        "qps_target": qps,
        "qps_achieved": round(len(done) / wall, 3) if wall else None,
        "gen_tok_per_s": round(tokens / wall, 1),
        "latency_p50_s": round(_pctl(lat, 0.50), 4),
        "latency_p95_s": round(_pctl(lat, 0.95), 4),
        "latency_p99_s": round(_pctl(lat, 0.99), 4),
        **_bucket_fields(done, flags),
        "requests": n_requests,
        "completed": len(done),
        "failed": n_requests - len(done),
        "new_tokens": new_tokens,
        "prompt_mix": args.prompt_mix,
        "prefill_threshold": threshold if args.disagg else None,
        "migrations": migrations,
        "engines": len(engines),
        "decode_compiled_programs": dec_stats["compiled_programs"],
    }


def _serve_flags(args) -> list:
    """The engine knobs forwarded to a `bpe-tpu serve` / `bpe-tpu warmup`
    subprocess (restart bench), mirroring what _make_engine builds
    in-process."""
    flags = []
    if args.paged:
        flags += ["--paged", "--block-size", str(args.block_size)]
        if args.prefill_chunk:
            flags += ["--prefill-chunk", str(args.prefill_chunk)]
        if args.kv_dtype != "act":
            flags += ["--kv-dtype", args.kv_dtype]
    if args.decode_attention:
        flags += ["--decode-attention", args.decode_attention]
    if args.weight_dtype != "act":
        flags += ["--weight-dtype", args.weight_dtype]
    if args.fused_sampling:
        flags += ["--fused-sampling"]
    return flags


def run_restart(args) -> dict:
    """Restart-to-traffic (ROADMAP item 5): time a replica from SPAWN to
    first token served THROUGH the router's rejoin path, cold (empty
    compile cache) vs `bpe-tpu warmup`-warmed — the rolling-deploy number
    a fleet operator actually waits on.  The parent stays on CPU (jax
    init would hold the accelerator the child serve needs); the router is
    the in-process jax-free `serving.router.Router` driven by hand."""
    import dataclasses
    import os
    import pickle
    import shutil
    import signal
    import subprocess
    import tempfile

    child_jax_platforms = os.environ.get("JAX_PLATFORMS")
    os.environ["JAX_PLATFORMS"] = "cpu"  # parent: params init only

    import jax as _jax

    import bpe_transformer_tpu.models as models
    from bpe_transformer_tpu.checkpointing import save_checkpoint
    from bpe_transformer_tpu.models import init_params
    from bpe_transformer_tpu.serving.router import Router

    config = getattr(models, CONFIGS[args.config])
    workdir = Path(tempfile.mkdtemp(prefix="bpe_restart_"))
    procs: list = []
    try:
        ckpt = workdir / "model.ckpt"
        save_checkpoint(
            ckpt,
            params=init_params(_jax.random.PRNGKey(0), config),
            extra={"model_config": dataclasses.asdict(config)},
        )
        tok_dir = workdir / "tok"
        tok_dir.mkdir()
        with open(tok_dir / "vocab.pkl", "wb") as f:
            pickle.dump({i: bytes([i]) for i in range(256)}, f)
        with open(tok_dir / "merges.pkl", "wb") as f:
            pickle.dump([], f)
        cache_dir = workdir / "xla_cache"

        import socket

        sock = socket.socket()
        sock.bind(("127.0.0.1", 0))
        port = sock.getsockname()[1]
        sock.close()

        child_env = dict(os.environ)
        if child_jax_platforms is None:
            child_env.pop("JAX_PLATFORMS", None)
        else:
            child_env["JAX_PLATFORMS"] = child_jax_platforms
        child_env["PYTHONPATH"] = str(Path(__file__).resolve().parent.parent)

        base_cmd = [
            sys.executable, "-m", "bpe_transformer_tpu.training.cli",
            "serve",
            "--checkpoint", str(ckpt),
            "--tokenizer-dir", str(tok_dir),
            "--port", str(port),
            "--slots", "2",
            "--max-new-tokens", "4",
        ] + _serve_flags(args)

        def spawn(extra):
            proc = subprocess.Popen(
                base_cmd + extra, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL, env=child_env,
            )
            procs.append(proc)
            return proc

        def time_to_first_token(extra, timeout_s=900.0):
            """Spawn the replica and drive the router by hand until a
            generate lands: the router marks the (absent) replica down,
            sees it rejoin via /statusz polls, and the first 200 is
            first-token time — exactly a rolling restart's window."""
            router = Router(
                [f"http://127.0.0.1:{port}"],
                poll_timeout_s=2.0, connect_timeout_s=2.0,
                request_timeout_s=600.0,
            )
            body = json.dumps(
                {"prompt_ids": [5, 6, 7, 8, 9, 10, 11],
                 "max_new_tokens": 4, "temperature": 0.0}
            ).encode()
            t0 = time.perf_counter()
            proc = spawn(extra)
            deadline = t0 + timeout_s
            while time.perf_counter() < deadline:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"replica exited rc={proc.returncode} before "
                        "serving"
                    )
                router.poll_once()
                if any(r.available for r in router.replicas):
                    code, _payload = router.handle_generate(body)
                    if code == 200:
                        return time.perf_counter() - t0, proc
                time.sleep(0.2)
            raise RuntimeError(f"no first token within {timeout_s}s")

        def stop(proc):
            proc.send_signal(signal.SIGTERM)  # serve drains gracefully
            try:
                proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)

        cold_s, proc = time_to_first_token([])
        stop(proc)

        t0 = time.perf_counter()
        warm_proc = subprocess.run(
            [
                sys.executable, "-m", "bpe_transformer_tpu.training.cli",
                "warmup",
                "--compile-cache", str(cache_dir),
                "--checkpoint", str(ckpt),
                "--slots", "2",
            ] + _serve_flags(args)
            + (["--kv-dtype", args.kv_dtype] if args.paged
               and args.kv_dtype == "act" else []),
            capture_output=True, text=True, env=child_env, timeout=1200,
        )
        warmup_s = time.perf_counter() - t0
        if warm_proc.returncode != 0:
            raise RuntimeError(f"warmup failed: {warm_proc.stderr[-500:]}")
        warm_summary = json.loads(
            warm_proc.stdout.strip().splitlines()[-1]
        )

        warm_s, proc = time_to_first_token(
            ["--compile-cache", str(cache_dir)]
        )
        stop(proc)
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()
        shutil.rmtree(workdir, ignore_errors=True)

    return {
        "cold_s": round(cold_s, 3),
        "warm_s": round(warm_s, 3),
        "warmup_s": round(warmup_s, 3),
        "speedup": round(cold_s / warm_s, 3) if warm_s else None,
        "programs_warmed": warm_summary.get("programs_compiled"),
        "engine": "paged" if args.paged else "dense",
        "decode_attention": args.decode_attention or "xla",
        "kv_dtype": args.kv_dtype if args.paged else None,
        "weight_dtype": args.weight_dtype,
        "fused_sampling": args.fused_sampling,
    }


#: Diurnal ramp (``--controller``/``--controller-static``): arrival-rate
#: factor on --qps and long-prompt fraction per phase — overnight lull,
#: a long-prompt-heavy peak, then a cooldown.  The shifting mix is what
#: drives the controller's threshold retune; the rate ramp is what
#: drives elastic scale-up.
RAMP_PHASES = (
    ("night", 0.5, 0.10),
    ("peak", 2.0, 0.40),
    ("cool", 1.0, 0.20),
)


def _free_port() -> int:
    import socket

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    return port


def run_controller_ramp(args) -> dict:
    """Diurnal-ramp fleet bench (ISSUE 20): the same Poisson ramp with a
    shifting prompt mix served by a real subprocess fleet — one
    always-on role=both replica, one prefill-tier replica, and one
    ELASTIC slot — either supervised by the closed-loop controller
    (``--controller``: retune + rebalance + scale-up actually fire) or
    left static (``--controller-static``: the fixed fleet the controller
    row is judged against).  ``--chaos`` additionally SIGKILLs the
    always-on replica mid-decode and blackholes its first ``/kv/import``
    (``BT_FAULTS``), so the row shows what the spawner-respawn +
    suspect-probe + retry-with-idempotency-key stack recovers.

    The parent stays jax-free on CPU (router, aggregator, and controller
    are all pure-stdlib); replicas own the chip.  One JSON row."""
    import dataclasses
    import os
    import pickle
    import shutil
    import tempfile
    import threading

    child_jax_platforms = os.environ.get("JAX_PLATFORMS")
    os.environ["JAX_PLATFORMS"] = "cpu"  # parent: params init only

    import jax as _jax

    import bpe_transformer_tpu.models as models
    from bpe_transformer_tpu.checkpointing import save_checkpoint
    from bpe_transformer_tpu.models import init_params
    from bpe_transformer_tpu.serving.controller import (
        FleetController,
        ReplicaSpawner,
    )
    from bpe_transformer_tpu.serving.router import (
        Router,
        make_router_http_server,
    )
    from bpe_transformer_tpu.telemetry.fleet import (
        FleetAggregator,
        make_fleet_http_server,
    )

    managed = bool(args.controller)
    config = getattr(models, CONFIGS[args.config])
    new_tokens = min(args.new_tokens, 16)
    n_requests = args.requests or 48
    base_qps = args.qps or 4.0
    if args.prompt_mix:
        short, long_, _ = _parse_prompt_mix(args.prompt_mix)
    else:
        short, long_ = 12, 160
    initial_threshold = args.prefill_threshold or 96

    workdir = Path(tempfile.mkdtemp(prefix="bpe_ramp_"))
    servers: list = []
    spawner = None
    router = None
    fleet = None
    stop = threading.Event()
    try:
        ckpt = workdir / "model.ckpt"
        save_checkpoint(
            ckpt,
            params=init_params(_jax.random.PRNGKey(0), config),
            extra={"model_config": dataclasses.asdict(config)},
        )
        tok_dir = workdir / "tok"
        tok_dir.mkdir()
        with open(tok_dir / "vocab.pkl", "wb") as f:
            pickle.dump({i: bytes([i]) for i in range(256)}, f)
        with open(tok_dir / "merges.pkl", "wb") as f:
            pickle.dump([], f)
        cache_dir = workdir / "xla_cache"
        repo_root = str(Path(__file__).resolve().parent.parent)

        env_prefix = ["env", f"PYTHONPATH={repo_root}"] + (
            [f"JAX_PLATFORMS={child_jax_platforms}"]
            if child_jax_platforms is not None
            else ["-u", "JAX_PLATFORMS"]
        )

        def serve_argv(port, role, extra_env=(), extra=()):
            return (
                env_prefix + list(extra_env) + [
                    sys.executable, "-m",
                    "bpe_transformer_tpu.training.cli", "serve",
                    "--checkpoint", str(ckpt),
                    "--tokenizer-dir", str(tok_dir),
                    "--port", str(port),
                    "--slots", "4",
                    "--max-new-tokens", str(new_tokens),
                    "--compile-cache", str(cache_dir),
                    "--paged", "--block-size", str(args.block_size),
                    "--role", role,
                ] + list(extra)
            )

        port_a, port_p, port_e = _free_port(), _free_port(), _free_port()
        url_a = f"http://127.0.0.1:{port_a}"
        url_p = f"http://127.0.0.1:{port_p}"
        url_e = f"http://127.0.0.1:{port_e}"

        chaos_env = ()
        if args.chaos:
            fault_dir = workdir / "faults_a"
            fault_dir.mkdir()
            # Fires once each (once_dir survives the respawn): the
            # always-on replica dies mid-decode and swallows its first
            # /kv/import; the spawner respawns it, the router probes it
            # back in, and the relay's idempotency-keyed retry lands.
            chaos_env = ("BT_FAULTS=" + json.dumps({
                "kill_at_decode_tick": 24,
                "http_blackhole": True,
                "http_fault_path": "/kv/import",
                "once_dir": str(fault_dir),
            }),)

        spawner = ReplicaSpawner([
            (url_a, serve_argv(port_a, "both", extra_env=chaos_env)),
            (url_p, serve_argv(
                port_p, "prefill", extra=("--evacuate-to", url_a),
            )),
            (url_e, serve_argv(
                port_e, "both", extra=("--evacuate-to", url_a),
            )),
        ])
        spawner.spawn()  # always-on decode-capable replica
        spawner.spawn()  # prefill tier; third slot stays elastic

        router = Router(
            [url_a, url_p, url_e],
            poll_interval_s=0.5, poll_timeout_s=2.0,
            connect_timeout_s=2.0, request_timeout_s=600.0,
            prefill_threshold=initial_threshold, suspect_after=3,
            probe_backoff_s=0.5, probe_backoff_max_s=4.0,
        )
        router.start()
        router_port = _free_port()
        router_httpd = make_router_http_server(
            router, port=router_port
        )
        servers.append(router_httpd)
        fleet = FleetAggregator(
            [url_a, url_p, url_e],
            router_url=f"http://127.0.0.1:{router_port}",
            poll_interval_s=1.0, poll_timeout_s=2.0,
        )
        fleet_port = _free_port()
        fleet_httpd = make_fleet_http_server(fleet, port=fleet_port)
        servers.append(fleet_httpd)
        for httpd in servers:
            threading.Thread(
                target=httpd.serve_forever, daemon=True
            ).start()

        controller = None
        if managed:
            controller = FleetController(
                f"http://127.0.0.1:{fleet_port}",
                router_url=f"http://127.0.0.1:{router_port}",
                spawner=spawner,
                poll_timeout_s=2.0, evidence_max_age_s=15.0,
                cooldown_s=10.0, action_timeout_s=120.0,
                scale_sustain_s=4.0, scale_down_idle_s=1e9,
                retune_min_samples=12, rebalance_min_gap=4,
            )

            def ctl_loop():
                while not stop.is_set():
                    try:
                        controller.run_once()
                    except Exception:  # noqa: BLE001 — keep ticking
                        pass
                    stop.wait(1.0)

            threading.Thread(target=ctl_loop, daemon=True).start()

        # Wait for the two always-on replicas to come up (compile-cached
        # spawns after the first pass are fast; a cold first pass pays
        # the ladder once here, outside the timed ramp).
        deadline = time.perf_counter() + 1200
        while time.perf_counter() < deadline:
            router.poll_once()
            if sum(r.available for r in router.replicas) >= 2:
                break
            time.sleep(1.0)
        else:
            raise RuntimeError("always-on replicas never came up")
        fleet.start()

        # Build the ramp: per-phase Poisson arrivals on a shared clock,
        # each request tagged with its phase.
        rng = np.random.default_rng(0)
        per_phase = max(n_requests // len(RAMP_PHASES), 1)
        schedule = []  # (arrival_s, phase_idx, prompt)
        t_cursor = 0.0
        for idx, (_, qps_factor, long_frac) in enumerate(RAMP_PHASES):
            prompts, _flags = _prompts_mix(
                rng, config, n_requests=per_phase,
                new_tokens=new_tokens, short=short, long_=long_,
                frac=long_frac,
            )
            gaps = rng.exponential(
                1.0 / (base_qps * qps_factor), size=per_phase
            )
            for prompt, gap in zip(prompts, gaps):
                t_cursor += float(gap)
                schedule.append((t_cursor, idx, prompt))

        lat: list = [None] * len(schedule)
        codes: list = [None] * len(schedule)

        def serve_one(i, t0):
            arrival, _, prompt = schedule[i]
            delay = arrival - (time.perf_counter() - t0)
            if delay > 0:
                time.sleep(delay)
            body = json.dumps({
                "prompt_ids": prompt, "max_new_tokens": new_tokens,
                "temperature": 1.0, "top_k": 50, "seed": i,
            }).encode()
            t_s = time.perf_counter()
            try:
                code, _payload = router.handle_generate(body)
            except Exception:  # noqa: BLE001 — the row reports it
                code = 599
            codes[i] = code
            if code == 200:
                lat[i] = time.perf_counter() - t_s

        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=serve_one, args=(i, t0), daemon=True)
            for i in range(len(schedule))
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=1900)
        wall = time.perf_counter() - t0

        phases_out = []
        for idx, (name, qps_factor, long_frac) in enumerate(RAMP_PHASES):
            sel = [i for i, (_, p, _pr) in enumerate(schedule) if p == idx]
            ok = [lat[i] for i in sel if lat[i] is not None]
            phases_out.append({
                "phase": name,
                "qps": round(base_qps * qps_factor, 3),
                "long_frac": long_frac,
                "requests": len(sel),
                "failed": sum(1 for i in sel if codes[i] != 200),
                "latency_p50_s": (
                    round(_pctl(ok, 0.50), 4) if ok else None
                ),
                "latency_p99_s": (
                    round(_pctl(ok, 0.99), 4) if ok else None
                ),
            })
        done = [v for v in lat if v is not None]

        router_page = router.statusz()
        ctl_fields = {}
        if controller is not None:
            stop.set()
            ctl_page = controller.statusz()
            by_action: dict = {}
            for rec in ctl_page.get("recent") or []:
                if rec.get("outcome") == "ok":
                    key = rec["action"]
                    by_action[key] = by_action.get(key, 0) + 1
            ctl_fields = {
                "controller_actions_ok": ctl_page["actions_ok"],
                "controller_actions_failed": ctl_page["actions_failed"],
                "controller_holds": ctl_page["holds"],
                "controller_breaker": ctl_page["breaker"],
                "scale_ups": by_action.get("scale_up", 0),
                "retunes": by_action.get("retune", 0),
                "rebalances": by_action.get("rebalance", 0),
            }
        row = {
            "mode": "controller" if managed else "static",
            "chaos": bool(args.chaos),
            "wall_s": round(wall, 3),
            "requests": len(schedule),
            "completed": len(done),
            "failed": len(schedule) - len(done),
            "latency_p50_s": (
                round(_pctl(done, 0.50), 4) if done else None
            ),
            "latency_p99_s": (
                round(_pctl(done, 0.99), 4) if done else None
            ),
            "phases": phases_out,
            "prefill_threshold_initial": initial_threshold,
            "prefill_threshold_final": router_page.get(
                "prefill_threshold"
            ),
            "threshold_updates": router_page.get("threshold_updates"),
            "replicas_suspected": router_page.get("suspected_total"),
            "suspect_probes": router_page.get("probes_total"),
            "suspect_recoveries": router_page.get("recoveries_total"),
            "respawns": sum(
                s["restarts"] for s in spawner.snapshot()
            ),
            **ctl_fields,
        }
    finally:
        stop.set()
        if fleet is not None:
            fleet.close()
        if router is not None:
            router.close()
        for httpd in servers:
            httpd.shutdown()
        if spawner is not None:
            spawner.stop_all(timeout_s=60.0)
        shutil.rmtree(workdir, ignore_errors=True)
    return row


def main() -> int:
    require_accelerator(Path(__file__).stem)
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", choices=sorted(CONFIGS), default="tinystories-4l")
    parser.add_argument("--concurrency", type=int, action="append", default=None,
                        help="slot-pool sizes to sweep (repeatable)")
    parser.add_argument("--requests", type=int, default=None,
                        help="requests per cell (default 4x concurrency)")
    parser.add_argument("--new-tokens", type=int, default=64)
    parser.add_argument("--qps", type=float, default=None,
                        help="open-loop mode: Poisson arrivals at this "
                        "target rate (default: closed loop)")
    parser.add_argument("--paged", action="store_true",
                        help="paged block-pool KV engine (radix prefix "
                        "sharing + chunked prefill)")
    parser.add_argument("--block-size", type=int, default=16)
    parser.add_argument("--prefill-chunk", type=int, default=None)
    parser.add_argument("--prefill-budget", type=int, default=None)
    parser.add_argument("--shared-prefix-len", type=int, default=0,
                        help="shared system-prefix length in tokens "
                        "(the prefix-cache target workload)")
    parser.add_argument("--shared-prefix-frac", type=float, default=0.5,
                        help="fraction of requests carrying the shared "
                        "prefix (with --shared-prefix-len)")
    parser.add_argument("--kv-dtype", choices=("act", "int8"),
                        default="act",
                        help="paged KV pool storage width (int8: "
                        "quantized blocks + per-block-per-head scales)")
    parser.add_argument("--decode-attention",
                        choices=("xla", "pallas", "paged"), default=None,
                        help="decode-step attention impl ('paged': the "
                        "block-pool-native flash kernel, no gather "
                        "transient; needs --paged)")
    parser.add_argument("--weight-dtype", choices=("act", "int8"),
                        default="act",
                        help="serving weight storage width (int8: "
                        "per-channel quantized matmul weights, dequant in "
                        "registers — rows carry tick_weight_bytes / "
                        "params_bytes so the ~2x weight-stream cut is "
                        "machine-checkable)")
    parser.add_argument("--fused-sampling", action="store_true",
                        help="fuse head projection + filtering + sampling "
                        "into one Pallas kernel per tick (logits never "
                        "reach HBM)")
    parser.add_argument("--speculate", type=int, default=0, metavar="K",
                        help="speculative decoding (needs --paged): a "
                        "truncated-layer draft proposes K tokens/slot per "
                        "tick, one target verify pass judges them; rows "
                        "carry accept_rate / tokens_per_target_step / "
                        "draft_overhead_frac")
    parser.add_argument("--draft-layers", type=int, default=1,
                        help="draft = the target's first N transformer "
                        "blocks (shared weights, zero extra memory; "
                        "with --speculate)")
    parser.add_argument("--prompt-mix", default=None,
                        metavar="SHORT,LONG,FRAC",
                        help="open-loop bimodal prompt mix (needs --qps + "
                        "--paged), e.g. 12,160,0.25: 25%% of prompts at "
                        "~160 tokens, the rest at ~12 — rows carry "
                        "per-bucket (short/long) p50/p95/p99 latency AND "
                        "decode-latency fields, the disaggregation "
                        "headline evidence")
    parser.add_argument("--replicas", type=int, default=2,
                        help="(with --prompt-mix) monolithic fleet size: "
                        "N role=both engines served round-robin — the "
                        "equal-engine-count baseline --disagg is judged "
                        "against")
    parser.add_argument("--disagg", action="store_true",
                        help="(with --prompt-mix) disaggregated fleet: "
                        "one prefill-role + one decode-role engine wired "
                        "through the real KV migration path — long "
                        "prompts prefill on the prefill engine and graft "
                        "onto the decode engine, short prompts bypass; "
                        "compare decode_p99_s vs the monolithic row")
    parser.add_argument("--prefill-threshold", type=int, default=None,
                        help="(with --disagg) prompt-token threshold for "
                        "the two-tier path (default: midpoint of the "
                        "prompt mix)")
    parser.add_argument("--controller", action="store_true",
                        help="diurnal-ramp fleet mode (ISSUE 20): a "
                        "subprocess fleet (always-on + prefill-tier + "
                        "one elastic slot) under the closed-loop "
                        "controller — retune/rebalance/scale-up fire "
                        "against the shifting mix and rate ramp; one "
                        "row with per-phase p50/p99 + action counts")
    parser.add_argument("--controller-static", action="store_true",
                        help="the same diurnal ramp WITHOUT the "
                        "controller — the static-fleet baseline the "
                        "--controller row is judged against")
    parser.add_argument("--chaos", action="store_true",
                        help="(with --controller) BT_FAULTS chaos: "
                        "SIGKILL the always-on replica mid-decode and "
                        "blackhole its first /kv/import — the row shows "
                        "what respawn + suspect-probe + idempotent "
                        "retry recover")
    parser.add_argument("--restart", action="store_true",
                        help="restart-to-traffic mode: time a replica "
                        "from spawn to first token through the router "
                        "rejoin path, cold vs bpe-tpu-warmup-warmed "
                        "(one row; ignores --concurrency/--qps)")
    args = parser.parse_args()

    if args.decode_attention == "paged" and not args.paged:
        print("--decode-attention paged needs --paged", file=sys.stderr)
        return 2
    if args.kv_dtype == "int8" and not args.paged:
        print("--kv-dtype int8 needs --paged", file=sys.stderr)
        return 2
    if args.speculate and not args.paged:
        print("--speculate needs --paged", file=sys.stderr)
        return 2
    if args.disagg and not args.prompt_mix:
        print("--disagg needs --prompt-mix", file=sys.stderr)
        return 2
    if args.prompt_mix and not (args.controller or args.controller_static) \
            and (args.qps is None or not args.paged):
        print("--prompt-mix needs --qps (open loop) and --paged "
              "(KV migration lives in the block pool)", file=sys.stderr)
        return 2

    if args.chaos and not args.controller:
        print("--chaos needs --controller", file=sys.stderr)
        return 2
    if args.controller and args.controller_static:
        print("--controller and --controller-static are exclusive",
              file=sys.stderr)
        return 2
    if args.controller or args.controller_static:
        cell = run_controller_ramp(args)
        print(json.dumps(
            {
                "metric": f"controller_ramp ({args.config}, "
                f"mode={cell['mode']}"
                + (", chaos" if cell["chaos"] else "") + ")",
                **cell,
                "platform": "subprocess",
            }
        ), flush=True)
        return 0

    if args.restart:
        cell = run_restart(args)
        print(json.dumps(
            {
                "metric": f"restart_to_traffic ({args.config}, "
                f"{cell['engine']}, attn={cell['decode_attention']})",
                **cell,
                "platform": "subprocess",
            }
        ), flush=True)
        return 0

    import dataclasses

    import bpe_transformer_tpu.models as models
    from bpe_transformer_tpu.models import init_params

    on_accel = jax.default_backend() != "cpu"
    config = dataclasses.replace(
        getattr(models, CONFIGS[args.config]),
        attention_impl="xla",
        decode_attention_impl=args.decode_attention or "xla",
    )
    params = init_params(jax.random.PRNGKey(0), config)
    levels = args.concurrency or ([1, 4, 8] if on_accel else [1, 2])
    new_tokens = args.new_tokens if on_accel else min(args.new_tokens, 8)

    measured_any = False
    for concurrency in levels:
        n_requests = args.requests or 4 * concurrency
        try:
            if args.prompt_mix:
                cell = run_open_fleet(
                    params, config,
                    concurrency=concurrency,
                    n_requests=n_requests,
                    new_tokens=new_tokens,
                    qps=args.qps,
                    args=args,
                )
                mode = f"qps={args.qps},mix={args.prompt_mix}"
            elif args.qps is not None:
                cell = run_open_loop(
                    params, config,
                    concurrency=concurrency,
                    n_requests=n_requests,
                    new_tokens=new_tokens,
                    qps=args.qps,
                    args=args,
                )
                mode = f"qps={args.qps}"
            else:
                cell = run_cell(
                    params, config,
                    concurrency=concurrency,
                    n_requests=n_requests,
                    new_tokens=new_tokens,
                    args=args,
                )
                mode = "closed"
        except Exception as exc:  # noqa: BLE001 - report the cell as absent
            print(f"concurrency={concurrency} failed: {exc!r}"[:300],
                  file=sys.stderr)
            continue
        measured_any = True
        if args.prompt_mix:
            engine = (
                "disagg" if args.disagg else f"mono-x{args.replicas}"
            )
        else:
            engine = "paged" if args.paged else "dense"
        if args.paged and args.kv_dtype != "act":
            engine += f"-{args.kv_dtype}"
        if args.decode_attention:
            engine += f"-{args.decode_attention}"
        if args.weight_dtype != "act":
            engine += "-w8"
        if args.fused_sampling:
            engine += "-fs"
        if args.speculate:
            engine += f"-spec{args.speculate}"
        print(
            json.dumps(
                {
                    "metric": f"serving_tokens_per_sec ({args.config}, "
                    f"slots={concurrency}, req={n_requests}, "
                    f"new={new_tokens}, {engine}, {mode}, "
                    f"{config.activation_dtype})",
                    **cell,
                    "decode_attention": args.decode_attention or "xla",
                    "device": str(jax.devices()[0]),
                    "platform": jax.devices()[0].platform,
                }
            ),
            flush=True,
        )
    return 0 if measured_any else 4


if __name__ == "__main__":
    sys.exit(main())
