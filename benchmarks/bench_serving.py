"""Serving-engine throughput/latency vs request concurrency.

The continuous-batching question in numbers: how much chip does a slot
pool recover as in-flight requests stack up?  For each concurrency level
the engine serves a fixed request load (ragged prompt lengths, shared
token budget) and reports aggregate generated tokens/sec plus p50/p95
request latency — the tradeoff curve capacity planning reads.

Run on a TPU host:  python benchmarks/bench_serving.py
Prints one JSON line per (config, concurrency) cell.

`--config tinystories-4l|gpt2-small-32k`, `--concurrency N` (repeatable),
`--requests M`, `--new-tokens K` restrict the grid so long runs can be
split across invocations (tunnel-outage hygiene).  Warmup (compilation of
the prefill buckets + tick) happens before timing, so cells measure
steady-state serving, not XLA.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from _accel import require_accelerator  # noqa: E402  (benchmarks/_accel.py)

import numpy as np

import jax

CONFIGS = {
    "tinystories-4l": "TINYSTORIES_4L",
    "gpt2-small-32k": "GPT2_SMALL_32K",
}


def _pctl(values, q):
    ordered = sorted(values)
    return ordered[min(len(ordered) - 1, max(0, round(q * len(ordered)) - 1))]


def run_cell(params, config, *, concurrency, n_requests, new_tokens, seed=0):
    from bpe_transformer_tpu.serving import ServingEngine

    rng = np.random.default_rng(seed)
    ctx = config.context_length
    # Ragged prompts across the bucket range, biased short (serving-shaped).
    lengths = rng.integers(8, min(ctx - new_tokens, 4 * 64), size=n_requests)
    prompts = [
        [int(t) for t in rng.integers(0, config.vocab_size, size=n)]
        for n in lengths
    ]

    with ServingEngine(
        params, config, slots=concurrency, max_queue=n_requests + 1
    ) as serving:
        # Warmup: one request per distinct bucket + the tick program, so
        # timed cells measure steady-state serving rather than XLA.
        for b in serving.engine.buckets:
            serving.generate([1] * min(b, ctx - 2), max_new_tokens=2,
                             temperature=0.0, timeout=600)

        # Submit everything up front; the scheduler feeds free slots.
        from bpe_transformer_tpu.serving import Request

        t0 = time.perf_counter()
        handles = [
            serving.submit(
                Request(
                    prompt_ids=tuple(p), max_new_tokens=new_tokens,
                    temperature=1.0, top_k=50, seed=i,
                )
            )
            for i, p in enumerate(prompts)
        ]
        results = [h.result(timeout=1800) for h in handles]
        wall = time.perf_counter() - t0
        latencies = [
            r.queue_wait_s + r.prefill_s + r.decode_s for r in results
        ]
        tokens = sum(len(r.token_ids) for r in results)
        compiled = serving.engine.compiled_programs()

    return {
        "wall_s": round(wall, 3),
        "gen_tok_per_s": round(tokens / wall, 1),
        "latency_p50_s": round(_pctl(latencies, 0.50), 4),
        "latency_p95_s": round(_pctl(latencies, 0.95), 4),
        "compiled_programs": compiled,
        "requests": n_requests,
        "new_tokens": new_tokens,
    }


def main() -> int:
    require_accelerator(Path(__file__).stem)
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", choices=sorted(CONFIGS), default="tinystories-4l")
    parser.add_argument("--concurrency", type=int, action="append", default=None,
                        help="slot-pool sizes to sweep (repeatable)")
    parser.add_argument("--requests", type=int, default=None,
                        help="requests per cell (default 4x concurrency)")
    parser.add_argument("--new-tokens", type=int, default=64)
    args = parser.parse_args()

    import dataclasses

    import bpe_transformer_tpu.models as models
    from bpe_transformer_tpu.models import init_params

    on_accel = jax.default_backend() != "cpu"
    config = dataclasses.replace(
        getattr(models, CONFIGS[args.config]),
        attention_impl="xla",
        decode_attention_impl="xla",
    )
    params = init_params(jax.random.PRNGKey(0), config)
    levels = args.concurrency or ([1, 4, 8] if on_accel else [1, 2])
    new_tokens = args.new_tokens if on_accel else min(args.new_tokens, 8)

    measured_any = False
    for concurrency in levels:
        n_requests = args.requests or 4 * concurrency
        try:
            cell = run_cell(
                params, config,
                concurrency=concurrency,
                n_requests=n_requests,
                new_tokens=new_tokens,
            )
        except Exception as exc:  # noqa: BLE001 - report the cell as absent
            print(f"concurrency={concurrency} failed: {exc!r}"[:300],
                  file=sys.stderr)
            continue
        measured_any = True
        print(
            json.dumps(
                {
                    "metric": f"serving_tokens_per_sec ({args.config}, "
                    f"slots={concurrency}, req={n_requests}, "
                    f"new={new_tokens}, {config.activation_dtype})",
                    **cell,
                    "device": str(jax.devices()[0]),
                    "platform": jax.devices()[0].platform,
                }
            ),
            flush=True,
        )
    return 0 if measured_any else 4


if __name__ == "__main__":
    sys.exit(main())
