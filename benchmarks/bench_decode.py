"""Decode-path throughput: KV-cached vs uncached autoregressive sampling.

The reference's contract stops at logits (it ships no sampler at all); this
framework's decode stack is `models/decode.py` (prefill + lax.scan'd
per-token steps over a KV cache, one XLA program per generation) with the
uncached full-forward path of `training/sampling.py` as the baseline.

Run on a TPU host:  python benchmarks/bench_decode.py
Prints one JSON line per (config, batch) with both tokens/sec figures.

`--config tinystories-4l|gpt2-small-32k` and `--batch N` restrict the grid
so long runs can be split across invocations (tunnel-outage hygiene).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from _accel import require_accelerator  # noqa: E402  (benchmarks/_accel.py)

import numpy as np

import jax
import jax.numpy as jnp

CONFIGS = {
    "tinystories-4l": "TINYSTORIES_4L",
    "gpt2-small-32k": "GPT2_SMALL_32K",
}
PROMPT_LEN = 64


def make_uncached_step(params, config):
    """One jitted full-forward sample step, built ONCE per config so timed
    iterations hit jax's jit cache (a fresh closure per call would recompile
    every dispatch and the 'uncached' baseline would measure compilation)."""
    from bpe_transformer_tpu.models.decode import _sample_from_logits
    from bpe_transformer_tpu.models.transformer import forward

    @jax.jit
    def step(buf, length, key):
        logits = forward(params, buf, config)[:, length - 1]
        key, sub = jax.random.split(key)
        nxt = _sample_from_logits(logits, sub, 1.0, None, None)
        buf = jax.lax.dynamic_update_slice(buf, nxt[:, None], (0, length))
        return buf, nxt, key

    return step


def _uncached_generate(step, config, prompt, key, max_new_tokens):
    """Full forward over the whole context buffer per emitted token — the
    sliding-window fallback of training/sampling.py, batched, timed as the
    baseline the KV cache is supposed to beat."""
    batch, plen = prompt.shape
    ctx = config.context_length
    buf = jnp.zeros((batch, ctx), jnp.int32)
    buf = jax.lax.dynamic_update_slice(buf, prompt, (0, 0))
    length = plen
    last = None
    for _ in range(max_new_tokens):
        buf, last, key = step(buf, jnp.asarray(length), key)
        length += 1
    return last


def _time(fn, *args, iters: int, label: str):
    try:
        t0 = time.perf_counter()
        out = fn(*args)  # compile + first run
        jax.block_until_ready(out)
        float(jax.device_get(jnp.asarray(out).reshape(-1)[0]))  # hard barrier
        print(
            f"{label}: compiled+first-run in {time.perf_counter() - t0:.1f}s",
            file=sys.stderr,
        )
        start = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        float(jax.device_get(jnp.asarray(out).reshape(-1)[0]))
        return (time.perf_counter() - start) / iters
    except Exception as exc:  # noqa: BLE001 - report the case as absent
        print(f"{label} failed: {exc!r}"[:300], file=sys.stderr)
        return None




def main() -> int:
    require_accelerator(Path(__file__).stem)
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", choices=sorted(CONFIGS), default=None)
    parser.add_argument("--batch", type=int, default=None)
    args = parser.parse_args()

    import dataclasses

    import bpe_transformer_tpu.models as models
    from bpe_transformer_tpu.models import init_params
    from bpe_transformer_tpu.models.decode import generate_cached

    on_accel = jax.default_backend() != "cpu"
    # BENCH_DECODE_NEW_TOKENS caps the generation length (the gpt2-scale
    # cells timed out at 128: scan-program remote compile + 128 sequential
    # uncached forwards).  NOTE the cached tok/s amortizes the fixed
    # prefill over the generated tokens, so rows at different lengths are
    # not directly comparable — every row records prompt=/new= for that.
    raw_new = os.environ.get(
        "BENCH_DECODE_NEW_TOKENS", "128" if on_accel else "16"
    )
    try:
        new_tokens = int(raw_new)
    except ValueError:
        print(f"invalid BENCH_DECODE_NEW_TOKENS={raw_new!r}", file=sys.stderr)
        return 2
    if new_tokens <= 0:
        print(f"BENCH_DECODE_NEW_TOKENS must be positive, got {raw_new}", file=sys.stderr)
        return 2
    # BENCH_DECODE_ATTN=pallas times the flash-decoding kernel
    # (kernels/pallas/decode_attention.py) on the cached path; rows carry a
    # dec= tag so the two formulations land as distinct evidence.
    decode_attn = os.environ.get("BENCH_DECODE_ATTN", "xla")
    if decode_attn not in ("xla", "pallas"):
        print(f"invalid BENCH_DECODE_ATTN={decode_attn!r}", file=sys.stderr)
        return 2
    # BENCH_DECODE_SKIP_UNCACHED=1: variant cells (e.g. the pallas rows)
    # only need the cached timing — re-running the minutes-long uncached
    # baseline the base cell already measured would burn tunnel-window time
    # and renew its timeout risk.
    skip_uncached = os.environ.get("BENCH_DECODE_SKIP_UNCACHED") == "1"
    iters = 3 if on_accel else 1

    names = [args.config] if args.config else sorted(CONFIGS)
    batches = [args.batch] if args.batch else [1, 8]
    measured_any = False
    for name in names:
        # Each preset keeps its own activation dtype (gpt2 presets are bf16:
        # bf16 KV cache + einsums on the cached path, bf16 forward on the
        # uncached baseline — same dtype both sides, so the comparison stays
        # algorithmic).
        config = dataclasses.replace(
            getattr(models, CONFIGS[name]),
            attention_impl="xla",
            decode_attention_impl=decode_attn,
        )
        params = init_params(jax.random.PRNGKey(0), config)
        rng = np.random.default_rng(0)
        for batch in batches:
            prompt = jnp.asarray(
                rng.integers(0, config.vocab_size, size=(batch, PROMPT_LEN)),
                dtype=jnp.int32,
            )
            key = jax.random.PRNGKey(1)

            t_cached = _time(
                lambda: generate_cached(
                    params, prompt, key, config=config,
                    max_new_tokens=new_tokens,
                ),
                iters=iters,
                label=f"cached {name} B={batch}",
            )
            if skip_uncached:
                t_uncached = None
            else:
                uncached_step = make_uncached_step(params, config)
                t_uncached = _time(
                    lambda: _uncached_generate(
                        uncached_step, config, prompt, key, new_tokens
                    ),
                    iters=iters,
                    label=f"uncached {name} B={batch}",
                )

            if t_cached or t_uncached:
                measured_any = True

            def tps(t):
                return round(batch * new_tokens / t, 1) if t else None

            print(
                json.dumps(
                    {
                        "metric": f"decode_tokens_per_sec ({name}, B={batch}, "
                        f"prompt={PROMPT_LEN}, new={new_tokens}, "
                        f"{config.activation_dtype})"
                        + (f" dec={decode_attn}" if decode_attn != "xla" else ""),
                        "kv_cached_tok_per_s": tps(t_cached),
                        "uncached_tok_per_s": tps(t_uncached),
                        "speedup": (
                            round(t_uncached / t_cached, 2)
                            if t_cached and t_uncached
                            else None
                        ),
                        "device": str(jax.devices()[0]),
                        "platform": jax.devices()[0].platform,
                    }
                ),
                flush=True,
            )
    # All timings failed -> nonzero so queue runners retry instead of
    # committing an all-null row and marking the cell done.
    return 0 if measured_any else 4


if __name__ == "__main__":
    sys.exit(main())
