"""Sequence-parallel schedule comparison: ring vs zig-zag vs Ulysses.

Under causal masking the contiguous ring computes every visiting K/V block
on every device and discards masked ones (device n-1 needs all n blocks,
device 0 one — and SPMD means everyone computes n).  The zig-zag layout
(shard i holds global chunks i and 2n-1-i) balances visible work and
computes two half-blocks per step, so per-device attention FLOPs drop
~2x at large mesh sizes.

The Ulysses all-to-all schedule (`parallel/ulysses.py`) is timed alongside
when the head count divides into the mesh (its constraint): it trades the
ring's n-1 K/V rotations for one head-scatter all_to_all each way and runs
full-sequence attention per head slice.  NOTE on reading CPU numbers: the
FLOP balance (ring-vs-zigzag ~2x) is schedule-arithmetic and transfers to
TPU; collective COSTS do not (host "collectives" are memcpys), so the
Ulysses column is a compute-balance datum only.

Runs the schedules over the virtual CPU mesh (or real devices when
present) and prints one JSON line with mean step times and the ratios.

Usage: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
           python benchmarks/bench_ring.py [--seq 4096] [--iters 10]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from functools import partial
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=4096)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--d", type=int, default=64)
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from bpe_transformer_tpu.parallel import make_mesh
    from bpe_transformer_tpu.parallel.ring_attention import (
        ring_self_attention,
        zigzag_indices,
        zigzag_ring_self_attention,
    )
    from bpe_transformer_tpu.utils.profiling import time_fn

    n = len(jax.devices())
    mesh = make_mesh({"seq": n})
    S = args.seq
    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(
        rng.standard_normal((1, args.heads, S, args.d)).astype(np.float32)
    )
    q, k, v = mk(), mk(), mk()

    spec = P(None, None, "seq", None)
    ring = jax.jit(
        jax.shard_map(
            partial(ring_self_attention, axis_name="seq", causal=True),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec, check_vma=False,
        )
    )
    zig = jax.jit(
        jax.shard_map(
            partial(zigzag_ring_self_attention, axis_name="seq"),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec, check_vma=False,
        )
    )
    perm = zigzag_indices(S, n)
    qz, kz, vz = q[..., perm, :], k[..., perm, :], v[..., perm, :]

    t_ring = time_fn(ring, q, k, v, iters=args.iters)
    t_zig = time_fn(zig, qz, kz, vz, iters=args.iters)
    result = {
        "metric": (
            f"causal sp attention step time (S={S}, H={args.heads}, "
            f"D={args.d}, {n} shards)"
        ),
        "contiguous_ms": round(t_ring["mean_s"] * 1e3, 2),
        "zigzag_ms": round(t_zig["mean_s"] * 1e3, 2),
        "speedup": round(t_ring["mean_s"] / t_zig["mean_s"], 3),
        "platform": jax.devices()[0].platform,
        "n_devices": n,
    }

    if args.heads % n == 0:
        from bpe_transformer_tpu.parallel.ulysses import ulysses_attention

        uly = jax.jit(
            jax.shard_map(
                partial(ulysses_attention, axis_name="seq"),
                mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
                check_vma=False,
            )
        )
        t_uly = time_fn(uly, q, k, v, iters=args.iters)
        result["ulysses_ms"] = round(t_uly["mean_s"] * 1e3, 2)
        result["ring_vs_ulysses"] = round(t_ring["mean_s"] / t_uly["mean_s"], 3)
    else:
        result["ulysses_ms"] = None
        result["note"] = (
            f"ulysses skipped: heads ({args.heads}) not a multiple of the "
            f"mesh ({n})"
        )
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
