#!/bin/bash
# Round-3 TPU benchmark queue: run everything that needs the real chip, in
# priority order, each with its own timeout.  Results land in
# /tmp/tpu_results (scratch) and benchmarks/captures/ (committed evidence;
# bench.py writes its own capture files there).
#
# Idempotent: jobs that already completed (marker in /tmp/tpu_results) are
# skipped, EXCEPT the headline bench.py which re-runs on every invocation to
# keep the replay capture as fresh as possible.  Safe to re-run on every
# tunnel recovery.
set -u
cd "$(dirname "$0")/.."
OUT=/tmp/tpu_results
CAP=benchmarks/captures
# Repo-local gitignored mirror (VERDICT r4 weak #7): done-markers, result
# jsonls, and the compile cache survive a container recycle here (the repo
# persists across rounds; /tmp may not), so a recycle costs nothing and a
# short window is never spent on re-compiles or re-measurements.
MIR=.scratch
mkdir -p "$OUT" "$CAP" "$MIR"
# Single-flight: the recovery watcher and manual invocations can race; two
# concurrent passes would contend for the one chip and pollute timings.
exec 9> "$OUT/queue.lock"
if ! flock -n 9; then
  echo "$(date -u +%FT%TZ) another queue pass is running; exiting" >> "$OUT/log"
  exit 0
fi
# UNDER the lock (a losing invocation's clobber-seed racing a running
# pass's mid-append could mirror a torn line; review r5):
# Restore idempotence state AND scratch evidence if /tmp was recycled since
# the last pass (no-clobber: live /tmp state always wins).  Restoring the
# jsonls before any job runs is what keeps the mirror a superset — run_job
# copies the whole outfile back after appending, which would otherwise
# clobber the mirror with a fresh near-empty file post-recycle.
cp -an "$MIR"/done_* "$OUT"/ 2>/dev/null || true
cp -an "$MIR"/*.jsonl "$OUT"/ 2>/dev/null || true
# Size-guarded (not existence-guarded) log restore: a lock-losing racer's
# single pre-lock line would otherwise recreate $OUT/log post-recycle and
# make the winner skip the restore, then clobber the mirrored history at
# pass end (review r5).  The log is diagnostics — replacing a near-empty
# post-recycle file with the mirrored history loses at most racer lines.
if [ -e "$MIR/queue_log" ] && \
   [ "$(stat -c%s "$OUT/log" 2>/dev/null || echo 0)" -lt "$(stat -c%s "$MIR/queue_log")" ]; then
  cp -a "$MIR/queue_log" "$OUT/log" 2>/dev/null || true
fi
# ...and reverse-seed: /tmp state that predates the mirror (earlier rounds'
# markers and raw jsonls) must get recycle protection NOW, not only after
# each job happens to re-run (review r5).  Safe to clobber — after the
# restore above /tmp is always a superset of the mirror.
cp -a "$OUT"/done_* "$MIR"/ 2>/dev/null || true
cp -a "$OUT"/*.jsonl "$MIR"/ 2>/dev/null || true
# Persistent XLA compilation cache: tunnel windows are short and first
# compiles cost 20-40 s each — re-runs across queue passes should not
# re-pay them.  Lives in the repo mirror (recycle-safe); a pre-existing
# /tmp cache from earlier rounds is folded in once (no-clobber).
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-$PWD/$MIR/jax_ccache}"
mkdir -p "$JAX_COMPILATION_CACHE_DIR"
if [ -d /tmp/jax_ccache ] && [ "$JAX_COMPILATION_CACHE_DIR" != /tmp/jax_ccache ]; then
  cp -an /tmp/jax_ccache/. "$JAX_COMPILATION_CACHE_DIR"/ 2>/dev/null || true
fi
log() { echo "$(date -u +%FT%TZ) $*" >> "$OUT/log"; }

wait_for_driver() {
  # A direct bench.py run (the driver's official capture) owns the chip:
  # pause between queue jobs while the flag's writer PID is alive.  A
  # dead writer (crash, Ctrl-C, SIGKILL) is detected within one poll and
  # its flag reclaimed; a 30-min hard cap guards against PID reuse.
  local waited=0 pid
  while [ -e "$OUT/driver_active" ] && [ $waited -lt 1800 ]; do
    pid=$(cat "$OUT/driver_active" 2>/dev/null)
    if ! [ "$pid" -gt 0 ] 2>/dev/null || ! kill -0 "$pid" 2>/dev/null; then
      log "driver flag orphaned (pid ${pid:-unreadable} dead); reclaiming"
      rm -f "$OUT/driver_active"; break
    fi
    [ $waited -eq 0 ] && log "driver bench active (pid $pid); queue paused"
    sleep 10; waited=$((waited + 10))
  done
  [ $waited -ge 1800 ] && log "driver wait cap hit; resuming queue"
  return 0
}

run_job() {  # run_job <marker> <timeout_s> <outfile> <cmd...>
  local marker="$1" tmo="$2" outfile="$3"; shift 3
  if [ "$marker" != "-" ] && [ -e "$OUT/done_$marker" ]; then
    log "skip $marker (done)"; return 0
  fi
  wait_for_driver
  log "start ${marker:-job}: $*"
  local tmp
  tmp=$(mktemp "$OUT/job.XXXXXX")
  timeout "$tmo" "$@" > "$tmp" 2>> "$OUT/log"
  local rc=$?
  # The tunnel can drop mid-queue and jax silently falls back to host CPU
  # with rc=0: CPU timings must never be recorded as TPU evidence or mark
  # the job done.
  if grep -qE 'TFRT_CPU|"platform": "cpu"|"platform": null|"value": null' "$tmp"; then
    log "rc=$rc but CPU-fallback/null result detected, discarding: $*"
    cat "$tmp" >> "$OUT/cpu_fallback.jsonl"; rm -f "$tmp"
    cp -a "$OUT/cpu_fallback.jsonl" "$MIR/" 2>/dev/null || true
    return 1
  fi
  # Promote output only on success: a timed-out/killed job's partial rows
  # must not land in committed capture files (each retry would append
  # duplicates — every invocation emits its rows only on completion).
  if [ "$rc" -eq 0 ]; then
    # Repair a torn tail first: a pass SIGKILLed mid-append can leave the
    # outfile ending mid-line; appending straight onto it would merge two
    # rows into one corrupt line.  The newline isolates the torn fragment
    # as its own (unparseable, reader-skipped) line instead (review r5).
    if [ -s "$outfile" ] && [ -n "$(tail -c1 "$outfile")" ]; then
      echo >> "$outfile"
    fi
    cat "$tmp" >> "$outfile"
    if [ "$marker" != "-" ]; then
      touch "$OUT/done_$marker" "$MIR/done_$marker"
    fi
    # Scratch outfiles ($OUT/*.jsonl) are raw evidence too: mirror them so
    # a recycle can't orphan rows that never made it into $CAP.
    case "$outfile" in "$OUT"/*) cp -a "$outfile" "$MIR/" 2>/dev/null || true;; esac
  else
    cat "$tmp" >> "$OUT/failed_runs.jsonl"
    cp -a "$OUT/failed_runs.jsonl" "$MIR/" 2>/dev/null || true
  fi
  rm -f "$tmp"
  log "rc=$rc: $*"
  return "$rc"
}

# 1. Headline (always re-run: refreshes the replay capture).
# BENCH_DRIVER_FLAG=0: a queue job must not raise the driver-priority flag
# (a timeout-kill would orphan it and pause the rest of this very pass).
# Snapshot the previous pass's capture FIRST: the regression self-report at
# the end of this pass compares the freshly measured capture against it.
HEADLINE_CAP="$CAP/tpu_capture_tinystories-4l.json"
if [ -e "$HEADLINE_CAP" ]; then
  cp -a "$HEADLINE_CAP" "$OUT/prev_headline_capture.json" 2>/dev/null || true
fi
run_job - 300 "$OUT/bench_headline.jsonl" env BENCH_DRIVER_FLAG=0 python bench.py

# 1b. North-star convergence run (VERDICT r3 #2): TinyStories 4L at the real
# config-1 shape trained ON THE CHIP to the precomputed torch-CPU reference
# val loss.  Checkpoints every eval to .scratch/northstar_ckpt.pkl (recycle-
# safe), so a tunnel drop mid-run RESUMES on the next pass; exits 0 (-> done
# marker) once the full measurement lands, whatever the verdict —
# benchmarks/captures/northstar.json records it honestly either way.
# ~200 steps of an 8M-param model: minutes of device time, run it early.
run_job northstar 900 "$OUT/northstar.jsonl" python benchmarks/northstar.py --phase jax

# 1c. Native-precision north-star (round 5): same protocol at TPU-default
# matmul precision with the 25 steps between evals in ONE scanned dispatch
# -- the run that shows reference val loss AND >=10x tok/s together
# (the parity run above clears the loss bar at 7.15x only because
# precision=highest emulates f32 on the MXU).  Writes
# benchmarks/captures/northstar_native.json; resumable like 1b.
run_job northstar_native 600 "$OUT/northstar.jsonl" \
  python benchmarks/northstar.py --phase jax --variant native

# 2. Compute-bound MFU on the real model sizes (VERDICT #2).
run_job gpt2s 1200 "$OUT/bench_gpt2s.jsonl" \
  env BENCH_DEADLINE_S=900 BENCH_NO_CPU_FALLBACK=1 python bench.py --config gpt2-small-32k
# Per-stage breakdown RIGHT AFTER the gpt2s capture (VERDICT #1's "what
# eats the predicted 33-43%": forward / backward / attention impl / CE
# chunking each timed in its own jit; ccache-warm from the capture above).
run_job breakdown 1500 "$CAP/breakdown.jsonl" \
  python benchmarks/bench_breakdown.py --config gpt2-small-32k
# GPT-2-medium's first-ever TPU number (VERDICT #1) before the
# lower-stakes re-captures: a short window must still land it.
run_job gpt2m 1500 "$OUT/bench_gpt2m.jsonl" \
  env BENCH_DEADLINE_S=1200 BENCH_NO_CPU_FALLBACK=1 python bench.py --config gpt2-medium
# The 4l headline attribution (VERDICT r3 weak #4): its 12.8%
# driver-visible MFU is believed dispatch-latency-bound behind the tunnel —
# the per-stage device times prove or refute that quantitatively.
run_job breakdown4l 600 "$CAP/breakdown.jsonl" \
  python benchmarks/bench_breakdown.py --config tinystories-4l
run_job ts12l 600 "$OUT/bench_12l.jsonl" \
  env BENCH_DEADLINE_S=420 BENCH_NO_CPU_FALLBACK=1 python bench.py --config tinystories-12l
run_job tsmoe 600 "$OUT/bench_moe.jsonl" \
  env BENCH_DEADLINE_S=420 BENCH_NO_CPU_FALLBACK=1 python bench.py --config tinystories-moe
# Dense-dispatch variant (same routing semantics).  The chip confirmed
# gather 118,025 vs einsum 69,896 tok/s on 2026-08-02, so TINYSTORIES_MOE
# now DEFAULTS to gather (the plain job above measures it) and einsum is
# the explicitly-suffixed variant (_einsum capture file), kept for the
# head-to-head record alongside bench_moe_dispatch.py below.
run_job tsmoe_einsum 600 "$OUT/bench_moe.jsonl" \
  env BENCH_DEADLINE_S=420 BENCH_NO_CPU_FALLBACK=1 BENCH_MOE_DISPATCH=einsum \
  python bench.py --config tinystories-moe

# 3. Attention kernel table, one length per invocation (VERDICT #3).
for seq in 16384 4096 1024; do
  run_job "attn$seq" 900 "$CAP/attention.jsonl" \
    python benchmarks/bench_attention.py --seq "$seq"
done
# Training-shaped row (gpt2-small head geometry, batched): the B=1 cells
# are launch-latency-dominated at 1k and noisy between runs.
for seq in 1024 4096; do
  run_job "attnB8_$seq" 900 "$CAP/attention.jsonl" \
    python benchmarks/bench_attention.py --seq "$seq" --batch 8 --heads 12
done

# 4. Decode path (VERDICT #7), one cell per invocation.  The gpt2 cells
# need the longer leash AND a shorter generation (their first 600 s
# attempts at 128 tokens produced no output: scan-program remote compile +
# 128 sequential uncached forwards at 124M params).  Cached tok/s
# amortizes the fixed prefill over fewer tokens at new=64, so the gpt2
# rows slightly UNDERSTATE the cache win vs the new=128 tinystories rows;
# every row is self-describing (prompt=/new= in the metric string).
for cfg in tinystories-4l gpt2-small-32k; do
  if [ "$cfg" = gpt2-small-32k ]; then tmo=1200; ntok=64; else tmo=600; ntok=128; fi
  for b in 1 8; do
    run_job "dec_${cfg}_$b" "$tmo" "$CAP/decode.jsonl" \
      env BENCH_DECODE_NEW_TOKENS=$ntok \
      python benchmarks/bench_decode.py --config "$cfg" --batch "$b"
  done
done
# Flash-decoding Pallas kernel head-to-head at the bandwidth-boundest cell
# (gpt2-small B=1; VERDICT r4 #6).  Parity is CPU-pinned in
# tests/test_kernels.py; this row is its first device timing.
# SKIP_UNCACHED: the base dec_* cells above already time the uncached
# baseline; these rows exist for the pallas-cached number only.
run_job dec_pallas_gpt2s_1 1200 "$CAP/decode.jsonl" \
  env BENCH_DECODE_NEW_TOKENS=64 BENCH_DECODE_ATTN=pallas BENCH_DECODE_SKIP_UNCACHED=1 \
  python benchmarks/bench_decode.py --config gpt2-small-32k --batch 1
run_job dec_pallas_ts4l_1 600 "$CAP/decode.jsonl" \
  env BENCH_DECODE_NEW_TOKENS=128 BENCH_DECODE_ATTN=pallas BENCH_DECODE_SKIP_UNCACHED=1 \
  python benchmarks/bench_decode.py --config tinystories-4l --batch 1
# Decode-phase attribution (r5): compile vs prefill vs per-token cost, per
# decode impl — diagnoses the gpt2 decode-cell timeouts quantitatively.
run_job breakdown_dec 1500 "$CAP/breakdown.jsonl" \
  python benchmarks/bench_breakdown.py --config gpt2-small-32k --batch 1 --decode

# 6. Tuning variants: deeper dispatch amortization for the small model and
# a bigger batch for gpt2-small (own capture file; may OOM -> discarded).
# _save_capture keeps the fastest same-shape capture, so these can only
# improve the replayed headline.  inner=100 = ONE dispatch for the whole
# 100-step measure: the pure device-rate ceiling (the default is now 40,
# so this probes what latency remains beyond it).
run_job inner100 300 "$OUT/bench_inner100.jsonl" \
  env BENCH_INNER_STEPS=100 BENCH_NO_CPU_FALLBACK=1 python bench.py
# Remat fallback only when B=64 doesn't fit un-rematerialized; once the
# fallback has succeeded, later passes skip the known-OOMing first attempt.
if [ ! -e "$OUT/done_gpt2s64r" ]; then
  run_job gpt2s64 1200 "$OUT/bench_gpt2s64.jsonl" \
    env BENCH_DEADLINE_S=900 BENCH_NO_CPU_FALLBACK=1 python bench.py --config gpt2-small-32k --batch 64 \
    || run_job gpt2s64r 1200 "$OUT/bench_gpt2s64.jsonl" \
      env BENCH_DEADLINE_S=900 BENCH_NO_CPU_FALLBACK=1 BENCH_REMAT=1 \
      python bench.py --config gpt2-small-32k --batch 64
fi
# Larger flash tile for the seq-1024 shape (own capture file keyed _blk512;
# cite in RESULTS.md if it wins).
run_job gpt2s_blk512 1200 "$OUT/bench_gpt2s_blk512.jsonl" \
  env BENCH_DEADLINE_S=900 BENCH_NO_CPU_FALLBACK=1 BENCH_FLASH_BLOCK=512 \
  python bench.py --config gpt2-small-32k

# Pallas fused-SwiGLU FFN at the gpt2 shape (parity-tested; never timed
# on chip).  Own capture file via the _ffn_pallas suffix (ADVICE r3/r4).
run_job gpt2s_ffnp 1200 "$OUT/bench_gpt2s_ffnp.jsonl" \
  env BENCH_DEADLINE_S=900 BENCH_NO_CPU_FALLBACK=1 BENCH_FFN_IMPL=pallas \
  python bench.py --config gpt2-small-32k

# MoE dispatch formulations head-to-head at the bench shape (bf16, chip).
run_job moedisp 600 "$CAP/moe_dispatch.jsonl" \
  python benchmarks/bench_moe_dispatch.py

# The 12l per-stage rows (measured 32.3% MFU pre-fix): what the remaining
# two-thirds goes to at the seq-512/xla-attention shape.
run_job breakdown12l 600 "$CAP/breakdown.jsonl" \
  python benchmarks/bench_breakdown.py --config tinystories-12l

# Serving engine (PR-2): continuous-batching tokens/sec + p50/p95 request
# latency vs slot-pool concurrency.  The curve capacity planning reads —
# how much chip the slot pool recovers as in-flight requests stack up.
# Each cell warms its prefill buckets first, so rows time steady-state
# serving; compiled_programs in every row pins the bounded-compile claim
# on real hardware.
for conc in 1 4 8; do
  run_job "serve_ts4l_$conc" 900 "$CAP/serving.jsonl" \
    python benchmarks/bench_serving.py --config tinystories-4l \
    --concurrency "$conc"
done
run_job serve_gpt2s_4 1800 "$CAP/serving.jsonl" \
  python benchmarks/bench_serving.py --config gpt2-small-32k \
  --concurrency 4 --requests 8

# Paged-KV serving (PR 8): open-loop Poisson arrivals with a shared
# system prefix on half the requests — dense row first (the headline the
# paged row is judged against), then the paged engine with radix prefix
# sharing + chunked prefill.  The self-report at the end diffs the two:
# prefix_hit_rate > 0 and lower prefill_compute_s is the paged win; the
# p99 columns pin decode latency under the same arrival process.
run_job serve_open_dense 900 "$CAP/serving_paged.jsonl" \
  python benchmarks/bench_serving.py --config tinystories-4l \
  --concurrency 8 --requests 64 --qps 8 --shared-prefix-len 64
run_job serve_open_paged 900 "$CAP/serving_paged.jsonl" \
  python benchmarks/bench_serving.py --config tinystories-4l \
  --concurrency 8 --requests 64 --qps 8 --shared-prefix-len 64 \
  --paged --block-size 16 --prefill-chunk 64 --prefill-budget 128

# Paged-NATIVE flash decode + int8 KV (ISSUE 9): the same arrival process
# served (a) through the block-table-native kernel — the per-tick
# contiguous KV gather is gone from the tick — and (b) additionally with
# int8 KV blocks (block 32: int8 sublane alignment).  Rows carry
# kv_pool_bytes / kv_bytes_per_token, so the memory-traffic claims land
# machine-checked next to the gather-path paged row above.
run_job serve_open_pnative 900 "$CAP/serving_paged.jsonl" \
  python benchmarks/bench_serving.py --config tinystories-4l \
  --concurrency 8 --requests 64 --qps 8 --shared-prefix-len 64 \
  --paged --block-size 16 --prefill-chunk 64 --prefill-budget 128 \
  --decode-attention paged
run_job serve_open_pnative_i8 900 "$CAP/serving_paged.jsonl" \
  python benchmarks/bench_serving.py --config tinystories-4l \
  --concurrency 8 --requests 64 --qps 8 --shared-prefix-len 64 \
  --paged --block-size 32 --prefill-chunk 64 --prefill-budget 128 \
  --decode-attention paged --kv-dtype int8

# Speculative decoding (ISSUE 10): the same arrival process through the
# spec engine — a 1-layer truncated-view draft proposes 4 tokens per slot
# per tick, one batched target verify pass judges them, rejected KV rolls
# back through the kvpool rewind.  Rows carry accept_rate /
# tokens_per_target_step / draft_overhead_frac; the self-report at the
# end judges them against the paged headline (tokens_per_target_step >> 1
# is the win — each emitted token costs a fraction of a target HBM
# sweep).
run_job serve_open_spec 900 "$CAP/serving_paged.jsonl" \
  python benchmarks/bench_serving.py --config tinystories-4l \
  --concurrency 8 --requests 64 --qps 8 --shared-prefix-len 64 \
  --paged --block-size 16 --prefill-chunk 64 --prefill-budget 128 \
  --speculate 4 --draft-layers 1

# int8-weight quantized decode + fused sample-in-kernel (ISSUE 11): the
# paged-native arrival process served with (a) per-channel int8 matmul
# weights dequantized in registers and the tick tail fused into one
# kernel, and (b) the same on the speculative engine (quantized verify +
# fused accept/residual).  Rows carry tick_weight_bytes / params_bytes /
# tick_arithmetic_intensity next to the serve_open_pnative headline, so
# the ~2x weight-stream cut and its tok/s effect land machine-checked.
run_job serve_open_w8 900 "$CAP/serving_paged.jsonl" \
  python benchmarks/bench_serving.py --config tinystories-4l \
  --concurrency 8 --requests 64 --qps 8 --shared-prefix-len 64 \
  --paged --block-size 16 --prefill-chunk 64 --prefill-budget 128 \
  --decode-attention paged --weight-dtype int8 --fused-sampling
run_job serve_open_w8_spec 900 "$CAP/serving_paged.jsonl" \
  python benchmarks/bench_serving.py --config tinystories-4l \
  --concurrency 8 --requests 64 --qps 8 --shared-prefix-len 64 \
  --paged --block-size 16 --prefill-chunk 64 --prefill-budget 128 \
  --speculate 4 --draft-layers 1 --weight-dtype int8 --fused-sampling

# Disaggregated prefill/decode serving (ISSUE 15): the bimodal long/short
# prompt mix served (a) by TWO monolithic role=both engines round-robin
# and (b) by one prefill-role + one decode-role engine wired through the
# KV migration path — equal engine count, same Poisson arrivals.  Rows
# carry per-bucket p50/p95/p99 latency + decode fields and the decode
# engine's compiled-program count (the no-chunk-ladder bound); the
# self-report at the end diffs decode_p99_s — the number disaggregation
# exists to move — and posts it next to the serve_open_pnative headline.
run_job serve_open_mix_mono 900 "$CAP/serving_disagg.jsonl" \
  python benchmarks/bench_serving.py --config tinystories-4l \
  --concurrency 8 --requests 64 --qps 8 --paged --block-size 16 \
  --prefill-chunk 64 --prefill-budget 128 \
  --prompt-mix 12,160,0.25 --replicas 2
run_job serve_open_disagg 900 "$CAP/serving_disagg.jsonl" \
  python benchmarks/bench_serving.py --config tinystories-4l \
  --concurrency 8 --requests 64 --qps 8 --paged --block-size 16 \
  --prefill-chunk 64 --prefill-budget 128 \
  --prompt-mix 12,160,0.25 --disagg

# Restart-to-traffic (ROADMAP item 5): one row timing a serve replica
# from SPAWN to first token through the router's rejoin path, cold vs
# `bpe-tpu warmup`-warmed compile cache — the rolling-deploy window.
# The bench parent pins itself to CPU; the spawned replicas own the chip
# sequentially.
run_job restart_traffic 1800 "$CAP/restart.jsonl" \
  python benchmarks/bench_serving.py --config tinystories-4l --restart \
  --paged --block-size 16 --decode-attention paged

# Self-healing fleet control plane (ISSUE 20): the diurnal ramp (rate
# ramp + shifting long-prompt mix) served by a real subprocess fleet —
# static baseline first (fixed threshold, no elastic slot spawned), then
# the controller-managed run (threshold retunes follow the mix, the
# sustained queue-growth alert spawns the elastic replica, hot sessions
# rebalance over the wire), then the chaos variant (the always-on
# replica SIGKILLed mid-decode + its first /kv/import blackholed): the
# row's failed/respawns/suspect_recoveries fields show what the
# respawn + suspect-probe + idempotent-retry stack recovered.  The
# parent is CPU-pinned jax-free (platform "subprocess"); replicas own
# the chip sequentially with the shared compile cache.
run_job controller_ramp_static 1800 "$CAP/controller_ramp.jsonl" \
  python benchmarks/bench_serving.py --config tinystories-4l \
  --controller-static --requests 48 --qps 4
run_job controller_ramp 1800 "$CAP/controller_ramp.jsonl" \
  python benchmarks/bench_serving.py --config tinystories-4l \
  --controller --requests 48 --qps 4
run_job fleet_chaos 1800 "$CAP/controller_ramp.jsonl" \
  python benchmarks/bench_serving.py --config tinystories-4l \
  --controller --chaos --requests 48 --qps 4

# Dynamics-introspection overhead (PR 4): the headline config with the
# in-graph telemetry.dynamics stats compiled into the step (per-layer
# norms, update ratios, activation taps), captured to its own file
# (suffix _dynamics) — the <2% tokens/sec overhead claim is measured on
# the chip, not asserted.  Marker "-" (re-run every pass, like the
# headline): the self-report at the end compares this capture to the
# SAME pass's fresh headline, so environment drift between passes can
# never masquerade as introspection overhead.
run_job - 300 "$OUT/bench_dynamics.jsonl" \
  env BENCH_DYNAMICS=1 BENCH_NO_CPU_FALLBACK=1 BENCH_DRIVER_FLAG=0 \
  python bench.py

# Performance attribution (PR 6): the XLA cost-model roofline of the
# headline config's compiled step + the measured compute/collective/host
# split on the real chip — the instrument every MFU optimisation that
# follows gates against.  --json emits one machine row (with "platform",
# so the CPU-fallback guard applies); the stream lands in the mirror-safe
# scratch for bpe-tpu report.
run_job attribution 900 "$OUT/attribution.jsonl" \
  python -m bpe_transformer_tpu.training.cli profile \
  --preset tinystories-4l --batch 32 --measure 10 \
  --metrics-jsonl "$MIR/attribution_stream.jsonl" --json

# Sharded optimizer + step overlap (PR 7): plain dp vs dp+ZeRO-1(+prefetch)
# through the real training loop on every local chip — the row carries
# per-chip opt-state bytes (expect ~1/N), the attribution host-gap split,
# and tok/s/chip for both variants, so the memory win and the throughput
# guardrail land in one machine-checked line.  "--json"-style platform
# field means the CPU-fallback guard applies.
run_job sharded_opt 1500 "$CAP/sharded_opt.jsonl" \
  python benchmarks/bench_sharded_opt.py --config tinystories-4l

# Training-MFU knob matrix (ISSUE 13): one measured full-step row per
# (remat_policy, grads_dtype, scan_layers) point on the headline config —
# graduated remat ladder, bf16 grad boundary, scanned layer stack — each
# carrying implied tok/s + mfu + the compiled step's peak_hbm_bytes.  The
# jax-free self-report at the end diffs the best row against the BENCH_r04
# headline (mfu=0.128) so every knob's win is measured, not asserted.
run_job mfu_push 1200 "$CAP/mfu_push.jsonl" \
  python benchmarks/bench_breakdown.py --config tinystories-4l \
  --batch 32 --mfu-push

# Kill-resume smoke (resilience layer, PR 5): SIGTERM a short training
# run midway on the chip and assert the preemption exit code + emergency
# checkpoint + clean --resume completion — the recovery paths the CPU
# chaos suite pins, proven against real TPU runtime behavior (slow
# SIGTERM delivery, device-buffer teardown) once per queue pass history.
run_job kill_resume 900 "$OUT/kill_resume.jsonl" \
  bash benchmarks/kill_resume_smoke.sh

# Multi-worker host tokenization (VERDICT r4 #7) is deliberately NOT a
# queue job: it needs no TPU, and running it here would hold queue.lock
# through a ~15-min CPU-only bench while a tunnel window closes.  The
# recovery watcher (tpu_watch.sh) owns that trap — it re-checks hourly,
# independent of TPU windows, and disarms once the grid is captured.

# Regression self-report (jax-free, CPU-only — holds no chip time): compare
# this pass's freshly measured headline capture against the one the
# previous pass left behind.  Exit 3 = regression beyond threshold; logged
# loudly (and mirrored) but never fatal — the queue's job is evidence, the
# report makes the delta machine-checked instead of eyeballed.
if [ -e "$OUT/prev_headline_capture.json" ] && [ -e "$HEADLINE_CAP" ] && \
   ! cmp -s "$OUT/prev_headline_capture.json" "$HEADLINE_CAP"; then
  env JAX_PLATFORMS=cpu python -m bpe_transformer_tpu.telemetry.report \
    "$HEADLINE_CAP" --baseline "$OUT/prev_headline_capture.json" \
    >> "$OUT/log" 2>&1
  case $? in
    3) log "REGRESSION: headline capture regressed vs previous pass (report above)";;
    0) log "headline capture delta vs previous pass: within threshold";;
    *) log "headline regression self-report failed (non-fatal)";;
  esac
fi
# Dynamics-overhead self-report (jax-free, CPU-only): the _dynamics
# capture vs the plain headline capture at a 2% gate.  Exit 3 = the
# in-graph introspection costs more than the documented budget; logged
# loudly, never fatal (evidence first).
DYN_CAP="$CAP/tpu_capture_tinystories-4l_dynamics.json"
if [ -e "$DYN_CAP" ] && [ -e "$HEADLINE_CAP" ]; then
  env JAX_PLATFORMS=cpu python -m bpe_transformer_tpu.telemetry.report \
    "$DYN_CAP" --baseline "$HEADLINE_CAP" --threshold-pct 2 \
    >> "$OUT/log" 2>&1
  case $? in
    3) log "DYNAMICS OVERHEAD: tokens/sec >2% below the plain headline (report above)";;
    0) log "dynamics overhead vs plain headline: within the 2% budget";;
    *) log "dynamics overhead self-report failed (non-fatal)";;
  esac
fi
# Attribution self-report (jax-free, CPU-only): surface the measured
# compute/collective/host-gap fractions next to the headline capture's
# numbers in the queue log — the "where the missing MFU goes" line an
# operator reads first after each pass.
if [ -s "$OUT/attribution.jsonl" ]; then
  ATTR_LINE=$(env JAX_PLATFORMS=cpu python - "$OUT/attribution.jsonl" <<'PY'
import json, sys

row = None
for ln in open(sys.argv[1]):
    ln = ln.strip()
    if not ln:
        continue
    try:
        r = json.loads(ln)
    except json.JSONDecodeError:
        continue
    if r.get("metric") == "attribution":
        row = r  # newest row wins
if row is None:
    sys.exit(0)


def pct(v):
    return f"{v:.0%}" if isinstance(v, (int, float)) else "n/a"


print(
    f"compute={pct(row.get('compute_frac'))} "
    f"collective={pct(row.get('collective_frac'))} "
    f"host_gap={pct(row.get('host_gap_frac'))} "
    f"device_ms={(row.get('device_step_s') or 0) * 1e3:.2f}"
)
PY
)
  [ -n "$ATTR_LINE" ] && log "attribution self-report: $ATTR_LINE"
fi
# Sharded-optimizer self-report (jax-free, CPU-only): the newest
# sharded_opt row's per-chip opt-state bytes, host-gap fractions, and
# tok/s/chip vs the plain variant AND vs the plain headline capture —
# the PR-7 "did the memory/overlap win land without costing speed" line.
if [ -s "$CAP/sharded_opt.jsonl" ]; then
  SHARD_LINE=$(env JAX_PLATFORMS=cpu python - "$CAP/sharded_opt.jsonl" "$HEADLINE_CAP" <<'PY'
import json, sys

row = None
for ln in open(sys.argv[1]):
    ln = ln.strip()
    if not ln:
        continue
    try:
        r = json.loads(ln)
    except json.JSONDecodeError:
        continue
    if r.get("metric") == "sharded_opt":
        row = r  # newest row wins
if row is None:
    sys.exit(0)


def pct(v):
    return f"{v:.0%}" if isinstance(v, (int, float)) else "n/a"


def num(v):
    return f"{v:,.0f}" if isinstance(v, (int, float)) else "n/a"


headline = None
try:
    with open(sys.argv[2]) as f:
        cap = json.load(f)
    parsed = cap.get("parsed") if isinstance(cap.get("parsed"), dict) else cap
    headline = parsed.get("value")
except Exception:
    pass

parts = [
    f"opt_bytes/chip {num(row.get('opt_state_bytes'))} "
    f"(plain {num(row.get('opt_state_bytes_plain'))}, "
    f"ratio {row.get('opt_bytes_ratio', 'n/a')})",
    f"host_gap {pct(row.get('host_gap_frac'))} "
    f"(plain {pct(row.get('host_gap_frac_plain'))})",
    f"tok/s/chip {num(row.get('value'))} "
    f"(plain {num(row.get('plain_tokens_per_sec_per_chip'))})",
]
if isinstance(headline, (int, float)):
    parts.append(f"headline capture {num(headline)}")
print("  ".join(parts))
PY
)
  [ -n "$SHARD_LINE" ] && log "sharded_opt self-report: $SHARD_LINE"
fi
# Training-MFU-push self-report (jax-free, CPU-only): the newest mfu_push
# matrix — per-knob tok/s + mfu + peak-HBM vs the baseline (none/f32) row
# and vs the replayed BENCH_r04 headline (674k tok/s/chip, mfu=0.128).
# NOTE: BENCH_r03/r04 are a replayed 2026-07-31 capture; the PR 7-12 chip
# jobs (sharded_opt, serve_open_pnative*, restart_traffic, serve_open_spec,
# serve_open_w8*) are still queued-but-unmeasured — drain this queue on a
# live chip window before claiming any cross-PR win.
if [ -s "$CAP/mfu_push.jsonl" ]; then
  MFU_LINE=$(env JAX_PLATFORMS=cpu python - "$CAP/mfu_push.jsonl" "$HEADLINE_CAP" <<'PY'
import json, sys

rows = {}
for ln in open(sys.argv[1]):
    ln = ln.strip()
    if not ln:
        continue
    try:
        r = json.loads(ln)
    except json.JSONDecodeError:
        continue
    if r.get("stage") == "mfu_push" and r.get("platform") != "cpu":
        key = (r.get("remat_policy"), r.get("grads_dtype"),
               bool(r.get("scan_layers")))
        rows[key] = r  # newest row per knob point wins
if not rows:
    sys.exit(0)

base = rows.get(("none", "float32", False))
best = max(rows.values(), key=lambda r: r.get("tokens_per_sec") or 0)
headline = None
try:
    with open(sys.argv[2]) as f:
        cap = json.load(f)
    parsed = cap.get("parsed") if isinstance(cap.get("parsed"), dict) else cap
    headline = parsed.get("value")
except Exception:
    pass


def knob(r):
    tags = [r.get("remat_policy") or "?", r.get("grads_dtype") or "?"]
    if r.get("scan_layers"):
        tags.append("scan")
    return "+".join(tags)


def num(v):
    return f"{v:,.0f}" if isinstance(v, (int, float)) else "n/a"


parts = [f"best {knob(best)}: {num(best.get('tokens_per_sec'))} tok/s "
         f"mfu={best.get('mfu', 'n/a')} "
         f"peak {num(best.get('peak_hbm_bytes'))} B"]
if base is not None and base is not best:
    parts.append(f"baseline none+f32: {num(base.get('tokens_per_sec'))} "
                 f"tok/s peak {num(base.get('peak_hbm_bytes'))} B")
if isinstance(headline, (int, float)):
    parts.append(f"BENCH_r04 headline {num(headline)} tok/s/chip (replayed "
                 "2026-07-31 capture; PR 7-12 chip jobs still undrained)")
print("  ".join(parts))
PY
)
  [ -n "$MFU_LINE" ] && log "mfu_push self-report: $MFU_LINE"
fi
# Paged-serving self-report (jax-free, CPU-only): newest paged vs dense
# open-loop rows — prefix-cache hit rate, prefill compute delta, and the
# p99 guardrail under the same Poisson arrivals.
if [ -s "$CAP/serving_paged.jsonl" ]; then
  PAGED_LINE=$(env JAX_PLATFORMS=cpu python - "$CAP/serving_paged.jsonl" <<'PY'
import json, sys

rows = {}
for ln in open(sys.argv[1]):
    ln = ln.strip()
    if not ln:
        continue
    try:
        r = json.loads(ln)
    except json.JSONDecodeError:
        continue
    if "qps_target" in r:
        rows[r.get("engine", "dense")] = r  # newest row per engine wins
paged, dense = rows.get("paged"), rows.get("dense")
if paged is None:
    sys.exit(0)


def num(v, d=4):
    return f"{v:,.{d}g}" if isinstance(v, (int, float)) else "n/a"


parts = [
    f"prefix_hit_rate {num(paged.get('prefix_hit_rate'))}",
    f"prefill_compute {num(paged.get('prefill_compute_s'))}s"
    + (f" (dense {num(dense.get('prefill_compute_s'))}s)" if dense else ""),
    f"p99 {num(paged.get('latency_p99_s'))}s"
    + (f" (dense {num(dense.get('latency_p99_s'))}s)" if dense else ""),
    f"tok/s {num(paged.get('gen_tok_per_s'))}"
    + (f" (dense {num(dense.get('gen_tok_per_s'))})" if dense else ""),
]
hits = paged.get("prefix_hits")
if isinstance(hits, (int, float)) and hits <= 0:
    parts.append("WARNING: no prefix-cache hits on a shared-prefix mix")
print("  ".join(parts))
PY
)
  [ -n "$PAGED_LINE" ] && log "paged serving self-report: $PAGED_LINE"
fi
# Paged-native / int8 self-report (jax-free, CPU-only): newest row per
# (decode_attention, kv_dtype) variant — tok/s, p99, and the KV-memory
# fields next to the gather-path paged row, i.e. "did deleting the
# gather and halving the KV width pay, and what did it cost in bytes".
if [ -s "$CAP/serving_paged.jsonl" ]; then
  NATIVE_LINE=$(env JAX_PLATFORMS=cpu python - "$CAP/serving_paged.jsonl" <<'PY'
import json, sys

rows = {}
for ln in open(sys.argv[1]):
    ln = ln.strip()
    if not ln:
        continue
    try:
        r = json.loads(ln)
    except json.JSONDecodeError:
        continue
    if "qps_target" in r and r.get("engine") == "paged":
        key = (r.get("decode_attention", "xla"), r.get("kv_dtype"))
        rows[key] = r  # newest row per variant wins
native = rows.get(("paged", "float32")) or rows.get(("paged", "bfloat16"))
int8 = next((r for (attn, kvd), r in rows.items()
             if attn == "paged" and kvd == "int8"), None)
gather = next((r for (attn, kvd), r in rows.items()
               if attn != "paged"), None)
if native is None and int8 is None:
    sys.exit(0)


def num(v, d=4):
    return f"{v:,.{d}g}" if isinstance(v, (int, float)) else "n/a"


parts = []
if native is not None:
    parts.append(
        f"native tok/s {num(native.get('gen_tok_per_s'))} "
        f"p99 {num(native.get('latency_p99_s'))}s"
        + (f" (gather tok/s {num(gather.get('gen_tok_per_s'))} "
           f"p99 {num(gather.get('latency_p99_s'))}s)" if gather else "")
    )
if int8 is not None:
    parts.append(
        f"int8 tok/s {num(int8.get('gen_tok_per_s'))} "
        f"kv/token {num(int8.get('kv_bytes_per_token'))}B "
        f"pool {num(int8.get('kv_pool_bytes'))}B"
        + (f" (fp kv/token {num(native.get('kv_bytes_per_token'))}B)"
           if native else "")
    )
print("  ".join(parts))
PY
)
  [ -n "$NATIVE_LINE" ] && log "paged-native/int8 self-report: $NATIVE_LINE"
fi
# Speculative-decoding self-report (jax-free, CPU-only): the newest spec
# row's acceptance evidence vs the paged headline under the same Poisson
# arrivals — accept_rate, emitted tokens per target verify pass (>1 means
# each token cost a fraction of a target HBM sweep), the draft's wall
# overhead, and the tok/s + p99 guardrails.
if [ -s "$CAP/serving_paged.jsonl" ]; then
  SPEC_LINE=$(env JAX_PLATFORMS=cpu python - "$CAP/serving_paged.jsonl" <<'PY'
import json, sys

spec = paged = None
for ln in open(sys.argv[1]):
    ln = ln.strip()
    if not ln:
        continue
    try:
        r = json.loads(ln)
    except json.JSONDecodeError:
        continue
    if "qps_target" not in r:
        continue
    if r.get("engine") == "spec":
        spec = r  # newest spec row wins
    elif r.get("engine") == "paged" and r.get("decode_attention") in (
        None, "xla"
    ) and r.get("kv_dtype") != "int8":
        paged = r  # the gather-path paged headline
if spec is None:
    sys.exit(0)


def num(v, d=4):
    return f"{v:,.{d}g}" if isinstance(v, (int, float)) else "n/a"


parts = [
    f"k={spec.get('speculate_k')}",
    f"accept_rate {num(spec.get('accept_rate'))}",
    f"tok/target-step {num(spec.get('tokens_per_target_step'))}",
    f"draft overhead {num(spec.get('draft_overhead_frac'))}",
    f"tok/s {num(spec.get('gen_tok_per_s'))}"
    + (f" (paged {num(paged.get('gen_tok_per_s'))})" if paged else ""),
    f"p99 {num(spec.get('latency_p99_s'))}s"
    + (f" (paged {num(paged.get('latency_p99_s'))}s)" if paged else ""),
]
tpts = spec.get("tokens_per_target_step")
if isinstance(tpts, (int, float)) and tpts <= 1.0:
    parts.append("WARNING: speculation emitted <= 1 token per target step")
print("  ".join(parts))
PY
)
  [ -n "$SPEC_LINE" ] && log "speculative-decoding self-report: $SPEC_LINE"
fi
# Quantized-weight decode self-report (jax-free, CPU-only): the newest
# int8-weight row vs the act-width paged-native headline under the same
# Poisson arrivals — the per-tick weight bytes the quantization halves,
# the tok/s + p99 guardrails, and the analytic tick-roofline floor.
if [ -s "$CAP/serving_paged.jsonl" ]; then
  W8_LINE=$(env JAX_PLATFORMS=cpu python - "$CAP/serving_paged.jsonl" <<'PY'
import json, sys

w8 = headline = None
for ln in open(sys.argv[1]):
    ln = ln.strip()
    if not ln:
        continue
    try:
        r = json.loads(ln)
    except json.JSONDecodeError:
        continue
    if "qps_target" not in r:
        continue
    if r.get("weight_dtype") == "int8" and r.get("engine") != "spec":
        w8 = r  # newest int8-weight row wins
    elif (
        r.get("decode_attention") == "paged"
        and r.get("weight_dtype") in (None, "float32", "bfloat16")
        and r.get("engine") != "spec"
    ):
        headline = r  # the act-width paged-native headline
if w8 is None:
    sys.exit(0)


def num(v, d=4):
    return f"{v:,.{d}g}" if isinstance(v, (int, float)) else "n/a"


parts = [
    f"tick weight bytes {num(w8.get('tick_weight_bytes'))}"
    + (
        f" (act {num(headline.get('tick_weight_bytes'))})"
        if headline else ""
    ),
    f"tok/s {num(w8.get('gen_tok_per_s'))}"
    + (f" (act {num(headline.get('gen_tok_per_s'))})" if headline else ""),
    f"p99 {num(w8.get('latency_p99_s'))}s"
    + (f" (act {num(headline.get('latency_p99_s'))}s)" if headline else ""),
    f"tick AI {num(w8.get('tick_arithmetic_intensity'))} flops/B",
    f"floor {num(w8.get('tick_projected_s'))}s/tick",
    "fused" if w8.get("fused_sampling") else "unfused",
]
tw, hw = w8.get("tick_weight_bytes"), (headline or {}).get("tick_weight_bytes")
if isinstance(tw, (int, float)) and isinstance(hw, (int, float)) and hw:
    ratio = tw / hw
    parts.append(f"weight-stream ratio {ratio:.2f}x")
    if ratio > 0.6:
        parts.append("WARNING: int8 weight stream not ~2x smaller")
print("  ".join(parts))
PY
)
  [ -n "$W8_LINE" ] && log "int8-weight decode self-report: $W8_LINE"
fi
# Disaggregated-serving self-report (jax-free, CPU-only): the newest
# disagg row vs the monolithic equal-engine-count row under the same
# bimodal Poisson mix — decode p99 (overall and short-bucket) is the
# headline disaggregation exists to move; migrations>0 proves the
# two-tier path actually carried the long prompts, and the decode
# engine's compiled-program count pins the no-chunk-ladder claim.  Judged
# next to the serve_open_pnative headline (NOTE: replayed-capture caveat
# — BENCH_r03/r04 are a 2026-07-31 replay; drain this queue on a live
# chip window before claiming any cross-PR win).
if [ -s "$CAP/serving_disagg.jsonl" ]; then
  DISAGG_LINE=$(env JAX_PLATFORMS=cpu python - "$CAP/serving_disagg.jsonl" <<'PY'
import json, sys

disagg = mono = None
for ln in open(sys.argv[1]):
    ln = ln.strip()
    if not ln:
        continue
    try:
        r = json.loads(ln)
    except json.JSONDecodeError:
        continue
    if "prompt_mix" not in r:
        continue
    if r.get("engine", "").startswith("disagg"):
        disagg = r  # newest disagg row wins
    elif r.get("engine", "").startswith("mono"):
        mono = r
if disagg is None:
    sys.exit(0)


def num(v, d=4):
    return f"{v:,.{d}g}" if isinstance(v, (int, float)) else "n/a"


parts = [
    f"decode p99 {num(disagg.get('decode_p99_s'))}s"
    + (f" (mono {num(mono.get('decode_p99_s'))}s)" if mono else ""),
    f"short-bucket decode p99 {num(disagg.get('short_decode_p99_s'))}s"
    + (f" (mono {num(mono.get('short_decode_p99_s'))}s)" if mono else ""),
    f"p99 {num(disagg.get('latency_p99_s'))}s"
    + (f" (mono {num(mono.get('latency_p99_s'))}s)" if mono else ""),
    f"migrations {disagg.get('migrations')}",
    f"decode-engine programs {disagg.get('decode_compiled_programs')}",
    f"failed {disagg.get('failed')}",
]
dp, mp = disagg.get("decode_p99_s"), (mono or {}).get("decode_p99_s")
if isinstance(dp, (int, float)) and isinstance(mp, (int, float)) and dp >= mp:
    parts.append("WARNING: disaggregated decode p99 NOT below monolithic")
if not disagg.get("migrations"):
    parts.append("WARNING: no migrations — the two-tier path never ran")
print("  ".join(parts))
PY
)
  [ -n "$DISAGG_LINE" ] && log "disaggregated-serving self-report: $DISAGG_LINE"
fi
# Controller-ramp self-report (jax-free, CPU-only): newest row per
# (mode, chaos) — the controller-managed ramp vs the static fleet on
# peak-phase p99 (elastic capacity + retune are supposed to move it),
# the action counts proving the loop actually acted, and the chaos
# row's recovery evidence (failed / respawns / suspect recoveries).
if [ -s "$CAP/controller_ramp.jsonl" ]; then
  CTRL_LINE=$(env JAX_PLATFORMS=cpu python - "$CAP/controller_ramp.jsonl" <<'PY'
import json, sys

rows = {}
for ln in open(sys.argv[1]):
    ln = ln.strip()
    if not ln:
        continue
    try:
        r = json.loads(ln)
    except json.JSONDecodeError:
        continue
    if str(r.get("metric", "")).startswith("controller_ramp"):
        rows[(r.get("mode"), bool(r.get("chaos")))] = r  # newest wins
managed = rows.get(("controller", False))
static = rows.get(("static", False))
chaos = rows.get(("controller", True))
if managed is None and chaos is None:
    sys.exit(0)


def num(v, d=4):
    return f"{v:,.{d}g}" if isinstance(v, (int, float)) else "n/a"


def peak(r):
    for ph in r.get("phases") or []:
        if ph.get("phase") == "peak":
            return ph.get("latency_p99_s")
    return None


parts = []
if managed is not None:
    parts.append(
        f"peak p99 {num(peak(managed))}s"
        + (f" (static {num(peak(static))}s)" if static else "")
    )
    parts.append(
        f"actions ok/failed {managed.get('controller_actions_ok')}"
        f"/{managed.get('controller_actions_failed')}"
        f" (scale_up {managed.get('scale_ups')}, retune "
        f"{managed.get('retunes')}, rebalance "
        f"{managed.get('rebalances')})"
    )
    parts.append(
        f"threshold {managed.get('prefill_threshold_initial')}"
        f"->{managed.get('prefill_threshold_final')}"
    )
    mp, sp = peak(managed), peak(static) if static else None
    if isinstance(mp, (int, float)) and isinstance(sp, (int, float)) \
            and mp >= sp:
        parts.append("WARNING: controller peak p99 NOT below static")
    if managed.get("controller_breaker") == "tripped":
        parts.append("WARNING: controller breaker tripped during ramp")
if chaos is not None:
    parts.append(
        f"chaos: failed {chaos.get('failed')}, respawns "
        f"{chaos.get('respawns')}, suspect recoveries "
        f"{chaos.get('suspect_recoveries')}"
    )
    if chaos.get("failed"):
        parts.append("WARNING: chaos ramp dropped requests")
print("  ".join(parts))
PY
)
  [ -n "$CTRL_LINE" ] && log "controller-ramp self-report: $CTRL_LINE"
fi
# Restart-to-traffic self-report (jax-free, CPU-only): the newest restart
# row's cold vs warmed spawn->first-token seconds — ROADMAP item 5's
# rolling-deploy number.
if [ -s "$CAP/restart.jsonl" ]; then
  RESTART_LINE=$(env JAX_PLATFORMS=cpu python - "$CAP/restart.jsonl" <<'PY'
import json, sys

row = None
for ln in open(sys.argv[1]):
    ln = ln.strip()
    if not ln:
        continue
    try:
        r = json.loads(ln)
    except json.JSONDecodeError:
        continue
    if str(r.get("metric", "")).startswith("restart_to_traffic"):
        row = r  # newest row wins
if row is None:
    sys.exit(0)


def num(v):
    return f"{v:,.3g}" if isinstance(v, (int, float)) else "n/a"


print(
    f"cold {num(row.get('cold_s'))}s -> warmed {num(row.get('warm_s'))}s "
    f"(speedup {num(row.get('speedup'))}x, warmup cost "
    f"{num(row.get('warmup_s'))}s, {row.get('programs_warmed')} programs)"
)
PY
)
  [ -n "$RESTART_LINE" ] && log "restart-to-traffic self-report: $RESTART_LINE"
fi
log "queue pass complete"
# Same size guard as the restore: never shrink the mirrored history.
if [ "$(stat -c%s "$OUT/log" 2>/dev/null || echo 0)" -ge "$(stat -c%s "$MIR/queue_log" 2>/dev/null || echo 0)" ]; then
  cp -a "$OUT/log" "$MIR/queue_log" 2>/dev/null || true
fi
