"""Host tokenization benchmarks, mirroring the reference's published numbers.

The reference's only published performance artifacts are notebook timings on
an M3 Pro laptop (SURVEY §6 / BASELINE.md): pre-tokenization throughput, BPE
training time, and streaming-encode time on TinyStories.  This script
measures the same three stages here — Python path vs the native C++ engine —
on a corpus assembled from the reference's fixture sample.

Usage:
    python benchmarks/bench_tokenization.py [--mb 20] [--vocab 10000]

Prints one JSON line per stage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

SAMPLE = Path("/root/reference/tests/fixtures/tinystories_sample.txt")


def usable_cores() -> int:
    """Cores this process can actually burn: CPU affinity intersected with
    the cgroup-v2 quota (this container advertises many host CPUs but pins
    the quota to 1 — `cpu_count()` alone would report a fantasy grid;
    VERDICT r4 #7 / benchmarks/RESULTS.md host-tokenization caveat)."""
    n = len(os.sched_getaffinity(0))
    try:
        quota_raw, period_raw = (
            Path("/sys/fs/cgroup/cpu.max").read_text().split()
        )
        if quota_raw != "max":
            n = min(n, max(1, int(int(quota_raw) / int(period_raw))))
    except (OSError, ValueError):
        pass
    return max(n, 1)


def build_corpus(mb: float, out: Path) -> Path:
    base = SAMPLE.read_text(encoding="utf-8")
    reps = max(1, int(mb * 1e6 / len(base.encode())))
    with open(out, "w", encoding="utf-8") as f:
        for _ in range(reps):
            f.write(base)
    return out


def timed(fn):
    t0 = time.perf_counter()
    result = fn()
    return time.perf_counter() - t0, result


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--mb", type=float, default=20.0)
    parser.add_argument("--vocab", type=int, default=10_000)
    parser.add_argument(
        "--grid-if-multicore",
        action="store_true",
        help="armed-trap mode (VERDICT r4 #7): exit immediately with no "
        "rows unless >1 core is actually usable; otherwise capture the "
        "2/4/8-worker scaling grid the parallel-scaling claim needs",
    )
    parser.add_argument(
        "--covered-file",
        type=Path,
        default=None,
        help="with --grid-if-multicore: also exit without rows when this "
        "JSONL already records a grid captured at >= the current core "
        "count (so the trap disarms once covered but RE-fires if the "
        "container later grows more cores)",
    )
    args = parser.parse_args()

    if args.grid_if_multicore:
        cores_now = usable_cores()
        if cores_now <= 1:
            print(
                f"single usable core ({cores_now}); multi-worker grid "
                "still environment-blocked — trap stays armed",
                file=sys.stderr,
            )
            return 0
        if args.covered_file is not None and args.covered_file.exists():
            covered = 0
            for line in args.covered_file.read_text().splitlines():
                try:
                    row = json.loads(line)
                    if isinstance(row, dict):  # torn fragments can parse as
                        # bare scalars; .get on those would AttributeError
                        covered = max(covered, int(row.get("usable_cores") or 0))
                except (json.JSONDecodeError, TypeError, ValueError):
                    continue
            if covered >= cores_now:
                print(
                    f"grid already captured at {covered} cores "
                    f"(now {cores_now}); trap disarmed",
                    file=sys.stderr,
                )
                return 0

    from multiprocessing import cpu_count

    from bpe_transformer_tpu.native import is_available
    from bpe_transformer_tpu.tokenization import BPETokenizer, BPETrainer
    from bpe_transformer_tpu.tokenization.pretokenization import count_pretokens

    tmp = Path(tempfile.mkdtemp(prefix="bench_tok_"))
    corpus = build_corpus(args.mb, tmp / "corpus.txt")
    size_mb = corpus.stat().st_size / 1e6
    specials = ["<|endoftext|>"]
    results = []

    def report(stage: str, seconds: float, python_seconds: float | None = None, **extra):
        rec = {
            "stage": stage,
            "seconds": round(seconds, 3),
            "mb_per_s": round(size_mb / seconds, 2),
            **extra,
        }
        if python_seconds is not None:
            rec["python_seconds"] = round(python_seconds, 3)
            rec["speedup"] = round(python_seconds / seconds, 2)
        results.append(rec)
        print(json.dumps(rec))

    # 1. Pre-tokenization counting: engine x workers grid (the reference's
    #    parallel_pretokenization anchor is 9.8-13.1 M pretokens/s with all
    #    cores on an M3 Pro, BASELINE.md).  ``pretokens/s`` counts the
    #    OCCURRENCES scanned (sum of counts), the anchor's unit.
    n_pretokens = None
    # count_pretokens clamps workers to the host CPU count; bench the
    # EFFECTIVE counts so no row is mislabeled (this container may expose
    # a single core, collapsing the grid).  `usable_cores()` (affinity ∧
    # cgroup quota), not cpu_count(): advertised host CPUs that the quota
    # never schedules would label fantasy rows.
    cores = usable_cores()
    worker_grid = sorted({min(w, cores) for w in (1, 2, 4, 8, cores)})
    for engine in (["python", "native"] if is_available() else ["python"]):
        for workers in worker_grid:
            t_count, counts = timed(
                lambda e=engine, w=workers: count_pretokens(
                    corpus, specials, training=True, n_workers=w,
                    parallel=w > 1, engine=e,
                )
            )
            n_pretokens = sum(counts.values())
            report(
                "pretokenize_count",
                t_count,
                engine=engine,
                n_workers=workers,
                pretokens_per_s=round(n_pretokens / t_count),
            )

    # 2. BPE training, full pipeline (native streams + C++ merge loop).
    trainer = BPETrainer(vocab_size=args.vocab, special_tokens=specials)
    t_native, _ = timed(lambda: trainer.train(corpus))
    os.environ["BT_NATIVE"] = "0"
    try:
        t_py, _ = timed(
            lambda: BPETrainer(
                vocab_size=args.vocab, special_tokens=specials
            ).train(corpus)
        )
    finally:
        os.environ.pop("BT_NATIVE", None)
    report(
        "bpe_train_full",
        t_native,
        python_seconds=t_py,
        engine="native" if is_available() else "python",
    )

    # 3. Streaming encode: native engine at 1/4/all workers (the C++
    #    encoder runs inside every pool worker), python-path serial anchor.
    #    The reference's anchor: 108.69 s for ~21 MB serial (BASELINE.md).
    tok = BPETokenizer(trainer.vocab, trainer.merges, specials)
    tok_py = BPETokenizer(dict(trainer.vocab), list(trainer.merges), specials)
    tok_py._native_tried = True

    def encode_stream(t, workers=None):
        with open(corpus, encoding="utf-8") as f:
            n = 0
            for _ in t.encode_iterable(f, n_workers=workers):
                n += 1
        return n

    t_enc_py, _ = timed(lambda: encode_stream(tok_py))
    n_tokens = None
    for workers in worker_grid:
        t_enc, n_tokens = timed(lambda w=workers: encode_stream(tok, workers=w))
        report(
            "encode_stream",
            t_enc,
            python_seconds=t_enc_py if workers == 1 else None,
            engine="native" if is_available() else "python",
            n_workers=workers,
            tokens_per_s=round(n_tokens / t_enc),
        )
    print(
        json.dumps(
            {
                "corpus_mb": round(size_mb, 1),
                "tokens": n_tokens,
                "pretokens": n_pretokens,
                "cpu_count": cpu_count(),
                "usable_cores": cores,
                "captured_at_utc": time.strftime(
                    "%Y-%m-%dT%H:%M:%S+00:00", time.gmtime()
                ),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
