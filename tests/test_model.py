"""Transformer block/LM vs an independent torch oracle + contract checks.

The reference's trained-weights fixture (`ts_tests/model.pt`) is absent from
the mounted checkout (.MISSING_LARGE_BLOBS), so full-LM snapshot parity is
unverifiable; instead an independent torch implementation of the pinned
architecture (pre-norm RMSNorm / RoPE / causal MHA / SwiGLU, head-concat
weight layout per `adapters.py:209-361`) serves as the oracle on random
weights drawn in the reference state-dict schema.
"""

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import jax
import jax.numpy as jnp

from bpe_transformer_tpu.models import (
    TS_TEST_CONFIG,
    ModelConfig,
    forward,
    init_params,
    params_from_state_dict,
    state_dict_from_params,
    transformer_block,
)
from bpe_transformer_tpu.ops import rope_tables

# ------------------------------------------------------ torch oracle


def torch_rope(x, positions, theta):
    d = x.shape[-1]
    inv = theta ** (-torch.arange(0, d, 2, dtype=torch.float32) / d)
    ang = positions.float()[:, None] * inv[None, :]
    cos, sin = torch.cos(ang), torch.sin(ang)
    xe, xo = x[..., 0::2], x[..., 1::2]
    out = torch.empty_like(x)
    out[..., 0::2] = xe * cos - xo * sin
    out[..., 1::2] = xe * sin + xo * cos
    return out


def torch_mha(x, qw, kw, vw, ow, n_heads, theta=None):
    b, s, d = x.shape
    dh = d // n_heads
    split = lambda t: (x @ t.T).view(b, s, n_heads, dh).transpose(1, 2)
    q, k, v = split(qw), split(kw), split(vw)
    if theta is not None:
        pos = torch.arange(s)
        q = torch_rope(q, pos, theta)
        k = torch_rope(k, pos, theta)
    scores = q @ k.transpose(-1, -2) / dh**0.5
    mask = torch.tril(torch.ones(s, s, dtype=torch.bool))
    scores = scores.masked_fill(~mask, float("-inf"))
    out = (F.softmax(scores, dim=-1) @ v).transpose(1, 2).reshape(b, s, d)
    return out @ ow.T


def torch_rmsnorm(x, w):
    return x * torch.rsqrt(x.pow(2).mean(-1, keepdim=True) + 1e-5) * w


def torch_block(x, w, n_heads, theta):
    h = torch_rmsnorm(x, w["ln1.weight"])
    x = x + torch_mha(
        h,
        w["attn.q_proj.weight"],
        w["attn.k_proj.weight"],
        w["attn.v_proj.weight"],
        w["attn.output_proj.weight"],
        n_heads,
        theta,
    )
    h = torch_rmsnorm(x, w["ln2.weight"])
    ffn = (
        F.silu(h @ w["ffn.w1.weight"].T) * (h @ w["ffn.w3.weight"].T)
    ) @ w["ffn.w2.weight"].T
    return x + ffn


def torch_lm(indices, sd, cfg: ModelConfig):
    x = F.embedding(indices, sd["token_embeddings.weight"])
    for i in range(cfg.num_layers):
        w = {k[len(f"layers.{i}.") :]: v for k, v in sd.items() if k.startswith(f"layers.{i}.")}
        x = torch_block(x, w, cfg.num_heads, cfg.rope_theta)
    x = torch_rmsnorm(x, sd["ln_final.weight"])
    return x @ sd["lm_head.weight"].T


def random_state_dict(cfg: ModelConfig, seed=0):
    g = torch.Generator().manual_seed(seed)
    rand = lambda *s: torch.randn(*s, generator=g) * 0.05
    sd = {
        "token_embeddings.weight": rand(cfg.vocab_size, cfg.d_model),
        "ln_final.weight": 1 + 0.1 * rand(cfg.d_model),
        "lm_head.weight": rand(cfg.vocab_size, cfg.d_model),
    }
    for i in range(cfg.num_layers):
        p = f"layers.{i}."
        sd[p + "attn.q_proj.weight"] = rand(cfg.d_model, cfg.d_model)
        sd[p + "attn.k_proj.weight"] = rand(cfg.d_model, cfg.d_model)
        sd[p + "attn.v_proj.weight"] = rand(cfg.d_model, cfg.d_model)
        sd[p + "attn.output_proj.weight"] = rand(cfg.d_model, cfg.d_model)
        sd[p + "ln1.weight"] = 1 + 0.1 * rand(cfg.d_model)
        sd[p + "ln2.weight"] = 1 + 0.1 * rand(cfg.d_model)
        sd[p + "ffn.w1.weight"] = rand(cfg.d_ff, cfg.d_model)
        sd[p + "ffn.w2.weight"] = rand(cfg.d_model, cfg.d_ff)
        sd[p + "ffn.w3.weight"] = rand(cfg.d_ff, cfg.d_model)
    return sd


CFG = TS_TEST_CONFIG


@pytest.fixture(scope="module")
def oracle_setup():
    sd = random_state_dict(CFG)
    params = params_from_state_dict({k: v.numpy() for k, v in sd.items()}, CFG.num_layers)
    g = torch.Generator().manual_seed(42)
    indices = torch.randint(0, CFG.vocab_size, (4, 12), generator=g)
    return sd, params, indices


def test_block_matches_torch_oracle(oracle_setup):
    sd, params, _ = oracle_setup
    g = torch.Generator().manual_seed(7)
    x = torch.randn(4, 12, CFG.d_model, generator=g)
    expected = torch_block(
        x, {k[len("layers.0.") :]: v for k, v in sd.items() if k.startswith("layers.0.")},
        CFG.num_heads, CFG.rope_theta,
    )
    cos, sin = rope_tables(CFG.d_head, CFG.context_length, CFG.rope_theta)
    actual = transformer_block(
        jnp.asarray(x.numpy()),
        params["layers"][0],
        CFG,
        (cos, sin),
        jnp.arange(12),
    )
    np.testing.assert_allclose(
        np.asarray(actual), expected.numpy(), atol=2e-5, rtol=1e-4
    )


def test_lm_matches_torch_oracle(oracle_setup):
    sd, params, indices = oracle_setup
    expected = torch_lm(indices, sd, CFG)
    actual = forward(params, jnp.asarray(indices.numpy()), CFG)
    np.testing.assert_allclose(
        np.asarray(actual), expected.numpy(), atol=1e-4, rtol=1e-2
    )


def test_lm_truncated_input(oracle_setup):
    sd, params, indices = oracle_setup
    truncated = indices[:, :6]
    expected = torch_lm(truncated, sd, CFG)
    actual = forward(params, jnp.asarray(truncated.numpy()), CFG)
    np.testing.assert_allclose(
        np.asarray(actual), expected.numpy(), atol=1e-4, rtol=1e-2
    )


def test_state_dict_roundtrip(oracle_setup):
    _, params, _ = oracle_setup
    flat = state_dict_from_params(params)
    rebuilt = params_from_state_dict(flat, CFG.num_layers)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params,
        rebuilt,
    )


def test_init_params_shapes():
    params = init_params(jax.random.PRNGKey(0), CFG)
    assert params["token_embeddings"].shape == (CFG.vocab_size, CFG.d_model)
    assert len(params["layers"]) == CFG.num_layers
    assert params["layers"][0]["ffn"]["w1"].shape == (CFG.d_ff, CFG.d_model)
    logits = forward(params, jnp.zeros((2, 8), dtype=jnp.int32), CFG)
    assert logits.shape == (2, 8, CFG.vocab_size)
    assert logits.dtype == jnp.float32


def test_remat_forward_is_identical(oracle_setup):
    _, params, indices = oracle_setup
    import dataclasses

    remat_cfg = dataclasses.replace(CFG, remat=True)
    base = forward(params, jnp.asarray(indices.numpy()), CFG)
    remat = forward(params, jnp.asarray(indices.numpy()), remat_cfg)
    np.testing.assert_allclose(np.asarray(base), np.asarray(remat), atol=1e-6)


def test_bfloat16_activation_path_runs():
    import dataclasses

    cfg = dataclasses.replace(CFG, activation_dtype="bfloat16")
    params = init_params(jax.random.PRNGKey(0), cfg)
    logits = forward(params, jnp.zeros((2, 8), dtype=jnp.int32), cfg)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_ablation_flags_change_output(oracle_setup):
    import dataclasses

    _, params, indices = oracle_setup
    ids = jnp.asarray(indices.numpy())
    base = np.asarray(forward(params, ids, CFG))
    for flag in ("remove_rmsnorm", "use_post_norm", "remove_rope"):
        cfg = dataclasses.replace(CFG, **{flag: True})
        alt = np.asarray(forward(params, ids, cfg))
        assert not np.allclose(alt, base), flag


def test_config_json_roundtrip(tmp_path, reference_fixtures):
    cfg = ModelConfig.from_json(
        reference_fixtures / "ts_tests" / "model_config.json"
    )
    assert cfg == TS_TEST_CONFIG
    cfg.to_json(tmp_path / "cfg.json")
    assert ModelConfig.from_json(tmp_path / "cfg.json") == cfg


# ------------------------------------------------ grouped-query attention


def test_gqa_equals_mha_with_repeated_kv_weights():
    """A GQA forward == an MHA forward whose K/V weights repeat each KV
    head's block once per query group (the defining GQA identity)."""
    import dataclasses

    from bpe_transformer_tpu.models import TS_TEST_CONFIG, forward, init_params

    cfg_gqa = dataclasses.replace(
        TS_TEST_CONFIG, vocab_size=256, num_kv_heads=2
    )  # 4 query heads, 2 KV heads
    cfg_mha = dataclasses.replace(TS_TEST_CONFIG, vocab_size=256)
    params = init_params(jax.random.PRNGKey(0), cfg_gqa)

    def repeat_kv(w):  # (kv*dh, d) -> (H*dh, d), each head block doubled
        dh = cfg_gqa.d_head
        blocks = [w[i * dh : (i + 1) * dh] for i in range(cfg_gqa.num_kv_heads)]
        group = cfg_gqa.num_heads // cfg_gqa.num_kv_heads
        return jnp.concatenate([b for blk in blocks for b in [blk] * group])

    mha_params = jax.tree_util.tree_map(lambda x: x, params)  # shallow copy
    mha_params["layers"] = [
        {
            **layer,
            "attn": {
                **layer["attn"],
                "k_proj": repeat_kv(layer["attn"]["k_proj"]),
                "v_proj": repeat_kv(layer["attn"]["v_proj"]),
            },
        }
        for layer in params["layers"]
    ]

    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, size=(2, 12)), jnp.int32
    )
    out_gqa = forward(params, ids, cfg_gqa)
    out_mha = forward(mha_params, ids, cfg_mha)
    np.testing.assert_allclose(
        np.asarray(out_gqa), np.asarray(out_mha), atol=1e-5
    )


def test_gqa_cached_decode_parity_and_cache_shape():
    """GQA: the KV cache holds only num_kv_heads, and cached greedy decode
    matches the full-forward argmax loop."""
    import dataclasses

    from bpe_transformer_tpu.models import TS_TEST_CONFIG, forward, init_params
    from bpe_transformer_tpu.models.decode import generate_cached, init_kv_cache

    cfg = dataclasses.replace(
        TS_TEST_CONFIG, vocab_size=256, context_length=32, num_kv_heads=1
    )
    cache = init_kv_cache(cfg, batch=2)
    assert cache[0]["k"].shape == (2, 1, 32, cfg.d_head)

    params = init_params(jax.random.PRNGKey(1), cfg)
    prompt = [3, 1, 4, 1, 5]
    out = generate_cached(
        params,
        jnp.asarray([prompt], jnp.int32),
        jax.random.PRNGKey(0),
        config=cfg,
        max_new_tokens=8,
        temperature=0.0,
    )
    seq = list(prompt)
    for _ in range(8):
        logits = forward(params, jnp.asarray([seq], jnp.int32), cfg)
        seq.append(int(jnp.argmax(logits[0, -1])))
    assert [int(t) for t in np.asarray(out[0])] == seq[len(prompt):]


def test_tied_embeddings_share_head():
    """tie_embeddings: no lm_head parameter; logits use the embedding
    matrix; training moves the tied matrix; cached decode matches the full
    forward."""
    import dataclasses

    from bpe_transformer_tpu.models import TS_TEST_CONFIG, forward, init_params
    from bpe_transformer_tpu.models.decode import generate_cached
    from bpe_transformer_tpu.optim import adamw_init
    from bpe_transformer_tpu.training.train_step import TrainHParams, make_train_step

    cfg = dataclasses.replace(
        TS_TEST_CONFIG, vocab_size=256, context_length=32, tie_embeddings=True
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    assert "lm_head" not in params

    # State-dict export/import stays an inverse pair without the head key.
    from bpe_transformer_tpu.models.transformer import (
        params_from_state_dict,
        state_dict_from_params,
    )

    sd = state_dict_from_params(params)
    assert "lm_head.weight" not in sd
    back = params_from_state_dict(sd, cfg.num_layers, tied=True)
    assert "lm_head" not in back
    # Untied load of a tied export fails FAST at the missing key.
    with pytest.raises(KeyError):
        params_from_state_dict(sd, cfg.num_layers)

    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, size=(2, 8)), jnp.int32
    )
    logits = forward(params, ids, cfg)
    assert logits.shape == (2, 8, 256)

    # Cached decode greedy parity (before training: the train step donates
    # and deletes the param buffers).
    prompt = [1, 2, 3, 4]
    out = generate_cached(
        params, jnp.asarray([prompt], jnp.int32), jax.random.PRNGKey(0),
        config=cfg, max_new_tokens=6, temperature=0.0,
    )
    seq = list(prompt)
    for _ in range(6):
        lg = forward(params, jnp.asarray([seq], jnp.int32), cfg)
        seq.append(int(jnp.argmax(lg[0, -1])))
    assert [int(t) for t in np.asarray(out[0])] == seq[len(prompt):]

    # Chunked-loss path exercises lm_head_weight too.
    cfg_chunk = dataclasses.replace(cfg, loss_chunk_size=8)
    step = make_train_step(cfg_chunk, TrainHParams(warmup_iters=1, cosine_cycle_iters=20))
    opt = adamw_init(params)
    p, s, m0 = step(params, opt, ids, jnp.roll(ids, -1, axis=1))
    for _ in range(5):
        p, s, m = step(p, s, ids, jnp.roll(ids, -1, axis=1))
    assert float(m["loss"]) < float(m0["loss"])
