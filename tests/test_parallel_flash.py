"""Flash/chunked ring attention parity (split from test_parallel.py: these
compile grad-of-shard_map programs with interpret-mode Pallas calls and
dominate the file's runtime).

Whole module lives behind the ``slow`` marker: every case runs grad-of-
shard_map with interpret-mode Pallas on the 8-device CPU mesh — minutes
each, far outside the tier-1 wall-clock budget.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.slow

from bpe_transformer_tpu.models import TS_TEST_CONFIG, init_params
from bpe_transformer_tpu.optim import adamw_init
from bpe_transformer_tpu.parallel import make_mesh
from bpe_transformer_tpu.training.train_step import TrainHParams, make_train_step

CFG = dataclasses.replace(TS_TEST_CONFIG, vocab_size=512)
HP = TrainHParams(warmup_iters=2, cosine_cycle_iters=10)


def _setup(seed=0):
    params = init_params(jax.random.PRNGKey(seed), CFG)
    opt_state = adamw_init(params)
    rng = np.random.default_rng(seed)
    x = rng.integers(0, CFG.vocab_size, size=(16, CFG.context_length))
    y = rng.integers(0, CFG.vocab_size, size=(16, CFG.context_length))
    return params, opt_state, jnp.asarray(x), jnp.asarray(y)


def test_ring_attention_kv_chunked_matches_unchunked():
    """Blockwise per-shard ring (kv_chunk) == full-block ring, values AND
    gradients (the chunk scan is rematerialized but numerically identical)."""
    from functools import partial

    from bpe_transformer_tpu.parallel.ring_attention import ring_self_attention
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh({"data": 2, "seq": 4})
    rng = np.random.default_rng(0)
    B, H, S, D = 2, 2, 64, 16
    q, k, v = (
        jnp.asarray(rng.normal(size=(B, H, S, D)).astype(np.float32))
        for _ in range(3)
    )
    spec = P("data", None, "seq", None)

    def run(kv_chunk):
        mapped = jax.shard_map(
            partial(
                ring_self_attention,
                axis_name="seq",
                causal=True,
                kv_chunk=kv_chunk,
            ),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )

        def scalar(q, k, v):
            return (mapped(q, k, v).astype(jnp.float32) ** 2).sum()

        val = scalar(q, k, v)
        grads = jax.grad(scalar, argnums=(0, 1, 2))(q, k, v)
        return val, grads

    v_full, g_full = run(None)
    v_chunk, g_chunk = run(4)  # 4 chunks per 16-long shard

    np.testing.assert_allclose(float(v_full), float(v_chunk), rtol=1e-6)
    for a, b in zip(g_full, g_chunk):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_sp_step_with_ring_kv_chunk_matches_single_device():
    """The sp train step under ring_kv_chunk reproduces the single-device
    update, like the unchunked sp test."""
    import dataclasses

    from bpe_transformer_tpu.parallel import make_sp_train_step, shard_sp_batch

    cfg = dataclasses.replace(CFG, ring_kv_chunk=4)
    params, opt_state, x, y = _setup()
    single = make_train_step(cfg, HP)
    p1, s1, m1 = single(params, opt_state, x, y)

    mesh = make_mesh({"data": 2, "seq": 4})
    params2, opt_state2, x2, y2 = _setup()
    step = make_sp_train_step(cfg, HP, mesh)
    x2, y2 = shard_sp_batch((x2, y2), mesh)
    p2, s2, m2 = step(params2, opt_state2, x2, y2)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        p1,
        p2,
    )


def test_ring_flash_attention_matches_xla_ring():
    """Ring + Pallas flash inside each shard (interpret mode on CPU):
    values and grads match the XLA online-softmax ring."""
    from functools import partial

    from bpe_transformer_tpu.parallel.ring_attention import (
        ring_flash_attention,
        ring_self_attention,
    )
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh({"data": 2, "seq": 4})
    rng = np.random.default_rng(0)
    B, H, S, D = 2, 2, 64, 16  # 4 shards of 16 tokens; 16-wide blocks
    q, k, v = (
        jnp.asarray(rng.normal(size=(B, H, S, D)).astype(np.float32))
        for _ in range(3)
    )
    spec = P("data", None, "seq", None)

    def run(fn):
        mapped = jax.shard_map(
            fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )

        def scalar(q, k, v):
            return (mapped(q, k, v).astype(jnp.float32) ** 2).sum()

        return scalar(q, k, v), jax.grad(scalar, argnums=(0, 1, 2))(q, k, v)

    v_ref, g_ref = run(partial(ring_self_attention, axis_name="seq", causal=True))
    v_fl, g_fl = run(
        partial(
            ring_flash_attention, axis_name="seq", block_q=16, block_k=16,
            interpret=True,
        )
    )
    np.testing.assert_allclose(float(v_ref), float(v_fl), rtol=1e-5)
    for a, b in zip(g_ref, g_fl):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4, rtol=1e-3)


def test_sp_step_with_ring_flash_matches_single_device():
    """sp training with attention_impl='flash' (ring-flash per shard)
    reproduces the single-device update within kernel tolerance."""
    import dataclasses

    from bpe_transformer_tpu.parallel import make_sp_train_step, shard_sp_batch

    cfg = dataclasses.replace(CFG, attention_impl="flash", flash_block_size=4)
    params, opt_state, x, y = _setup()
    single = make_train_step(dataclasses.replace(CFG), HP)  # XLA single-device
    p1, s1, m1 = single(params, opt_state, x, y)

    mesh = make_mesh({"data": 2, "seq": 4})
    params2, opt_state2, x2, y2 = _setup()
    step = make_sp_train_step(cfg, HP, mesh)
    x2, y2 = shard_sp_batch((x2, y2), mesh)
    p2, s2, m2 = step(params2, opt_state2, x2, y2)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-4)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4
        ),
        p1,
        p2,
    )


def test_zigzag_ring_flash_matches_xla_zigzag():
    """Zig-zag ring with the Pallas kernel per sub-block (interpret mode):
    values and grads match the XLA zig-zag ring."""
    from functools import partial

    from bpe_transformer_tpu.parallel.ring_attention import (
        zigzag_ring_flash_attention,
        zigzag_ring_self_attention,
    )
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh({"data": 2, "seq": 4})
    rng = np.random.default_rng(0)
    B, H, S, D = 2, 2, 128, 16  # 4 shards x 32 local (two 16-chunks)
    q, k, v = (
        jnp.asarray(rng.normal(size=(B, H, S, D)).astype(np.float32))
        for _ in range(3)
    )
    spec = P("data", None, "seq", None)

    def run(fn):
        mapped = jax.shard_map(
            fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )

        def scalar(q, k, v):
            return (mapped(q, k, v).astype(jnp.float32) ** 2).sum()

        return scalar(q, k, v), jax.grad(scalar, argnums=(0, 1, 2))(q, k, v)

    v_ref, g_ref = run(partial(zigzag_ring_self_attention, axis_name="seq"))
    v_fl, g_fl = run(
        partial(
            zigzag_ring_flash_attention, axis_name="seq", block_q=16,
            block_k=16, interpret=True,
        )
    )
    np.testing.assert_allclose(float(v_ref), float(v_fl), rtol=1e-5)
    for a, b in zip(g_ref, g_fl):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4, rtol=1e-3)


def test_sp_zigzag_flash_step_matches_single_device():
    """sp training with zigzag=True AND attention_impl='flash' (both
    long-context optimizations together) == the single-device update."""
    import dataclasses

    from bpe_transformer_tpu.parallel import make_sp_train_step, shard_sp_batch

    cfg = dataclasses.replace(CFG, attention_impl="flash", flash_block_size=4)
    params, opt_state, x, y = _setup()
    single = make_train_step(CFG, HP)  # XLA single-device oracle
    p1, s1, m1 = single(params, opt_state, x, y)

    mesh = make_mesh({"data": 2, "seq": 4})
    params2, opt_state2, x2, y2 = _setup()
    step = make_sp_train_step(cfg, HP, mesh, zigzag=True)
    x2, y2 = shard_sp_batch((x2, y2), mesh, zigzag=True)
    p2, s2, m2 = step(params2, opt_state2, x2, y2)

    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-4)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-4
        ),
        p1,
        p2,
    )
