"""Unit tests for bench.py's capture bookkeeping (host-only, no jax run).

The capture files are the offline replay source for the driver's official
benchmark — the suffix keying (every replay-guarded knob gets its own
file; ADVICE r3/r4) and the keep-prior rules (a fresh live measurement
must never be displaced by an unreplayable or less complete capture) are
load-bearing evidence plumbing, so they get direct tests.
"""

from pathlib import Path

import pytest

from conftest import REPO_ROOT, load_script_module


@pytest.fixture()
def bench(monkeypatch, tmp_path):
    # Import bench.py fresh with a scratch capture dir so tests can't touch
    # the committed evidence under benchmarks/captures/.
    monkeypatch.syspath_prepend(str(REPO_ROOT / "benchmarks"))
    mod = load_script_module("bench_under_test", "bench.py")
    mod.CAPTURE_DIR = tmp_path
    mod.ARGS.config = "tinystories-4l"
    mod.ARGS.batch = 32
    mod.ARGS.attention = None
    mod.ARGS.flash_block = None
    # The queue exports these; an inherited value would suffix every
    # capture path and fail the default-knob assertions spuriously.
    for var in ("BENCH_FFN_IMPL", "BENCH_MOE_DISPATCH", "BENCH_REMAT",
                "BENCH_REMAT_POLICY", "BENCH_SCAN_LAYERS",
                "BENCH_GRADS_DTYPE"):
        monkeypatch.delenv(var, raising=False)
    return mod


def test_capture_path_default_knobs(bench):
    assert bench._capture_path().name == "tpu_capture_tinystories-4l.json"


def test_capture_path_suffixes_every_guarded_knob(bench, monkeypatch):
    bench.ARGS.batch = 64
    bench.ARGS.flash_block = 512
    monkeypatch.setenv("BENCH_FFN_IMPL", "pallas")
    monkeypatch.setenv("BENCH_MOE_DISPATCH", "gather")
    monkeypatch.setenv("BENCH_REMAT", "1")
    name = bench._capture_path().name
    # Full impl name, not an initial (two impls sharing a first letter must
    # not collide; ADVICE r4).
    assert "_ffn_pallas" in name
    assert "_b64" in name and "_blk512" in name
    # BENCH_REMAT=1 is the deprecated alias for the full policy.
    assert "_gather" in name and "_rp_full" in name


def test_capture_path_suffixes_mfu_push_knobs(bench, monkeypatch):
    monkeypatch.setenv("BENCH_REMAT_POLICY", "save_attn")
    monkeypatch.setenv("BENCH_SCAN_LAYERS", "1")
    monkeypatch.setenv("BENCH_GRADS_DTYPE", "bfloat16")
    name = bench._capture_path().name
    assert "_rp_save_attn" in name and "_scan" in name and "_gbf16" in name


def test_capture_path_moe_dispatch_default_tracks_preset(bench, monkeypatch):
    """TINYSTORIES_MOE defaults to gather (chip-confirmed 2026-08-02), so
    for the moe config BENCH_MOE_DISPATCH=gather is NOT a deviation and
    einsum IS — the unsuffixed capture must always hold the run a bare
    `bench.py --config tinystories-moe` would produce."""
    bench.ARGS.config = "tinystories-moe"
    bench.ARGS.batch = bench.BENCH_CONFIGS["tinystories-moe"][1]
    assert bench._capture_path().name == "tpu_capture_tinystories-moe.json"
    monkeypatch.setenv("BENCH_MOE_DISPATCH", "gather")
    assert bench._capture_path().name == "tpu_capture_tinystories-moe.json"
    monkeypatch.setenv("BENCH_MOE_DISPATCH", "einsum")
    assert bench._capture_path().name == "tpu_capture_tinystories-moe_einsum.json"


def test_preset_moe_dispatch_mirror_in_sync(bench):
    """bench.py mirrors the presets' moe_dispatch without importing the
    package (replay must not initialize jax); this is the sync check."""
    from bpe_transformer_tpu import models

    for name, (attr, *_rest) in bench.BENCH_CONFIGS.items():
        preset = getattr(models, attr)
        assert bench._preset_moe_dispatch(name) == preset.moe_dispatch, name


def test_capture_path_remat_policy_tracks_preset_for_gpt2_medium(
    bench, monkeypatch
):
    """gpt2-medium's preset policy moved to save_attn (PR 13): the bare
    run stays unsuffixed, matching the policy explicitly is not a
    deviation, and the deprecated BENCH_REMAT=1 (-> full) now IS one —
    old full-remat captures must not replay for the new default."""
    bench.ARGS.config = "gpt2-medium"
    bench.ARGS.batch = 16
    assert bench._capture_path().name == "tpu_capture_gpt2-medium.json"
    monkeypatch.setenv("BENCH_REMAT_POLICY", "save_attn")
    assert bench._capture_path().name == "tpu_capture_gpt2-medium.json"
    monkeypatch.delenv("BENCH_REMAT_POLICY")
    monkeypatch.setenv("BENCH_REMAT", "1")
    assert bench._capture_path().name == "tpu_capture_gpt2-medium_rp_full.json"


def test_preset_remat_policy_mirror_in_sync(bench):
    """bench.py mirrors the presets' resolved remat policy without
    importing the package (replay must not initialize jax)."""
    from bpe_transformer_tpu import models

    for name, (attr, *_rest) in bench.BENCH_CONFIGS.items():
        preset = getattr(models, attr)
        assert (
            bench._preset_remat_policy(name) == preset.resolved_remat_policy
        ), name


def _fresh_result(bench, value=100.0, steps=100):
    bench.RESULT.clear()
    bench.RESULT.update(
        platform="tpu", value=value, measure_steps=steps, batch=32,
        metric="m", unit="u", vs_baseline=None, mfu=0.1, config="tinystories-4l",
    )


def _write_prior(bench, **kw):
    import json

    payload = {"batch": 32, **kw}
    bench._capture_path().write_text(json.dumps(payload))


def _read_capture(bench):
    import json

    return json.loads(bench._capture_path().read_text())


def test_save_capture_keeps_more_complete_prior(bench):
    _write_prior(bench, value=50.0, measure_steps=100)
    _fresh_result(bench, value=200.0, steps=10)  # faster but 10x fewer steps
    bench._save_capture()
    assert _read_capture(bench)["value"] == 50.0


def test_save_capture_keeps_faster_at_equal_steps(bench):
    _write_prior(bench, value=150.0, measure_steps=100)
    _fresh_result(bench, value=100.0, steps=100)
    bench._save_capture()
    assert _read_capture(bench)["value"] == 150.0


def test_save_capture_replaces_slower_prior(bench):
    _write_prior(bench, value=50.0, measure_steps=100)
    _fresh_result(bench, value=100.0, steps=100)
    bench._save_capture()
    assert _read_capture(bench)["value"] == 100.0
    assert "captured_at_utc" in _read_capture(bench)


def test_save_capture_never_keeps_null_value_prior(bench):
    # A null-value capture can never replay (replay guard + queue grep both
    # reject it): keeping it over a live measurement would permanently lose
    # the offline fallback (review r5).
    _write_prior(bench, value=None, measure_steps=1000)
    _fresh_result(bench, value=100.0, steps=10)
    bench._save_capture()
    assert _read_capture(bench)["value"] == 100.0


def test_save_capture_backfills_torch_baseline_into_kept_prior(bench):
    _write_prior(bench, value=150.0, measure_steps=100)
    _fresh_result(bench, value=100.0, steps=100)
    bench.RESULT["torch_cpu_tokens_per_sec"] = 10.0
    bench._save_capture()
    kept = _read_capture(bench)
    assert kept["value"] == 150.0
    assert kept["torch_cpu_tokens_per_sec"] == 10.0
    assert kept["vs_baseline"] == 15.0
    assert "torch_baseline_carried_from" in kept


def test_replay_refuses_shape_and_knob_mismatches(bench, capsys):
    # All priors are written at the DEFAULT capture path with a mismatched
    # STORED field, so each refusal exercises the in-function guard (a
    # path-suffix mismatch would short-circuit on file-not-found and prove
    # nothing about the guards; review r5).
    _write_prior(
        bench, value=100.0, measure_steps=100, platform="tpu",
        attention_impl="xla", flash_block_size=256,
    )
    assert bench._try_replay_capture() is True
    bench.RESULT.clear()
    bench._init_result()

    # Stored batch differs from the requested one.
    _write_prior(
        bench, value=100.0, measure_steps=100, platform="tpu", batch=64,
        attention_impl="xla", flash_block_size=256,
    )
    assert bench._try_replay_capture() is False
    assert "not replaying" in capsys.readouterr().err

    # Stored attention impl differs from what this run would use.
    _write_prior(
        bench, value=100.0, measure_steps=100, platform="tpu",
        attention_impl="flash", flash_block_size=256,
    )
    assert bench._try_replay_capture() is False

    # Stored ffn impl differs from the (default xla) request.
    _write_prior(
        bench, value=100.0, measure_steps=100, platform="tpu",
        attention_impl="xla", flash_block_size=256, ffn_impl="pallas",
    )
    assert bench._try_replay_capture() is False

    # A null-value capture never replays at all.
    _write_prior(
        bench, value=None, measure_steps=100, platform="tpu",
        attention_impl="xla", flash_block_size=256,
    )
    assert bench._try_replay_capture() is False
