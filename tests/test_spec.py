"""Speculative decoding (`serving/spec/`): draft propose, batched target
verify, Leviathan rejection sampling, KV rewind.

The correctness bar (ISSUE 10): **greedy speculative decode is
token-identical to non-speculative greedy** — across plain, shared-prefix
and chunked-prefill scenarios — because greedy acceptance collapses to
"accept while the target argmax agrees".  Sampled decoding is pinned
statistically: the emitted-token distribution must match the target's
knob-filtered softmax (Leviathan's distribution-preservation theorem),
within sampling noise.  Compile counts stay bounded (chunk ladder +
verify + draft ladder + propose; the plain tick program never compiles),
and the acceptance gauges flow engine -> stats -> /statusz -> /metrics ->
report -> compare gate.
"""

import dataclasses
import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import jax

from bpe_transformer_tpu.models import TS_TEST_CONFIG, init_params
from bpe_transformer_tpu.serving import ServingEngine
from bpe_transformer_tpu.serving.engine import SlotPoolEngine
from bpe_transformer_tpu.serving.kvpool.paged_engine import PagedEngine
from bpe_transformer_tpu.serving.spec.draft import DraftModel, DraftSpec
from bpe_transformer_tpu.serving.spec.engine import SpecEngine

pytestmark = pytest.mark.serving

REPO = Path(__file__).resolve().parent.parent

CFG = dataclasses.replace(TS_TEST_CONFIG, vocab_size=128, context_length=32)

DRAFT = DraftSpec(truncate_layers=1)


@pytest.fixture(scope="module")
def setup():
    params = init_params(jax.random.PRNGKey(0), CFG)
    rng = np.random.default_rng(0)
    prompts = [
        [int(t) for t in rng.integers(0, CFG.vocab_size, size=n)]
        for n in (3, 7, 12, 19)
    ]
    return params, prompts


@pytest.fixture(scope="module")
def dense_engine(setup):
    params, _ = setup
    return SlotPoolEngine(params, CFG, slots=2, min_bucket=8)


@pytest.fixture(scope="module")
def spec_engine(setup):
    # Shared across the parity/bounded-compile/gauge tests: per-engine jit
    # caches make engines the expensive resource in this module (same
    # policy as test_kvpool/test_serving).
    params, _ = setup
    return SpecEngine(
        params, CFG, draft=DRAFT, speculate_k=3, slots=2, block_size=8,
        min_bucket=8,
    )


@pytest.fixture(scope="module")
def chunked_spec_engine(setup):
    params, _ = setup
    return SpecEngine(
        params, CFG, draft=DRAFT, speculate_k=2, slots=2, block_size=8,
        min_bucket=8, prefill_chunk=8,
    )


def _run(engine, prompt, **knobs):
    event = engine.admit(prompt, **knobs)
    out = [event.token]
    slot = event.slot
    while not event.finished:
        events = engine.tick()
        mine = [e for e in events if e.slot == slot]
        out.extend(e.token for e in mine)
        event = mine[-1]
    return out


# ------------------------------------------------------------ DraftSpec


def test_draft_spec_validation_rejects_bad_configs():
    with pytest.raises(ValueError, match="vocab_size"):
        DraftSpec(truncate_layers=1, vocab_size=999).validate_against(CFG)
    with pytest.raises(ValueError, match="truncate_layers"):
        DraftSpec(truncate_layers=CFG.num_layers + 1).validate_against(CFG)
    with pytest.raises(ValueError, match="not both"):
        DraftSpec(truncate_layers=1, d_model=16).validate_against(CFG)
    with pytest.raises(ValueError, match="incomplete"):
        DraftSpec(d_model=16, num_layers=1).validate_against(CFG)
    with pytest.raises(ValueError, match="unknown key"):
        DraftSpec.from_dict({"truncate_layers": 1, "nope": 2})
    # A matching explicit vocab and a full geometry both pass.
    DraftSpec(truncate_layers=1, vocab_size=CFG.vocab_size).validate_against(
        CFG
    )
    DraftSpec(d_model=16, num_layers=1, num_heads=2, d_ff=32).validate_against(
        CFG
    )


def test_draft_model_truncated_view_shares_target_arrays(setup):
    params, _ = setup
    draft = DraftModel(params, CFG, DraftSpec(truncate_layers=1))
    assert draft.config.num_layers == 1
    assert draft.config.vocab_size == CFG.vocab_size
    # Zero extra weight memory: the layer list is a slice of the target's.
    assert draft.param_bytes == 0
    assert draft.params["layers"][0] is params["layers"][0]
    assert len(draft.params["layers"]) == 1


def test_draft_model_geometry_initializes_own_params(setup):
    params, _ = setup
    spec = DraftSpec(d_model=16, num_layers=1, num_heads=2, d_ff=32, seed=7)
    draft = DraftModel(params, CFG, spec)
    assert draft.config.d_model == 16
    assert draft.param_bytes > 0
    assert draft.config.context_length == CFG.context_length


def test_spec_engine_rejects_mismatched_draft(setup):
    params, _ = setup
    with pytest.raises(ValueError, match="vocab"):
        SpecEngine(
            params, CFG, draft=DraftSpec(truncate_layers=1, vocab_size=64),
            speculate_k=2, slots=1, block_size=8,
        )
    with pytest.raises(ValueError, match="speculate_k"):
        SpecEngine(
            params, CFG, draft=DRAFT, speculate_k=0, slots=1, block_size=8
        )


# ------------------------------------------------------- greedy parity


def test_greedy_parity_with_dense_engine(setup, dense_engine, spec_engine):
    """ACCEPTANCE (ISSUE 10): greedy speculative decode is token-identical
    to non-speculative greedy — the Leviathan rule at temp 0 collapses to
    "accept while the target argmax agrees, then emit the target argmax",
    so speculation changes tick count, never tokens."""
    _, prompts = setup
    for prompt in prompts:
        assert _run(spec_engine, prompt, max_new_tokens=10,
                    temperature=0.0) == \
            _run(dense_engine, prompt, max_new_tokens=10, temperature=0.0), \
            f"spec/dense greedy divergence for prompt {prompt}"
    # Speculation actually sped something up: fewer target steps than
    # emitted tokens (acceptance > 0 for a self-drafted model).
    gauges = spec_engine.spec_gauges()
    assert gauges["spec_accept_rate"] is not None
    assert gauges["spec_tokens_per_target_step"] > 1.0


def test_greedy_parity_through_shared_prefix(setup, dense_engine,
                                             spec_engine):
    """Radix-shared prompt blocks + verify-pass writes + rewind stay
    token-identical: rewinding must copy-on-write rather than scribble
    over blocks the cache still indexes."""
    _, prompts = setup
    base = prompts[3]
    first = base + [15, 16]
    second = base + [19, 11, 12]
    assert _run(spec_engine, first, max_new_tokens=8, temperature=0.0) == \
        _run(dense_engine, first, max_new_tokens=8, temperature=0.0)
    slot = spec_engine.begin(second, max_new_tokens=8, temperature=0.0)
    assert spec_engine.slot_shared_len(slot) == 16
    event = spec_engine.prefill_step(slot)
    while event is None:
        event = spec_engine.prefill_step(slot)
    out = [event.token]
    while not event.finished:
        mine = [e for e in spec_engine.tick() if e.slot == slot]
        out.extend(e.token for e in mine)
        event = mine[-1]
    assert out == _run(dense_engine, second, max_new_tokens=8,
                       temperature=0.0)


def test_greedy_parity_chunked_prefill(setup, dense_engine,
                                       chunked_spec_engine):
    """Chunked prefill (the same machinery the verify pass generalizes)
    composes with speculation: long prompts split into chunks, then the
    spec ticks take over — tokens unchanged."""
    _, prompts = setup
    for prompt in (prompts[2], prompts[3]):
        assert _run(chunked_spec_engine, prompt, max_new_tokens=8,
                    temperature=0.0) == \
            _run(dense_engine, prompt, max_new_tokens=8, temperature=0.0)


def test_greedy_parity_batched_slots(setup, dense_engine, spec_engine):
    """Two slots decoding together (per-slot variable acceptance inside
    one fixed-K verify program) match their solo dense runs."""
    _, prompts = setup
    expected = {
        0: _run(dense_engine, prompts[0], max_new_tokens=6, temperature=0.0),
        1: _run(dense_engine, prompts[1], max_new_tokens=6, temperature=0.0),
    }
    ev0 = spec_engine.admit(prompts[0], max_new_tokens=6, temperature=0.0)
    ev1 = spec_engine.admit(prompts[1], max_new_tokens=6, temperature=0.0)
    outs = {ev0.slot: [ev0.token], ev1.slot: [ev1.token]}
    done = {ev0.slot: ev0.finished, ev1.slot: ev1.finished}
    by_slot = {ev0.slot: 0, ev1.slot: 1}
    while not all(done.values()):
        for e in spec_engine.tick():
            outs[e.slot].append(e.token)
            if e.finished:
                done[e.slot] = True
    for slot, idx in by_slot.items():
        assert outs[slot] == expected[idx], f"slot {slot} diverged"


# --------------------------------------------------- sampling behavior


def _filtered_softmax(params, tokens, *, top_k):
    """The target's next-token distribution after ``tokens``, under the
    same runtime knob filtering the serving sampler applies — the ``p`` of
    the Leviathan theorem."""
    import jax.numpy as jnp

    from bpe_transformer_tpu.models.decode import init_kv_cache, prefill
    from bpe_transformer_tpu.serving.engine import filter_logits

    bucket = 8 if len(tokens) <= 8 else 16
    padded = np.zeros((1, bucket), np.int32)
    padded[0, : len(tokens)] = tokens
    logits, _ = prefill(
        params, jnp.asarray(padded), CFG,
        init_kv_cache(CFG, 1, dtype=jnp.float32),
        last_pos=jnp.asarray([len(tokens) - 1]),
    )
    filt = filter_logits(
        np.asarray(logits, np.float32),
        np.asarray([1.0], np.float32),
        np.asarray([top_k], np.int32),
        np.asarray([2.0], np.float32),  # top-p disabled (>= 1)
    )
    p = np.exp(filt[0] - filt[0].max())
    return p / p.sum()


def test_sampled_distribution_preserved(setup):
    """Leviathan distribution preservation, measured: with temp 1 +
    top-k 4, the spec path's second-token draw matches the target's
    knob-filtered conditional softmax within sampling noise.  The draft
    proposes from a DIFFERENT distribution (1 of 3 layers), so acceptance
    is partial — exactly the regime the accept/residual math must keep
    unbiased in.  Token 0 comes from the prefill sampler (dense-identical
    by construction); token 1 is the first draw through accept/resample,
    so we histogram t1 CONDITIONED on the most frequent t0 and compare to
    p(.|t0)."""
    params, prompts = setup
    prompt = prompts[1]
    engine = SpecEngine(
        params, CFG, draft=DRAFT, speculate_k=2, slots=1, block_size=8,
        min_bucket=8,
    )
    n = 400
    pairs: dict = {}
    for seed in range(n):
        out = _run(
            engine, prompt, max_new_tokens=2, temperature=1.0, top_k=4,
            seed=seed,
        )
        pairs.setdefault(out[0], []).append(out[1])
    t0, draws = max(pairs.items(), key=lambda kv: len(kv[1]))
    assert len(draws) >= 50, "top-4 sampling should concentrate first tokens"
    ref = _filtered_softmax(params, prompt + [t0], top_k=4)
    emp = np.zeros(CFG.vocab_size)
    for t1 in draws:
        emp[t1] += 1
    emp /= emp.sum()
    tv = 0.5 * np.abs(emp - ref).sum()
    # TV noise floor for a 4-support distribution at >=50 draws is
    # ~sqrt(k/n) ≈ 0.15-0.3; a BROKEN acceptance rule (e.g. emitting the
    # draft's distribution, which comes from a different model) moves TV
    # by O(1).
    assert tv < 0.30, (
        f"spec-path draw diverges from target distribution: TV={tv:.3f} "
        f"(support {np.flatnonzero(ref > 0).tolist()}, n={len(draws)})"
    )
    # Sampling path exercised the acceptance/bonus machinery.
    g = engine.spec_gauges()
    assert g["spec_proposed_tokens"] > 0
    assert 0.0 <= g["spec_accept_rate"] <= 1.0


def test_sampled_generation_respects_stop_and_length(setup, spec_engine):
    _, prompts = setup
    out = _run(
        spec_engine, prompts[0], max_new_tokens=5, temperature=0.9,
        top_k=8, seed=11,
    )
    assert len(out) == 5
    assert all(0 <= t < CFG.vocab_size for t in out)
    # stop_id: the emission loop must break INSIDE a multi-token window —
    # the stop token is the generation's last, nothing after it leaks out.
    greedy = _run(spec_engine, prompts[0], max_new_tokens=6,
                  temperature=0.0)
    stop_id = greedy[2]
    stop = _run(
        spec_engine, prompts[0], max_new_tokens=20, temperature=0.0,
        stop_id=stop_id,
    )
    assert stop == greedy[: greedy.index(stop_id) + 1]


# ------------------------------------------------------- compile bound


def test_bounded_compile_and_no_plain_tick(setup, dense_engine, spec_engine,
                                           chunked_spec_engine):
    """ACCEPTANCE (ISSUE 10): compile count stays within the ladder bound
    + draft ladder + propose + verify (+1 once a CoW rewind ran), and the
    plain decode-tick program NEVER compiles on the spec path — every
    spec tick is a verify pass."""
    for engine in (spec_engine, chunked_spec_engine):
        bound = (
            len(engine.buckets)          # target chunk ladder
            + 1                          # verify
            + len(engine.draft_buckets)  # draft prefill ladder
            + 1                          # propose
            + engine._copy_jit._cache_size()  # CoW copy, if any ran
        )
        assert engine.compiled_programs() <= bound, (
            f"{engine.compiled_programs()} programs > bound {bound}"
        )
        assert engine._tick_jit._cache_size() == 0, (
            "the plain tick compiled on the spec path"
        )
        assert engine._verify_jit._cache_size() == 1
        assert engine._propose_jit._cache_size() == 1


# -------------------------------------------- block-starved speculation


def test_speculation_window_shrinks_when_pool_is_dry(setup):
    """A block-starved slot shrinks its speculation window (rooms < K)
    instead of stalling or raising: the admission-time reservation always
    backs at least one decode position."""
    params, prompts = setup
    # Pool sized to the admission reservation EXACTLY: prompt 12 tokens +
    # 4 new = 16 positions = 2 blocks (+1 trash).  Verify scratch beyond
    # the reservation is never available.
    engine = SpecEngine(
        params, CFG, draft=DRAFT, speculate_k=3, slots=1, block_size=8,
        min_bucket=8, num_blocks=3, prefix_cache=False,
    )
    dense = SlotPoolEngine(params, CFG, slots=1, min_bucket=8)
    out = _run(engine, prompts[2], max_new_tokens=4, temperature=0.0)
    assert out == _run(dense, prompts[2], max_new_tokens=4, temperature=0.0)
    # The pool gave back everything on release.
    assert engine.allocator.free_count == engine.allocator.usable_blocks


def test_int8_spec_generation_stays_coherent(setup):
    """int8 pools under the sequential verify quantizer + rewind-then-
    regrow: generation completes, rewinds happen, and the acceptance
    gauges stay sane.  (Token-level int8 parity with the plain int8
    engine is NOT promised — the verify pass quantizes K+1 rows against
    final block scales, plain ticks against per-step scales; both are
    within quantization error of the fp path.)"""
    params, prompts = setup
    engine = SpecEngine(
        params, CFG, draft=DRAFT, speculate_k=2, slots=1, block_size=8,
        min_bucket=8, kv_dtype="int8",
    )
    out = _run(engine, prompts[1], max_new_tokens=10, temperature=0.0)
    assert len(out) == 10
    assert all(0 <= t < CFG.vocab_size for t in out)
    g = engine.spec_gauges()
    assert g["spec_rewound_tokens"] >= 0
    assert g["spec_accept_rate"] is not None
    # fp greedy reference: int8 may flip near-ties but must stay close —
    # the first couple of tokens ride large logit margins in practice.
    fp = SpecEngine(
        params, CFG, draft=DRAFT, speculate_k=2, slots=1, block_size=8,
        min_bucket=8,
    )
    fp_out = _run(fp, prompts[1], max_new_tokens=10, temperature=0.0)
    assert out[0] == fp_out[0], "int8 diverged at the very first token"
    # Block scales stayed finite and non-negative (rewound rows fold into
    # the scale until the block is vacated — documented semantics).
    for layer in engine._pool:
        k_scale = np.asarray(layer["k_scale"])
        assert np.isfinite(k_scale).all() and (k_scale >= 0).all()


# --------------------------------------------------- serving + telemetry


def test_serving_engine_spec_end_to_end(setup, tmp_path):
    """ACCEPTANCE (ISSUE 10): the gauges flow end to end — engine stats ->
    /statusz payload -> Prometheus exposition -> kind="spec" records ->
    report section -> compare-gate metrics — and greedy generations match
    the non-speculative paged serving engine."""
    from bpe_transformer_tpu.telemetry import MetricsLogger, Telemetry
    from bpe_transformer_tpu.telemetry.monitor import (
        fold_prometheus,
        parse_prometheus,
        render_frame,
    )
    from bpe_transformer_tpu.telemetry.report import (
        extract_compare_metrics,
        render_report,
        summarize,
    )

    params, prompts = setup
    jsonl = tmp_path / "serve_spec.jsonl"
    logger = MetricsLogger(jsonl_path=str(jsonl))
    telemetry = Telemetry(sink=logger.log)

    with ServingEngine(
        params, CFG, slots=2, paged=True, block_size=8,
        speculate_k=2, draft_spec=DRAFT, telemetry=telemetry,
        engine_record_every_s=0.0,
    ) as serving:
        results = [
            serving.generate(p, max_new_tokens=8, temperature=0.0)
            for p in prompts[:3]
        ]
    logger.close()

    with ServingEngine(
        params, CFG, slots=2, paged=True, block_size=8
    ) as plain:
        plain_results = [
            plain.generate(p, max_new_tokens=8, temperature=0.0)
            for p in prompts[:3]
        ]
    for r, pr in zip(results, plain_results):
        assert r.token_ids == pr.token_ids
        assert r.finish_reason == pr.finish_reason

    # stats(): engine kind + the acceptance gauges.
    with ServingEngine(
        params, CFG, slots=2, paged=True, block_size=8,
        speculate_k=2, draft_spec=DRAFT,
    ) as serving:
        serving.generate(prompts[0], max_new_tokens=6, temperature=0.0)
        stats = serving.stats()
        assert stats["engine_kind"] == "spec"
        assert stats["spec_k"] == 2
        assert stats["spec_accept_rate"] is not None
        assert stats["spec_tokens_per_target_step"] >= 1.0
        page = serving.statusz()
        assert page["engine_kind"] == "spec"
        assert page["speculate_k"] == 2
        assert page["kvpool"]["spec_accept_rate"] == \
            stats["spec_accept_rate"]
        text = serving.prometheus_metrics()
    state = fold_prometheus(parse_prometheus(text))
    assert state["spec_k"] == 2
    assert "spec_accept_rate" in state
    assert "spec_tokens_per_target_step" in state
    assert "spec" in render_frame(state, "test")

    # The JSONL stream carries kind="spec" records the report renders and
    # the compare gate extracts.
    records = [json.loads(ln) for ln in jsonl.read_text().splitlines()]
    spec_records = [r for r in records if r.get("kind") == "spec"]
    assert spec_records, "no kind='spec' records on the engine cadence"
    for r in spec_records:
        assert r["k"] == 2
        assert r["proposed"] >= r["accepted"]
    report = render_report(records)
    assert "speculative decoding" in report
    metrics = extract_compare_metrics(summarize(records))
    assert "accept_rate" in metrics
    assert metrics["accept_rate"][1] == "higher"
    assert "tokens_per_target_step" in metrics


def test_serving_engine_speculate_requires_paged_and_draft(setup):
    params, _ = setup
    with pytest.raises(ValueError, match="paged"):
        ServingEngine(params, CFG, speculate_k=2, draft_spec=DRAFT)
    with pytest.raises(ValueError, match="draft_spec"):
        ServingEngine(params, CFG, paged=True, speculate_k=2)


# ------------------------------------------------- fixture-pinned surfaces


def test_spec_fixture_pins_report_monitor_compare():
    """tests/fixtures/spec_tiny.jsonl is the pinned wire format: report
    section, monitor fold, and the compare gate must keep reading it
    (schema check #5 keeps the kind covered)."""
    from bpe_transformer_tpu.telemetry.monitor import fold_records, render_frame
    from bpe_transformer_tpu.telemetry.report import (
        compare_metrics,
        extract_compare_metrics,
        render_report,
        summarize,
    )

    records = [
        json.loads(ln)
        for ln in (REPO / "tests/fixtures/spec_tiny.jsonl")
        .read_text().splitlines()
    ]
    summary = summarize(records)
    assert summary["spec"]["accept_rate"] == 0.625
    assert summary["spec"]["tokens_per_target_step"] == 3.5
    report = render_report(records)
    assert "== speculative decoding (2 samples) ==" in report
    assert "accept rate 62.5%" in report

    state = fold_records(records)
    assert state["spec_accept_rate"] == 0.625
    frame = render_frame(state, "test")
    assert "spec   k 4  accept 62%" in frame

    metrics = extract_compare_metrics(summary)
    regressed = dict(metrics)
    regressed["accept_rate"] = (0.3, "higher")
    rows, regressions = compare_metrics(metrics, regressed)
    assert "accept_rate" in regressions
    rows, regressions = compare_metrics(metrics, metrics)
    assert not regressions


# ----------------------------------------------------------- CLI fast-fail


def _cli(args, **env_extra):
    import os

    return subprocess.run(
        [sys.executable, "-m", "bpe_transformer_tpu.training.cli"] + args,
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": str(REPO), **env_extra},
        cwd=str(REPO),
    )


@pytest.mark.slow
def test_cli_speculate_fast_fail_rc2(tmp_path):
    """ACCEPTANCE (satellite): serve/warmup reject impossible --speculate
    combinations up front with rc 2 — structural errors before any model
    load, vocab mismatch right after config resolution (never a deep
    shape error mid-compile)."""
    draft = tmp_path / "draft.json"
    draft.write_text(json.dumps({"truncate_layers": 1}))
    bad_vocab = tmp_path / "bad_vocab.json"
    bad_vocab.write_text(json.dumps(
        {"d_model": 16, "num_layers": 1, "num_heads": 2, "d_ff": 32,
         "vocab_size": 17}
    ))
    bad_keys = tmp_path / "bad_keys.json"
    bad_keys.write_text(json.dumps({"truncate_layers": 1, "bogus": True}))

    # Structural failures never touch the (nonexistent) checkpoint.
    proc = _cli(["serve", "--checkpoint", "/nonexistent",
                 "--tokenizer-dir", "/nonexistent", "--speculate", "2"])
    assert proc.returncode == 2 and "--paged" in proc.stderr
    proc = _cli(["serve", "--checkpoint", "/nonexistent",
                 "--tokenizer-dir", "/nonexistent", "--paged",
                 "--speculate", "2"])
    assert proc.returncode == 2 and "--draft-config" in proc.stderr
    proc = _cli(["serve", "--checkpoint", "/nonexistent",
                 "--tokenizer-dir", "/nonexistent", "--paged",
                 "--speculate", "2", "--draft-config", str(bad_keys)])
    assert proc.returncode == 2 and "unknown key" in proc.stderr
    proc = _cli(["serve", "--checkpoint", "/nonexistent",
                 "--tokenizer-dir", "/nonexistent", "--paged",
                 "--draft-config", str(draft)])
    assert proc.returncode == 2 and "--speculate" in proc.stderr

    # Vocab mismatch: config resolution happens, engines never build.
    proc = _cli(["warmup", "--compile-cache", str(tmp_path / "cc"),
                 "--preset", "ts-test", "--paged", "--speculate", "2",
                 "--draft-config", str(bad_vocab)])
    assert proc.returncode == 2 and "vocab_size" in proc.stderr
    proc = _cli(["warmup", "--compile-cache", str(tmp_path / "cc"),
                 "--paged", "--speculate", "2"])
    assert proc.returncode == 2 and "--draft-config" in proc.stderr


@pytest.mark.slow
def test_warmup_spec_cli_two_process_cache_hits(tmp_path):
    """`bpe-tpu warmup --speculate` AOT-compiles the spec ladder (chunk +
    verify + draft prefill + propose) into the persistent cache; a second
    process restarts warm."""
    draft = tmp_path / "draft.json"
    draft.write_text(json.dumps({"truncate_layers": 1}))
    cache_dir = tmp_path / "xla_cache"

    def run():
        proc = _cli([
            "warmup", "--compile-cache", str(cache_dir),
            "--preset", "ts-test", "--paged", "--block-size", "8",
            "--slots", "2", "--kv-dtype", "act",
            "--speculate", "3", "--draft-config", str(draft),
        ], XLA_FLAGS="")
        assert proc.returncode == 0, proc.stderr[-2000:]
        return json.loads(proc.stdout.strip().splitlines()[-1])

    cold = run()
    assert cold["engine"] == "spec" and cold["speculate"] == 3
    assert cold["cache_hits"] == 0
    # chunk ladder + verify + draft ladder + propose, one kv dtype.
    assert cold["programs_compiled"] <= 2 * (len(cold["buckets"]) + 1)
    warm = run()
    assert warm["cache_hits"] > 0
