"""Regression tests for benchmarks/northstar.py's jax phase — the TPU
queue's highest-priority job (VERDICT r4 #1).  Runs the real phase_jax on
CPU at a 4-step protocol against a temp torch-reference artifact, covering
the self-describing capture fields (ADVICE r4), the exhausted-checkpoint
cleanup, the mismatched-checkpoint discard, and the legacy /tmp checkpoint
migration (VERDICT r4 #8) — the paths a tunnel window exercises with no
chance to debug."""

import json

import pytest

from conftest import REPO_ROOT, load_script_module


@pytest.fixture()
def northstar(monkeypatch, tmp_path):
    monkeypatch.setenv("NORTHSTAR_STEPS", "4")
    monkeypatch.syspath_prepend(str(REPO_ROOT / "benchmarks"))
    mod = load_script_module("northstar_under_test", "benchmarks/northstar.py")
    assert mod.STEPS == 4
    mod.EVAL_EVERY = 2
    mod.TORCH_JSON = tmp_path / "torch.json"
    mod.CAPTURE = tmp_path / "northstar.json"
    mod.CAPTURE_NATIVE = tmp_path / "northstar_native.json"
    mod.CKPT = tmp_path / "scratch" / "ckpt.pkl"
    mod.LEGACY_CKPT = tmp_path / "legacy" / "ckpt.pkl"
    mod.TORCH_JSON.write_text(
        json.dumps(
            {
                "steps": 4,
                "final_val_loss": 9.0,
                "tokens_per_sec": 100.0,
                "config": "smoke",
            }
        )
    )
    return mod


@pytest.mark.slow
def test_phase_jax_capture_is_self_describing(northstar):
    assert northstar.phase_jax(allow_cpu=True) == 0
    cap = json.loads(northstar.CAPTURE.read_text())
    assert cap["reference_tolerance"] == northstar.VAL_TOLERANCE
    assert cap["val_loss_delta_vs_torch"] == pytest.approx(
        cap["final_val_loss"]["jax"] - 9.0, abs=1e-3
    )
    assert cap["steps"] == 4 and cap["platform"] == "cpu"
    # The exhausted checkpoint is cleared so a deliberate re-run is fresh.
    assert not northstar.CKPT.exists()


@pytest.mark.slow
def test_phase_jax_discards_mismatched_checkpoint(northstar):
    from bpe_transformer_tpu.checkpointing import save_checkpoint
    import numpy as np

    # A checkpoint claiming a different platform/protocol must not seed the
    # run: phase_jax discards it and trains from scratch to completion.
    northstar.CKPT.parent.mkdir(parents=True)
    save_checkpoint(
        northstar.CKPT,
        params={"w": np.zeros(1)},
        opt_state=None,
        iteration=99,
        extra={"curve": [], "train_s": 0.0, "platform": "tpu", "steps": 4},
    )
    assert northstar.phase_jax(allow_cpu=True) == 0
    cap = json.loads(northstar.CAPTURE.read_text())
    assert len(cap["curve"]) == 2  # evals at steps 2 and 4: a FULL fresh run


@pytest.mark.slow
def test_phase_jax_native_variant_matches_parity_math(northstar):
    """The native variant (scanned dispatch) must produce the SAME update
    math as the per-step parity loop: on CPU both run at full f32 precision,
    so the two curves agree to float tolerance.  Also pins the native
    artifact's self-description (variant, steps_per_dispatch, own capture
    file, own checkpoint name)."""
    assert northstar.phase_jax(allow_cpu=True) == 0
    assert northstar.phase_jax(allow_cpu=True, variant="native") == 0
    parity = json.loads(northstar.CAPTURE.read_text())
    native = json.loads(northstar.CAPTURE_NATIVE.read_text())
    assert native["variant"] == "native"
    assert native["steps_per_dispatch"] == northstar.EVAL_EVERY
    assert parity.get("variant", "parity") == "parity"
    # Same protocol, same init, same batches; CPU runs both at true f32 —
    # the scan changes dispatch, not numerics.
    for p_pt, n_pt in zip(parity["curve"], native["curve"]):
        assert p_pt["step"] == n_pt["step"]
        assert n_pt["val_loss"] == pytest.approx(p_pt["val_loss"], abs=1e-4)
    assert not (northstar.CKPT.parent / f"native_{northstar.CKPT.name}").exists()


@pytest.mark.slow
def test_phase_jax_migrates_legacy_tmp_checkpoint(northstar):
    from bpe_transformer_tpu.checkpointing import save_checkpoint
    import numpy as np

    # A legacy checkpoint moves to the new location, then (being
    # platform-mismatched here) is discarded through the normal guard —
    # proving the migration itself ran.
    northstar.LEGACY_CKPT.parent.mkdir(parents=True)
    save_checkpoint(
        northstar.LEGACY_CKPT,
        params={"w": np.zeros(1)},
        opt_state=None,
        iteration=99,
        extra={"curve": [], "train_s": 0.0, "platform": "tpu", "steps": 4},
    )
    assert northstar.phase_jax(allow_cpu=True) == 0
    assert not northstar.LEGACY_CKPT.exists()  # migrated away
    assert json.loads(northstar.CAPTURE.read_text())["steps"] == 4
