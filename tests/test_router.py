"""Fleet router: health polling, weighted balancing, drain/death
failover with request replay, and the two-replica integration test
(drain mid-load -> zero failed requests -> rejoin after restart).

The router is jax-free (it fronts replicas from a box with no
accelerator runtime); the unit tests exercise it against canned stdlib
HTTP replicas, the integration test against two real in-process
`ServingEngine` replicas.
"""

import dataclasses
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import numpy as np
import pytest

from bpe_transformer_tpu.serving.router import (
    Router,
    make_router_http_server,
)

pytestmark = pytest.mark.serving

REPO = Path(__file__).resolve().parent.parent


# ------------------------------------------------------------ fake replica


class _FakeReplica:
    """A canned /statusz + /generate stdlib server (no engine, no jax)."""

    def __init__(self, *, slots=4, active=0, queue=0, kv_free=None,
                 kv_total=None, draining=False, generate_code=200,
                 generate_delay_s=0.0, role=None, export_code=200,
                 port=0):
        self.statusz = {
            "worker_alive": True,
            "draining": draining,
            "queue_depth": queue,
            "slots": slots,
            "active_slots": active,
        }
        if role is not None:
            self.statusz["role"] = role
        if kv_total is not None:
            self.statusz["kvpool"] = {
                "kv_blocks_free": kv_free,
                "kv_blocks_total": kv_total,
            }
        self.generate_code = generate_code
        self.generate_delay_s = generate_delay_s
        self.export_code = export_code
        self.requests_served = 0
        self.exports_served = 0
        self.imports_served = 0
        self.import_bodies: list = []  # payload bytes /kv/import received
        self.seen_request_ids: list = []  # X-Request-Id headers received
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _reply(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/statusz":
                    return self._reply(200, outer.statusz)
                return self._reply(404, {"error": "?"})

            def do_POST(self):
                outer.seen_request_ids.append(
                    self.headers.get("X-Request-Id")
                )
                length = int(self.headers.get("Content-Length", 0))
                data = self.rfile.read(length)
                rid = self.headers.get("X-Request-Id") or "x"
                if self.path == "/kv/export":
                    # A prefill-role replica: the finished prefix leaves
                    # as an opaque binary payload.
                    if outer.export_code != 200:
                        return self._reply(
                            outer.export_code, {"error": "export refused"}
                        )
                    outer.exports_served += 1
                    body = b"BPEKV-FAKE-PAYLOAD:" + rid.encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "application/octet-stream"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return None
                if self.path == "/kv/import":
                    outer.import_bodies.append(data)
                    if outer.generate_code != 200:
                        return self._reply(
                            outer.generate_code, {"error": "import refused"}
                        )
                    outer.imports_served += 1
                    return self._reply(
                        200,
                        {"token_ids": [7, 8, 9],
                         "finish_reason": "length",
                         "request_id": rid, "timings": {}},
                    )
                if outer.generate_delay_s:
                    time.sleep(outer.generate_delay_s)
                if outer.generate_code != 200:
                    detail = (
                        "serving engine is draining (shutting down)"
                        if outer.generate_code == 503
                        else "bad"
                    )
                    return self._reply(
                        outer.generate_code, {"error": detail}
                    )
                outer.requests_served += 1
                # Like the real serve layer: adopt the forwarded trace id
                # as the request_id (minting one when none was sent).
                rid = self.headers.get("X-Request-Id") or "x"
                return self._reply(
                    200,
                    {"token_ids": [1, 2], "finish_reason": "length",
                     "request_id": rid, "timings": {}},
                )

        self.server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.url = f"http://127.0.0.1:{self.server.server_address[1]}"
        self.thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )
        self.thread.start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()
        self.thread.join(timeout=10)


def _body(i=0):
    return json.dumps({"prompt_ids": [1, 2, int(i)], "max_new_tokens": 2}).encode()


# ------------------------------------------------------------------ units


def test_router_polls_health_and_weights_by_capacity():
    """The loaded replica loses the pick: weight favors free slots/blocks
    and penalizes queue depth."""
    idle = _FakeReplica(slots=4, active=0, queue=0, kv_free=60, kv_total=64)
    busy = _FakeReplica(slots=4, active=4, queue=3, kv_free=4, kv_total=64)
    try:
        router = Router([idle.url, busy.url])
        router.poll_once()
        states = {r.url: r for r in router.replicas}
        assert states[idle.url].available and states[busy.url].available
        assert states[idle.url].weight() > states[busy.url].weight()
        order = router.pick_order()
        assert order[0].url == idle.url
        code, payload = router.handle_generate(_body())
        assert code == 200 and payload["replica"] == idle.url
        assert idle.requests_served == 1 and busy.requests_served == 0
    finally:
        idle.close()
        busy.close()


def test_router_skips_draining_and_dead_replicas():
    draining = _FakeReplica(draining=True)
    healthy = _FakeReplica()
    try:
        router = Router([draining.url, "http://127.0.0.1:9", healthy.url])
        router.poll_once()
        order = router.pick_order()
        assert [r.url for r in order] == [healthy.url]
        dead = next(
            r for r in router.replicas if r.url == "http://127.0.0.1:9"
        )
        assert not dead.healthy and dead.consecutive_failures == 1
        page = router.statusz()
        assert page["available"] == 1
    finally:
        draining.close()
        healthy.close()


def test_router_replays_on_drain_503_and_connection_failure():
    """A replica that 503s mid-drain (or drops the connection) loses the
    request to the next-best replica — the caller sees one success."""
    # Poll sees it healthy; the drain lands between poll and request.
    draining = _FakeReplica(slots=8, generate_code=503)
    healthy = _FakeReplica(slots=1, active=1)  # worse weight: tried second
    try:
        router = Router([draining.url, healthy.url])
        router.poll_once()
        assert router.pick_order()[0].url == draining.url
        code, payload = router.handle_generate(_body())
        assert code == 200 and payload["replica"] == healthy.url
        assert router.requests_retried == 1
        drained_state = next(
            r for r in router.replicas if r.url == draining.url
        )
        assert drained_state.draining, "the 503 must flag the drain"
        # Next pick skips it without waiting for a poll.
        assert [r.url for r in router.pick_order()] == [healthy.url]
    finally:
        draining.close()
        healthy.close()

    # Connection-refused path: mark down + replay.
    survivor = _FakeReplica()
    try:
        router = Router([survivor.url, "http://127.0.0.1:9"])
        router.poll_once()
        for r in router.replicas:  # force the dead one to be tried first
            r.healthy = True
            r.slots = 4 if r.url != survivor.url else 1
        code, payload = router.handle_generate(_body())
        assert code == 200 and payload["replica"] == survivor.url
        assert router.requests_failed == 0
    finally:
        survivor.close()


def test_router_slow_response_is_not_replayed():
    """A replica that ACCEPTED a request but answers slower than the
    request timeout is still running the generation: the router fails
    THIS request through as 504 without marking the replica down or
    duplicating the work on a peer."""
    slow = _FakeReplica(slots=8, generate_delay_s=0.6)
    fallback = _FakeReplica(slots=1, active=1)
    try:
        router = Router(
            [slow.url, fallback.url], request_timeout_s=0.2,
        )
        router.poll_once()
        assert router.pick_order()[0].url == slow.url
        code, payload = router.handle_generate(_body())
        assert code == 504 and "not replayed" in payload["error"]
        assert fallback.requests_served == 0, "slow must not be replayed"
        slow_state = next(r for r in router.replicas if r.url == slow.url)
        assert slow_state.healthy, "a slow replica is not a dead replica"
        assert router.requests_retried == 0
    finally:
        slow.close()
        fallback.close()


def test_router_passes_client_errors_through_without_retry():
    bad = _FakeReplica(generate_code=400)
    fallback = _FakeReplica(slots=1, active=1)
    try:
        router = Router([bad.url, fallback.url])
        router.poll_once()
        code, _ = router.handle_generate(_body())
        assert code == 400
        assert fallback.requests_served == 0, "4xx must not be replayed"
        # The caller's error is not a FLEET failure: it must not burn the
        # availability SLO's budget (separate client-error counter).
        assert router.requests_failed == 0
        assert router.requests_client_errors == 1
        page = router.statusz()
        assert page["requests_client_errors"] == 1
        assert "requests_client_errors_total 1" in router.prometheus_metrics()
    finally:
        bad.close()
        fallback.close()


def test_router_http_surface_and_metrics():
    replica = _FakeReplica()
    try:
        router = Router([replica.url])
        router.poll_once()
        server = make_router_http_server(router, port=0)
        port = server.server_address[1]
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            base = f"http://127.0.0.1:{port}"
            req = urllib.request.Request(
                f"{base}/generate", data=_body(),
                headers={"Content-Type": "application/json"},
            )
            out = json.loads(urllib.request.urlopen(req, timeout=30).read())
            assert out["token_ids"] == [1, 2]
            page = json.loads(
                urllib.request.urlopen(f"{base}/statusz", timeout=30).read()
            )
            assert page["requests_routed"] == 1
            assert page["replicas"][0]["healthy"]
            prom = urllib.request.urlopen(
                f"{base}/metrics", timeout=30
            ).read().decode()
            assert "bpe_tpu_router_requests_routed_total 1" in prom
            assert 'replica_healthy{replica="' in prom
            health = json.loads(
                urllib.request.urlopen(f"{base}/healthz", timeout=30).read()
            )
            assert health["ok"]
        finally:
            server.shutdown()
            server.server_close()
            thread.join(timeout=10)
    finally:
        replica.close()


def test_router_importable_and_runnable_without_jax():
    """ACCEPTANCE: the route front is jax-free, pinned like monitor —
    importing and constructing it must not touch jax."""
    script = (
        "import sys\n"
        "sys.modules['jax'] = None\n"  # any `import jax` now raises
        "from bpe_transformer_tpu.serving.router import Router, main\n"
        "from bpe_transformer_tpu.serving import PrefillBudget\n"
        "from bpe_transformer_tpu.serving.kvpool.blocks import "
        "BlockAllocator\n"
        "router = Router(['http://127.0.0.1:9'])\n"
        "router.poll_once()\n"
        "assert not router.replicas[0].healthy\n"
        "assert router.handle_generate(b'{}')[0] == 503\n"
        "print('ok')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": str(REPO)},
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert proc.stdout.strip() == "ok"


def test_suspect_quarantine_probe_backoff_and_recovery():
    """ISSUE 20 suspect quarantine: suspect_after consecutive connect
    failures exclude a replica from routing AND from the poll sweep; it
    is probed on an exponential-backoff schedule (doubling, capped), and
    a successful probe readmits it — a respawned replica rejoins without
    the fleet paying a connect timeout per poll while it was gone."""
    clk = {"t": 0.0}
    live = _FakeReplica()
    ghost = _FakeReplica()
    dead_port = ghost.server.server_address[1]
    ghost.close()
    url_dead = f"http://127.0.0.1:{dead_port}"
    try:
        router = Router(
            [live.url, url_dead], poll_interval_s=3600.0,
            poll_timeout_s=1.0, suspect_after=2,
            probe_backoff_s=1.0, probe_backoff_max_s=4.0,
            clock=lambda: clk["t"],
        )
        dead = next(r for r in router.replicas if r.url == url_dead)
        router.poll_once()
        assert not dead.suspect and dead.consecutive_failures == 1
        router.poll_once()
        assert dead.suspect
        assert dead.probe_backoff_s == 1.0
        assert dead.next_probe_t == pytest.approx(1.0)
        page = router.statusz()
        assert page["suspect"] == 1 and page["suspected_total"] == 1
        assert [r.url for r in router.pick_order()] == [live.url]

        # Inside the backoff window the sweep SKIPS the suspect entirely.
        probes0 = router.probes_total
        clk["t"] = 0.5
        router.poll_once()
        assert router.probes_total == probes0
        assert dead.consecutive_failures == 2

        # Each failed probe doubles the next deadline, up to the cap.
        clk["t"] = 1.1
        router.poll_once()
        assert router.probes_total == probes0 + 1
        assert dead.probe_backoff_s == 2.0
        clk["t"] = 3.2
        router.poll_once()
        assert dead.probe_backoff_s == 4.0
        clk["t"] = 7.5
        router.poll_once()
        assert dead.probe_backoff_s == 4.0  # capped

        # Recovery: the replica returns at the same URL; one successful
        # probe clears the quarantine and rejoins it to the rotation.
        revived = _FakeReplica(port=dead_port)
        try:
            clk["t"] = 12.0
            router.poll_once()
            assert not dead.suspect and dead.available
            assert dead.next_probe_t is None
            assert router.recoveries_total == 1
            assert {r.url for r in router.pick_order()} == {
                live.url, url_dead
            }
        finally:
            revived.close()
    finally:
        live.close()


def test_prompt_mix_window_and_threshold_retune_endpoint():
    """ISSUE 20 tier retuning evidence + actuator: the router observes
    the live prompt-length mix even with the two-tier threshold unarmed,
    and POST /admin/threshold retunes (or disarms) the split at runtime
    with validation."""
    rep = _FakeReplica()
    try:
        router = Router([rep.url], prompt_mix_window=64)
        router.poll_once()
        for n in (4, 8, 12, 100):
            code, _ = router.handle_generate(
                json.dumps(
                    {"prompt_ids": [1] * n, "max_new_tokens": 2}
                ).encode()
            )
            assert code == 200
        # A text prompt is estimated at ~4 chars/token.
        router.handle_generate(
            json.dumps({"prompt": "x" * 40, "max_new_tokens": 2}).encode()
        )
        mix = router.prompt_mix_summary()
        assert mix["count"] == 5
        assert mix["p50"] == 10 and mix["max"] == 100
        assert mix["long_frac"] is None  # threshold unarmed

        assert router.set_prefill_threshold(12) == 12
        assert router.prompt_mix_summary()["long_frac"] == pytest.approx(
            2 / 5
        )
        with pytest.raises(ValueError, match=">= 1"):
            router.set_prefill_threshold(0)

        server = make_router_http_server(router, port=0)
        port = server.server_address[1]
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            base = f"http://127.0.0.1:{port}"

            def post_threshold(value):
                req = urllib.request.Request(
                    f"{base}/admin/threshold",
                    data=json.dumps({"prefill_threshold": value}).encode(),
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=30) as resp:
                    return json.loads(resp.read())

            assert post_threshold(48) == {"prefill_threshold": 48}
            page = json.loads(
                urllib.request.urlopen(
                    f"{base}/statusz", timeout=30
                ).read()
            )
            assert page["prefill_threshold"] == 48
            assert page["threshold_updates"] == 2
            assert page["prompt_mix"]["count"] == 5
            # None disarms two-tier routing; garbage is a 400.
            assert post_threshold(None) == {"prefill_threshold": None}
            assert router.prefill_threshold is None
            try:
                post_threshold(0)
                raise AssertionError("expected 400")
            except urllib.error.HTTPError as err:
                assert err.code == 400
                assert ">= 1" in json.loads(err.read())["error"]
        finally:
            server.shutdown()
            server.server_close()
            thread.join(timeout=10)
    finally:
        rep.close()


# ------------------------------------------------------------ integration


@pytest.mark.slow
def test_router_two_replicas_drain_failover_and_rejoin():
    """ACCEPTANCE: router + two in-process paged replicas under threaded
    load; one replica drains mid-load (PR-5 drain) — zero failed
    requests, traffic rebalances to the survivor — then the drained
    replica restarts on the same port and rejoins the rotation.

    Behind the ``slow`` marker (like PR 5's subprocess E2Es): two real
    engines + threaded HTTP load is the heaviest test in the router
    module, and the failover/drain/4xx routing DECISIONS are covered
    tier-1 by the fake-replica unit tests above."""
    import jax

    from bpe_transformer_tpu.models import TS_TEST_CONFIG, init_params
    from bpe_transformer_tpu.serving import ServingEngine, make_http_server

    cfg = dataclasses.replace(
        TS_TEST_CONFIG, vocab_size=128, context_length=32
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    prompts = [
        [int(t) for t in rng.integers(0, cfg.vocab_size, size=6)]
        for _ in range(24)
    ]

    def start_replica(port=0):
        serving = ServingEngine(
            params, cfg, slots=2, min_bucket=8, paged=True, block_size=8
        )
        serving.start()
        server = make_http_server(serving, port=port)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        return serving, server, server.server_address[1]

    serving_a, server_a, port_a = start_replica()
    serving_b, server_b, port_b = start_replica()
    url_a = f"http://127.0.0.1:{port_a}"
    url_b = f"http://127.0.0.1:{port_b}"

    router = Router([url_a, url_b], poll_interval_s=0.1).start()
    rserver = make_router_http_server(router, port=0)
    rthread = threading.Thread(target=rserver.serve_forever, daemon=True)
    rthread.start()
    rport = rserver.server_address[1]

    results, errors = [], []

    def fire(i):
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{rport}/generate",
                data=json.dumps(
                    {"prompt_ids": prompts[i], "max_new_tokens": 6,
                     "temperature": 0.0}
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            results.append(
                json.loads(urllib.request.urlopen(req, timeout=120).read())
            )
        except Exception as exc:  # noqa: BLE001 — the assertion is "none"
            errors.append(repr(exc))

    try:
        # Phase 1: both replicas take traffic.
        threads = [
            threading.Thread(target=fire, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors
        used = {r["replica"] for r in results}
        assert used == {url_a, url_b}, f"no initial balance: {used}"

        # Phase 2: drain A mid-load — requests racing the drain must be
        # replayed on B, and zero requests may fail.
        threads = [
            threading.Thread(target=fire, args=(i,)) for i in range(8, 16)
        ]
        for t in threads[:4]:
            t.start()
        drainer = threading.Thread(
            target=lambda: serving_a.drain(timeout_s=60)
        )
        drainer.start()
        for t in threads[4:]:
            t.start()
        for t in threads:
            t.join(timeout=120)
        drainer.join(timeout=120)
        assert not errors, errors
        assert len(results) == 16

        # Poll must now see A draining; new traffic goes only to B.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            router.poll_once()
            state_a = next(r for r in router.replicas if r.url == url_a)
            if state_a.draining:
                break
        assert state_a.draining
        assert [r.url for r in router.pick_order()] == [url_b]
        fire(16)
        assert not errors and results[-1]["replica"] == url_b

        # Phase 3: "restart" A on the SAME port (rolling deploy) — the
        # poller brings it back and traffic rebalances without operator
        # action.
        server_a.shutdown()
        server_a.server_close()
        serving_a.close()
        serving_a, server_a, _ = start_replica(port=port_a)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            router.poll_once()
            state_a = next(r for r in router.replicas if r.url == url_a)
            if state_a.available:
                break
        assert state_a.available, "restarted replica never rejoined"
        for i in range(17, 23):
            fire(i)
        assert not errors, errors
        rejoined = {r["replica"] for r in results[-6:]}
        assert url_a in rejoined, "no traffic returned to the rejoined replica"

        page = router.statusz()
        assert page["requests_failed"] == 0, page
        assert page["requests_routed"] == len(results)
    finally:
        router.close()
        rserver.shutdown()
        rserver.server_close()
        rthread.join(timeout=10)
        for server, serving in (
            (server_a, serving_a), (server_b, serving_b)
        ):
            server.shutdown()
            server.server_close()
            serving.close()


def test_router_session_affinity_sticky_and_fallback():
    """ISSUE 9 satellite: a "session" body key makes routing sticky — the
    hashed home replica is tried FIRST even when weighted order prefers a
    peer — with weighted fallback (and an honest affinity-miss count)
    when the home is down; sessionless traffic keeps pure weighting."""
    import zlib

    # a: index 0, deliberately the WORSE-weighted replica.
    a = _FakeReplica(slots=2, active=1)
    b = _FakeReplica(slots=8)
    try:
        router = Router([a.url, b.url])
        router.poll_once()
        # A session that hashes to index 0 (replica a).
        session = next(
            s for s in (str(i) for i in range(64))
            if zlib.crc32(s.encode()) % 2 == 0
        )
        assert router.sticky_replica(session).url == a.url

        def body(sess=None):
            payload = {"prompt_ids": [1, 2], "max_new_tokens": 2}
            if sess is not None:
                payload["session"] = sess
            return json.dumps(payload).encode()

        for _ in range(2):
            code, payload = router.handle_generate(body(session))
            assert code == 200 and payload["replica"] == a.url, (
                "sticky home must beat weighted order"
            )
        # Sessionless traffic still goes to the better-weighted replica.
        code, payload = router.handle_generate(body())
        assert code == 200 and payload["replica"] == b.url

        page = router.statusz()
        assert page["session_requests"] == 2
        assert page["affinity_hits"] == 2
        assert page["affinity_hit_rate"] == 1.0

        # Home down -> weighted fallback serves the session (counted as a
        # miss: its prefix blocks start cold on the peer).
        a.close()
        code, payload = router.handle_generate(body(session))
        assert code == 200 and payload["replica"] == b.url
        page = router.statusz()
        assert page["session_requests"] == 3
        assert page["affinity_hits"] == 2
        assert abs(page["affinity_hit_rate"] - 2 / 3) < 1e-6

        prom = router.prometheus_metrics()
        assert "bpe_tpu_router_session_requests_total 3" in prom
        assert "bpe_tpu_router_affinity_hits_total 2" in prom
    finally:
        for replica in (a, b):
            try:
                replica.close()
            except Exception:  # noqa: BLE001 — a may already be closed
                pass


# ----------------------------------------------------- tracing (ISSUE 12)


class _ListTelemetry:
    """Minimal Telemetry stand-in: collects emitted records, provides the
    now() the router's span emission reads."""

    def __init__(self):
        self.records = []
        self._t0 = time.monotonic()

    def now(self):
        return round(time.monotonic() - self._t0, 6)

    def emit(self, record):
        self.records.append(record)


def test_router_trace_spans_failover_shows_both_hops():
    """ACCEPTANCE (tracing, router side): a request that fails over
    records one router/hop span per ATTEMPTED replica — the dead hop with
    its failure outcome, the serving hop with connect/ttfb timings — plus
    pick and request envelope spans, all tagged with the SAME trace id,
    which is also forwarded to the replica as X-Request-Id."""
    from bpe_transformer_tpu.telemetry.schema import validate_record

    survivor = _FakeReplica()
    telemetry = _ListTelemetry()
    try:
        router = Router(
            [survivor.url, "http://127.0.0.1:9"], telemetry=telemetry
        )
        router.poll_once()
        for r in router.replicas:  # force the dead replica first
            r.healthy = True
            r.slots = 4 if r.url != survivor.url else 1
        code, payload = router.handle_generate(
            _body(), trace_id="trace-hops-1"
        )
        assert code == 200 and payload["request_id"] == "trace-hops-1"
        assert survivor.seen_request_ids == ["trace-hops-1"]

        spans = [r for r in telemetry.records if r.get("kind") == "span"]
        assert all(s["request_id"] == "trace-hops-1" for s in spans)
        by_path: dict = {}
        for s in spans:
            by_path.setdefault(s["path"], []).append(s)
        assert set(by_path) == {"router/pick", "router/hop",
                                "router/request"}
        hops = sorted(by_path["router/hop"], key=lambda s: s["hop"])
        assert len(hops) == 2
        assert hops[0]["outcome"] == "connect_failed"
        assert hops[0]["replica"] == "http://127.0.0.1:9"
        assert hops[1]["outcome"] == "ok" and hops[1]["status"] == 200
        assert hops[1]["replica"] == survivor.url
        assert hops[1]["ttfb_s"] >= 0 and hops[1]["connect_s"] >= 0
        (request_span,) = by_path["router/request"]
        assert request_span["hops"] == 2
        assert request_span["replica"] == survivor.url
        assert request_span["status"] == 200
        # Cross-stream ordering contract: absolute start stamps present.
        assert all(
            isinstance(s.get("time_unix"), float) for s in spans
        )
        for s in spans:
            assert validate_record(s) == [], s
    finally:
        survivor.close()


def test_router_echoes_request_id_on_success_and_both_error_paths():
    """Satellite pin: X-Request-Id comes back on EVERY router response —
    success, the all-replicas-down 503, and the not-replayed 504 read
    timeout — and an inbound id is honored, not replaced."""
    # Success + inbound honor.
    replica = _FakeReplica()
    try:
        router = Router([replica.url])
        router.poll_once()
        server = make_router_http_server(router, port=0)
        port = server.server_address[1]
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate", data=_body(),
                headers={"Content-Type": "application/json",
                         "X-Request-Id": "client-id-7"},
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.headers["X-Request-Id"] == "client-id-7"
                payload = json.loads(resp.read())
            assert payload["request_id"] == "client-id-7"
            assert replica.seen_request_ids == ["client-id-7"]
        finally:
            server.shutdown()
            server.server_close()
            thread.join(timeout=10)
    finally:
        replica.close()

    # 503: no available replica.  The router MINTS an id when the client
    # sent none, so even this failure is traceable.
    router = Router(["http://127.0.0.1:9"])
    router.poll_once()
    server = make_router_http_server(router, port=0)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=_body(),
            headers={"Content-Type": "application/json"},
        )
        try:
            urllib.request.urlopen(req, timeout=30)
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as err:
            assert err.code == 503
            minted = err.headers["X-Request-Id"]
            assert minted and len(minted) == 32
            assert json.loads(err.read())["request_id"] == minted
    finally:
        server.shutdown()
        server.server_close()
        thread.join(timeout=10)

    # 504: established-but-slow replica (not replayed) still echoes.
    slow = _FakeReplica(generate_delay_s=0.6)
    try:
        router = Router([slow.url], request_timeout_s=0.2)
        router.poll_once()
        server = make_router_http_server(router, port=0)
        port = server.server_address[1]
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate", data=_body(),
                headers={"Content-Type": "application/json",
                         "X-Request-Id": "timeout-id-9"},
            )
            try:
                urllib.request.urlopen(req, timeout=30)
                raise AssertionError("expected 504")
            except urllib.error.HTTPError as err:
                assert err.code == 504
                assert err.headers["X-Request-Id"] == "timeout-id-9"
                body = json.loads(err.read())
                assert body["request_id"] == "timeout-id-9"
                assert "not replayed" in body["error"]
        finally:
            server.shutdown()
            server.server_close()
            thread.join(timeout=10)
    finally:
        slow.close()


def test_router_metrics_jsonl_cli_writes_trace_stream(tmp_path):
    """`bpe-tpu route --metrics-jsonl`: the router narrates its stream
    jax-free — manifest header (host_manifest: no device probe), spans
    per request, footer — parseable by the report loader."""
    script = (
        "import sys, threading, urllib.request, json\n"
        "sys.modules['jax'] = None\n"
        "from bpe_transformer_tpu.serving.router import main\n"
        "import bpe_transformer_tpu.serving.router as router_mod\n"
        "server_holder = {}\n"
        "orig = router_mod.make_router_http_server\n"
        "def capture(router, host='127.0.0.1', port=8100):\n"
        "    server = orig(router, host, port)\n"
        "    server_holder['server'] = server\n"
        "    def stop():\n"
        "        import time\n"
        "        time.sleep(1.0)\n"
        "        server.shutdown()\n"
        "    threading.Thread(target=stop, daemon=True).start()\n"
        "    return server\n"
        "router_mod.make_router_http_server = capture\n"
        "rc = main(['--replica', 'http://127.0.0.1:9', '--port', '0',\n"
        "           '--metrics-jsonl', sys.argv[1]])\n"
        "print('rc', rc)\n"
    )
    out = tmp_path / "router_metrics.jsonl"
    proc = subprocess.run(
        [sys.executable, "-c", script, str(out)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": str(REPO)},
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    from bpe_transformer_tpu.telemetry.report import load_records

    records = load_records(out)
    kinds = [r.get("kind") for r in records]
    assert kinds[0] == "manifest" and kinds[-1] == "footer"
    manifest = records[0]
    assert manifest["run_kind"] == "route"
    assert "devices" not in manifest  # host_manifest: no backend probe


# ----------------------------- two-tier disaggregated routing (ISSUE 15)


def test_router_partitions_fleet_by_role_and_threshold():
    """Long prompts take the two-tier path (export on the prefill-role
    replica, import on the decode pool); short prompts bypass straight to
    decode-capable replicas; prefill-role replicas NEVER take a whole
    /generate."""
    prefill = _FakeReplica(slots=8, role="prefill")
    decode = _FakeReplica(slots=2, role="decode")
    try:
        router = Router([prefill.url, decode.url], prefill_threshold=8)
        router.poll_once()
        roles = {r.url: r.role for r in router.replicas}
        assert roles == {prefill.url: "prefill", decode.url: "decode"}
        # The generate pool excludes the prefill-role replica even though
        # its weight is higher.
        assert [r.url for r in router.pick_order()] == [decode.url]
        assert [r.url for r in router.pick_order(pool="prefill")] == [
            prefill.url
        ]

        long_body = json.dumps(
            {"prompt_ids": list(range(16)), "max_new_tokens": 2}
        ).encode()
        code, payload = router.handle_generate(long_body, trace_id="tt-1")
        assert code == 200 and payload["token_ids"] == [7, 8, 9]
        assert payload["replica"] == decode.url
        assert prefill.exports_served == 1
        assert decode.imports_served == 1
        # The payload crossed the router opaquely, trace id intact.
        assert decode.import_bodies[0] == b"BPEKV-FAKE-PAYLOAD:tt-1"
        assert router.requests_migrated == 1

        code, payload = router.handle_generate(_body())  # 3-token prompt
        assert code == 200 and payload["token_ids"] == [1, 2]
        assert decode.requests_served == 1
        assert prefill.exports_served == 1, "short prompts bypass prefill"
        page = router.statusz()
        assert page["requests_migrated"] == 1
        assert page["prefill_threshold"] == 8
        assert any(r["role"] == "prefill" for r in page["replicas"])
        assert "requests_migrated_total 1" in router.prometheus_metrics()
        assert 'role="prefill"' in router.prometheus_metrics()
    finally:
        prefill.close()
        decode.close()


def test_router_two_tier_failover_and_degradation():
    """A refused export fails over across the prefill pool and, when the
    whole tier is out, degrades to single-tier decode routing (never an
    error); a refused import fails over across the decode pool."""
    # Export 503 on the only prefill replica -> the request is served
    # whole by the decode replica.
    sick_prefill = _FakeReplica(role="prefill", export_code=503)
    decode = _FakeReplica(role="decode")
    try:
        router = Router(
            [sick_prefill.url, decode.url], prefill_threshold=4
        )
        router.poll_once()
        code, payload = router.handle_generate(
            json.dumps({"prompt_ids": list(range(12))}).encode()
        )
        assert code == 200 and payload["token_ids"] == [1, 2]
        assert decode.requests_served == 1
        assert router.requests_migrated == 0
        assert router.requests_failed == 0
    finally:
        sick_prefill.close()
        decode.close()

    # Import 503 on the best decode replica -> the payload replays on the
    # next decode replica (grafts are deterministic; replay is safe).
    prefill = _FakeReplica(role="prefill")
    full = _FakeReplica(slots=8, role="decode", generate_code=503)
    spare = _FakeReplica(slots=1, active=1, role="decode")
    try:
        router = Router(
            [prefill.url, full.url, spare.url], prefill_threshold=4
        )
        router.poll_once()
        code, payload = router.handle_generate(
            json.dumps({"prompt_ids": list(range(12))}).encode()
        )
        assert code == 200 and payload["replica"] == spare.url
        assert full.import_bodies and spare.imports_served == 1
        assert router.requests_migrated == 1
    finally:
        prefill.close()
        full.close()
        spare.close()


def test_router_without_threshold_ignores_roles_of_both():
    """No threshold / no prefill tier: pre-ISSUE-15 behavior is intact —
    role 'both' (or missing) replicas balance exactly as before."""
    a = _FakeReplica(slots=4)           # no role field at all (old replica)
    b = _FakeReplica(slots=4, role="both")
    try:
        router = Router([a.url, b.url])
        router.poll_once()
        assert {r.url for r in router.pick_order()} == {a.url, b.url}
        code, _ = router.handle_generate(_body())
        assert code == 200
    finally:
        a.close()
        b.close()
