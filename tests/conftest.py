"""Shared test configuration.

Must run before anything imports jax: forces the CPU platform with 8 virtual
devices so multi-chip sharding (data-parallel psum, FSDP partitioning) is
exercised without TPU hardware — the TPU-native analogue of a fake
distributed backend.
"""

import os
from pathlib import Path

# Force CPU for the test suite regardless of ambient configuration: numeric
# parity tolerances assume f32 host matmuls, and the virtual 8-device mesh
# only exists on the host platform.  (Benchmarks run on TPU via bench.py.)
# This container's site customization imports jax at interpreter boot and
# force-selects an accelerator platform via jax.config, so an env var alone
# is not enough — override the config before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# jax 0.4.x compat: tests call jax.shard_map directly (the spelling newer
# jax exports); alias the experimental symbol before any test module loads.
from bpe_transformer_tpu.compat.shardmap import ensure_shard_map  # noqa: E402

ensure_shard_map()

import pytest  # noqa: E402

REPO_ROOT = Path(__file__).resolve().parent.parent


def load_script_module(name: str, relpath: str):
    """Import a top-level script (bench.py, benchmarks/*.py) as a module
    under a test-private name — the shared loader for script-unit tests so
    the 5-line spec boilerplate isn't copied per file."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(name, REPO_ROOT / relpath)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod

#: The upstream reference checkout (read-only).  Tests that pin numerics or
#: token ids against its fixtures/snapshots skip gracefully when absent.
REFERENCE_ROOT = Path("/root/reference")
REFERENCE_FIXTURES = REFERENCE_ROOT / "tests" / "fixtures"
REFERENCE_SNAPSHOTS = REFERENCE_ROOT / "tests" / "_snapshots"

requires_reference = pytest.mark.skipif(
    not REFERENCE_FIXTURES.is_dir(),
    reason="reference checkout with fixtures not mounted",
)


@pytest.fixture(scope="session")
def reference_fixtures() -> Path:
    if not REFERENCE_FIXTURES.is_dir():
        pytest.skip("reference fixtures not available")
    return REFERENCE_FIXTURES


@pytest.fixture(scope="session")
def reference_snapshots() -> Path:
    if not REFERENCE_SNAPSHOTS.is_dir():
        pytest.skip("reference snapshots not available")
    return REFERENCE_SNAPSHOTS


@pytest.fixture(scope="session")
def tiny_corpus(tmp_path_factory) -> Path:
    """A small synthetic training corpus with document separators."""
    lines = []
    words = [
        "the", "quick", "brown", "fox", "jumps", "over", "lazy", "dog",
        "pack", "my", "box", "with", "five", "dozen", "liquor", "jugs",
        "sphinx", "of", "black", "quartz", "judge", "vow",
    ]
    for i in range(400):
        line = " ".join(words[(i + j) % len(words)] for j in range(12))
        lines.append(line + ("." if i % 3 else "!"))
        if i % 25 == 24:
            lines.append("<|endoftext|>")
    path = tmp_path_factory.mktemp("corpus") / "tiny_corpus.txt"
    path.write_text("\n".join(lines) + "\n", encoding="utf-8")
    return path
