"""KV-cached decoding: numerics vs the full forward, and sampler integration."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bpe_transformer_tpu.models import TS_TEST_CONFIG, forward, init_params
from bpe_transformer_tpu.models.decode import (
    decode_step,
    generate_cached,
    init_kv_cache,
    prefill,
)

CFG = dataclasses.replace(TS_TEST_CONFIG, vocab_size=512, context_length=32)


@pytest.fixture(scope="module")
def setup():
    params = init_params(jax.random.PRNGKey(0), CFG)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, CFG.vocab_size, size=(2, 12)), jnp.int32)
    return params, ids


def _stepwise_decode_parity(
    params, ids, cfg, ref, prefill_len, atol=1e-4, lm_head=None,
    cache_dtype=jnp.float32,
):
    """Shared parity scaffold: prefill then token-by-token decode_step,
    asserting logits against ``ref`` (a (B, S, V) full-forward run) at the
    prefill boundary and every subsequent position.  Returns the final
    (logits, cache) for any extra per-test assertions."""
    cache = init_kv_cache(cfg, ids.shape[0], dtype=cache_dtype)
    logits, cache = prefill(
        params, ids[:, :prefill_len], cfg, cache, lm_head=lm_head
    )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref[:, prefill_len - 1]), atol=atol
    )
    for p in range(prefill_len, ids.shape[1]):
        logits, cache = decode_step(
            params, ids[:, p], jnp.asarray(p), cache, cfg, lm_head=lm_head
        )
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(ref[:, p]), atol=atol,
            err_msg=f"position {p}",
        )
    return logits, cache


def test_prefill_matches_forward(setup):
    params, ids = setup
    full = forward(params, ids, CFG)  # (B, S, V)
    cache = init_kv_cache(CFG, ids.shape[0])
    logits, _ = prefill(params, ids, CFG, cache)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[:, -1]), atol=1e-4
    )


def test_decode_step_matches_forward(setup):
    """Feeding tokens one by one through the cache reproduces the full
    forward's logits at every position."""
    params, ids = setup
    _stepwise_decode_parity(params, ids, CFG, forward(params, ids, CFG), 4)


@pytest.mark.slow
def test_generate_cached_greedy_matches_uncached(setup):
    """temperature=0: the cached sampler and the sliding-window sampler must
    produce identical token sequences."""
    from bpe_transformer_tpu.training.sampling import generate_ids

    params, ids = setup
    prompt = [int(t) for t in np.asarray(ids[0, :5])]
    cached = generate_ids(params, CFG, prompt, max_new_tokens=10, temperature=0.0)

    out = generate_cached(
        params,
        jnp.asarray([prompt], jnp.int32),
        jax.random.PRNGKey(0),
        config=CFG,
        max_new_tokens=10,
        temperature=0.0,
    )
    assert cached == [int(t) for t in np.asarray(out[0])]

    # And against the explicit full-forward argmax loop.
    seq = list(prompt)
    for _ in range(10):
        logits = forward(params, jnp.asarray([seq], jnp.int32), CFG)
        seq.append(int(jnp.argmax(logits[0, -1])))
    assert cached == seq[len(prompt):]


@pytest.mark.parametrize(
    "variant",
    [
        dict(use_post_norm=True),
        dict(ffn_type="moe", n_experts=4, capacity_factor=64.0),
        dict(
            ffn_type="moe",
            n_experts=4,
            router_top_k=2,
            capacity_factor=64.0,
            use_post_norm=True,
        ),
    ],
    ids=["post_norm", "moe_top1", "moe_top2_post_norm"],
)
@pytest.mark.slow
def test_cached_decode_parity_block_variants(variant):
    """Round-2 coverage: the cached path handles post-norm and MoE blocks
    (capacity generous so per-call routing has no drops) with logits parity
    at every position and greedy-token parity."""
    cfg = dataclasses.replace(CFG, **variant)
    params = init_params(jax.random.PRNGKey(1), cfg)
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(2, 12)), jnp.int32)

    _stepwise_decode_parity(params, ids, cfg, forward(params, ids, cfg), 4)

    # Greedy generation: cached sampler == explicit full-forward argmax loop.
    prompt = [int(t) for t in np.asarray(ids[0, :5])]
    cached = generate_cached(
        params,
        jnp.asarray([prompt], jnp.int32),
        jax.random.PRNGKey(0),
        config=cfg,
        max_new_tokens=8,
        temperature=0.0,
    )
    seq = list(prompt)
    for _ in range(8):
        lg = forward(params, jnp.asarray([seq], jnp.int32), cfg)
        seq.append(int(jnp.argmax(lg[0, -1])))
    assert [int(t) for t in np.asarray(cached[0])] == seq[len(prompt):]


def test_generate_cached_shapes_and_range(setup):
    params, _ = setup
    out = generate_cached(
        params,
        jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32),
        jax.random.PRNGKey(1),
        config=CFG,
        max_new_tokens=7,
        temperature=1.0,
        top_k=20,
    )
    assert out.shape == (2, 7)
    assert np.all((np.asarray(out) >= 0) & (np.asarray(out) < CFG.vocab_size))


def test_generate_cached_context_overflow_raises(setup):
    params, _ = setup
    with pytest.raises(ValueError, match="exceeds"):
        generate_cached(
            params,
            jnp.asarray([[1] * 30], jnp.int32),
            jax.random.PRNGKey(0),
            config=CFG,
            max_new_tokens=10,
        )


def test_sampler_long_generation_falls_back(setup):
    """Generation past the context window still works (sliding window)."""
    from bpe_transformer_tpu.training.sampling import generate_ids

    params, _ = setup
    out = generate_ids(
        params, CFG, [1, 2, 3], max_new_tokens=40, temperature=0.0
    )
    assert len(out) == 40


def test_top_p_sampling_masks_tail(setup):
    """top_p keeps only the nucleus: with a peaked distribution and small p,
    sampling must always return the argmax; samples stay in vocab range."""
    import jax

    from bpe_transformer_tpu.models.decode import _sample_from_logits

    logits = jnp.log(
        jnp.asarray([[0.6, 0.25, 0.1, 0.04, 0.01]], jnp.float32)
    )
    for seed in range(8):
        tok = _sample_from_logits(
            logits, jax.random.PRNGKey(seed), temperature=1.0,
            top_k=None, top_p=0.5,
        )
        assert int(tok[0]) == 0  # only the 0.6 token is in the 0.5 nucleus

    # p large enough to admit the top two: both appear, the tail never does.
    seen = set()
    for seed in range(40):
        tok = _sample_from_logits(
            logits, jax.random.PRNGKey(seed), temperature=1.0,
            top_k=None, top_p=0.85,
        )
        seen.add(int(tok[0]))
    assert seen == {0, 1}

    # Degenerate p never masks everything: p=0 reduces to greedy.
    for seed in range(4):
        tok = _sample_from_logits(
            logits, jax.random.PRNGKey(seed), temperature=1.0,
            top_k=None, top_p=0.0,
        )
        assert int(tok[0]) == 0

    # End-to-end through the cached sampler.
    params, _ = setup
    out = generate_cached(
        params,
        jnp.asarray([[1, 2, 3]], jnp.int32),
        jax.random.PRNGKey(0),
        config=CFG,
        max_new_tokens=5,
        temperature=1.0,
        top_p=0.9,
    )
    assert out.shape == (1, 5)
    assert np.all((np.asarray(out) >= 0) & (np.asarray(out) < CFG.vocab_size))


@pytest.mark.slow  # 870s tier-1 budget (PR 11 sweep; ISSUE 11 tooling guard) — runs in the full matrix
def test_moe_decode_default_capacity_no_drops():
    """At the DEFAULT capacity_factor the cached path must not drop tokens
    its full forward keeps: decode derives capacity from context_length
    (decode._ffn_decode), so the per-step few-token calls are drop-free and
    the whole cached chain reproduces a drop-free full forward exactly."""
    cfg = dataclasses.replace(
        CFG, ffn_type="moe", n_experts=4, capacity_factor=1.25
    )
    nodrop = dataclasses.replace(cfg, capacity_factor=100.0)
    params = init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(2, 12)), jnp.int32)

    # Drop-free oracle: the default-capacity cached chain must match it.
    _stepwise_decode_parity(params, ids, cfg, forward(params, ids, nodrop), 4)


@pytest.mark.slow
def test_moe_decode_step_dropfree_with_degenerate_capacity():
    """Even when the full-length expert capacity is below the batch size
    (many experts, tiny context), single-token decode steps must stay
    drop-free: the derived capacity floors at the batch."""
    cfg = dataclasses.replace(
        CFG,
        context_length=16,
        ffn_type="moe",
        n_experts=64,
        capacity_factor=1.0,  # full-length cap = ceil(8*16/64) = 2 < B=8
    )
    nodrop = dataclasses.replace(cfg, capacity_factor=100.0)
    params = init_params(jax.random.PRNGKey(5), cfg)
    rng = np.random.default_rng(5)
    B = 8
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(B, 10)), jnp.int32)

    _stepwise_decode_parity(params, ids, cfg, forward(params, ids, nodrop), 2)


@pytest.mark.slow  # 870s tier-1 budget (PR 11 sweep; ISSUE 11 tooling guard) — runs in the full matrix
def test_bf16_cached_decode_close_to_bf16_forward():
    """The cached path honors activation_dtype: under bf16 the whole chain
    (params cast once, bf16 KV cache, bf16 einsums, f32 softmax/logits)
    tracks the bf16 full forward closely — the gpt2 presets are bf16, so
    they must get the O(1)-per-token path, not the sliding-window fallback."""
    cfg = dataclasses.replace(CFG, activation_dtype="bfloat16")
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(7)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(2, 12)), jnp.int32)

    ref = forward(params, ids, cfg)  # bf16 compute, f32 logits

    from bpe_transformer_tpu.models.transformer import lm_head_weight

    act = jnp.bfloat16
    head = lm_head_weight(params, cfg).astype(jnp.float32)  # master, f32
    cast = jax.tree_util.tree_map(lambda p: p.astype(act), params)
    logits, cache = _stepwise_decode_parity(
        cast, ids, cfg, ref, 4, atol=0.1, lm_head=head, cache_dtype=act
    )
    assert logits.dtype == jnp.float32
    assert cache[0]["k"].dtype == act


def test_generate_ids_bf16_uses_cached_fast_path(monkeypatch):
    """generate_ids routes bf16 configs through generate_cached now."""
    from bpe_transformer_tpu.models import decode as decode_mod
    from bpe_transformer_tpu.training.sampling import generate_ids

    cfg = dataclasses.replace(CFG, activation_dtype="bfloat16")
    params = init_params(jax.random.PRNGKey(1), cfg)

    calls = []
    real = decode_mod.generate_cached
    monkeypatch.setattr(
        decode_mod,
        "generate_cached",
        lambda *a, **k: calls.append(1) or real(*a, **k),
    )
    out = generate_ids(params, cfg, [1, 2, 3], max_new_tokens=6, temperature=0.5)
    assert calls, "bf16 config took the slow sliding-window path"
    assert len(out) == 6 and all(0 <= t < cfg.vocab_size for t in out)


@pytest.mark.slow  # 870s tier-1 budget (PR 11 sweep; ISSUE 11 tooling guard) — runs in the full matrix
def test_pallas_decode_attention_impl_matches_xla(setup):
    """decode_attention_impl="pallas" (flash-decoding kernel) reproduces the
    grouped-einsum decode path: same greedy tokens end-to-end and matching
    step logits (kernel parity itself is pinned in tests/test_kernels.py)."""
    params, ids = setup
    cfg_pallas = dataclasses.replace(CFG, decode_attention_impl="pallas")

    _stepwise_decode_parity(params, ids, cfg_pallas, forward(params, ids, CFG), 4)

    prompt = ids[:, :5]
    a = generate_cached(
        params, prompt, jax.random.PRNGKey(0), config=CFG,
        max_new_tokens=8, temperature=0.0,
    )
    b = generate_cached(
        params, prompt, jax.random.PRNGKey(0), config=cfg_pallas,
        max_new_tokens=8, temperature=0.0,
    )
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_pallas_decode_attention_impl_gqa():
    """The kernel path reads the COMPACT GQA cache (no head expansion):
    per-step logits match the full forward on a grouped-query config."""
    gqa = dataclasses.replace(
        CFG, num_kv_heads=2, decode_attention_impl="pallas"
    )
    params = init_params(jax.random.PRNGKey(1), gqa)
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, gqa.vocab_size, size=(2, 10)), jnp.int32)
    _stepwise_decode_parity(params, ids, gqa, forward(params, ids, gqa), 3)


def test_prefill_flash_matches_xla(setup):
    """attention_impl="flash" routes the prefill through the Pallas flash
    kernel (no O(plen^2) score buffer); logits match the materialized path
    and greedy generation is identical end-to-end."""
    params, ids = setup
    cfg_flash = dataclasses.replace(CFG, attention_impl="flash")

    cache = init_kv_cache(CFG, ids.shape[0])
    logits_xla, cache_xla = prefill(params, ids, CFG, cache)
    cache = init_kv_cache(cfg_flash, ids.shape[0])
    logits_fl, cache_fl = prefill(params, ids, cfg_flash, cache)
    np.testing.assert_allclose(
        np.asarray(logits_fl), np.asarray(logits_xla), atol=2e-4
    )
    # The cache contents are impl-independent (written before attention).
    for lx, lf in zip(cache_xla, cache_fl):
        np.testing.assert_allclose(np.asarray(lx["k"]), np.asarray(lf["k"]), atol=1e-6)

    prompt = ids[:, :5]
    a = generate_cached(
        params, prompt, jax.random.PRNGKey(0), config=CFG,
        max_new_tokens=8, temperature=0.0,
    )
    b = generate_cached(
        params, prompt, jax.random.PRNGKey(0), config=cfg_flash,
        max_new_tokens=8, temperature=0.0,
    )
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sample_from_logits_edge_cases():
    """Sampler edge cases: temperature=0 (greedy argmax), top_k=1, the
    top_p mass boundary, and combined top_k+top_p filtering."""
    from bpe_transformer_tpu.models.decode import _sample_from_logits

    probs = [0.6, 0.25, 0.1, 0.04, 0.01]
    logits = jnp.log(jnp.asarray([probs], jnp.float32))

    # temperature=0: exact greedy, RNG-independent.
    for seed in range(4):
        tok = _sample_from_logits(
            logits, jax.random.PRNGKey(seed), temperature=0.0, top_k=None
        )
        assert int(tok[0]) == 0

    # top_k=1: only the argmax survives at ANY temperature.
    for seed in range(8):
        tok = _sample_from_logits(
            logits, jax.random.PRNGKey(seed), temperature=2.0, top_k=1
        )
        assert int(tok[0]) == 0

    def support(top_k, top_p, n=40):
        seen = set()
        for seed in range(n):
            tok = _sample_from_logits(
                logits, jax.random.PRNGKey(seed), temperature=1.0,
                top_k=top_k, top_p=top_p,
            )
            seen.add(int(tok[0]))
        return seen

    # top_p mass boundary: "mass BEFORE the token < p" means p exactly at
    # the leading probability excludes the runner-up; a hair above admits
    # it (the cumulative 0.6 is no longer < 0.6, but IS < 0.61).
    assert support(None, 0.6) == {0}
    assert support(None, 0.61) == {0, 1}

    # Combined: top_p acts on the top_k-RENORMALIZED distribution.  With
    # top_k=2 the two survivors renormalize to ~{0.706, 0.294}; p=0.4 cuts
    # the runner-up there, p=0.99 keeps exactly the top-k pair.
    assert support(2, 0.4) == {0}
    assert support(2, 0.99) == {0, 1}


@pytest.mark.slow  # 870s tier-1 budget (PR 11 sweep; ISSUE 11 tooling guard) — runs in the full matrix
def test_generate_cached_stop_id_pins_and_truncates(setup):
    """Satellite: the KV-cached fast path honors stop_id — post-stop tokens
    are pinned to stop_id inside the scan, and generate_ids' host-side
    truncation makes cached and sliding-window generation agree on stopped
    sequences."""
    from bpe_transformer_tpu.training.sampling import generate_ids

    params, ids = setup
    prompt = [int(t) for t in np.asarray(ids[0, :5])]
    free_run = generate_ids(params, CFG, prompt, max_new_tokens=10, temperature=0.0)
    sid = free_run[4]
    first = free_run.index(sid)

    # The raw cached program: stop at the first occurrence, then pinned.
    out = generate_cached(
        params,
        jnp.asarray([prompt], jnp.int32),
        jax.random.PRNGKey(0),
        config=CFG,
        max_new_tokens=10,
        temperature=0.0,
        stop_id=int(sid),
    )
    out = [int(t) for t in np.asarray(out[0])]
    assert out[first] == sid
    assert out[: first + 1] == free_run[: first + 1]
    assert all(t == sid for t in out[first:]), "post-stop tokens not pinned"

    # generate_ids (fast path) truncates to ... + [stop_id], agreeing with
    # the sliding-window path's early exit semantics.
    stopped = generate_ids(
        params, CFG, prompt, max_new_tokens=10, temperature=0.0,
        stop_id=int(sid),
    )
    assert stopped == free_run[: first + 1]

    # And with a stop_id that never fires, output is unchanged.
    never = generate_ids(
        params, CFG, prompt, max_new_tokens=10, temperature=0.0,
        stop_id=CFG.vocab_size + 7,
    )
    assert never == free_run


def test_decode_step_vector_positions_match_scalar(setup):
    """The per-slot generalization: a (B,) position vector with an active
    mask reproduces the scalar-pos logits for each row at its own depth,
    and inactive rows leave their cache untouched."""
    params, ids = setup
    full = forward(params, ids, CFG)

    # Two sequences prefixed to DIFFERENT lengths inside one batched cache.
    plens = [4, 7]
    cache = init_kv_cache(CFG, 2)
    for row, plen in enumerate(plens):
        row_cache = init_kv_cache(CFG, 1)
        _, row_cache = prefill(params, ids[row : row + 1, :plen], CFG, row_cache)
        cache = [
            {
                "k": layer["k"].at[row].set(filled["k"][0]),
                "v": layer["v"].at[row].set(filled["v"][0]),
            }
            for layer, filled in zip(cache, row_cache)
        ]

    pos = jnp.asarray(plens)
    tokens = jnp.stack([ids[0, plens[0]], ids[1, plens[1]]])

    # Both rows active at ragged depths: each row's logits match the full
    # forward at ITS position.
    logits, new_cache = decode_step(
        params, tokens, pos, cache, CFG, active=jnp.asarray([True, True])
    )
    for row, plen in enumerate(plens):
        np.testing.assert_allclose(
            np.asarray(logits[row]), np.asarray(full[row, plen]), atol=1e-4,
            err_msg=f"row {row} at pos {plen}",
        )
    assert not np.array_equal(
        np.asarray(new_cache[0]["k"][1]), np.asarray(cache[0]["k"][1])
    )

    # Inactive rows freeze: row 1's cache is bit-identical after the step
    # (its logits are computed but discarded by the engine).
    _, masked_cache = decode_step(
        params, tokens, pos, cache, CFG, active=jnp.asarray([True, False])
    )
    assert not np.array_equal(
        np.asarray(masked_cache[0]["k"][0]), np.asarray(cache[0]["k"][0])
    )
    np.testing.assert_array_equal(
        np.asarray(masked_cache[0]["k"][1]), np.asarray(cache[0]["k"][1])
    )


def test_vector_pos_pallas_matches_xla(setup):
    """The flash-decoding kernel accepts per-batch causal frontiers: same
    outputs as the grouped-einsum path at ragged positions."""
    from bpe_transformer_tpu.kernels.pallas.decode_attention import (
        decode_attention,
        xla_decode_attention,
    )

    rng = np.random.default_rng(11)
    B, H, KV, ctx, d = 3, 4, 2, 32, 8
    q = jnp.asarray(rng.standard_normal((B, H, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, KV, ctx, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, KV, ctx, d)), jnp.float32)
    pos = jnp.asarray([3, 17, 31])
    ref = xla_decode_attention(q, k, v, pos)
    out = decode_attention(q, k, v, pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    # Scalar pos still matches (the pre-generalization contract).
    np.testing.assert_allclose(
        np.asarray(decode_attention(q, k, v, 9)),
        np.asarray(xla_decode_attention(q, k, v, 9)),
        atol=2e-5,
    )


def test_top_k_threshold_matches_sort_formulation():
    """lax.top_k thresholding is equivalent to the previous full-sort kth
    selection (ties included: everything >= the k-th largest survives)."""
    from bpe_transformer_tpu.models.decode import _sample_from_logits

    rng = np.random.default_rng(7)
    logits = jnp.asarray(rng.standard_normal((4, 64)).astype(np.float32))
    # Inject ties at the boundary to pin tie behavior.
    logits = logits.at[:, 10].set(logits[:, 3])
    for k in (1, 5, 64):
        kth_sort = jnp.sort(logits, axis=-1)[..., -k][..., None]
        kth_topk = jax.lax.top_k(logits, k)[0][..., -1:]
        np.testing.assert_allclose(np.asarray(kth_sort), np.asarray(kth_topk))
    # And the sampler still runs with top_k through the jitted path.
    out = _sample_from_logits(logits, jax.random.PRNGKey(0), 1.0, 5)
    assert out.shape == (4,)


def test_generate_cached_with_tp_sharded_params():
    """Multi-chip INFERENCE with no decode-specific sharding code: GSPMD
    propagates the tensor-parallel parameter shardings through prefill, the
    KV cache, and the scanned token loop, reproducing the single-device
    greedy tokens exactly."""
    from bpe_transformer_tpu.parallel import make_mesh, shard_params

    cfg = dataclasses.replace(TS_TEST_CONFIG, vocab_size=512, context_length=32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(2, 8)), jnp.int32)
    ref = generate_cached(
        params, prompt, jax.random.PRNGKey(1), config=cfg,
        max_new_tokens=6, temperature=0.0,
    )

    mesh = make_mesh({"data": 2, "model": 4})
    sharded = shard_params(params, mesh, "tp")
    out = generate_cached(
        sharded, prompt, jax.random.PRNGKey(1), config=cfg,
        max_new_tokens=6, temperature=0.0,
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


@pytest.mark.slow
def test_pallas_decode_attention_impl_moe_block():
    """The flash-decoding kernel composes with MoE blocks (attention is
    FFN-independent, but the integration deserves its own pin): per-step
    logits match the full forward on a routed-FFN config."""
    cfg = dataclasses.replace(
        CFG,
        ffn_type="moe",
        n_experts=4,
        capacity_factor=64.0,
        decode_attention_impl="pallas",
    )
    params = init_params(jax.random.PRNGKey(3), cfg)
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(2, 10)), jnp.int32)
    _stepwise_decode_parity(params, ids, cfg, forward(params, ids, cfg), 3)
